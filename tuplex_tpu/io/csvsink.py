"""CSV output (reference: FileOutputOperator + buildWithCSVRowWriter,
core/include/physical/PipelineBuilder.h:238 — rows stream to the file from
the compiled pipeline, never boxed into the driver language).

`write_partitions_csv` streams columnar partitions straight into Arrow's CSV
writer: numeric leaves wrap as Arrow arrays zero-copy, string leaves pack
their byte matrices into Arrow string buffers with vectorized numpy — no
python tuple ever materializes for normal-case rows. Partitions carrying
boxed fallback rows (rare) fall back to python formatting to keep row order
exact. Remote URIs stream through the VFS backends."""

from __future__ import annotations

import csv
import os
from typing import Optional, Sequence

import numpy as np

from ..core import typesys as T
from ..runtime import columns as C
from .vfs import VirtualFileSystem


def _resolve_path(path: str) -> str:
    if VirtualFileSystem._scheme(path) != "file":
        return path
    p = VirtualFileSystem._strip(path)
    if path.endswith("/") or os.path.isdir(p):
        os.makedirs(p, exist_ok=True)
        return os.path.join(p, "part0.csv")
    parent = os.path.dirname(p)
    if parent:
        os.makedirs(parent, exist_ok=True)
    return p


def write_csv(path: str, rows: list, columns: Optional[Sequence[str]] = None,
              delimiter: str = ",") -> None:
    """Boxed-row writer (small results / compatibility path)."""
    path = _resolve_path(path)
    with VirtualFileSystem.open_write(path) as bp:
        import io as _io

        fp = _io.TextIOWrapper(bp, newline="", encoding="utf-8")
        w = csv.writer(fp, delimiter=delimiter)
        if columns:
            w.writerow(columns)
        for r in rows:
            w.writerow(list(r) if isinstance(r, tuple) else [r])
        fp.flush()
        fp.detach()


def _leaf_to_arrow(part: C.Partition, ci: int, ct: T.Type):
    """One output column as an Arrow array, built WITHOUT boxing; None if
    the column shape needs the python path (nested tuples etc.)."""
    import pyarrow as pa

    base = ct.without_option() if ct.is_optional() else ct
    n = part.num_rows
    if isinstance(base, T.TupleType) or base is T.EMPTYTUPLE:
        return None
    leaf = part.leaves.get(str(ci))
    if isinstance(leaf, C.NumericLeaf):
        mask = None if leaf.valid is None else ~leaf.valid[:n]
        data = np.asarray(leaf.data[:n])
        if data.dtype == np.bool_:
            # python's csv writer renders True/False; Arrow writes
            # true/false — keep one casing across both paths
            svals = np.where(data, "True", "False")
            return pa.array(svals, mask=mask)
        return pa.array(data, mask=mask)
    if isinstance(leaf, C.StrLeaf):
        lens = leaf.lengths[:n].astype(np.int64)
        inside = np.arange(leaf.bytes.shape[1])[None, :] < lens[:, None]
        flat = np.ascontiguousarray(leaf.bytes[:n])[inside]
        offsets = np.zeros(n + 1, dtype=np.int32)
        np.cumsum(lens, out=offsets[1:])
        arr = pa.StringArray.from_buffers(
            n, pa.py_buffer(offsets.tobytes()), pa.py_buffer(flat.tobytes()))
        if leaf.valid is not None:
            import pyarrow.compute as pc

            arr = pc.if_else(pa.array(leaf.valid[:n]), arr,
                             pa.scalar(None, pa.string()))
        return arr
    if isinstance(leaf, C.NullLeaf):
        return pa.nulls(n)
    return None


def _part_path(path: str, idx: int, multi: bool,
               part_name_generator=None) -> str:
    """Output path for part `idx` (reference: defaultPartNameGenerator /
    user part_name_generator, dataset.py tocsv). Multi-part output ALWAYS
    treats `path` as a directory — no filename heuristics that could
    disagree with the single-file resolver. A raising generator propagates:
    the reference documents it "should not raise", and silently mixing
    naming schemes would hide the user's bug."""
    if not multi:
        return _resolve_path(path)
    name = f"part{idx}.csv" if part_name_generator is None \
        else str(part_name_generator(idx))
    if VirtualFileSystem._scheme(path) == "file":
        root = VirtualFileSystem._strip(path)
        os.makedirs(root or ".", exist_ok=True)
        return os.path.join(root, name)
    return path.rstrip("/") + "/" + name


def write_partitions_csv(path: str, partitions: list,
                         columns: Optional[Sequence[str]] = None,
                         delimiter: str = ",", backend=None,
                         part_size: int = 0, num_rows: int = -1,
                         num_parts: int = 0, part_name_generator=None,
                         null_value: Optional[str] = None,
                         header=True) -> None:
    """Stream partitions to one or more csv part files without
    materializing python rows (reference: FileOutputOperator splitting —
    num_parts splits evenly with the last part smallest, part_size rotates
    parts on a byte budget; dataset.py:500-509 signature parity)."""
    import io as _io

    import pyarrow as pa
    import pyarrow.csv as pacsv

    if isinstance(header, (list, tuple)):
        columns = list(header)
        header = True

    def header_bytes(cols) -> bytes:
        txt = _io.StringIO()
        csv.writer(txt, delimiter=delimiter,
                   lineterminator="\r\n").writerow(list(cols))
        return txt.getvalue().encode("utf-8")

    opts = pacsv.WriteOptions(include_header=False, delimiter=delimiter)

    parts = list(partitions)
    total = sum(p.num_rows for p in parts)
    if num_rows >= 0:
        total = min(total, num_rows)
    multi = num_parts > 0 or part_size > 0
    # rows per part: even split for num_parts (last part smallest);
    # part_size rotates on the running byte budget instead
    rows_per_part = -(-total // num_parts) if num_parts > 0 else None

    state = {"sink": None, "cm": None, "idx": 0, "rows": 0, "bytes": 0,
             "written": 0}

    def close_current():
        if state["cm"] is not None:
            state["cm"].__exit__(None, None, None)   # finalizes VFS uploads
            state["cm"] = state["sink"] = None

    def open_next(cols):
        close_current()
        p = _part_path(path, state["idx"], multi, part_name_generator)
        state["cm"] = VirtualFileSystem.open_write(p)
        state["sink"] = state["cm"].__enter__()
        state["idx"] += 1
        state["rows"] = 0
        state["bytes"] = 0
        if header and cols is not None:
            state["sink"].write(header_bytes(cols))

    def emit(payload: bytes, nrows: int, cols):
        if state["sink"] is None:
            open_next(cols)
        elif multi and state["rows"] > 0 and (
                (rows_per_part is not None and
                 state["rows"] + nrows > rows_per_part) or
                (rows_per_part is None and part_size > 0 and
                 state["bytes"] + len(payload) > part_size)):
            open_next(cols)
        state["sink"].write(payload)
        state["rows"] += nrows
        state["bytes"] += len(payload)
        state["written"] += nrows

    first_cols = columns
    try:
        for part in parts:
            if backend is not None:
                backend.mm.touch(part)
            if part.num_rows == 0:
                continue
            if num_rows >= 0 and state["written"] >= num_rows:
                break
            take = part.num_rows
            if num_rows >= 0:
                take = min(take, num_rows - state["written"])
            cols = first_cols or part.user_columns or \
                [f"_{i}" for i in range(len(part.schema.types))]
            # num_parts rotation points are GLOBAL row multiples: chunk
            # this partition exactly at them so a dataset spanning many
            # partitions still yields exactly num_parts files
            sizes = None
            if rows_per_part is not None:
                sizes, pos = [], state["written"]
                end = pos + take
                while pos < end:
                    nb = (pos // rows_per_part + 1) * rows_per_part
                    sizes.append(min(nb, end) - pos)
                    pos = min(nb, end)
            payloads = _part_payloads(part, take, delimiter, null_value,
                                      opts, sizes, part_size)
            for payload, nrows in payloads:
                emit(payload, nrows, cols)
        if state["sink"] is None:
            # empty result: still produce one (possibly header-only) file
            open_next(first_cols)
    finally:
        close_current()


def _chunk_sizes(part, take: int, sizes, part_size: int) -> list[int]:
    """Chunk plan for one partition: explicit global num_parts boundaries
    when given, else a byte-budget granularity for part_size, else one
    chunk."""
    if sizes is not None:
        return sizes
    if part_size and part_size > 0:
        # rotation granularity from the columnar size as a bytes/row proxy
        # (csv rendering inflates numerics but the order is right)
        nbytes = 0
        for leaf in part.leaves.values():
            arr = getattr(leaf, "bytes", None)
            if arr is None:
                arr = getattr(leaf, "data", None)
            if arr is not None:
                nbytes += arr.nbytes
        est = max(8, nbytes // max(1, part.num_rows))
        chunk = max(16, min(take, part_size // est))
        return [min(chunk, take - o) for o in range(0, take, chunk)]
    return [take]


def _part_payloads(part, take: int, delimiter: str,
                   null_value: Optional[str], opts,
                   sizes, part_size):
    """Yield (csv_bytes, n_rows) chunks for one partition, split exactly at
    part-rotation points so `emit` only ever rotates between chunks."""
    import io as _io

    import pyarrow as pa
    import pyarrow.compute as pc
    import pyarrow.csv as pacsv

    chunks = _chunk_sizes(part, take, sizes, part_size)
    arrays = None
    if not part.fallback:
        arrays = [_leaf_to_arrow(part, ci, ct)
                  for ci, ct in enumerate(part.schema.types)]
        if any(a is None for a in arrays):
            arrays = None
    if arrays is None:
        # boxed / nested partitions (rare): python formatting keeps row
        # order exact — same chunk plan as the columnar path
        rows = C.partition_to_pylist(part)[:take]
        off = 0
        for n in chunks:
            txt = _io.StringIO()
            w = csv.writer(txt, delimiter=delimiter, lineterminator="\r\n")
            for r in rows[off: off + n]:
                cells = list(r) if isinstance(r, tuple) else [r]
                if null_value is not None:
                    cells = [null_value if c is None else c for c in cells]
                w.writerow(cells)
            yield txt.getvalue().encode("utf-8"), n
            off += n
        return
    if take < part.num_rows:
        arrays = [a.slice(0, take) for a in arrays]
    if null_value is not None:
        arrays = [pc.fill_null(pc.cast(a, pa.string()), null_value)
                  if a.null_count else a for a in arrays]
    names = [str(i) for i in range(len(arrays))]
    off = 0
    for n in chunks:
        table = pa.table(dict(zip(names,
                                  [a.slice(off, n) for a in arrays])))
        buf = pa.BufferOutputStream()
        pacsv.write_csv(table, buf, opts)
        yield buf.getvalue().to_pybytes(), n
        off += n
