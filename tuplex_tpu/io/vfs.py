"""Virtual filesystem with URI-scheme dispatch.

Re-designs the reference's VirtualFileSystem (reference:
io/include/VirtualFileSystem.h — posix + S3 impls selected by URI prefix).
S3/GCS backends are gated on their SDKs being importable; local posix always
works. Zero-egress environments simply never exercise the remote schemes.
"""

from __future__ import annotations

import glob as _glob
import os
import shutil
from typing import Optional


class VirtualFileSystem:
    @staticmethod
    def _scheme(uri: str) -> str:
        if "://" in uri:
            return uri.split("://", 1)[0]
        return "file"

    @staticmethod
    def _strip(uri: str) -> str:
        return uri.split("://", 1)[1] if "://" in uri else uri

    # ------------------------------------------------------------------
    @classmethod
    def ls(cls, pattern: str) -> list[str]:
        scheme = cls._scheme(pattern)
        if scheme == "file":
            p = cls._strip(pattern)
            if os.path.isdir(p):
                return sorted(os.path.join(p, f) for f in os.listdir(p))
            return sorted(_glob.glob(p))
        if scheme in ("s3", "gs"):
            return cls._remote(scheme).ls(pattern)
        raise ValueError(f"unsupported scheme {scheme!r}")

    @classmethod
    def glob_input(cls, pattern: str) -> list[str]:
        """Comma-separated patterns / dirs / globs -> file list (reference:
        FileInputOperator detectFiles)."""
        out: list[str] = []
        for pat in pattern.split(","):
            pat = pat.strip()
            if not pat:
                continue
            scheme = cls._scheme(pat)
            if scheme == "file":
                p = cls._strip(pat)
                if os.path.isdir(p):
                    out.extend(sorted(
                        os.path.join(p, f) for f in os.listdir(p)
                        if os.path.isfile(os.path.join(p, f))))
                elif os.path.isfile(p):
                    out.append(p)
                else:
                    out.extend(sorted(_glob.glob(p)))
            else:
                out.extend(cls._remote(scheme).ls(pat))
        return out

    @classmethod
    def cp(cls, src: str, dst: str) -> None:
        if cls._scheme(src) == "file" and cls._scheme(dst) == "file":
            shutil.copy(cls._strip(src), cls._strip(dst))
            return
        raise ValueError("remote cp not available in this environment")

    @classmethod
    def rm(cls, pattern: str) -> None:
        for p in cls.ls(pattern):
            if os.path.isdir(p):
                shutil.rmtree(p)
            else:
                os.remove(p)

    @classmethod
    def open_read(cls, uri: str, mode: str = "rb"):
        if cls._scheme(uri) == "file":
            return open(cls._strip(uri), mode)
        raise ValueError(f"unsupported scheme for open: {uri}")

    @classmethod
    def file_size(cls, uri: str) -> int:
        if cls._scheme(uri) == "file":
            return os.path.getsize(cls._strip(uri))
        raise ValueError(f"unsupported scheme: {uri}")

    @staticmethod
    def _remote(scheme: str):
        raise ValueError(
            f"{scheme}:// requires a cloud SDK not present in this "
            f"environment (zero-egress); stage files locally instead")
