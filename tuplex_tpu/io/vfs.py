"""Virtual filesystem with URI-scheme dispatch.

Re-designs the reference's VirtualFileSystem (reference:
io/include/VirtualFileSystem.h + io/src/S3FileSystemImpl.cc — posix + S3
impls selected by URI prefix). Remote backends register per scheme:
S3 (boto3) and GCS (google-cloud-storage) construct lazily when their SDK
imports; tests (and zero-egress environments) can register any object that
implements the small backend protocol — see MemoryObjectStore.
"""

from __future__ import annotations


import glob as _glob
import io as _io
import os
import shutil
from typing import Optional


class VirtualFileSystem:
    _backends: dict[str, object] = {}

    @staticmethod
    def _scheme(uri: str) -> str:
        if "://" in uri:
            return uri.split("://", 1)[0]
        return "file"

    @staticmethod
    def is_dir_path(path: str) -> bool:
        """Whether `path` denotes a DIRECTORY target for writers (trailing
        slash, or an existing local directory) — the single definition the
        sinks and the sink-pushdown trigger share."""
        import os as _os

        if path.endswith("/"):
            return True
        if VirtualFileSystem._scheme(path) != "file":
            return False
        return _os.path.isdir(VirtualFileSystem._strip(path))

    @staticmethod
    def _strip(uri: str) -> str:
        return uri.split("://", 1)[1] if "://" in uri else uri

    # -- backend registry ----------------------------------------------------
    @classmethod
    def register_backend(cls, scheme: str, backend) -> None:
        """Install (or override) the backend for a URI scheme. Backends
        implement: ls(pattern)->list[str], open_read(uri)->file-like,
        open_write(uri)->file-like, file_size(uri)->int, rm(uri)->None."""
        cls._backends[scheme] = backend

    @classmethod
    def _remote(cls, scheme: str):
        b = cls._backends.get(scheme)
        if b is None:
            b = _default_backend(scheme) or _env_backend(scheme)
            if b is None:
                raise ValueError(
                    f"{scheme}:// needs its cloud SDK (boto3 / "
                    f"google-cloud-storage), which is not importable here; "
                    f"register_backend() a custom store or stage files "
                    f"locally")
            cls._backends[scheme] = b
        return b

    # ------------------------------------------------------------------
    @classmethod
    def ls(cls, pattern: str) -> list[str]:
        scheme = cls._scheme(pattern)
        if scheme == "file":
            p = cls._strip(pattern)
            if os.path.isdir(p):
                return sorted(os.path.join(p, f) for f in os.listdir(p))
            return sorted(_glob.glob(p))
        return cls._remote(scheme).ls(pattern)

    @classmethod
    def glob_input(cls, pattern: str) -> list[str]:
        """Comma-separated patterns / dirs / globs -> file list (reference:
        FileInputOperator detectFiles)."""
        out: list[str] = []
        for pat in pattern.split(","):
            pat = pat.strip()
            if not pat:
                continue
            scheme = cls._scheme(pat)
            if scheme == "file":
                p = cls._strip(pat)
                if os.path.isdir(p):
                    out.extend(sorted(
                        os.path.join(p, f) for f in os.listdir(p)
                        if os.path.isfile(os.path.join(p, f))))
                elif os.path.isfile(p):
                    out.append(p)
                else:
                    out.extend(sorted(_glob.glob(p)))
            else:
                out.extend(cls._remote(scheme).ls(pat))
        return out

    @classmethod
    def cp(cls, src: str, dst: str) -> None:
        if cls._scheme(src) == "file" and cls._scheme(dst) == "file":
            shutil.copy(cls._strip(src), cls._strip(dst))
            return
        with cls.open_read(src) as r, cls.open_write(dst) as w:
            shutil.copyfileobj(r, w)

    @classmethod
    def rm(cls, pattern: str) -> None:
        scheme = cls._scheme(pattern)
        if scheme == "file":
            for p in cls.ls(pattern):
                if os.path.isdir(p):
                    shutil.rmtree(p)
                else:
                    os.remove(p)
            return
        be = cls._remote(scheme)
        for uri in be.ls(pattern):
            be.rm(uri)

    @classmethod
    def open_read(cls, uri: str, mode: str = "rb"):
        scheme = cls._scheme(uri)
        if scheme == "file":
            return open(cls._strip(uri), mode)
        return cls._remote(scheme).open_read(uri)

    @classmethod
    def open_write(cls, uri: str, mode: str = "wb"):
        scheme = cls._scheme(uri)
        if scheme == "file":
            parent = os.path.dirname(cls._strip(uri))
            if parent:
                os.makedirs(parent, exist_ok=True)
            return open(cls._strip(uri), mode)
        return cls._remote(scheme).open_write(uri)

    @classmethod
    def file_size(cls, uri: str) -> int:
        scheme = cls._scheme(uri)
        if scheme == "file":
            return os.path.getsize(cls._strip(uri))
        return cls._remote(scheme).file_size(uri)


# ---------------------------------------------------------------------------
# remote backends
# ---------------------------------------------------------------------------

def _split_bucket_key(uri: str) -> tuple[str, str, str]:
    scheme, rest = uri.split("://", 1)
    bucket, _, key = rest.partition("/")
    return scheme, bucket, key


class S3Backend:
    """boto3-backed object store (reference: io/src/S3FileSystemImpl.cc).
    Constructed only when boto3 imports; network behavior is the SDK's."""

    def __init__(self, client=None):
        if client is None:
            import boto3  # gated: raises ImportError without the SDK

            client = boto3.client("s3")
        self.client = client

    def ls(self, pattern: str) -> list[str]:
        scheme, bucket, key = _split_bucket_key(pattern)
        prefix = key.split("*", 1)[0].split("?", 1)[0]
        out = []
        paginator = self.client.get_paginator("list_objects_v2")
        for page in paginator.paginate(Bucket=bucket, Prefix=prefix):
            for obj in page.get("Contents", []):
                uri = f"{scheme}://{bucket}/{obj['Key']}"
                if _uri_matches(uri, pattern):
                    out.append(uri)
        return sorted(out)

    def open_read(self, uri: str):
        _, bucket, key = _split_bucket_key(uri)
        body = self.client.get_object(Bucket=bucket, Key=key)["Body"]
        return _io.BytesIO(body.read())

    def open_write(self, uri: str):
        _, bucket, key = _split_bucket_key(uri)
        return _ObjectWriteBuffer(
            lambda data: self.client.put_object(Bucket=bucket, Key=key,
                                                Body=data))

    def file_size(self, uri: str) -> int:
        _, bucket, key = _split_bucket_key(uri)
        return self.client.head_object(Bucket=bucket,
                                       Key=key)["ContentLength"]

    def rm(self, uri: str) -> None:
        _, bucket, key = _split_bucket_key(uri)
        self.client.delete_object(Bucket=bucket, Key=key)


class GCSBackend:
    """google-cloud-storage-backed object store."""

    def __init__(self, client=None):
        if client is None:
            from google.cloud import storage  # gated on the SDK

            client = storage.Client()
        self.client = client

    def _blob(self, uri: str):
        _, bucket, key = _split_bucket_key(uri)
        return self.client.bucket(bucket).blob(key)

    def ls(self, pattern: str) -> list[str]:
        scheme, bucket, key = _split_bucket_key(pattern)
        prefix = key.split("*", 1)[0].split("?", 1)[0]
        out = []
        for blob in self.client.list_blobs(bucket, prefix=prefix):
            uri = f"{scheme}://{bucket}/{blob.name}"
            if _uri_matches(uri, pattern):
                out.append(uri)
        return sorted(out)

    def open_read(self, uri: str):
        return _io.BytesIO(self._blob(uri).download_as_bytes())

    def open_write(self, uri: str):
        blob = self._blob(uri)
        return _ObjectWriteBuffer(lambda data: blob.upload_from_string(data))

    def file_size(self, uri: str) -> int:
        blob = self._blob(uri)
        blob.reload()
        return int(blob.size)

    def rm(self, uri: str) -> None:
        self._blob(uri).delete()


class MemoryObjectStore:
    """In-memory fake object store implementing the backend protocol — the
    test double for the remote schemes (reference tests their S3 impl only
    against real AWS; a local fake keeps this path CI-testable)."""

    def __init__(self):
        self.objects: dict[str, bytes] = {}

    def put(self, uri: str, data: bytes) -> None:
        self.objects[uri] = data

    def ls(self, pattern: str) -> list[str]:
        if "*" not in pattern and "?" not in pattern:
            if pattern in self.objects:
                return [pattern]
            prefix = pattern.rstrip("/") + "/"
            return sorted(u for u in self.objects if u.startswith(prefix))
        return sorted(u for u in self.objects if _uri_matches(u, pattern))

    def open_read(self, uri: str):
        if uri not in self.objects:
            raise FileNotFoundError(uri)
        return _io.BytesIO(self.objects[uri])

    def open_write(self, uri: str):
        return _ObjectWriteBuffer(lambda data: self.put(uri, data))

    def file_size(self, uri: str) -> int:
        return len(self.objects[uri])

    def rm(self, uri: str) -> None:
        self.objects.pop(uri, None)


class _ObjectWriteBuffer(_io.BytesIO):
    """Buffers writes and uploads the whole object on close (object stores
    have no append)."""

    def __init__(self, upload):
        super().__init__()
        self._upload = upload

    def close(self):
        if not self.closed:
            self._upload(self.getvalue())
        super().close()


def _uri_matches(uri: str, pattern: str) -> bool:
    if "*" not in pattern and "?" not in pattern:
        return uri == pattern or uri.startswith(pattern.rstrip("/") + "/")
    # glob semantics matching the local path: '*'/'?' do NOT cross '/'
    # ('**' does) — fnmatch's '*' would silently pull in nested keys
    import re as _re

    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == "*":
            if pattern[i:i + 2] == "**":
                out.append(".*")
                i += 2
                continue
            out.append("[^/]*")
        elif c == "?":
            out.append("[^/]")
        else:
            out.append(_re.escape(c))
        i += 1
    return _re.fullmatch("".join(out), uri) is not None


def is_remote_uri(path: str) -> bool:
    """True for scheme-dispatched (object-store) paths; file:// is local."""
    return "://" in path and not path.startswith("file://")


def join_uri(base: str, name: str) -> str:
    """Path join that keeps remote URI schemes intact."""
    if "://" in base:
        return f"{base.rstrip('/')}/{name}"
    return os.path.join(base, name)


def _default_backend(scheme: str):
    try:
        if scheme == "s3":
            return S3Backend()
        if scheme == "gs":
            return GCSBackend()
    except ImportError:
        return None
    return None


def _env_backend(scheme: str):
    """Backend factory from TUPLEX_VFS_BACKENDS="scheme=module:fn,..." —
    how detached worker PROCESSES (serverless backend) install custom
    object stores: register_backend() is process-local, but workers
    inherit the environment (reference analog: the Lambda handler gets
    its S3 client from its runtime environment, lambda_main.cc)."""
    spec = os.environ.get("TUPLEX_VFS_BACKENDS", "")
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry or "=" not in entry:
            continue
        sch, target = entry.split("=", 1)
        if sch.strip() != scheme or ":" not in target:
            continue
        mod_name, fn_name = target.rsplit(":", 1)
        import importlib

        try:
            return getattr(importlib.import_module(mod_name), fn_name)()
        except Exception as e:
            # a CONFIGURED backend that fails to build must fail loudly —
            # falling through to the "needs its cloud SDK" error buries
            # the real cause (review r4)
            raise ValueError(
                f"TUPLEX_VFS_BACKENDS entry {entry!r} failed to build: "
                f"{type(e).__name__}: {e}") from e
    return None


def files_fingerprint(files, extra=None) -> Optional[str]:
    """Cheap content identity for a list of LOCAL files: (path, mtime_ns,
    size) per file, hashed with any extra context. Returns None when any
    file can't be stat'd locally (remote URIs: no cheap stable identity),
    which disables cross-job plan memoization for that source."""
    import hashlib

    h = hashlib.sha256()
    try:
        for path in files:
            if "://" in str(path):
                return None
            st = os.stat(path)
            h.update(f"{path}|{st.st_mtime_ns}|{st.st_size};".encode())
    except OSError:
        return None
    if extra is not None:
        h.update(repr(extra).encode())
    return h.hexdigest()[:24]
