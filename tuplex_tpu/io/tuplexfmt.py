"""Native binary partition format (reference: FileFormat::OUTFMT_TUPLEX,
LocalBackend.cc:1597 — the engine's own output format, loadable without
re-sniffing or re-decoding).

Layout: a DIRECTORY holding one `part-NNNNN.npz` per partition (the spill
module's leaf encoding — zero boxing on write or read) plus a pickled
manifest carrying the schema and boxed fallback rows. Like the reference's
format this is an INTERNAL interchange format: load only files your own
jobs wrote (the manifest is a pickle)."""

from __future__ import annotations

import os
import pickle

import numpy as np

from ..core import typesys as T
from ..core.errors import TuplexException
from ..core.row import Row
from ..plan import logical as L
from ..runtime import columns as C
from ..runtime.spill import SpilledPartition, _leaves_to_npz_dict

_MANIFEST = "tuplex_manifest.pkl"


def _is_not_found(exc: Exception) -> bool:
    """True for missing-object errors from any store (local, S3, GCS).
    SDK classes are matched structurally so neither SDK is required:
    botocore ClientError carries an error Code, google-cloud raises a
    class literally named NotFound."""
    if isinstance(exc, FileNotFoundError):
        return True
    code = ""
    try:
        code = str(exc.response["Error"]["Code"])  # type: ignore[attr-defined]
    except Exception:
        pass
    if code in ("404", "NoSuchKey", "NoSuchBucket"):
        return True
    return type(exc).__name__ in ("NotFound", "BlobNotFoundError")


from .vfs import is_remote_uri as _is_remote  # noqa: E402
from .vfs import join_uri as _join  # noqa: E402


def write_partitions_tuplex(path: str, partitions: list,
                            backend=None) -> None:
    """Atomic overwrite (local paths): part files carry a fresh run nonce
    so an existing manifest stays consistent until the new manifest lands
    via os.replace (the commit point); stale part files are swept only
    afterwards. Remote schemes (s3://, the serverless scratch staging —
    reference: S3 upload, AWSLambdaBackend.cc:306-330) go through the VFS
    backend; object stores have no rename, so the manifest PUT is the
    commit point there (same last-writer-wins semantics as the
    reference's S3 output)."""
    import uuid

    from .vfs import VirtualFileSystem as VFS

    remote = _is_remote(path)
    if not remote:
        os.makedirs(path, exist_ok=True)
    nonce = uuid.uuid4().hex[:8]
    manifest: list[dict] = []
    for i, part in enumerate(partitions):
        if backend is not None:
            backend.mm.touch(part)
        fname = f"part-{nonce}-{i:05d}.npz"
        arrays = _leaves_to_npz_dict(part)
        obj_leaves = {p: leaf.values for p, leaf in part.leaves.items()
                      if isinstance(leaf, C.ObjectLeaf)}
        if remote:
            with VFS.open_write(_join(path, fname)) as fp:
                np.savez(fp, **arrays)
        else:
            np.savez(_join(path, fname), **arrays)
        manifest.append({
            "file": fname,
            "schema": part.schema,
            "num_rows": part.num_rows,
            "start_index": part.start_index,
            "normal_mask": part.normal_mask,
            "fallback": dict(part.fallback),
            "obj_leaves": obj_leaves,
        })
    if remote:
        with VFS.open_write(_join(path, _MANIFEST)) as fp:
            pickle.dump(manifest, fp)
        keep = {e["file"] for e in manifest} | {_MANIFEST}
        for uri in VFS.ls(_join(path, "part-*")):
            if uri.rsplit("/", 1)[-1] not in keep:
                VFS.rm(uri)
        return
    tmp = os.path.join(path, f".{_MANIFEST}.{nonce}")
    with open(tmp, "wb") as fp:
        pickle.dump(manifest, fp)
    os.replace(tmp, os.path.join(path, _MANIFEST))
    # single-writer semantics (like the reference's output formats):
    # concurrent writers to one dataset directory are unsupported. Readers
    # opened BEFORE an overwrite raise a clean TuplexException on next read.
    keep = {e["file"] for e in manifest} | {_MANIFEST}
    for f in os.listdir(path):
        stale_part = f.startswith("part-")
        stale_tmp = f.startswith("." + _MANIFEST)   # interrupted writes
        if f not in keep and (stale_part or stale_tmp):
            try:
                os.unlink(os.path.join(path, f))
            except OSError:
                pass


class TuplexFileSourceOperator(L.LogicalOperator):
    """Source over a directory written by write_partitions_tuplex: columnar
    leaves map straight back into partitions — no sniffing, no decode stage
    (reference: cached OUTFMT_TUPLEX partitions reload without parsing)."""

    def __init__(self, options, path: str):
        super().__init__([])
        self.path = path
        if _is_remote(path):
            from .vfs import VirtualFileSystem as VFS

            try:
                with VFS.open_read(_join(path, _MANIFEST)) as fp:
                    self.manifest = pickle.load(fp)
            except Exception as e:
                raise TuplexException(
                    f"not a readable tuplex dataset at {path!r}: "
                    f"{type(e).__name__}: {e}") from e
        else:
            with open(os.path.join(path, _MANIFEST), "rb") as fp:
                self.manifest = pickle.load(fp)
        if not self.manifest:
            raise TuplexException(f"empty tuplex dataset at {path!r}")
        self._schema = self.manifest[0]["schema"]
        self._sample: "list[Row] | None" = None

    def schema(self) -> T.RowType:
        return self._schema

    def sample(self) -> list[Row]:
        if self._sample is not None:
            return list(self._sample)
        part = self._load([self.manifest[0]])[0]
        k = min(256, part.num_rows)
        # slice BEFORE boxing: large partitions must not pay full-partition
        # python conversion for a 256-row sample
        idx = np.arange(k, dtype=np.int64)
        sub = C.gather_partition(part, idx, idx, k)
        sub.normal_mask = None if part.normal_mask is None \
            else part.normal_mask[:k]
        sub.fallback = {i: v for i, v in part.fallback.items() if i < k}
        cols = C.user_columns(self._schema)
        self._sample = [Row.from_value(v, cols)
                        for v in C.partition_to_pylist(sub)]
        return list(self._sample)

    def _load(self, entries) -> list[C.Partition]:
        from ..runtime.spill import load_leaves_npz

        remote = _is_remote(self.path)
        parts = []
        for e in entries:
            try:
                if remote:
                    from .vfs import VirtualFileSystem as VFS

                    with VFS.open_read(_join(self.path, e["file"])) as fp:
                        leaves = load_leaves_npz(fp)
                else:
                    leaves = load_leaves_npz(
                        os.path.join(self.path, e["file"]))
            except Exception as exc:
                # only MISSING-object errors mean the dataset was
                # overwritten under us; transient network/auth failures
                # from remote SDKs must surface as themselves
                if not _is_not_found(exc):
                    raise
                raise TuplexException(
                    f"tuplex dataset at {self.path!r} was overwritten "
                    f"after this reader opened it (or a part object is "
                    f"missing: {type(exc).__name__}); reopen with "
                    f"tuplexfile()") from exc
            leaves.update({p: C.ObjectLeaf(v)
                           for p, v in e["obj_leaves"].items()})
            parts.append(C.Partition(
                schema=e["schema"], num_rows=e["num_rows"],
                leaves=leaves, normal_mask=e["normal_mask"],
                fallback=dict(e["fallback"]),
                start_index=e["start_index"]))
        return parts

    def load_partitions(self, context, projection=None) -> list[C.Partition]:
        return self._load(self.manifest)

    def iter_partitions(self, context, projection=None):
        for e in self.manifest:
            yield self._load([e])[0]


def make_tuplex_operator(options, path: str):
    if not _is_remote(path) and (
            not os.path.isdir(path) or not os.path.exists(
                os.path.join(path, _MANIFEST))):
        raise TuplexException(f"not a tuplex dataset directory: {path!r}")
    return TuplexFileSourceOperator(options, path)
