"""CSV ingestion: sniffing, Arrow-backed bulk reads, device-fused decoding.

Re-designs the reference's CSV stack (reference:
utils/src/CSVStatistic.cc — sample-based delimiter/header/type sniffing;
core/src/logical/FileInputOperator.cc:195-260 — normal-case vs general-case
row type; physical/JITCSVSourceTaskBuilder.cc + CSVParseRowGenerator.cc —
parsing fused INTO the compiled pipeline) for the TPU model:

  * sniffing: python-side over a 256KB sample (delimiter candidates scored by
    per-line count consistency, header detected by type mismatch, per-column
    normal-case type at tuplex.normalcaseThreshold)
  * bulk read: pyarrow.csv (Arrow C++, multithreaded) with ALL columns read
    as strings — structural parsing only, no type conversion on host
  * type decoding runs ON DEVICE inside the fused stage function
    (DecodeOperator → parse_i64/parse_f64 kernels + null-value matching);
    cells that fail to parse raise into the error lattice and re-run on the
    interpreter — the dual-mode CSV semantics of the reference, vectorized
"""

from __future__ import annotations

import csv as _pycsv
import io as _io
from typing import Any, Optional, Sequence

import numpy as np

from ..core import typesys as T
from ..core.errors import TuplexException
from ..core.row import Row
from ..plan import logical as L
from ..runtime import columns as C
from .vfs import VirtualFileSystem, files_fingerprint

DEFAULT_NULL_VALUES = ("",)
_DELIM_CANDIDATES = (",", ";", "|", "\t")


# ---------------------------------------------------------------------------
# sniffing (CSVStatistic semantics)
# ---------------------------------------------------------------------------

def sniff_delimiter(sample_text: str) -> str:
    lines = [ln for ln in sample_text.splitlines() if ln.strip()][:64]
    best, best_score = ",", -1.0
    for d in _DELIM_CANDIDATES:
        counts = []
        for ln in lines:
            try:
                row = next(_pycsv.reader([ln], delimiter=d))
                counts.append(len(row))
            except Exception:
                counts.append(1)
        if not counts:
            continue
        from collections import Counter

        mode, freq = Counter(counts).most_common(1)[0]
        if mode <= 1:
            score = 0.0
        else:
            score = freq / len(counts) * mode
        if score > best_score:
            best, best_score = d, score
    return best

def _cell_type(cell: str, null_values: Sequence[str]) -> T.Type:
    if cell in null_values:
        return T.NULL
    try:
        int(cell)
        return T.I64
    except ValueError:
        pass
    try:
        float(cell)
        return T.F64
    except ValueError:
        pass
    if cell.lower() in ("true", "false"):
        return T.BOOL
    return T.STR


def detect_header(rows: list[list[str]], null_values: Sequence[str]) -> bool:
    """First row is a header iff all its cells are non-numeric strings AND
    some body column has a different type (reference: CSVStatistic header
    heuristic)."""
    if len(rows) < 2:
        return False
    head = rows[0]
    if any(_cell_type(c, ()) is not T.STR or c == "" for c in head):
        return False
    body_types = []
    k = len(head)
    for ci in range(k):
        col = [r[ci] for r in rows[1:] if len(r) == k]
        ts = {_cell_type(c, null_values) for c in col} - {T.NULL}
        body_types.append(ts)
    # any column whose body is uniformly non-str => header
    if any(ts and T.STR not in ts for ts in body_types):
        return True
    # all-string file: header iff first row values never reappear
    flat = {c for r in rows[1:] for c in r}
    return not any(h in flat for h in head)


def infer_column_types(rows: list[list[str]], k: int,
                       null_values: Sequence[str], threshold: float,
                       ) -> tuple[list[T.Type], list[T.Type]]:
    """(normal_types, general_types) per column — the normal case speculates
    the majority type at the threshold; the general case is the supertype of
    every sampled cell (reference: FileInputOperator.cc:228-232 keeps BOTH
    row types; the general one feeds the compiled resolve path)."""
    types = []
    general_types = []
    for ci in range(k):
        cells = [r[ci] for r in rows if len(r) == k]
        vals: list[Any] = []
        for c in cells:
            ct = _cell_type(c, null_values)
            if ct is T.NULL:
                vals.append(None)
            elif ct is T.I64:
                vals.append(int(c))
            elif ct is T.F64:
                vals.append(float(c))
            elif ct is T.BOOL:
                vals.append(c.lower() == "true")
            else:
                vals.append(c)
        nc, gc, _ = T.normal_case_type(vals, threshold)
        if nc is T.UNKNOWN or nc is T.PYOBJECT:
            nc = T.STR
        if gc is T.UNKNOWN:
            gc = nc
        # any mix the supertype can't name as a primitive decodes as the raw
        # string — the cells ARE strings, downstream UDFs parse them
        gb = gc.without_option() if gc.is_optional() else gc
        if gb not in (T.I64, T.F64, T.BOOL, T.STR, T.NULL):
            gc = T.option(T.STR) if gc.is_optional() else T.STR
        types.append(nc)
        general_types.append(gc)
    return types, general_types


class CSVStatistic:
    """Sniffing result over a file sample."""

    def __init__(self, sample_bytes: bytes, options,
                 delimiter: Optional[str] = None,
                 header: Optional[bool] = None,
                 null_values: Optional[Sequence[str]] = None,
                 columns: Optional[Sequence[str]] = None,
                 type_hints: Optional[dict] = None,
                 quotechar: str = '"'):
        text = sample_bytes.decode("utf-8", errors="replace")
        # drop a possibly-truncated last line
        if not sample_bytes.endswith(b"\n") and "\n" in text:
            text = text[: text.rfind("\n")]
        self.null_values = tuple(null_values) if null_values is not None \
            else DEFAULT_NULL_VALUES
        self.delimiter = delimiter or sniff_delimiter(text)
        self.quotechar = quotechar or '"'
        rows = list(_pycsv.reader(_io.StringIO(text),
                                  delimiter=self.delimiter,
                                  quotechar=self.quotechar))
        rows = [r for r in rows if r]
        if not rows:
            raise TuplexException("empty CSV sample")
        self.has_header = detect_header(rows, self.null_values) \
            if header is None else header
        body = rows[1:] if self.has_header else rows
        from collections import Counter

        k = Counter(len(r) for r in body).most_common(1)[0][0] if body else \
            len(rows[0])
        self.num_columns = k
        if columns:
            self.columns = list(columns)
        elif self.has_header:
            self.columns = [c if c else f"_{i}"
                            for i, c in enumerate(rows[0])]
        else:
            self.columns = [f"_{i}" for i in range(k)]
        threshold = options.get_float("tuplex.normalcaseThreshold", 0.9)
        max_rows = options.get_int("tuplex.csv.maxDetectionRows", 1000)
        self.types, self.general_types = infer_column_types(
            body[:max_rows], k, self.null_values, threshold)
        if type_hints:
            for key, t in type_hints.items():
                idx = key if isinstance(key, int) else self.columns.index(key)
                self.types[idx] = t
                self.general_types[idx] = t   # a hint overrides speculation
        self.sample_rows = body[:max_rows]


# ---------------------------------------------------------------------------
# logical operators
# ---------------------------------------------------------------------------


def _host_sharded_gate(files: list, context) -> bool:
    """Common preconditions for per-host byte-range reads: real
    multi-process SPMD on the multihost backend, single-file source,
    option enabled."""
    if len(files) != 1 or not context.options_store.get_bool(
            "tuplex.tpu.hostShardedReads", True):
        return False
    from ..exec.multihost import MultiHostBackend

    if not isinstance(context.backend, MultiHostBackend):
        return False
    import jax

    nproc = jax.process_count()
    # host-block slot quantization assumes devices split evenly across
    # processes (hostblock_stage_fn pads each block to 8*ldev slots); an
    # uneven split would mis-assemble make_array_from_process_local_data,
    # so fall back to whole reads for odd topologies (3 devices / 2 hosts)
    if nproc <= 1 or context.backend.n_devices % nproc != 0:
        return False
    return True

class CSVSourceOperator(L.LogicalOperator):
    """Raw-cell CSV source: every column is Option[str] (missing cell = None).

    Typed decoding is a separate fused DecodeOperator so parsing runs on
    device (reference analog: CellSourceTaskBuilder feeding the codegen'd
    pipeline)."""

    def __init__(self, options, pattern: str, stat: CSVStatistic,
                 files: list[str]):
        super().__init__([])
        self.options = options
        self.pattern = pattern
        self.stat = stat
        self.files = files
        self._raw_schema = T.row_of(
            stat.columns, [T.option(T.STR)] * stat.num_columns)

    def schema(self) -> T.RowType:
        return self._raw_schema

    def source_key(self):
        # the stat OUTCOME (delimiter/header/columns/null values/speculated
        # types) captures every sniffing parameter incl. per-call overrides
        # and type hints — two calls that sniff identically may share
        stat = self.stat
        return files_fingerprint(
            self.files, extra=(
                self.pattern, stat.delimiter, stat.has_header,
                tuple(stat.columns), tuple(stat.null_values),
                tuple(t.name for t in stat.types),
                tuple(t.name for t in stat.general_types),
                len(stat.sample_rows)))

    def sample(self) -> list[Row]:
        k = self.stat.num_columns
        out = []
        for r in self.stat.sample_rows:
            cells: list = list(r[:k]) + [None] * max(0, k - len(r))
            out.append(Row(cells, self.stat.columns))
        return out

    # -- bulk read ----------------------------------------------------------
    def _host_sharded(self, context) -> bool:
        """Per-host byte-range CSV reads under REAL multi-process SPMD
        (reference splits CSV inputs by byte range the same way,
        inputSplitSize tasks). Newline alignment is exact only without
        quoted newlines; _load_host_sharded verifies quote-freeness over
        the WHOLE file (each host checks its own fragment, verdicts
        allgather) and falls back to whole reads otherwise."""
        return _host_sharded_gate(self.files, context)

    def load_partitions(self, context, projection=None) -> list[C.Partition]:
        if self._host_sharded(context):
            sharded = self._load_host_sharded(context, projection)
            if sharded is not None:
                return sharded
        parts: list[C.Partition] = []
        offset = 0
        for path in self.files:
            for p in self._read_file(context, path, offset, projection):
                parts.append(p)
                offset += p.num_rows
        return parts

    def _load_host_sharded(self, context, projection=None):
        """ONE host-block partition from this process's byte range of the
        file (parallel/hostio; executed by
        MultiHostBackend._execute_hostblock) — or None when the exact
        quote gate rejects the file (caller falls back to whole reads)."""
        import pyarrow as pa
        import pyarrow.csv as pacsv

        import jax

        from ..parallel.hostio import allgather_obj, read_bytes_range

        pid, nproc = jax.process_index(), jax.process_count()
        stat = self.stat
        frag = read_bytes_range(self.files[0], pid, nproc)
        # EXACT quote gate: the fragments cover every byte of the file, so
        # one allgathered verdict proves quote-freeness globally (a quote
        # anywhere could hide a quoted newline a byte-range split would
        # sever — potentially silently, if the severed halves still parse
        # with k cells). Quoted files re-read whole; rare and correct.
        qc = (getattr(stat, "quotechar", '"') or '"').encode()
        if any(allgather_obj(qc in frag)):
            return None
        has_header = stat.has_header and pid == 0
        bad_rows: list[tuple[int, str]] = []

        def on_invalid(row):
            bad_rows.append((row.number or 0, row.text or ""))
            return "skip"

        out_columns = list(projection) if projection else stat.columns
        raw_schema = T.row_of(out_columns,
                              [T.option(T.STR)] * len(out_columns))
        proj_idx = [stat.columns.index(c) for c in out_columns]
        max_w = context.options_store.get_int("tuplex.tpu.maxStrBytes",
                                              4096)
        if frag.strip():
            table = pacsv.read_csv(
                pa.BufferReader(frag),
                read_options=pacsv.ReadOptions(
                    use_threads=True, block_size=1 << 24,
                    column_names=stat.columns,
                    skip_rows=1 if has_header else 0,
                    autogenerate_column_names=False),
                parse_options=pacsv.ParseOptions(
                    delimiter=stat.delimiter,
                    quote_char=getattr(stat, "quotechar", '"'),
                    invalid_row_handler=on_invalid),
                convert_options=pacsv.ConvertOptions(
                    column_types={c: pa.string() for c in stat.columns},
                    include_columns=list(projection) if projection
                    else None,
                    strings_can_be_null=False))
        else:
            table = pa.table({c: pa.array([], pa.string())
                              for c in out_columns})
        if bad_rows:
            scanned = _scan_bad_records(
                self.files[0], stat,
                text=frag.decode("utf-8", errors="replace"),
                skip_header=has_header)
        else:
            scanned = []
        if bad_rows and len(scanned) == len(bad_rows):
            total = table.num_rows + len(scanned)
            part = next(_spliced_partitions(
                table, scanned, raw_schema, proj_idx, max_w,
                max(total, 1), 0))
        else:
            part = _table_to_partition(table, raw_schema, max_w, 0)
            if bad_rows:    # positions unrecoverable: trail them (rare)
                tail = _bad_rows_partition(bad_rows, stat, proj_idx,
                                           raw_schema, part.num_rows)
                vals = C.partition_to_pylist(part) +                     C.partition_to_pylist(tail)
                part = C.build_partition(vals, raw_schema, start_index=0)
        counts = allgather_obj(part.num_rows)
        part.start_index = sum(counts[:pid])
        part.host_block = {"pid": pid, "nproc": nproc, "counts": counts}
        return [part]

    def iter_partitions(self, context, projection=None):
        """STREAMING read: yield partitions as Arrow record batches arrive,
        so take(n) touches only the file prefix it consumes (reference:
        range tasks over inputSplitSize, LocalBackend.cc:552-611).

        Structurally-invalid rows are yielded as one trailing fallback
        partition per file (position splicing needs a whole-file scan, which
        streaming exists to avoid); the eager load_partitions path keeps
        exact merge-in-order for them."""
        import pyarrow as pa
        import pyarrow.csv as pacsv

        stat = self.stat
        max_w = context.options_store.get_int("tuplex.tpu.maxStrBytes", 4096)
        out_columns = list(projection) if projection else stat.columns
        raw_schema = T.row_of(out_columns,
                              [T.option(T.STR)] * len(out_columns))
        proj_idx = [stat.columns.index(c) for c in out_columns]
        split = context.options_store.get_size(
            "tuplex.inputSplitSize", 1 << 22)
        read_opts = pacsv.ReadOptions(
            use_threads=True,
            block_size=max(1 << 14, min(split, 1 << 26)),
            column_names=stat.columns,
            skip_rows=1 if stat.has_header else 0,
            autogenerate_column_names=False)
        conv_opts = pacsv.ConvertOptions(
            column_types={c: pa.string() for c in stat.columns},
            include_columns=list(projection) if projection else None,
            strings_can_be_null=False)
        offset = 0
        for path in self.files:
            bad_rows: list[tuple[int, str]] = []

            def on_invalid(row, _bad=bad_rows):
                _bad.append((row.number or 0, row.text or ""))
                return "skip"

            parse_opts = pacsv.ParseOptions(
                delimiter=stat.delimiter,
                quote_char=getattr(stat, "quotechar", '"'),
                invalid_row_handler=on_invalid)
            with pacsv.open_csv(_csv_input(path), read_options=read_opts,
                                parse_options=parse_opts,
                                convert_options=conv_opts) as reader:
                for batch in reader:
                    if batch.num_rows == 0:
                        continue
                    tbl = pa.Table.from_batches([batch])
                    p = _table_to_partition(tbl, raw_schema, max_w, offset)
                    offset += p.num_rows
                    yield p
            if bad_rows:
                p = _bad_rows_partition(bad_rows, stat, proj_idx, raw_schema,
                                        offset)
                offset += p.num_rows
                yield p

    def _read_file(self, context, path: str, base_index: int,
                   projection=None):
        import pyarrow as pa
        import pyarrow.csv as pacsv

        stat = self.stat
        k = stat.num_columns
        bad_rows: list[tuple[int, str]] = []

        def on_invalid(row):
            bad_rows.append((row.number or 0, row.text or ""))
            return "skip"

        # Always read under the USER-FACING column names (skipping the header
        # line instead of parsing it): with user-overridden `columns=`, the
        # file's header names differ from stat.columns, and keying
        # include_columns / column_types by the wrong namespace raised
        # ArrowKeyError / silently skipped the read-as-string coercion
        # (advisor finding, round 1).
        read_opts = pacsv.ReadOptions(
            use_threads=True,
            block_size=1 << 24,
            column_names=stat.columns,
            skip_rows=1 if stat.has_header else 0,
            autogenerate_column_names=False)
        parse_opts = pacsv.ParseOptions(
            delimiter=stat.delimiter,
            quote_char=getattr(stat, "quotechar", '"'),
            invalid_row_handler=on_invalid)
        conv_opts = pacsv.ConvertOptions(
            column_types={c: pa.string() for c in stat.columns},
            include_columns=list(projection) if projection else None,
            strings_can_be_null=False)
        out_columns = list(projection) if projection else stat.columns
        raw_schema = T.row_of(out_columns,
                              [T.option(T.STR)] * len(out_columns))
        table = pacsv.read_csv(_csv_input(path), read_options=read_opts,
                               parse_options=parse_opts,
                               convert_options=conv_opts)

        max_w = context.options_store.get_int("tuplex.tpu.maxStrBytes", 4096)
        rows_per_part = _csv_rows_per_partition(context, table)
        n = table.num_rows
        proj_idx = [stat.columns.index(c) for c in out_columns]
        if bad_rows:
            # Arrow's InvalidRow.number is None in this version, so recover
            # each bad row's original position with one lenient python-csv
            # scan (dirty path only) and splice it back at its slot as a
            # boxed fallback row — keeps merge-in-order exact for malformed
            # rows like the reference (advisor finding, round 1).
            scanned = _scan_bad_records(path, stat)
            if len(scanned) == len(bad_rows):
                yield from _spliced_partitions(
                    table, scanned, raw_schema, proj_idx, max_w,
                    rows_per_part, base_index)
                return
        start = 0
        for m in _chunk_sizes(n, rows_per_part):
            chunk = table.slice(start, m)
            yield _table_to_partition(chunk, raw_schema, max_w,
                                      base_index + start)
            start += m
        # position recovery failed (python csv disagreed with Arrow about
        # which rows are malformed): append bad rows as one trailing
        # partition — output order for them diverges from the reference
        if bad_rows:
            yield _bad_rows_partition(bad_rows, stat, proj_idx, raw_schema,
                                      base_index + n)


def _bad_rows_partition(bad_rows: list, stat: "CSVStatistic",
                        proj_idx: list, raw_schema: T.RowType,
                        start_index: int) -> C.Partition:
    """Trailing partition of leniently re-parsed structurally-bad rows
    (shared by the eager fallback and streaming paths)."""
    vals = []
    for _, text in bad_rows:
        try:
            cells = next(_pycsv.reader(
                [text], delimiter=stat.delimiter,
                quotechar=getattr(stat, "quotechar", '"')))
        except Exception:
            cells = [text]
        vals.append(tuple(cells[i] if i < len(cells) else None
                          for i in proj_idx))
    return C.build_partition(vals, raw_schema, start_index=start_index)


def _scan_bad_records(path: str, stat: "CSVStatistic", text=None,
                      skip_header=None) -> list[tuple[int, list]]:
    """[(data-row ordinal, cells)] for records whose cell count != k —
    python-csv replica of Arrow's invalid-row criterion, used to recover the
    original positions Arrow doesn't report. Ordinals count ALL non-empty
    data records (good + bad) in file order, excluding the header.
    `text` scans a fragment instead of the file (host-sharded reads)."""
    k = stat.num_columns
    out: list[tuple[int, list]] = []
    if text is None:
        with VirtualFileSystem.open_read(path, "rb") as fp:
            text = fp.read().decode("utf-8", errors="replace")
    ordinal = 0
    skip_header = stat.has_header if skip_header is None else skip_header
    for rec in _pycsv.reader(_io.StringIO(text), delimiter=stat.delimiter,
                             quotechar=getattr(stat, "quotechar", '"')):
        if not rec:
            continue  # blank line: Arrow skips it too
        if skip_header:
            skip_header = False
            continue
        if len(rec) != k:
            out.append((ordinal, rec))
        ordinal += 1
    return out


def _spliced_partitions(table, scanned: list, raw_schema: T.RowType,
                        proj_idx: list[int], max_w: int, rows_per_part: int,
                        base_index: int):
    """Partitions over the ORIGINAL row-ordinal space: surviving Arrow rows
    keep their true slots, structurally-bad rows occupy theirs as boxed
    fallback slots (normal_mask False -> interpreter path)."""
    n = table.num_rows
    nb = len(scanned)
    bad_ord = np.asarray([o for o, _ in scanned], dtype=np.int64)
    boxed = [tuple(cells[i] if i < len(cells) else None for i in proj_idx)
             for _, cells in scanned]
    total = n + nb
    # original ordinal of the j-th surviving row: j + |{i : bad_ord[i]-i <= j}|
    surv = np.arange(n, dtype=np.int64) + np.searchsorted(
        bad_ord - np.arange(nb), np.arange(n), side="right")
    start = 0
    for m in _chunk_sizes(total, rows_per_part):
        j0, j1 = np.searchsorted(surv, [start, start + m])
        bi0, bi1 = np.searchsorted(bad_ord, [start, start + m])
        tp = _table_to_partition(table.slice(int(j0), int(j1 - j0)),
                                 raw_schema, max_w, base_index + start)
        if bi1 == bi0:
            yield tp  # no bad slots here: chunk is contiguous, j1-j0 == m
        else:
            pos = surv[j0:j1] - start
            gp = C.gather_partition(tp, pos, np.arange(j1 - j0), m)
            gp.start_index = base_index + start
            mask = np.ones(m, np.bool_)
            if tp.normal_mask is not None:
                mask[pos] = tp.normal_mask
            fb = {int(pos[i]): v for i, v in tp.fallback.items()}
            for o, bx in zip(bad_ord[bi0:bi1].tolist(), boxed[bi0:bi1]):
                mask[o - start] = False
                fb[o - start] = bx
            gp.normal_mask = mask
            gp.fallback = fb
            yield gp
        start += m


def _csv_input(path: str):
    """Path for local files, a file-like from the VFS for remote URIs —
    pyarrow.csv accepts both."""
    if VirtualFileSystem._scheme(path) == "file":
        return path
    return VirtualFileSystem.open_read(path)


def _csv_rows_per_partition(context, table) -> int:
    psize = context.options_store.get_size("tuplex.partitionSize", 32 << 20)
    per_row = max(16, table.nbytes // max(table.num_rows, 1) * 2)
    return max(256, int(psize // per_row))


def _chunk_sizes(total: int, cap: int) -> list[int]:
    """Balanced partition sizes: a near-cap total otherwise yields a tiny
    tail partition whose fixed dispatch cost (~0.2 s of pure per-call RPC
    tax on the tunneled TPU) dwarfs its rows. Absorb a small tail entirely
    (within +25% of cap), else ceil-divide into equal chunks."""
    if total <= 0:
        return []
    if total <= cap + cap // 4:
        return [total]
    import math

    k = math.ceil(total / cap)
    base, rem = divmod(total, k)
    return [base + (1 if i < rem else 0) for i in range(k)]


def _table_to_partition(table, schema: T.RowType, max_w: int,
                        start_index: int) -> C.Partition:
    """Arrow string columns -> fixed-width byte-matrix leaves, vectorized.

    Over-long cells (>{max_w}B) force their row to the boxed fallback path.
    """
    n = table.num_rows
    leaves: dict[str, C.Leaf] = {}
    too_long_rows = np.zeros(n, dtype=np.bool_)
    col_arrays = []
    for ci in range(table.num_columns):
        arr = table.column(ci).combine_chunks()
        col_arrays.append(arr)

    for ci, arr in enumerate(col_arrays):
        import pyarrow as pa

        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        arr = arr.cast(pa.large_string())
        valid = np.ones(n, dtype=np.bool_)
        if arr.null_count:
            valid = np.asarray(arr.is_valid())
        leaf, full_lens = C.arrow_string_to_leaf(arr, n, max_w, valid,
                                                 return_full_lens=True)
        # rows with over-long cells keep their slot but box via fallback
        too_long_rows |= full_lens > max_w
        leaves[str(ci)] = leaf

    part = C.Partition(schema=schema, num_rows=n, leaves=leaves,
                       start_index=start_index)
    if too_long_rows.any():
        mask = ~too_long_rows
        fallback = {}
        for i in np.nonzero(too_long_rows)[0].tolist():
            fallback[i] = tuple(
                (a[i].as_py() if a[i].is_valid else None)
                for a in col_arrays)
        part.normal_mask = mask
        part.fallback = fallback
    return part


class TextSourceOperator(L.LogicalOperator):
    """One row per line (reference: logical FileInputOperator text mode +
    physical/TextReader.cc)."""

    def __init__(self, options, pattern: str, files: list[str],
                 null_values: Optional[Sequence[str]] = None):
        super().__init__([])
        self.pattern = pattern
        self.files = files
        self.null_values = tuple(null_values) if null_values else ()
        self._schema = T.row_of(
            ["_0"], [T.option(T.STR) if self.null_values else T.STR])
        self._sample_lines: Optional[list[str]] = None

    def _null_map(self, lines):
        if not self.null_values:
            return lines
        nv = set(self.null_values)
        return [None if ln in nv else ln for ln in lines]

    def source_key(self):
        return files_fingerprint(self.files,
                                 extra=(self.pattern, self.null_values))

    def schema(self) -> T.RowType:
        return self._schema

    def sample(self) -> list[Row]:
        if self._sample_lines is None:
            lines: list[str] = []
            for f in self.files[:1]:
                with VirtualFileSystem.open_read(f, "rb") as fp:
                    chunk = fp.read(256 << 10).decode("utf-8",
                                                      errors="replace")
                lines = chunk.splitlines()[:1000]
            self._sample_lines = lines
        return [Row((ln,), None)
                for ln in self._null_map(self._sample_lines)]

    def _host_sharded(self, context) -> bool:
        """Per-host byte-range reads apply under REAL multi-process SPMD on
        a single-file source (reference analog: per-worker S3 input ranges,
        AWSLambdaBackend.cc:410-430). Option-gated; everything else reads
        whole files."""
        return _host_sharded_gate(self.files, context)

    def load_partitions(self, context, projection=None) -> list[C.Partition]:
        if self._host_sharded(context):
            import jax

            from ..parallel.hostio import allgather_obj, \
                read_text_lines_range

            pid, nproc = jax.process_index(), jax.process_count()
            lines = self._null_map(
                read_text_lines_range(self.files[0], pid, nproc))
            counts = allgather_obj(len(lines))
            part = C.build_partition(lines, self._schema,
                                     start_index=sum(counts[:pid]))
            part.host_block = {"pid": pid, "nproc": nproc,
                               "counts": counts}
            return [part]
        parts = []
        offset = 0
        for f in self.files:
            with VirtualFileSystem.open_read(f, "rb") as fp:
                text = fp.read().decode("utf-8", errors="replace")
            lines = self._null_map(text.splitlines())
            psize = context.options_store.get_size(
                "tuplex.partitionSize", 32 << 20)
            rows_pp = max(256, psize // 64)
            for s in range(0, len(lines), rows_pp):
                chunk = lines[s: s + rows_pp]
                parts.append(C.build_partition(chunk, self._schema,
                                               start_index=offset + s))
            offset += len(lines)
        return parts


# ---------------------------------------------------------------------------
# factory
# ---------------------------------------------------------------------------

_STAT_CACHE: dict = {}          # (file sig, sniff params) -> CSVStatistic
_STAT_CACHE_CAP = 64


def _file_sig(path: str):
    """Stat identity when cheaply stat-able; None => uncacheable."""
    return files_fingerprint([path])


def make_csv_operator(options, pattern: str, columns=None, header=None,
                      delimiter=None, type_hints=None, null_values=None,
                      quotechar: Optional[str] = None):
    if quotechar is None:
        quotechar = options.get_str("tuplex.csv.quotechar", '"') or '"'
    files = VirtualFileSystem.glob_input(pattern)
    if not files:
        raise TuplexException(f"no files match {pattern!r}")
    max_sample = options.get_size("tuplex.csv.maxDetectionMemory", 256 << 10)
    if null_values is None:
        null_values = DEFAULT_NULL_VALUES
    # sniffing an unchanged file with unchanged params is deterministic:
    # memoize so re-planned pipelines (repeat actions, benchmarks) skip the
    # sample read + type inference (reference re-runs CSVStatistic per plan)
    sig = _file_sig(files[0])
    skey = None
    if sig is not None:
        skey = (sig, max_sample, delimiter, header, quotechar,
                tuple(null_values),
                tuple(columns) if columns else None,
                tuple(sorted(type_hints.items())) if type_hints else None,
                options.get_float("tuplex.normalcaseThreshold", 0.9),
                options.get_int("tuplex.csv.maxDetectionRows", 1000))
        stat = _STAT_CACHE.get(skey)
        if stat is not None:
            src = CSVSourceOperator(options, pattern, stat, files)
            return L.DecodeOperator(src, _decoded_schema(stat),
                                    stat.null_values,
                                    general=T.row_of(stat.columns,
                                                     stat.general_types))
    with VirtualFileSystem.open_read(files[0], "rb") as fp:
        sample = fp.read(max_sample)
    stat = CSVStatistic(sample, options, delimiter=delimiter, header=header,
                        null_values=null_values, columns=columns,
                        type_hints=type_hints, quotechar=quotechar)
    if skey is not None:
        if len(_STAT_CACHE) >= _STAT_CACHE_CAP:
            _STAT_CACHE.pop(next(iter(_STAT_CACHE)))
        _STAT_CACHE[skey] = stat
    src = CSVSourceOperator(options, pattern, stat, files)
    return L.DecodeOperator(src, _decoded_schema(stat), stat.null_values,
                            general=T.row_of(stat.columns,
                                             stat.general_types))


def _decoded_schema(stat: CSVStatistic) -> T.RowType:
    return T.row_of(stat.columns, stat.types)


def make_text_operator(options, pattern: str, null_values=None):
    files = VirtualFileSystem.glob_input(pattern)
    if not files:
        raise TuplexException(f"no files match {pattern!r}")
    return TextSourceOperator(options, pattern, files,
                              null_values=null_values)
