"""ORC input/output via Arrow (reference: io/ ORC types + OrcReader;
dataset.toorc at python/tuplex/dataset.py:554).

ORC files carry types, so unlike CSV there is no sniff/decode stage: columns
convert straight into typed leaves (nulls become Option)."""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..core import typesys as T
from ..core.errors import TuplexException
from ..core.row import Row
from ..plan import logical as L
from ..runtime import columns as C
from .vfs import VirtualFileSystem, files_fingerprint


def _arrow_to_type(at) -> T.Type:
    import pyarrow as pa

    if pa.types.is_boolean(at):
        return T.BOOL
    if pa.types.is_integer(at):
        return T.I64
    if pa.types.is_floating(at):
        return T.F64
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        return T.STR
    return T.PYOBJECT


def table_to_partitions(table, max_w: int, rows_per_part: int,
                        start_index: int = 0) -> list[C.Partition]:
    """Typed Arrow table -> typed partitions (shared by ORC and future
    parquet/arrow sources)."""
    import pyarrow as pa

    cols = table.column_names
    types: list[T.Type] = []
    for f in table.schema:
        base = _arrow_to_type(f.type)
        col = table.column(f.name)
        types.append(T.option(base) if col.null_count > 0 and
                     base is not T.PYOBJECT else base)
    schema = T.row_of(cols, types)
    parts: list[C.Partition] = []
    n = table.num_rows
    start = 0
    while start < n or (n == 0 and not parts):
        m = min(rows_per_part, n - start) if n else 0
        chunk = table.slice(start, m)
        leaves: dict[str, C.Leaf] = {}
        for ci, name in enumerate(cols):
            arr = chunk.column(ci).combine_chunks()
            t = types[ci]
            base = t.without_option() if t.is_optional() else t
            valid = None
            if t.is_optional():
                valid = np.asarray(arr.is_valid())
            if base is T.STR:
                sarr = arr.cast(pa.large_string())
                leaves[str(ci)] = C.arrow_string_to_leaf(sarr, m, max_w,
                                                         valid)
            elif base in (T.I64, T.F64, T.BOOL):
                dtype = {T.I64: np.int64, T.F64: np.float64,
                         T.BOOL: np.bool_}[base]
                np_arr = np.asarray(
                    arr.fill_null(0) if valid is not None else arr
                ).astype(dtype)
                leaves[str(ci)] = C.NumericLeaf(np_arr, valid)
            else:
                leaves[str(ci)] = C.ObjectLeaf(arr.to_pylist())
        parts.append(C.Partition(schema=schema, num_rows=m, leaves=leaves,
                                 start_index=start_index + start))
        if n == 0:
            break
        start += m
    return parts


class ORCSourceOperator(L.LogicalOperator):
    def __init__(self, options, pattern: str, files: list[str],
                 columns: Optional[Sequence[str]] = None):
        super().__init__([])
        self.options = options
        self.pattern = pattern
        self.files = files
        self.user_cols = list(columns) if columns else None
        self._schema: Optional[T.RowType] = None
        self._sample: Optional[list[Row]] = None

    def source_key(self):
        return files_fingerprint(
            self.files, extra=(self.pattern, self.user_cols))

    def _load_meta(self):
        if self._schema is not None:
            return
        import pyarrow.orc as paorc

        f = paorc.ORCFile(self.files[0])
        # sample from the first stripe only — never materialize the file
        # just to plan (reference: sampling reads csv.maxDetectionMemory)
        try:
            table = f.read_stripe(0)
        except Exception:
            table = f.read()
        import pyarrow as pa

        if isinstance(table, pa.RecordBatch):
            table = pa.Table.from_batches([table])
        max_w = self.options.get_int("tuplex.tpu.maxStrBytes", 4096)
        parts = table_to_partitions(table.slice(0, min(256, table.num_rows)),
                                    max_w, 256)
        schema = parts[0].schema
        if self.user_cols:
            schema = T.row_of(self.user_cols, schema.types)
        self._schema = schema
        self._sample = []
        for p in parts[:1]:
            vals = C.partition_to_pylist(p)
            cols = C.user_columns(schema)
            for v in vals[:256]:
                self._sample.append(Row.from_value(v, cols))

    def schema(self) -> T.RowType:
        self._load_meta()
        return self._schema  # type: ignore[return-value]

    def sample(self) -> list[Row]:
        self._load_meta()
        return list(self._sample or [])

    def load_partitions(self, context, projection=None) -> list[C.Partition]:
        import pyarrow.orc as paorc

        max_w = context.options_store.get_int("tuplex.tpu.maxStrBytes", 4096)
        psize = context.options_store.get_size("tuplex.partitionSize",
                                               32 << 20)
        parts: list[C.Partition] = []
        offset = 0
        for path in self.files:
            table = paorc.ORCFile(path).read(
                columns=list(projection) if projection else None)
            per_row = max(16, table.nbytes // max(table.num_rows, 1) * 2)
            rows_pp = max(256, int(psize // per_row))
            new = table_to_partitions(table, max_w, rows_pp, offset)
            if self.user_cols:
                for p in new:
                    p.schema = T.row_of(self.user_cols, p.schema.types)
            parts.extend(new)
            offset += table.num_rows
        return parts


def make_orc_operator(options, pattern: str, columns=None):
    files = VirtualFileSystem.glob_input(pattern)
    if not files:
        raise TuplexException(f"no files match {pattern!r}")
    return ORCSourceOperator(options, pattern, files, columns)


def write_orc(path: str, rows: list, columns: Optional[Sequence[str]] = None
              ) -> None:
    import pyarrow as pa
    import pyarrow.orc as paorc

    import os

    if path.endswith("/") or os.path.isdir(path):
        os.makedirs(path, exist_ok=True)
        path = os.path.join(path, "part0.orc")
    if rows and isinstance(rows[0], tuple):
        cols = list(zip(*rows)) if rows else []
        names = list(columns) if columns and len(columns) == len(cols) else \
            [f"_{i}" for i in range(len(cols))]
        table = pa.table({n: list(c) for n, c in zip(names, cols)})
    else:
        name = columns[0] if columns else "_0"
        table = pa.table({name: rows})
    paorc.write_table(table, path)


def write_partitions_orc(path: str, partitions: list,
                         columns: Optional[Sequence[str]] = None,
                         backend=None, part_size: int = 0,
                         num_rows: int = -1, num_parts: int = 0,
                         part_name_generator=None) -> None:
    """Stream partitions to ORC from columnar buffers (no boxing for
    normal-case rows); boxed/nested partitions fall back to write_orc.
    Splitting parity with tocsv (reference: FileOutputOperator): num_parts
    slices the Arrow table at exact global row multiples (zero-copy),
    part_size rotates on a byte budget, num_rows limits output."""
    import os

    import pyarrow as pa
    import pyarrow.orc as paorc

    from ..runtime import columns as C
    from .csvsink import _leaf_to_arrow

    multi = num_parts > 0 or part_size > 0
    part_root = None
    if multi:
        part_root = path.rstrip("/")
        os.makedirs(part_root, exist_ok=True)
    elif path.endswith("/") or os.path.isdir(path):
        os.makedirs(path, exist_ok=True)
        path = os.path.join(path, "part0.orc")

    def part_file(idx: int) -> str:
        if not multi:
            return path
        name = f"part{idx}.orc" if part_name_generator is None \
            else str(part_name_generator(idx))
        return os.path.join(part_root, name)
    tables = []
    boxed_rows: list = []
    names = None
    for part in partitions:
        if backend is not None:
            backend.mm.touch(part)
        if part.num_rows == 0:
            continue
        cols = columns or part.user_columns or \
            [f"_{i}" for i in range(len(part.schema.types))]
        names = names or [str(c) for c in cols]
        arrays = None
        if not part.fallback:
            arrays = [_leaf_to_arrow(part, ci, ct)
                      for ci, ct in enumerate(part.schema.types)]
            if any(a is None for a in arrays):
                arrays = None
        if arrays is None:
            boxed_rows.extend(C.partition_to_pylist(part))
            continue
        tables.append(pa.table(dict(zip(names, arrays))))
    if boxed_rows or not tables:
        rows = []
        for part in partitions:
            if backend is not None:
                backend.mm.touch(part)   # earlier touches may have spilled it
            rows.extend(C.partition_to_pylist(part))
        if num_rows >= 0:
            rows = rows[:num_rows]
        if not multi:
            write_orc(path, rows, columns)
            return
        if num_parts > 0:
            n_parts = num_parts
        else:
            # estimate bytes/row from a sample of the boxed rows so the
            # byte budget is honored like the columnar paths
            probe = rows[:64]
            est = max(8, sum(len(str(r)) for r in probe)
                      // max(1, len(probe)))
            n_parts = max(1, -(-len(rows) * est // part_size))
        per = -(-max(len(rows), 1) // n_parts)
        widx = 0
        for i in range(n_parts):
            chunk = rows[i * per:(i + 1) * per]
            if not chunk:
                continue   # ORC cannot type an empty untyped table
            write_orc(part_file(widx), chunk, columns)
            widx += 1
        return
    table = pa.concat_tables(tables, promote_options="default")
    if num_rows >= 0:
        table = table.slice(0, num_rows)
    if not multi:
        paorc.write_table(table, path)
        return
    if num_parts > 0:
        per = -(-table.num_rows // num_parts)
        n_parts = num_parts
    else:
        per_bytes = max(1, table.nbytes // max(1, table.num_rows))
        per = max(16, part_size // per_bytes)
        n_parts = -(-table.num_rows // per)
    widx = 0
    for i in range(n_parts):
        chunk = table.slice(i * per, per)
        if chunk.num_rows == 0 and i > 0:
            continue   # short datasets: never emit trailing empty parts
        paorc.write_table(chunk, part_file(widx))
        widx += 1
