"""Host-sharded input reading + cross-host row exchange for multi-process
SPMD (reference analog: AWSLambdaBackend's workers each read their OWN S3
input range, AWSLambdaBackend.cc:410-430; exception rows travel back to
the driver as S3 parts :468-506 — here the ranges are per-HOST byte
splits of the input file and the exchange rides jax.distributed).
"""

from __future__ import annotations

import pickle
from typing import Any

import numpy as np


def read_bytes_range(path: str, pid: int, nproc: int) -> bytes:
    """The file bytes of every LINE whose starting byte falls in this
    host's range [size*pid/nproc, size*(pid+1)/nproc) — the classic
    newline-aligned byte split (reference: tuplex.inputSplitSize range
    tasks, LocalBackend.cc:552-611). Concatenation over hosts == the
    whole file; no byte is read twice."""
    from ..io.vfs import VirtualFileSystem

    size = VirtualFileSystem.file_size(path)
    start = size * pid // nproc
    end = size * (pid + 1) // nproc
    if start >= end:
        return b""
    with VirtualFileSystem.open_read(path, "rb") as fp:
        if start > 0:
            # a line STARTING at `start` belongs to us only if the previous
            # byte ends a line; otherwise the partial line belongs to the
            # previous host — skip through its newline
            fp.seek(start - 1)
            prev = fp.read(1)
            if prev != b"\n":
                fp.readline()
        else:
            fp.seek(0)
        chunks = []
        pos = fp.tell()
        while pos < end:
            line = fp.readline()
            if not line:
                break
            chunks.append(line)
            pos += len(line)
    return b"".join(chunks)


def read_text_lines_range(path: str, pid: int, nproc: int) -> list[str]:
    """read_bytes_range decoded and split: union over hosts == the
    whole-file readlines."""
    return read_bytes_range(path, pid, nproc).decode(
        "utf-8", errors="replace").splitlines()


def allgather_obj(obj: Any) -> list:
    """All-gather an arbitrary picklable object across processes (small
    control-plane payloads: counts, widths, resolved fallback rows). The
    bytes pad to the global max length and ride one process_allgather."""
    import jax

    if jax.process_count() == 1:
        return [obj]
    from jax.experimental import multihost_utils as mh

    data = np.frombuffer(pickle.dumps(obj), dtype=np.uint8)
    n = np.asarray(mh.process_allgather(np.int64(data.size)))
    cap = int(n.max())
    padded = np.zeros(cap, dtype=np.uint8)
    padded[: data.size] = data
    gathered = np.asarray(mh.process_allgather(padded))  # [P, cap]
    return [pickle.loads(gathered[p, : int(n[p])].tobytes())
            for p in range(gathered.shape[0])]
