"""Device-mesh execution of stage functions.

The TPU replacement for the reference's executor thread pool + (absent)
shuffle layer (reference: core/include/Executor.h WorkQueue;
SURVEY.md §2.10): partitions are row-sharded across a `jax.sharding.Mesh`
and the SAME fused stage function runs under pjit — row-wise pipelines
partition with zero collectives; aggregates/joins add psum/all_gather inside
the traced function (see parallel/collectives.py).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..runtime.jaxcfg import jax, jnp

DATA_AXIS = "data"


def make_mesh(n_devices: Optional[int] = None, axis: str = DATA_AXIS):
    from jax.sharding import Mesh

    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), (axis,))


def row_sharding(mesh, axis: str = DATA_AXIS):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(axis))


def shard_stage_fn(raw_fn, mesh, axis: str = DATA_AXIS):
    """jit a stage function with every leading-dim array row-sharded over the
    mesh. Row-wise stage bodies partition trivially (XLA inserts no
    collectives); reduction stages contain their own psums."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    shard = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())     # 0-d scalars (e.g. '#seed'): replicate

    def sharded(arrays):
        placed = {k: jax.device_put(v, shard if v.ndim else repl)
                  for k, v in arrays.items()}
        return raw_fn(placed)

    return jax.jit(sharded)


def pad_batch_for_mesh(arrays: dict, n_devices: int) -> dict:
    """Pad the leading dim to a multiple of the mesh size (XLA requires
    divisible sharding)."""
    b = arrays["#rowvalid"].shape[0]
    target = -(-b // n_devices) * n_devices
    if target == b:
        return arrays
    out = {}
    for k, v in arrays.items():
        if np.ndim(v) == 0:             # scalars (e.g. '#seed') replicate
            out[k] = v
            continue
        pad = [(0, target - b)] + [(0, 0)] * (v.ndim - 1)
        out[k] = np.pad(np.asarray(v), pad)
    return out
