"""Device-mesh execution of stage functions.

The TPU replacement for the reference's executor thread pool + (absent)
shuffle layer (reference: core/include/Executor.h WorkQueue;
SURVEY.md §2.10): partitions are row-sharded across a `jax.sharding.Mesh`
and the SAME fused stage function runs under pjit — row-wise pipelines
partition with zero collectives; aggregates/joins add psum/all_gather inside
the traced function (see parallel/collectives.py).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..runtime.jaxcfg import jax, jnp

DATA_AXIS = "data"


def make_mesh(n_devices: Optional[int] = None, axis: str = DATA_AXIS):
    from jax.sharding import Mesh

    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), (axis,))


def make_mesh_of(devices, axis: str = DATA_AXIS):
    """Mesh over an explicit (surviving) device list — the elastic
    partial-mesh rebuild path."""
    from jax.sharding import Mesh

    return Mesh(np.array(list(devices)), (axis,))


def row_sharding(mesh, axis: str = DATA_AXIS):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P(axis))


def shard_stage_fn(raw_fn, mesh, axis: str = DATA_AXIS):
    """jit a stage function with every leading-dim array row-sharded over the
    mesh. Row-wise stage bodies partition trivially (XLA inserts no
    collectives); reduction stages contain their own psums.

    Single-process (CI's virtual mesh, a single-host TPU slice): inputs
    device_put inside the jit. Multi-process (jax.distributed / DCN): each
    process stages ONLY ITS ROW RANGE of the batch
    (make_array_from_process_local_data — host-sharded staging, so H2D is
    1/P per host), and outputs are constrained to replicated so every
    process can materialize results host-side (np.asarray on a
    fully-replicated array is local)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    shard = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())     # 0-d scalars (e.g. '#seed'): replicate
    nproc = jax.process_count()

    if nproc == 1:
        def sharded(arrays):
            placed = {k: jax.device_put(v, shard if v.ndim else repl)
                      for k, v in arrays.items()}
            return raw_fn(placed)

        return jax.jit(sharded)

    def replicated_out(arrays):
        out = raw_fn(arrays)
        return jax.tree.map(
            lambda o: jax.lax.with_sharding_constraint(o, repl), out)

    jfn = jax.jit(replicated_out)
    pid = jax.process_index()

    def local_row_range(shape):
        """This process's contiguous row range under `shard` — derived from
        the sharding's own index map, NOT a uniform b/nproc split (devices
        need not spread evenly across processes, e.g. a 3-device mesh over
        2 hosts)."""
        los, his = [], []
        for d, idx in shard.devices_indices_map(shape).items():
            if d.process_index != pid:
                continue
            sl = idx[0]
            los.append(0 if sl.start is None else sl.start)
            his.append(shape[0] if sl.stop is None else sl.stop)
        if not los:
            return 0, 0     # no addressable mesh device on this process
        return min(los), max(his)

    def dispatch(arrays):
        placed = {}
        for k, v in arrays.items():
            if np.ndim(v) == 0:
                placed[k] = jax.device_put(v, repl)
                continue
            v = np.asarray(v)
            lo, hi = local_row_range(v.shape)
            placed[k] = jax.make_array_from_process_local_data(
                shard, np.ascontiguousarray(v[lo:hi]), v.shape)
        return jfn(placed)

    return dispatch


def hostblock_stage_fn(raw_fn, mesh, block_rows: int, axis: str = DATA_AXIS):
    """Multi-process dispatch where each process's LOCAL staged batch IS
    its shard: the global batch is [host0 block | host1 block | ...] with
    every block `block_rows` slots (tail-padded per host), assembled via
    make_array_from_process_local_data. block_rows must divide evenly
    over each process's devices. Outputs replicate (every host
    materializes the full result). Powers host-sharded reads
    (parallel/hostio): the data a process stages is only what IT read."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    shard = NamedSharding(mesh, P(axis))
    repl = NamedSharding(mesh, P())
    nproc = jax.process_count()

    def replicated_out(arrays):
        out = raw_fn(arrays)
        return jax.tree.map(
            lambda o: jax.lax.with_sharding_constraint(o, repl), out)

    jfn = jax.jit(replicated_out)

    def dispatch(local_arrays):
        placed = {}
        for k, v in local_arrays.items():
            if np.ndim(v) == 0:
                placed[k] = jax.device_put(v, repl)
                continue
            v = np.ascontiguousarray(np.asarray(v))
            assert v.shape[0] == block_rows, (k, v.shape, block_rows)
            gshape = (block_rows * nproc,) + v.shape[1:]
            placed[k] = jax.make_array_from_process_local_data(
                shard, v, gshape)
        return jfn(placed)

    return dispatch


def materialize_np(x) -> np.ndarray:
    """Host-materialize a mesh output. Single-process (or replicated /
    fully-addressable) arrays convert directly; under jax.distributed a
    row-sharded output spans other processes' devices, so gather it
    (process_allgather over DCN) first."""
    if jax.process_count() == 1:
        return np.asarray(x)
    if not hasattr(x, "sharding") or x.is_fully_replicated \
            or x.is_fully_addressable:
        return np.asarray(x)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def pad_batch_for_mesh(arrays: dict, n_devices: int) -> dict:
    """Pad the leading dim to a multiple of the mesh size (XLA requires
    divisible sharding)."""
    b = arrays["#rowvalid"].shape[0]
    target = -(-b // n_devices) * n_devices
    if target == b:
        return arrays
    out = {}
    for k, v in arrays.items():
        if np.ndim(v) == 0:             # scalars (e.g. '#seed') replicate
            out[k] = v
            continue
        pad = [(0, target - b)] + [(0, 0)] * (v.ndim - 1)
        out[k] = np.pad(np.asarray(v), pad)
    return out
