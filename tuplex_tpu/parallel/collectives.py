"""Mesh-parallel reductions: the ICI collective layer.

The reference combines per-thread aggregates on the driver
(reference: LocalBackend.cc:911-919 thread-local tables + 2219
createFinalHashmap). On a mesh the same associative-combine contract becomes
XLA collectives: every device folds its row shard, then `psum`/`pmin`/`pmax`
over the data axis combines partials ON THE INTERCONNECT — no host
round-trip (SURVEY §2.10 item 5: "segment-reduce on device + psum over ICI").
"""

from __future__ import annotations

from typing import Callable, Sequence

import numpy as np

from ..runtime.jaxcfg import jax, jnp
from .mesh import DATA_AXIS

_BIG = 1 << 62


def reduce_identity(reducer: str, is_float: bool):
    """Neutral element per reducer — single source of truth shared with the
    host-side merge (exec/aggexec)."""
    if reducer == "sum":
        return 0.0 if is_float else 0
    if reducer == "min":
        return float("inf") if is_float else _BIG
    return float("-inf") if is_float else -_BIG


def _ident_arr(reducer: str, dtype):
    return jnp.asarray(
        reduce_identity(reducer, jnp.issubdtype(dtype, jnp.floating)), dtype)


def _batch_specs(arrays_example, axis):
    """Row-shard every batched array; replicate 0-d scalars ('#seed')."""
    import numpy as np
    from jax.sharding import PartitionSpec as P

    return {k: P(axis) if np.ndim(v) else P()
            for k, v in arrays_example.items()}


def sharded_fold_fn(eval_exprs: Callable, reducers: Sequence[str], mesh,
                    arrays_example, axis: str = DATA_AXIS):
    """Build a jitted mesh-parallel fold (ONE compile per cache entry: the
    returned callable has stable identity — cache it per stage/shape).

    eval_exprs(arrays) -> (list_of_[B]_value_arrays, ok_mask[B]) — the
    emitter-traced fold expressions (same trace as the single-chip path).
    Each device reduces its row shard locally, then combines with psum/
    pmin/pmax over the mesh axis; the result replicates on every device.
    """
    from jax.sharding import PartitionSpec as P

    from ..runtime import tracing as TR
    from ..runtime.jaxcfg import shard_map_compat

    def local_fold(arrays):
        vals, ok = eval_exprs(arrays)
        outs = []
        for v, red in zip(vals, reducers):
            masked = jnp.where(ok, v, _ident_arr(red, v.dtype))
            if red == "sum":
                outs.append(jax.lax.psum(masked.sum(), axis))
            elif red == "min":
                outs.append(jax.lax.pmin(masked.min(), axis))
            else:
                outs.append(jax.lax.pmax(masked.max(), axis))
        # ok mask travels back row-sharded so the host can route err rows to
        # the interpreter fold
        return tuple(outs) + (ok,)

    with TR.span("collective:build-fold", "compile") as _sp:
        _sp.set("reducers", list(reducers))
        specs = _batch_specs(arrays_example, axis)
        fn = shard_map_compat(local_fold, mesh, (specs,),
                              tuple(P() for _ in reducers) + (P(axis),))
        return jax.jit(fn)


def sharded_segment_fold_fn(eval_exprs: Callable, reducers: Sequence[str],
                            nseg: int, mesh, arrays_example,
                            axis: str = DATA_AXIS):
    """Mesh-parallel aggregateByKey: per-device segment reduction over local
    rows, then psum/pmin/pmax of the [nseg] partial tables across the mesh
    (the shuffle-free grouped aggregate: key codes are global, partial
    tables combine on ICI)."""
    from jax.sharding import PartitionSpec as P

    from ..runtime.jaxcfg import shard_map_compat

    def local_fold(arrays, codes):
        vals, ok = eval_exprs(arrays)
        outs = []
        for v, red in zip(vals, reducers):
            masked = jnp.where(ok, v, _ident_arr(red, v.dtype))
            if red == "sum":
                seg = jax.ops.segment_sum(masked, codes,
                                          num_segments=nseg + 1)
                outs.append(jax.lax.psum(seg, axis))
            elif red == "min":
                seg = jax.ops.segment_min(masked, codes,
                                          num_segments=nseg + 1)
                outs.append(jax.lax.pmin(seg, axis))
            else:
                seg = jax.ops.segment_max(masked, codes,
                                          num_segments=nseg + 1)
                outs.append(jax.lax.pmax(seg, axis))
        # per-segment ok counts: the host skips creating groups whose rows
        # ALL failed (ghost-group guard), + the ok mask for err routing
        counts = jax.lax.psum(
            jax.ops.segment_sum(ok.astype(jnp.int32), codes,
                                num_segments=nseg + 1), axis)
        return tuple(outs) + (counts, ok)

    from ..runtime import tracing as TR

    with TR.span("collective:build-segment-fold", "compile") as _sp:
        _sp.set("reducers", list(reducers)).set("nseg", nseg)
        specs = _batch_specs(arrays_example, axis)
        fn = shard_map_compat(local_fold, mesh, (specs, P(axis)),
                              tuple(P() for _ in reducers) + (P(), P(axis)))
        return jax.jit(fn)
