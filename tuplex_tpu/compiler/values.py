"""Columnar symbolic values (CV) — what the emitter's abstract interpreter
pushes around while tracing a UDF over column batches.

A CV is one of:
  * const    — a compile-time Python scalar (specialized into the trace, the
               way the reference bakes constants into LLVM IR)
  * numeric  — data [B] (+ valid [B] when Option)
  * str      — sbytes [B, W] + slen [B] (+ valid)
  * null     — the None value for every row
  * tuple    — tuple of CVs (+ valid for Option[Tuple]); may carry field names
               (row values: dict-style access x['col'] resolves here, the
               reference's dict-access rewrite UDF.h:183)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Sequence

import numpy as np

from ..core import typesys as T
from ..core.errors import NotCompilable
from ..runtime.jaxcfg import jnp

_MISSING = object()


@dataclass
class CV:
    t: T.Type
    data: Any = None            # numeric payload [B]
    valid: Any = None           # Option validity [B] (None => always valid)
    sbytes: Any = None          # str payload [B, W]
    slen: Any = None            # str lengths [B]
    elts: Optional[tuple] = None          # tuple elements (CVs)
    names: Optional[tuple] = None         # field names for row-tuples
    const: Any = _MISSING       # compile-time constant
    kind: Optional[str] = None  # special object marker ("match" = re result)

    # -- predicates ----------------------------------------------------------
    @property
    def is_const(self) -> bool:
        return self.const is not _MISSING

    @property
    def base(self) -> T.Type:
        return self.t.without_option() if self.t.is_optional() else self.t

    def __repr__(self):
        if self.is_const:
            return f"CV(const={self.const!r})"
        return f"CV({self.t})"


def const_cv(value: Any) -> CV:
    return CV(t=T.infer_type(value), const=value)


def null_cv() -> CV:
    return CV(t=T.NULL, const=None)


def tuple_cv(elts: Sequence[CV], names: Optional[Sequence[str]] = None,
             valid: Any = None, kind: Optional[str] = None) -> CV:
    ts = tuple(e.t for e in elts)
    t = T.tuple_of(*ts)
    if valid is not None:
        t = T.option(t)
    return CV(t=t, elts=tuple(elts), names=tuple(names) if names else None,
              valid=valid, kind=kind)


def materialize(cv: CV, b: int) -> CV:
    """Broadcast a const CV to batch arrays of length b."""
    if not cv.is_const:
        return cv
    v = cv.const
    if v is None:
        return CV(t=T.NULL, const=None)  # null stays symbolic
    if isinstance(v, bool):
        return CV(t=T.BOOL, data=jnp.full(b, v, dtype=bool))
    if isinstance(v, int):
        return CV(t=T.I64, data=jnp.full(b, v, dtype=jnp.int64))
    if isinstance(v, float):
        return CV(t=T.F64, data=jnp.full(b, v, dtype=jnp.float64))
    if isinstance(v, str):
        from ..ops import strings as S

        sb, sl = S.broadcast_const(v, b)
        return CV(t=T.STR, sbytes=sb, slen=sl)
    if isinstance(v, tuple):
        return tuple_cv([materialize(const_cv(x), b) for x in v])
    raise NotCompilable(f"cannot materialize constant {type(v).__name__}")


def cv_arrays(cv: CV, out: list) -> None:
    """Append the CV tree's arrays to `out` in deterministic order
    (inverse: cv_rebuild)."""
    if cv.is_const:
        return
    for f in ("data", "valid", "sbytes", "slen"):
        v = getattr(cv, f)
        if v is not None:
            out.append(v)
    if cv.elts is not None:
        for e in cv.elts:
            cv_arrays(e, out)


def cv_rebuild(cv: CV, it) -> CV:
    """Rebuild a CV tree consuming arrays from `it`."""
    import dataclasses

    if cv.is_const:
        return cv
    kw = {}
    for f in ("data", "valid", "sbytes", "slen"):
        if getattr(cv, f) is not None:
            kw[f] = next(it)
    elts = cv.elts
    if elts is not None:
        elts = tuple(cv_rebuild(e, it) for e in elts)
    return dataclasses.replace(cv, elts=elts, **kw)


def dtype_for(t: T.Type):
    if t is T.BOOL:
        return np.bool_
    if t is T.I64:
        return np.int64
    if t is T.F64:
        return np.float64
    raise NotCompilable(f"no dtype for {t}")
