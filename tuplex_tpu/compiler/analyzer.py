"""Plan-time UDF static analysis: traceability, exception sites, purity.

Tuplex's headline trick is deciding *before* execution what the compiled
normal-case path can and cannot handle (reference: UDF.h hintInputSchema /
the compile-or-fallback split in StageBuilder.cc). Our port previously
learned a UDF was untraceable only when the emitter threw mid-trace and the
row got stamped PYTHON_FALLBACK. This module runs ONE AST+closure pass per
UDF at plan time and produces a structured ``UDFReport``:

* **traceability verdict** — construct sites the emitter can never compile
  (generators, try/except, global/closure mutation, I/O calls, recursion,
  unbounded ``while``, dynamic ``exec``/``eval``). The planner routes such
  operators to the interpreter pipeline at *plan* time; the emitter is never
  invoked for them. Findings inside an ``if`` arm are marked *conditional*:
  sample-driven branch speculation may prune the arm, so those stay with the
  trace probe (reference: RemoveDeadBranchesVisitor semantics).
* **exception-site inventory** — AST nodes mapped to the ``ExceptionCode``
  the compiled path can emit there (division -> ZERODIVISIONERROR,
  ``row[k]`` -> KEYERROR, ``int(s)`` -> VALUEERROR, attribute on an Option
  value -> NULLERROR...), so physical planning knows each stage's possible
  error codes without sampling.
* **purity/determinism verdict** — ``random``/``time`` calls and
  mutable-global reads. Nondeterministic chains disable the cross-job
  sample/schema memo (plan/logical.py), branch speculation, and are flagged
  on cache() materialization (plan/cacheop.py).

Everything is exposed as human-readable diagnostics with source locations
via ``python -m tuplex_tpu lint <script.py>`` and ``DataSet.explain(lint=
True)``. Analysis cost is recorded in STATS (api/metrics.py: analyzer_ms).
"""

from __future__ import annotations

import ast
import dataclasses
import time
import types
from typing import Any, Optional

from ..core.errors import ExceptionCode

# -- module call classification ---------------------------------------------

# calls that compile to nothing sensible on device and always will
_DYNAMIC_CALLS = {"eval", "exec", "compile", "__import__", "globals",
                  "locals", "vars", "delattr", "setattr"}
_IO_CALLS = {"open", "input", "print", "breakpoint"}
# module-level calls that are I/O or process state: never device material
_IO_MODULES = {"os", "sys", "io", "shutil", "subprocess", "socket",
               "urllib", "requests", "pathlib"}
# nondeterminism markers. NOTE: `random` COMPILES (the emitter stages a
# per-partition #seed) — it is an impurity verdict, not a fallback one.
_NONDET_MODULES = {"random", "time", "datetime", "uuid", "secrets"}

_FINDINGS_CAP = 64


@dataclasses.dataclass(frozen=True)
class Finding:
    kind: str                 # "fallback" | "exception" | "impure"
    reason: str               # human-readable, one line
    lineno: int               # relative to the UDF source (or absolute in
    col: int                  # lint-file mode; see UDFReport.abs_lines)
    code: Optional[ExceptionCode] = None   # exception-site code
    conditional: bool = False  # inside an if-arm branch speculation may prune


@dataclasses.dataclass
class UDFReport:
    name: str
    params: tuple
    filename: str = "<udf>"
    line_base: int = 1        # absolute line of the UDF's first source line
    abs_lines: bool = False   # linenos in findings are already file-absolute
    findings: list = dataclasses.field(default_factory=list)
    deterministic: bool = True
    mutates_globals: bool = False
    # sample-free specialization verdict (compiler/typeinfer.py): the
    # statically inferred result type when the abstract interpreter decided
    # it EXACTLY, else None with `inferred_why` explaining what aborted.
    # Stamped per-operator (a per-op report COPY — two operators sharing a
    # code object may see different input schemas) by op_static_verdict,
    # and by lint_file in schema-free lint mode.
    inferred_type: Any = None
    inferred_why: str = ""

    # -- verdicts ----------------------------------------------------------
    @property
    def fallback_findings(self) -> list:
        return [f for f in self.findings if f.kind == "fallback"]

    @property
    def exception_findings(self) -> list:
        return [f for f in self.findings if f.kind == "exception"]

    @property
    def impure_findings(self) -> list:
        return [f for f in self.findings if f.kind == "impure"]

    @property
    def must_fallback(self) -> bool:
        """Any construct the emitter can never compile (incl. conditional
        sites that speculation might prune)."""
        return bool(self.fallback_findings)

    def must_fallback_now(self, speculate: bool = True) -> bool:
        """The PLAN-time routing verdict: route to the interpreter without
        attempting a trace. With speculation on, findings inside if-arms are
        left to the trace probe (the sample profile may prune the arm)."""
        return self.routing_finding(speculate) is not None

    def routing_finding(self, speculate: bool = True) -> Optional[Finding]:
        """The first fallback finding that actually triggers plan-time
        routing under the given speculation mode — diagnostics must cite
        THIS site, not a cold-arm finding the trace probe still owns."""
        for f in self.fallback_findings:
            if not (f.conditional and speculate):
                return f
        return None

    @property
    def pure(self) -> bool:
        return not self.impure_findings and not self.mutates_globals

    def exception_codes(self) -> set:
        return {f.code for f in self.exception_findings if f.code is not None}

    # -- rendering ---------------------------------------------------------
    def loc(self, f: Finding) -> str:
        line = f.lineno if self.abs_lines else self.line_base + f.lineno - 1
        return f"{self.filename}:{line}"

    def verdict_line(self) -> str:
        if self.must_fallback:
            path = "INTERPRETER (plan-time fallback)"
        else:
            path = "compiled fast path candidate"
        purity = "pure" if self.pure else (
            "nondeterministic" if not self.deterministic else "impure")
        return f"{self.name}({', '.join(self.params)}) " \
               f"[{self.filename}:{self.line_base}] — {path}; {purity}"

    @property
    def statically_typed(self) -> bool:
        return self.inferred_type is not None

    def typed_line(self) -> Optional[str]:
        """"statically typed: yes/no + why not" — None when inference never
        ran for this report (e.g. aggregate/join UDFs it does not cover)."""
        if self.inferred_type is not None:
            return f"statically typed: yes — {self.inferred_type.name} " \
                   "(sample trace skipped)"
        if self.inferred_why:
            return f"statically typed: no — {self.inferred_why}"
        return None

    def format(self, indent: str = "") -> list:
        out = [indent + self.verdict_line()]
        tl = self.typed_line()
        if tl is not None:
            out.append(f"{indent}  typed     {tl}")
        for f in self.fallback_findings:
            cond = " [cold-arm: trace probe decides]" if f.conditional else ""
            out.append(f"{indent}  fallback  {self.loc(f)}: {f.reason}{cond}")
        for f in self.exception_findings:
            code = f.code.name if f.code is not None else "?"
            out.append(f"{indent}  exc-site  {self.loc(f)}: {f.reason} "
                       f"-> {code}")
        for f in self.impure_findings:
            out.append(f"{indent}  impure    {self.loc(f)}: {f.reason}")
        return out


# ===========================================================================
# the single AST pass
# ===========================================================================

class _UdfVisitor(ast.NodeVisitor):
    """One walk over a UDF body collecting all three finding categories.

    Scope discipline: `locals_` over-approximates bound-in-body names (any
    Store), so global reads are under-reported, never over-reported. A
    nested lambda/def whose parameter shadows analysis-relevant names is a
    fallback site anyway (the emitter has no nested-scope support)."""

    def __init__(self, report: UDFReport, self_name: str,
                 globals_map: dict, module_names: dict, locals_: set):
        self.r = report
        self.self_name = self_name
        self.globals_map = globals_map
        self.module_names = module_names
        self.locals = locals_
        self.if_depth = 0
        self._impure_names: set = set()

    # -- helpers -----------------------------------------------------------
    def _add(self, kind: str, node: ast.AST, reason: str,
             code: Optional[ExceptionCode] = None,
             conditional: Optional[bool] = None) -> None:
        if len(self.r.findings) >= _FINDINGS_CAP:
            return
        cond = self.if_depth > 0 if conditional is None else conditional
        self.r.findings.append(Finding(
            kind=kind, reason=reason, lineno=getattr(node, "lineno", 1),
            col=getattr(node, "col_offset", 0), code=code, conditional=cond))

    def _fallback(self, node, reason, conditional=None):
        self._add("fallback", node, reason, conditional=conditional)

    def _exc(self, node, reason, code):
        self._add("exception", node, reason, code=code)

    # -- conditionality tracking -------------------------------------------
    def visit_If(self, node: ast.If) -> None:
        self.visit(node.test)
        self.if_depth += 1
        for s in node.body:
            self.visit(s)
        for s in node.orelse:
            self.visit(s)
        self.if_depth -= 1

    def visit_IfExp(self, node: ast.IfExp) -> None:
        self.visit(node.test)
        self.if_depth += 1
        self.visit(node.body)
        self.visit(node.orelse)
        self.if_depth -= 1

    # -- definite fallback constructs --------------------------------------
    def visit_Yield(self, node) -> None:
        # a yield anywhere makes the whole function a generator: scope-wide
        self._fallback(node, "generator (yield)", conditional=False)
        self.generic_visit(node)

    def visit_YieldFrom(self, node) -> None:
        self._fallback(node, "generator (yield from)", conditional=False)
        self.generic_visit(node)

    def visit_Await(self, node) -> None:
        self._fallback(node, "async construct (await)", conditional=False)
        self.generic_visit(node)

    def visit_Try(self, node) -> None:
        self._fallback(node, "try/except block")
        self.generic_visit(node)

    def visit_TryStar(self, node) -> None:          # pragma: no cover
        self._fallback(node, "try/except* block")
        self.generic_visit(node)

    def visit_With(self, node) -> None:
        self._fallback(node, "with block")
        self.generic_visit(node)

    def visit_AsyncWith(self, node) -> None:
        self._fallback(node, "async with block", conditional=False)

    def visit_AsyncFor(self, node) -> None:
        self._fallback(node, "async for loop", conditional=False)

    def visit_Import(self, node) -> None:
        self._fallback(node, "import inside UDF body")

    def visit_ImportFrom(self, node) -> None:
        self._fallback(node, "import inside UDF body")

    def visit_Delete(self, node) -> None:
        self._fallback(node, "del statement")

    def visit_Match(self, node) -> None:
        self._fallback(node, "match statement")
        self.generic_visit(node)

    def visit_Global(self, node: ast.Global) -> None:
        # scope-wide declaration in CPython regardless of where it appears
        self._fallback(node, f"global mutation ({', '.join(node.names)})",
                       conditional=False)
        self.r.mutates_globals = True

    def visit_Nonlocal(self, node: ast.Nonlocal) -> None:
        self._fallback(node,
                       f"closure-cell mutation ({', '.join(node.names)})",
                       conditional=False)
        self.r.mutates_globals = True

    def visit_FunctionDef(self, node) -> None:
        self._fallback(node, f"nested function def {node.name!r}")

    def visit_AsyncFunctionDef(self, node) -> None:
        self._fallback(node, "async function def", conditional=False)

    def visit_ClassDef(self, node) -> None:
        self._fallback(node, f"class def {node.name!r} inside UDF")

    def visit_Lambda(self, node: ast.Lambda) -> None:
        # a nested lambda value has no device representation; its body is a
        # separate scope — don't descend (misattributed locals/globals)
        self._fallback(node, "nested lambda")

    def visit_SetComp(self, node) -> None:
        self._fallback(node, "set comprehension")

    def visit_While(self, node: ast.While) -> None:
        test = node.test
        const_true = isinstance(test, ast.Constant) and bool(test.value)
        if const_true and not _has_own_break(node):
            self._fallback(node, "unbounded while (constant-true, no break)")
        else:
            self._exc(node, "while loop past the unroll cap interprets "
                      "the row", ExceptionCode.LOOPCAPEXCEEDED)
        self.generic_visit(node)

    # -- assignments: global-structure mutation -----------------------------
    def _check_target(self, tgt: ast.AST) -> None:
        root = tgt
        while isinstance(root, (ast.Subscript, ast.Attribute)):
            root = root.value
        if isinstance(root, ast.Name) and root is not tgt \
                and root.id not in self.locals:
            self._fallback(tgt, f"mutates captured global {root.id!r}")
            self.r.mutates_globals = True

    def _check_target_tree(self, t: ast.AST) -> None:
        """Every assignment slot in a (possibly nested tuple/list) target."""
        if isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                self._check_target_tree(el)
        elif isinstance(t, ast.Starred):
            self._check_target_tree(t.value)
        elif isinstance(t, (ast.Subscript, ast.Attribute)):
            self._check_target(t)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._check_target_tree(t)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_target_tree(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node) -> None:
        self._check_target_tree(node.target)
        self.generic_visit(node)

    # -- calls ---------------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Name):
            n = fn.id
            if n in _DYNAMIC_CALLS:
                self._fallback(node, f"dynamic code/introspection ({n})")
            elif n in _IO_CALLS:
                self._fallback(node, f"I/O call ({n})")
            elif n == self.self_name and n:
                self._fallback(node, f"recursive call to {n!r}")
            elif n in ("int", "float") and node.args:
                a = node.args[0]
                if not (isinstance(a, ast.Constant)
                        and isinstance(a.value, (int, float))):
                    self._exc(node, f"{n}() parse of a non-constant",
                              ExceptionCode.VALUEERROR)
        elif isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name):
            # classify by the module's REAL name, not the local binding —
            # `import random as rnd` / modules passed through closures must
            # not dodge the verdict
            real = self.module_names.get(fn.value.id)
            if real is not None:
                if real in _IO_MODULES:
                    self._fallback(node, f"I/O module call "
                                   f"({fn.value.id}.{fn.attr})")
                elif real in _NONDET_MODULES:
                    self._add("impure", node,
                              f"nondeterministic call "
                              f"{fn.value.id}.{fn.attr}()")
                    self.r.deterministic = False
        self.generic_visit(node)

    # -- exception-site inventory -------------------------------------------
    def visit_Subscript(self, node: ast.Subscript) -> None:
        if isinstance(node.ctx, ast.Load) and \
                not isinstance(node.slice, ast.Slice):
            key = node.slice
            if isinstance(key, ast.Constant) and isinstance(key.value, str):
                self._exc(node, f"subscript [{key.value!r}]",
                          ExceptionCode.KEYERROR)
            else:
                self._exc(node, "indexed subscript",
                          ExceptionCode.INDEXERROR)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.ctx, ast.Load):
            base = node.value
            if not (isinstance(base, ast.Name)
                    and base.id in self.module_names):
                self._exc(node, f"attribute/method .{node.attr} on a "
                          "possibly-None (Option) value",
                          ExceptionCode.NULLERROR)
        self.generic_visit(node)

    def visit_BinOp(self, node: ast.BinOp) -> None:
        if isinstance(node.op, (ast.Div, ast.FloorDiv, ast.Mod)):
            right_nonzero_const = (isinstance(node.right, ast.Constant)
                                   and isinstance(node.right.value,
                                                  (int, float))
                                   and node.right.value != 0)
            left_is_fmt = isinstance(node.op, ast.Mod) and (
                isinstance(node.left, ast.JoinedStr)
                or (isinstance(node.left, ast.Constant)
                    and isinstance(node.left.value, str)))
            if not right_nonzero_const and not left_is_fmt:
                opn = {ast.Div: "/", ast.FloorDiv: "//",
                       ast.Mod: "%"}[type(node.op)]
                self._exc(node, f"division ({opn})",
                          ExceptionCode.ZERODIVISIONERROR)
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert) -> None:
        self._exc(node, "assert", ExceptionCode.ASSERTIONERROR)
        self.generic_visit(node)

    def visit_Raise(self, node: ast.Raise) -> None:
        exc = node.exc
        name = None
        if isinstance(exc, ast.Call) and isinstance(exc.func, ast.Name):
            name = exc.func.id
        elif isinstance(exc, ast.Name):
            name = exc.id
        from ..core.errors import code_for_name

        code = code_for_name(name or "")
        self._exc(node, f"raise {name or '?'}",
                  code if code is not None else ExceptionCode.UNKNOWN)
        self.generic_visit(node)

    # -- purity: mutable-global reads ---------------------------------------
    def visit_Name(self, node: ast.Name) -> None:
        if isinstance(node.ctx, ast.Load) and node.id not in self.locals \
                and node.id not in self._impure_names:
            v = self.globals_map.get(node.id)
            if isinstance(v, (list, dict, set, bytearray)):
                self._impure_names.add(node.id)
                self._add("impure", node,
                          f"reads mutable global {node.id!r} "
                          f"({type(v).__name__})")


def _has_own_break(loop: ast.AST) -> bool:
    """Whether a loop body contains a break bound to THIS loop. Breaks in a
    nested loop's body belong to that loop — but a break in a nested loop's
    `else:` block binds to the ENCLOSING loop, so those still count. The
    loop's own `orelse` is excluded (a break there binds further out)."""
    stack = list(loop.body)
    while stack:
        n = stack.pop()
        if isinstance(n, ast.Break):
            return True
        if isinstance(n, (ast.While, ast.For, ast.AsyncFor)):
            stack.extend(n.orelse)   # nested loop's else binds to THIS loop
            continue
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                          ast.ClassDef)):
            continue                 # new scope: break is a SyntaxError there
        stack.extend(ast.iter_child_nodes(n))
    return False


def _bound_names(node: ast.AST) -> set:
    """Over-approximate the names bound inside a UDF body (params added by
    the caller): any Store/walrus/for/comprehension target."""
    out: set = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
            out.add(n.id)
        elif isinstance(n, ast.NamedExpr) and isinstance(n.target, ast.Name):
            out.add(n.target.id)
        elif isinstance(n, ast.arg):
            out.add(n.arg)
    return out


def _all_params(node) -> tuple:
    a = node.args
    names = [x.arg for x in
             list(getattr(a, "posonlyargs", [])) + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return tuple(names)


def analyze_tree(node: ast.AST, name: str = "<udf>",
                 globals_map: Optional[dict] = None,
                 module_names=None,
                 filename: str = "<udf>", line_base: int = 1,
                 abs_lines: bool = False) -> UDFReport:
    """Analyze one Lambda/FunctionDef AST node. `globals_map` carries the
    captured closure/global VALUES when available (runtime mode);
    `module_names` maps names known to be modules to the module's REAL name
    (lint mode derives them from the script's imports; a plain set/iterable
    is accepted as the identity mapping)."""
    globals_map = globals_map or {}
    if module_names is None:
        module_names = {k: v.__name__.split(".")[0]
                        for k, v in globals_map.items()
                        if isinstance(v, types.ModuleType)}
    elif not isinstance(module_names, dict):
        module_names = {n: n for n in module_names}
    params = _all_params(node) if hasattr(node, "args") else ()
    rpt = UDFReport(name=name, params=params, filename=filename,
                    line_base=line_base, abs_lines=abs_lines)
    body = node.body if isinstance(node, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)) else [node.body]
    locals_ = set(params)
    for s in body:
        locals_ |= _bound_names(s)
    v = _UdfVisitor(rpt, name, globals_map, module_names, locals_)
    if isinstance(node, ast.AsyncFunctionDef):
        v._fallback(node, "async function def", conditional=False)
    for s in body:
        v.visit(s)
    return rpt


# ===========================================================================
# runtime entry points (UDFSource / operators / plans)
# ===========================================================================

STATS = {"analyze_calls": 0, "analyze_ms": 0.0, "plan_fallback_ops": 0,
         # sample-free specialization (compiler/typeinfer.py): operators
         # whose output type the abstract interpreter decided exactly, and
         # how many CPython sample traces that verdict let planning skip
         "inferred_ops": 0, "sample_traces_skipped": 0}


def snapshot() -> dict:
    return dict(STATS)


def delta(snap: dict) -> dict:
    return {k: STATS[k] - snap.get(k, 0) for k in STATS}


# (code object, globals signature) -> UDFReport. LRU: the old grow-then-
# .clear() pattern dropped every warm report the moment one insert crossed
# the cap (utils/lru.py — same fix as the plan/logical.py schema memos)
from ..utils.lru import LruDict

_udf_memo: LruDict = LruDict(4096)


def _globals_sig(globs: dict) -> tuple:
    """The slice of the captured globals the analysis actually reads:
    module identities (purity/I-O classification) and which names hold
    mutable containers. Two closures sharing a code object but capturing
    different modules must NOT share a verdict."""
    mods = tuple(sorted(
        (k, v.__name__.split(".")[0]) for k, v in globs.items()
        if isinstance(v, types.ModuleType)))
    muts = tuple(sorted(
        k for k, v in globs.items()
        if isinstance(v, (list, dict, set, bytearray))))
    return (mods, muts)


def analyze_udf(udf) -> UDFReport:
    """Report for a reflected UDFSource; memoized per (code object,
    globals signature) — analysis is source-determined except for the
    module/mutability classification of captured globals."""
    code = getattr(udf.func, "__code__", None)
    key = (code, _globals_sig(udf.globals)) if code is not None else None
    if key is not None and key in _udf_memo:
        return _udf_memo[key]
    from ..runtime import tracing as _tr

    t0 = time.perf_counter()
    filename = code.co_filename if code is not None else "<udf>"
    line_base = code.co_firstlineno if code is not None else 1
    with _tr.span("plan:analyze-udf", "plan") as _sp:
        if not udf.source:
            rpt = UDFReport(name=udf.name, params=tuple(udf.params),
                            filename=filename, line_base=line_base)
            rpt.findings.append(Finding(
                kind="fallback", reason="no retrievable UDF source",
                lineno=1, col=0, conditional=False))
        else:
            rpt = analyze_tree(udf.tree, name=udf.name,
                               globals_map=udf.globals,
                               filename=filename, line_base=line_base)
        if _sp is not _tr.NOOP:
            _sp.set("udf", udf.name).set("findings", len(rpt.findings))
    STATS["analyze_calls"] += 1
    STATS["analyze_ms"] += (time.perf_counter() - t0) * 1e3
    if key is not None:
        _udf_memo[key] = rpt
    return rpt


_UDF_ATTRS = ("udf", "combine_udf", "aggregate_udf")


def op_reports(op) -> list:
    """[(udf attribute name, UDFReport)] for every UDF an operator carries;
    memoized on the operator (operators are immutable once planned)."""
    memo = getattr(op, "_az_reports", None)
    if memo is None:
        memo = []
        for attr in _UDF_ATTRS:
            u = getattr(op, attr, None)
            if u is not None:
                memo.append((attr, analyze_udf(u)))
        try:
            op._az_reports = memo
        except (AttributeError, TypeError):   # pragma: no cover
            pass
    return memo


def op_analysis(op) -> Optional[UDFReport]:
    """The report of an operator's primary (fused) UDF, or None."""
    for attr, rep in op_reports(op):
        if attr == "udf":
            return rep
    return None


def op_nondeterministic(op) -> bool:
    return any(not rep.deterministic for _, rep in op_reports(op))


def chain_reports(sink) -> list:
    """[(op, udf attr, report)] over the whole upstream DAG of `sink`."""
    out, seen, stack = [], set(), [sink]
    while stack:
        op = stack.pop()
        if id(op) in seen:
            continue
        seen.add(id(op))
        for attr, rep in op_reports(op):
            out.append((op, attr, rep))
        stack.extend(getattr(op, "parents", ()))
    return out


def chain_deterministic(op) -> bool:
    return all(rep.deterministic for _, _, rep in chain_reports(op))


# ===========================================================================
# dead-resolver lint (ROADMAP "lint-driven authoring loop")
# ===========================================================================

# Codes whose raising constructs the analyzer inventories EXHAUSTIVELY
# within the statically-typed subset: subscripts (KeyError/IndexError — one
# group, since a variable-keyed dict subscript classifies as INDEXERROR),
# division, assert/raise. ValueError & friends are deliberately absent:
# known-total calls like str.index or math.sqrt raise them without an
# inventory entry, so "not in the inventory" proves nothing there.
_DEAD_RESOLVER_GROUPS = (
    frozenset({ExceptionCode.KEYERROR, ExceptionCode.INDEXERROR}),
    frozenset({ExceptionCode.ZERODIVISIONERROR}),
    frozenset({ExceptionCode.ASSERTIONERROR}),
)

#: builtins the abstract interpreter treats as type-total — calls to these
#: cannot raise the _DEAD_RESOLVER_GROUPS codes
_KNOWN_TOTAL_CALLS = {"int", "float", "str", "bool", "len", "ord", "repr",
                      "abs", "min", "max", "round", "sum", "chr", "sorted"}


def dead_resolver_reason(rep: UDFReport, exc_class=None, code=None,
                         exc_name: str = "",
                         fully_typed: bool = False) -> Optional[str]:
    """Reason string when a ``resolve(exc_class)`` / ``ignore(exc_class)``
    guarding the operator described by `rep` is PROVABLY dead, else None.

    The proof is deliberately narrow: the target class must map to a code
    whose raisers the inventory covers exhaustively (_DEAD_RESOLVER_GROUPS),
    the UDF must carry no fallback findings, and `fully_typed` must assert
    that every call in the body is in the known-pure tables (the abstract
    interpreter's exact verdict at plan time; a syntactic call whitelist in
    schema-free lint mode) — otherwise an unknown callee could smuggle the
    exception in and the warning would be wrong."""
    if not fully_typed or rep.must_fallback:
        return None
    if code is None and exc_class is not None:
        from ..core.errors import code_for_exception_class

        code = code_for_exception_class(exc_class)
        exc_name = exc_name or getattr(exc_class, "__name__", "?")
    if code is None:
        return None
    group = next((g for g in _DEAD_RESOLVER_GROUPS if code in g), None)
    if group is None:
        return None
    if rep.exception_codes() & group:
        return None
    return (f"dead resolver: targets {exc_name or code.name}, but "
            f"{rep.name}'s exception inventory proves it can never "
            f"raise it")


def _calls_all_known(node: ast.AST, module_names: dict) -> bool:
    """Schema-free stand-in for the abstract interpreter's exact verdict:
    every call in the UDF body is a known-total builtin, a method name
    from the interpreter's pure tables, or a pure-table module function.
    Those callees can raise ValueError-family errors but none of the
    _DEAD_RESOLVER_GROUPS codes."""
    from .typeinfer import (_MODULE_FNS, _STR_TO_BOOL, _STR_TO_I64,
                            _STR_TO_LIST, _STR_TO_STR)

    known_methods = (_STR_TO_STR | _STR_TO_I64 | _STR_TO_BOOL
                     | _STR_TO_LIST
                     | {"partition", "rpartition", "get", "keys", "values",
                        "index", "count", "format"})
    for n in ast.walk(node):
        if not isinstance(n, ast.Call):
            continue
        fn = n.func
        if isinstance(fn, ast.Name) and fn.id in _KNOWN_TOTAL_CALLS:
            continue
        if isinstance(fn, ast.Attribute):
            if isinstance(fn.value, ast.Name) \
                    and fn.value.id in module_names:
                if (module_names[fn.value.id], fn.attr) in _MODULE_FNS:
                    continue
                return False
            if fn.attr in known_methods:
                continue
        return False
    return True


# ===========================================================================
# `python -m tuplex_tpu lint` — static lint of a pipeline script
# ===========================================================================

_UDF_METHODS = {"map", "filter", "withColumn", "mapColumn", "resolve",
                "aggregate", "aggregateByKey"}


def _script_module_fns(tree: ast.Module) -> dict:
    """{name -> Lambda/FunctionDef node} for every def / lambda-assignment
    in the script (incl. defs nested inside functions — a UDF defined in
    main() must not silently escape a --strict gate)."""
    module_fns: dict = {}
    for s in ast.walk(tree):
        if isinstance(s, ast.FunctionDef):
            module_fns.setdefault(s.name, s)
        elif isinstance(s, ast.Assign) and isinstance(s.value, ast.Lambda):
            for t in s.targets:
                if isinstance(t, ast.Name):
                    module_fns.setdefault(t.id, s.value)
    return module_fns


def _collect_script_udfs(tree: ast.Module):
    """(node, name) for every UDF passed to a DataSet-shaped method call:
    inline lambdas plus module-level defs/lambda-assignments referenced by
    name. Purely syntactic — the script is never imported or executed."""
    module_fns = _script_module_fns(tree)
    out, seen = [], set()

    def add(node, name):
        if id(node) not in seen:
            seen.add(id(node))
            out.append((node, name))

    for n in ast.walk(tree):
        if not (isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute)
                and n.func.attr in _UDF_METHODS):
            continue
        for a in n.args:
            if isinstance(a, ast.Lambda):
                add(a, "<lambda>")
            elif isinstance(a, ast.Name) and a.id in module_fns:
                add(module_fns[a.id], a.id)
    return sorted(out, key=lambda p: getattr(p[0], "lineno", 0))


def _script_dead_resolvers(tree: ast.Module, module_names: dict,
                           path: str) -> list:
    """Syntactic dead-resolver findings: `X.resolve(Exc, fn)` /
    `X.ignore(Exc)` chained directly after a UDF-carrying DataSet method
    whose exception inventory provably cannot raise Exc. Returns
    "file:line: reason" strings. Purely syntactic, same soundness bar as
    dead_resolver_reason (the schema-free `fully_typed` proxy is the
    known-call whitelist)."""
    from ..core.errors import code_for_name

    module_fns = _script_module_fns(tree)
    out = []
    for n in ast.walk(tree):
        if not (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in ("resolve", "ignore")
                and n.args and isinstance(n.args[0], ast.Name)):
            continue
        code = code_for_name(n.args[0].id)
        # the guarded call: walk down through stacked resolve/ignore links
        recv = n.func.value
        while (isinstance(recv, ast.Call)
               and isinstance(recv.func, ast.Attribute)
               and recv.func.attr in ("resolve", "ignore")):
            recv = recv.func.value
        if not (isinstance(recv, ast.Call)
                and isinstance(recv.func, ast.Attribute)
                and recv.func.attr in (_UDF_METHODS - {"resolve"})):
            continue
        udf_node = udf_name = None
        for a in recv.args:
            if isinstance(a, ast.Lambda):
                udf_node, udf_name = a, "<lambda>"
                break
            if isinstance(a, ast.Name) and a.id in module_fns:
                udf_node, udf_name = module_fns[a.id], a.id
                break
        if udf_node is None:
            continue
        rep = analyze_tree(udf_node, name=udf_name,
                           module_names=module_names, filename=path,
                           line_base=getattr(udf_node, "lineno", 1),
                           abs_lines=True)
        reason = dead_resolver_reason(
            rep, code=code, exc_name=n.args[0].id,
            fully_typed=_calls_all_known(udf_node, module_names))
        if reason:
            out.append(f"{path}:{getattr(n, 'lineno', 1)}: {reason}")
    return out


def _script_resolver_suggestions(tree: ast.Module, module_names: dict,
                                 path: str) -> list:
    """Positive suggestions (the dead-resolver lint's twin): a UDF call
    whose exception inventory contains ONLY exact Python exception classes
    and that no chained ``resolve``/``ignore`` guards gets a "consider a
    resolver or ignore" line. Same syntactic soundness bar as
    ``_script_dead_resolvers`` — suggested only when every call in the
    body is whitelisted-total (an unknown callee could raise anything, so
    no "can only raise" claim is made)."""
    from ..core.errors import exception_class_for_code

    module_fns = _script_module_fns(tree)
    guarded: set = set()
    guarded_names: set = set()
    for n in ast.walk(tree):
        if not (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in ("resolve", "ignore")):
            continue
        recv = n.func.value
        while (isinstance(recv, ast.Call)
               and isinstance(recv.func, ast.Attribute)
               and recv.func.attr in ("resolve", "ignore")):
            recv = recv.func.value
        guarded.add(id(recv))
        if isinstance(recv, ast.Name):
            # `ds2 = ds.resolve(...)`: the guard attaches through a
            # variable, not a chained call — any UDF call assigned to
            # that name counts as guarded (claiming "no resolver" on it
            # would be wrong)
            guarded_names.add(recv.id)
    for n in ast.walk(tree):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
            for t in n.targets:
                if isinstance(t, ast.Name) and t.id in guarded_names:
                    guarded.add(id(n.value))
    out = []
    for n in ast.walk(tree):
        if not (isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in (_UDF_METHODS - {"resolve"})
                and id(n) not in guarded):
            continue
        udf_node = udf_name = None
        for a in n.args:
            if isinstance(a, ast.Lambda):
                udf_node, udf_name = a, "<lambda>"
                break
            if isinstance(a, ast.Name) and a.id in module_fns:
                udf_node, udf_name = module_fns[a.id], a.id
                break
        if udf_node is None:
            continue
        rep = analyze_tree(udf_node, name=udf_name,
                           module_names=module_names, filename=path,
                           line_base=getattr(udf_node, "lineno", 1),
                           abs_lines=True)
        if rep.must_fallback \
                or not _calls_all_known(udf_node, module_names):
            continue
        codes = sorted(rep.exception_codes())
        if not codes or any(exception_class_for_code(int(c)) is None
                            for c in codes):
            continue
        names = "/".join(c.name for c in codes)
        out.append(
            f"{path}:{getattr(n, 'lineno', 1)}: suggestion: "
            f"{udf_name} can only raise {names} — consider a "
            f".resolve() or .ignore() after .{n.func.attr}()")
    return out


def _script_module_names(tree: ast.Module) -> dict:
    """{local binding -> real top-level module name} from the script's
    imports, so `import random as rnd` still classifies as nondeterministic."""
    mods: dict = {}
    for n in ast.walk(tree):
        if isinstance(n, ast.Import):
            for al in n.names:
                base = al.name.split(".")[0]
                mods[(al.asname or al.name).split(".")[0]] = base
        elif isinstance(n, ast.ImportFrom) and n.module:
            for al in n.names:
                mods[al.asname or al.name] = n.module.split(".")[0]
    return mods


def lint_file(path: str, strict: bool = False, stream=None) -> int:
    """Analyze every UDF a script hands to DataSet methods and print
    per-UDF diagnostics with exact file:line locations. Returns a process
    exit code: non-zero only under --strict with fallback findings."""
    import sys

    stream = stream if stream is not None else sys.stdout

    def emit(line=""):
        print(line, file=stream)

    with open(path) as fp:
        src = fp.read()
    try:
        tree = ast.parse(src, filename=path)
    except SyntaxError as e:
        emit(f"{path}: syntax error: {e}")
        return 2
    module_names = _script_module_names(tree)
    udfs = _collect_script_udfs(tree)
    if not udfs:
        emit(f"{path}: no UDFs found (no DataSet-style "
             f"map/filter/withColumn/... calls)")
        return 0
    n_fallback = n_sites = n_typed = 0
    emit(f"lint report for {path} — {len(udfs)} UDF(s)")
    for node, name in udfs:
        rpt = analyze_tree(node, name=name, module_names=module_names,
                           filename=path,
                           line_base=getattr(node, "lineno", 1),
                           abs_lines=True)
        # schema-free type verdict (compiler/typeinfer.infer_tree): only
        # input-independent UDFs come out exact at lint time, but the WHY
        # on the rest tells the author what blocks sample-free planning
        try:
            from .typeinfer import infer_tree

            v = infer_tree(node, module_names)
            rpt.inferred_type = v.type
            rpt.inferred_why = "" if v.exact else (v.why or "undecidable")
            n_typed += 1 if v.exact else 0
        except Exception:   # pragma: no cover - lint stays best-effort
            pass
        n_fallback += len(rpt.fallback_findings)
        n_sites += len(rpt.exception_findings)
        emit()
        for line in rpt.format():
            emit(line)
    dead = _script_dead_resolvers(tree, module_names, path)
    if dead:
        emit()
        for line in dead:
            emit(line)
    suggestions = _script_resolver_suggestions(tree, module_names, path)
    if suggestions:
        emit()
        for line in suggestions:
            emit(line)
    emit()
    emit(f"{len(udfs)} UDF(s): {n_fallback} fallback finding(s), "
         f"{n_sites} exception site(s), {n_typed} statically typed, "
         f"{len(dead)} dead resolver(s), "
         f"{len(suggestions)} suggestion(s)")
    # suggestions are positive/advisory: never a --strict failure
    return 1 if (strict and (n_fallback or dead)) else 0
