"""Sample-free specialization: abstract type inference over UDF ASTs.

Tuplex's data-driven compilation pays a per-plan tax we inherited: every
operator's output schema comes from tracing the UDF over sample rows
(plan/logical.py ``_infer_schema`` -> ``cached_sample()``), even when the
result type is fully decidable from the AST alone. This module is an
abstract interpreter over the UDF's AST on the ``core/typesys`` lattice:
transfer functions for arithmetic / comparison / str-method chains,
subscripts against the input ``RowType``, conditionals joining both arms,
bounded loop fixpoints — and a top element ("undecidable") that cleanly
aborts to the sample trace (reference contrast: the reference always
executes the UDF over sample rows, TraceVisitor.h:25-80; SystemML-style
fusion planning makes the same move from executed evidence to static facts,
PAPERS.md).

Soundness contract (the acceptance bar): an EXACT verdict must equal what
the sample trace would have speculated — never a different concrete type.
Anything data-dependent (None on *some* control path, mixed numeric arms,
unknown calls, reflection) widens to undecidable and the planner falls back
to the CPython sample trace. In particular:

* joining two DIFFERENT concrete types (i64 vs f64, str vs i64) aborts —
  the trace would majority-vote a type the static view can't know;
* a join that introduces ``None`` from a control path (``return None`` on
  one arm) reports the Option shape but stays INEXACT: whether the sample
  actually contains Nones is data the AST doesn't have;
* optionality that comes from the INPUT SCHEMA (an ``Option[str]`` column
  passed through) stays exact — it was speculated from data already.

Operator entry points (``static_op_schema`` / ``op_static_verdict``) mirror
the calling conventions of ``plan/logical.py apply_udf_python`` exactly, so
a static verdict binds parameters the same way the trace would have.

Gate: ``tuplex.tpu.staticTypes`` (default on; Context applies it via
``set_enabled``) with env escape hatch ``TUPLEX_STATIC_TYPES=0``.
"""

from __future__ import annotations

import ast
import os
from typing import Any, Optional

from ..core import typesys as T

__all__ = ["Verdict", "Undecidable", "infer_udf", "infer_tree",
           "static_op_schema", "op_static_verdict", "enabled",
           "set_enabled"]


# ---------------------------------------------------------------------------
# gate
# ---------------------------------------------------------------------------

_flag = True      # set by Context from tuplex.tpu.staticTypes


def set_enabled(on: bool) -> None:
    global _flag
    _flag = bool(on)


def enabled() -> bool:
    """Static inference gate: TUPLEX_STATIC_TYPES env wins (escape hatch /
    A-B benchmarking), else whatever the last Context configured."""
    env = os.environ.get("TUPLEX_STATIC_TYPES")
    if env is not None:
        return env.strip().lower() not in ("0", "false", "no", "off")
    return _flag


class Undecidable(Exception):
    """Raised by a transfer function when the result type depends on data
    (or on constructs outside the abstract domain). Caught at the verdict
    boundary: the operator then falls back to the sample trace."""

    def __init__(self, why: str):
        super().__init__(why)
        self.why = why


class Verdict:
    """Outcome of inferring one UDF's return type.

    ``type`` is the exact result type when decidable, else None and ``why``
    says what aborted. ``shape`` carries the best-known (sound but possibly
    data-dependent) type for diagnostics even when inexact."""

    __slots__ = ("type", "why", "shape")

    def __init__(self, type_: Optional[T.Type], why: str = "",
                 shape: Optional[T.Type] = None):
        self.type = type_
        self.why = why
        self.shape = shape if shape is not None else type_

    @property
    def exact(self) -> bool:
        return self.type is not None

    def describe(self) -> str:
        if self.exact:
            return f"yes — {self.type.name}"
        if self.shape is not None:
            return f"no ({self.shape.name} shape) — {self.why}"
        return f"no — {self.why}"

    def __repr__(self):
        return f"Verdict({self.describe()})"


_NO_CONST = object()


class AV:
    """Abstract value: a lattice type plus (optionally) a known literal
    constant and, for dict literals with constant str keys, the record
    view (ordered names) a MapOperator needs for named output columns."""

    __slots__ = ("t", "const", "record", "why")

    def __init__(self, t: Optional[T.Type], const: Any = _NO_CONST,
                 record=None, why: str = ""):
        self.t = t                 # None == TOP (poisoned; use aborts)
        self.const = const
        self.record = record       # (names tuple, types tuple) | None
        self.why = why             # reason when t is None

    def use(self) -> T.Type:
        """The type, for an operation that needs one — aborts on TOP."""
        if self.t is None:
            raise Undecidable(self.why or "value undecidable")
        return self.t

    def base(self) -> T.Type:
        """Type with Option stripped — for operations that raise on None
        (the raising rows are excluded from the traced schema the same
        way, so unwrapping preserves trace equivalence)."""
        t = self.use()
        return t.without_option() if t.is_optional() else t


def _av(t: T.Type, const: Any = _NO_CONST) -> AV:
    return AV(t, const)


TOP = AV(None, why="undecidable")


# ---------------------------------------------------------------------------
# known-pure call tables
# ---------------------------------------------------------------------------

# str methods returning str
_STR_TO_STR = {"lower", "upper", "strip", "lstrip", "rstrip", "replace",
               "title", "capitalize", "casefold", "swapcase", "center",
               "ljust", "rjust", "zfill", "format", "join", "removeprefix",
               "removesuffix", "expandtabs"}
_STR_TO_I64 = {"find", "rfind", "index", "rindex", "count"}
_STR_TO_BOOL = {"startswith", "endswith", "isdigit", "isalpha", "isalnum",
                "isspace", "islower", "isupper", "isnumeric", "isdecimal",
                "istitle", "isidentifier"}
_STR_TO_LIST = {"split", "rsplit", "splitlines"}

# (module, attr) -> result type for pure, type-total module calls
_MODULE_FNS = {
    ("math", "ceil"): T.I64, ("math", "floor"): T.I64,
    ("math", "trunc"): T.I64,
    ("math", "sqrt"): T.F64, ("math", "log"): T.F64,
    ("math", "log2"): T.F64, ("math", "log10"): T.F64,
    ("math", "exp"): T.F64, ("math", "pow"): T.F64,
    ("math", "sin"): T.F64, ("math", "cos"): T.F64,
    ("math", "tan"): T.F64, ("math", "atan"): T.F64,
    ("math", "atan2"): T.F64, ("math", "hypot"): T.F64,
    ("math", "fabs"): T.F64, ("math", "fmod"): T.F64,
    ("math", "copysign"): T.F64,
    ("math", "isnan"): T.BOOL, ("math", "isinf"): T.BOOL,
    ("string", "capwords"): T.STR,
}
_MODULE_CONSTS = {("math", "pi"): T.F64, ("math", "e"): T.F64,
                  ("math", "inf"): T.F64, ("math", "nan"): T.F64,
                  ("math", "tau"): T.F64}


# ---------------------------------------------------------------------------
# the interpreter
# ---------------------------------------------------------------------------

class _Abs:
    """One abstract run over a UDF body. Collects return-value AVs; joins
    environments at control merges; bounded fixpoint over loops."""

    _LOOP_ROUNDS = 4

    def __init__(self, globals_map: dict, module_names: dict):
        self.globals_map = globals_map or {}
        self.module_names = module_names or {}
        self.returns: list[AV] = []
        # a join introduced optionality from a CONTROL PATH (not the input
        # schema): the result shape is sound but whether Nones occur is
        # data — the verdict must stay inexact (see module docstring)
        self.null_join: Optional[str] = None

    # -- joins --------------------------------------------------------------
    def join_types(self, a: T.Type, b: T.Type) -> T.Type:
        if a is b:
            return a
        if a is T.NULL:
            self.null_join = self.null_join or \
                f"None on some control path (joins {b.name})"
            return T.option(b)
        if b is T.NULL:
            return self.join_types(b, a)
        if a.is_optional() or b.is_optional():
            ab, bb = a.without_option(), b.without_option()
            if ab is bb:
                # Option[T] vs T: all values conform to Option[T], but the
                # trace may or may not have seen a None — data-dependent
                if a.is_optional() != b.is_optional():
                    self.null_join = self.null_join or \
                        f"optionality differs across arms ({a.name} vs " \
                        f"{b.name})"
                return T.option(ab)
            raise Undecidable(f"arms disagree: {a.name} vs {b.name}")
        if isinstance(a, T.TupleType) and isinstance(b, T.TupleType) \
                and len(a) == len(b):
            return T.tuple_of(*(self.join_types(x, y)
                                for x, y in zip(a.elements, b.elements)))
        if isinstance(a, T.ListType) and isinstance(b, T.ListType):
            return T.list_of(self.join_types(a.elt, b.elt))
        if isinstance(a, T.RowType) and isinstance(b, T.RowType) \
                and a.columns == b.columns:
            return T.row_of(a.columns,
                            [self.join_types(x, y)
                             for x, y in zip(a.types, b.types)])
        if isinstance(a, T.DictType) and isinstance(b, T.DictType):
            # dict VALUE types mirror infer_type's super_type fold (that is
            # what the trace would compute), not the strict join
            return T.dict_of(T.super_type(a.key, b.key),
                             T.super_type(a.val, b.val))
        # different concrete types: the trace would majority-vote — abort
        raise Undecidable(f"arms disagree: {a.name} vs {b.name}")

    def join_avs(self, a: AV, b: AV) -> AV:
        if a.t is None or b.t is None:
            return AV(None, why=(a.why or b.why or "join of undecidable"))
        try:
            t = self.join_types(a.t, b.t)
        except Undecidable as e:
            return AV(None, why=e.why)
        record = None
        if a.record is not None and b.record is not None \
                and a.record[0] == b.record[0]:
            try:
                record = (a.record[0],
                          tuple(self.join_types(x, y)
                                for x, y in zip(a.record[1], b.record[1])))
            except Undecidable:
                record = None
        const = a.const if (a.const is not _NO_CONST
                            and a.const == b.const) else _NO_CONST
        return AV(t, const, record)

    def join_envs(self, a: dict, b: dict) -> dict:
        out = {}
        for k in a:
            if k in b:
                out[k] = a[k] if a[k] is b[k] else self.join_avs(a[k], b[k])
        # names bound on only one path are possibly-unbound: drop them
        # (a later use aborts, which is the sound answer)
        return out

    # -- statements ---------------------------------------------------------
    def exec_block(self, stmts, env: dict) -> bool:
        """Run statements; returns True when control can FALL THROUGH the
        end of the block (False: every path returned/raised)."""
        for s in stmts:
            if not self.exec_stmt(s, env):
                return False
        return True

    def exec_stmt(self, s: ast.stmt, env: dict) -> bool:
        if isinstance(s, ast.Return):
            self.returns.append(self.eval(s.value, env)
                                if s.value is not None else _av(T.NULL, None))
            return False
        if isinstance(s, ast.Raise):
            # a raising path contributes nothing to the output schema: the
            # row becomes an exception row, excluded from the trace too
            return False
        if isinstance(s, (ast.Pass, ast.Break, ast.Continue)):
            # break/continue end the block conservatively: the loop
            # fixpoint already joins every iteration's env
            return not isinstance(s, (ast.Break, ast.Continue))
        if isinstance(s, ast.Assign):
            val = self.eval(s.value, env)
            for tgt in s.targets:
                self.assign(tgt, val, env)
            return True
        if isinstance(s, ast.AugAssign):
            val = self._binop_av(self.eval(s.target, env), s.op,
                                 self.eval(s.value, env))
            self.assign(s.target, val, env)
            return True
        if isinstance(s, ast.AnnAssign):
            if s.value is not None:
                self.assign(s.target, self.eval(s.value, env), env)
            return True
        if isinstance(s, ast.If):
            return self.exec_if(s, env)
        if isinstance(s, (ast.While, ast.For)):
            self.exec_loop(s, env)
            return True
        if isinstance(s, ast.Expr):
            try:               # value discarded: a failed transfer on a
                self.eval(s.value, env)   # bare expression poisons nothing
            except Undecidable:
                pass
            return True
        if isinstance(s, ast.Assert):
            try:
                self.eval(s.test, env)
            except Undecidable:
                pass
            return True
        raise Undecidable(
            f"statement {type(s).__name__} outside the abstract domain")

    def exec_if(self, s: ast.If, env: dict) -> bool:
        try:
            self.eval(s.test, env)
        except Undecidable:
            pass                     # a test we can't type still branches
        env_t = dict(env)
        env_f = dict(env)
        self.narrow(s.test, env_t, env_f)
        ft = self.exec_block(s.body, env_t)
        ff = self.exec_block(s.orelse, env_f)
        if ft and ff:
            merged = self.join_envs(env_t, env_f)
        elif ft:
            merged = env_t
        elif ff:
            merged = env_f
        else:
            return False
        env.clear()
        env.update(merged)
        return True

    def exec_loop(self, s, env: dict) -> None:
        """Bounded fixpoint: join the loop body's effect until stable (or
        poison the unstable names). The post-loop env joins the zero-trip
        path, so types only widen."""
        if isinstance(s, ast.While):
            try:
                self.eval(s.test, env)
            except Undecidable:
                pass
        body = list(s.body)
        if isinstance(s, ast.For):
            try:
                self.assign(s.target, self._iter_elt(self.eval(s.iter, env)),
                            env)
            except Undecidable:
                self._poison_target(s.target, env, "loop target undecidable")
        entry = dict(env)
        cur = dict(env)
        for _ in range(self._LOOP_ROUNDS):
            it = dict(cur)
            self.exec_block(body, it)
            if isinstance(s, ast.For):
                try:
                    self.assign(s.target,
                                self._iter_elt(self.eval(s.iter, it)), it)
                except Undecidable:
                    self._poison_target(s.target, it, "loop target")
            joined = self.join_envs(cur, it)
            # keep entry-only names alive across the join (zero-trip path)
            for k, v in cur.items():
                joined.setdefault(k, v)
            if all(k in cur and joined[k].t is cur[k].t
                   and joined[k].record == cur[k].record
                   for k in joined) and set(joined) == set(cur):
                cur = joined
                break
            cur = joined
        else:
            # no fixpoint inside the budget: poison what the body binds
            from .analyzer import _bound_names

            for k in _bound_names(s):
                if k in cur:
                    cur[k] = AV(None, why=f"{k!r} unstable across loop")
        # loop may run zero times: join with the entry env
        merged = self.join_envs(entry, cur)
        for k, v in cur.items():
            merged.setdefault(k, v)
        if s.orelse:
            self.exec_block(list(s.orelse), merged)
        env.clear()
        env.update(merged)

    def _iter_elt(self, it: AV) -> AV:
        t = it.base()
        if t is T.STR:
            return _av(T.STR)
        if isinstance(t, T.ListType):
            return _av(t.elt)
        if isinstance(t, T.TupleType):
            elts = [_av(e) for e in t.elements]
            out = elts[0]
            for e in elts[1:]:
                out = self.join_avs(out, e)
            if out.t is None:
                raise Undecidable(out.why)
            return out
        if isinstance(t, T.DictType):
            return _av(t.key)
        if isinstance(t, T.RowType):
            out = _av(t.types[0])
            for e in t.types[1:]:
                out = self.join_avs(out, _av(e))
            if out.t is None:
                raise Undecidable(out.why)
            return out
        raise Undecidable(f"iteration over {t.name}")

    def assign(self, tgt, val: AV, env: dict) -> None:
        if isinstance(tgt, ast.Name):
            env[tgt.id] = val
            return
        if isinstance(tgt, (ast.Tuple, ast.List)):
            vt = val.use()
            elts = None
            if isinstance(vt, T.TupleType) and len(vt) == len(tgt.elts):
                elts = [_av(e) for e in vt.elements]
            elif isinstance(vt, T.ListType):
                elts = [_av(vt.elt)] * len(tgt.elts)
            if elts is None or any(isinstance(e, ast.Starred)
                                   for e in tgt.elts):
                raise Undecidable("unpacking outside the abstract domain")
            for sub, sv in zip(tgt.elts, elts):
                self.assign(sub, sv, env)
            return
        if isinstance(tgt, ast.Subscript):
            # store into a local container: update a record's column when
            # decidable, else poison the base name (sound)
            base = tgt.value
            if isinstance(base, ast.Name) and base.id in env:
                bav = env[base.id]
                key = tgt.slice
                if bav.record is not None and isinstance(key, ast.Constant) \
                        and isinstance(key.value, str):
                    names, types = bav.record
                    vt = val.use()
                    if key.value in names:
                        i = names.index(key.value)
                        types = types[:i] + (vt,) + types[i + 1:]
                    else:
                        names = names + (key.value,)
                        types = types + (vt,)
                    env[base.id] = AV(
                        T.dict_of(T.STR, _dict_val_super(types)),
                        record=(names, types))
                    return
                env[base.id] = AV(None,
                                  why=f"subscript store into {base.id!r}")
            return
        if isinstance(tgt, ast.Attribute):
            # attribute stores never type a UDF result; analyzer flags
            # global mutation separately
            return
        raise Undecidable(f"assignment target {type(tgt).__name__}")

    def _poison_target(self, tgt, env: dict, why: str) -> None:
        if isinstance(tgt, ast.Name):
            env[tgt.id] = AV(None, why=why)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                self._poison_target(e, env, why)

    # -- truthiness narrowing ----------------------------------------------
    def narrow(self, test, env_true: dict, env_false: dict) -> None:
        """Path-sensitive Option narrowing for the common guards:
        ``if x: ...`` / ``if not x`` / ``if x is (not) None``. In the arm
        where x is known non-None, Option[T] narrows to T — matching the
        trace, which only ever observes the values that reach the arm."""
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return self.narrow(test.operand, env_false, env_true)
        name = None
        none_test = False
        if isinstance(test, ast.Name):
            name = test.id
        elif isinstance(test, ast.Compare) and len(test.ops) == 1 \
                and isinstance(test.left, ast.Name) \
                and isinstance(test.comparators[0], ast.Constant) \
                and test.comparators[0].value is None:
            name = test.left.id
            none_test = True
            if isinstance(test.ops[0], ast.Is):
                env_true, env_false = env_false, env_true   # x is None
            elif not isinstance(test.ops[0], ast.IsNot):
                return
        if name is None:
            return
        av = env_true.get(name)
        if av is not None and av.t is not None and av.t.is_optional():
            env_true[name] = AV(av.t.without_option())
        if none_test:
            avf = env_false.get(name)
            if avf is not None and avf.t is not None \
                    and avf.t.is_optional():
                env_false[name] = _av(T.NULL, None)

    # -- expressions --------------------------------------------------------
    def eval(self, e: ast.expr, env: dict) -> AV:
        if isinstance(e, ast.Constant):
            v = e.value
            t = T.infer_type(v)
            if t is T.PYOBJECT:
                raise Undecidable(f"constant {v!r} has no columnar type")
            return AV(t, v if isinstance(v, (bool, int, float, str))
                      or v is None else _NO_CONST)
        if isinstance(e, ast.Name):
            return self._load_name(e.id, env)
        if isinstance(e, ast.BinOp):
            return self._binop_av(self.eval(e.left, env), e.op,
                                  self.eval(e.right, env))
        if isinstance(e, ast.UnaryOp):
            return self._unary(e, env)
        if isinstance(e, ast.BoolOp):
            out = self.eval(e.values[0], env)
            for sub in e.values[1:]:
                out = self.join_avs(out, self.eval(sub, env))
            if out.t is None:
                raise Undecidable(out.why)
            return out
        if isinstance(e, ast.Compare):
            # comparisons are type-total for schema purposes: rows whose
            # comparison raises are excluded from the trace anyway
            for sub in (e.left, *e.comparators):
                try:
                    self.eval(sub, env)
                except Undecidable:
                    pass
            return _av(T.BOOL)
        if isinstance(e, ast.IfExp):
            try:
                self.eval(e.test, env)
            except Undecidable:
                pass
            env_t, env_f = dict(env), dict(env)
            self.narrow(e.test, env_t, env_f)
            out = self.join_avs(self.eval(e.body, env_t),
                                self.eval(e.orelse, env_f))
            if out.t is None:
                raise Undecidable(out.why)
            return out
        if isinstance(e, ast.Subscript):
            return self._subscript(e, env)
        if isinstance(e, ast.Call):
            return self._call(e, env)
        if isinstance(e, ast.Attribute):
            return self._attribute(e, env)
        if isinstance(e, ast.JoinedStr):
            for v in e.values:
                if isinstance(v, ast.FormattedValue):
                    try:
                        self.eval(v.value, env)
                    except Undecidable:
                        pass
            return _av(T.STR)
        if isinstance(e, ast.Tuple):
            elts = [self.eval(x, env) for x in e.elts]
            return AV(T.tuple_of(*(a.use() for a in elts)))
        if isinstance(e, ast.List):
            if not e.elts:
                return _av(T.EMPTYLIST)
            elts = [self.eval(x, env) for x in e.elts]
            out = elts[0]
            for a in elts[1:]:
                out = self.join_avs(out, a)
            if out.t is None:
                raise Undecidable(out.why)
            return AV(T.list_of(out.use()))
        if isinstance(e, ast.Dict):
            return self._dict_literal(e, env)
        if isinstance(e, ast.NamedExpr):
            val = self.eval(e.value, env)
            self.assign(e.target, val, env)
            return val
        if isinstance(e, ast.Slice):
            raise Undecidable("bare slice")
        raise Undecidable(
            f"expression {type(e).__name__} outside the abstract domain")

    def _load_name(self, name: str, env: dict) -> AV:
        if name in env:
            av = env[name]
            if av.t is None:
                raise Undecidable(av.why or f"{name!r} undecidable")
            return av
        if name in self.module_names:
            return AV(None, why=f"module {name!r} used as a value")
        if name in self.globals_map:
            v = self.globals_map[name]
            if isinstance(v, (bool, int, float, str)) or v is None:
                t = T.infer_type(v)
                if t is not T.PYOBJECT:
                    return AV(t, v)
            if isinstance(v, (list, tuple, dict)):
                t = T.infer_type(v)
                if t is not T.PYOBJECT:
                    return AV(t)      # container contents, no const
            raise Undecidable(f"captured global {name!r} "
                              f"({type(v).__name__}) undecidable")
        if name in ("True", "False"):     # pragma: no cover - py>=3 keyword
            return _av(T.BOOL, name == "True")
        # unknown free name: builtins used as values, NameError at runtime
        raise Undecidable(f"unbound name {name!r}")

    # -- operators ----------------------------------------------------------
    def _numeric(self, t: T.Type) -> T.Type:
        """Arithmetic operand domain; bools arithmetically act as ints."""
        if t is T.BOOL:
            return T.I64
        if t is T.I64 or t is T.F64:
            return t
        raise Undecidable(f"arithmetic on {t.name}")

    def _binop_av(self, a: AV, op, b: AV) -> AV:
        ta, tb = a.base(), b.base()
        if isinstance(op, ast.Add):
            if ta is T.STR and tb is T.STR:
                return _av(T.STR)
            if isinstance(ta, T.ListType) and isinstance(tb, T.ListType):
                return AV(T.list_of(self.join_types(ta.elt, tb.elt)))
            if isinstance(ta, T.TupleType) and isinstance(tb, T.TupleType):
                return AV(T.tuple_of(*ta.elements, *tb.elements))
            return self._arith(ta, tb)
        if isinstance(op, ast.Mult):
            if ta is T.STR and self._is_intlike(tb):
                return _av(T.STR)
            if self._is_intlike(ta) and tb is T.STR:
                return _av(T.STR)
            if isinstance(ta, T.ListType) and self._is_intlike(tb):
                return AV(ta)
            return self._arith(ta, tb)
        if isinstance(op, (ast.Sub,)):
            return self._arith(ta, tb)
        if isinstance(op, ast.Div):
            self._numeric(ta), self._numeric(tb)
            return _av(T.F64)
        if isinstance(op, ast.FloorDiv):
            na, nb = self._numeric(ta), self._numeric(tb)
            return _av(T.F64 if T.F64 in (na, nb) else T.I64)
        if isinstance(op, ast.Mod):
            if ta is T.STR:
                return _av(T.STR)          # printf-style formatting
            na, nb = self._numeric(ta), self._numeric(tb)
            return _av(T.F64 if T.F64 in (na, nb) else T.I64)
        if isinstance(op, ast.Pow):
            na, nb = self._numeric(ta), self._numeric(tb)
            if T.F64 in (na, nb):
                return _av(T.F64)
            if b.const is not _NO_CONST and isinstance(b.const, int) \
                    and b.const >= 0:
                return _av(T.I64)
            raise Undecidable("int ** int with data-dependent exponent "
                              "(may be float)")
        if isinstance(op, (ast.BitAnd, ast.BitOr, ast.BitXor)):
            if ta is T.BOOL and tb is T.BOOL:
                return _av(T.BOOL)
            self._numeric(ta), self._numeric(tb)
            if T.F64 in (ta, tb):
                raise Undecidable("bitwise op on float")
            return _av(T.I64)
        if isinstance(op, (ast.LShift, ast.RShift)):
            self._numeric(ta), self._numeric(tb)
            return _av(T.I64)
        if isinstance(op, ast.MatMult):
            raise Undecidable("matrix multiply in a UDF")
        raise Undecidable(f"operator {type(op).__name__}")

    def _arith(self, ta: T.Type, tb: T.Type) -> AV:
        na, nb = self._numeric(ta), self._numeric(tb)
        return _av(T.F64 if T.F64 in (na, nb) else T.I64)

    @staticmethod
    def _is_intlike(t: T.Type) -> bool:
        return t is T.I64 or t is T.BOOL

    def _unary(self, e: ast.UnaryOp, env: dict) -> AV:
        if isinstance(e.op, ast.Not):
            try:
                self.eval(e.operand, env)
            except Undecidable:
                pass
            return _av(T.BOOL)
        v = self.eval(e.operand, env)
        t = self._numeric(v.base())
        if isinstance(e.op, ast.Invert):
            if t is T.F64:
                raise Undecidable("~ on float")
            return _av(T.I64)
        return _av(t)

    # -- subscripts against the input RowType -------------------------------
    def _subscript(self, e: ast.Subscript, env: dict) -> AV:
        base = self.eval(e.value, env)
        bt = base.base()
        sl = e.slice
        if isinstance(sl, ast.Slice):
            for part in (sl.lower, sl.upper, sl.step):
                if part is not None:
                    self.eval(part, env)
            if bt is T.STR:
                return _av(T.STR)
            if isinstance(bt, T.ListType):
                return AV(bt)
            if isinstance(bt, T.TupleType):
                raise Undecidable("tuple slice")
            raise Undecidable(f"slice of {bt.name}")
        key = self.eval(sl, env)
        if isinstance(bt, T.RowType):
            if key.const is not _NO_CONST and isinstance(key.const, str):
                if key.const not in bt.columns:
                    raise Undecidable(f"unknown column {key.const!r}")
                return _av(bt.col_type(key.const))
            if key.const is not _NO_CONST and isinstance(key.const, int) \
                    and not isinstance(key.const, bool):
                i = key.const if key.const >= 0 else len(bt) + key.const
                if 0 <= i < len(bt):
                    return _av(bt.types[i])
                raise Undecidable("row index out of range")
            raise Undecidable("row subscript with data-dependent key")
        if base.record is not None and key.const is not _NO_CONST \
                and isinstance(key.const, str):
            names, types = base.record
            if key.const in names:
                return _av(types[names.index(key.const)])
            raise Undecidable(f"unknown dict key {key.const!r}")
        if bt is T.STR:
            return _av(T.STR)
        if isinstance(bt, T.ListType):
            return _av(bt.elt)
        if isinstance(bt, T.TupleType):
            if key.const is not _NO_CONST and isinstance(key.const, int) \
                    and not isinstance(key.const, bool):
                i = key.const if key.const >= 0 else len(bt) + key.const
                if 0 <= i < len(bt):
                    return _av(bt.elements[i])
                raise Undecidable("tuple index out of range")
            out = _av(bt.elements[0])
            for t in bt.elements[1:]:
                out = self.join_avs(out, _av(t))
            if out.t is None:
                raise Undecidable(out.why)
            return out
        if isinstance(bt, T.DictType):
            return _av(bt.val)
        raise Undecidable(f"subscript of {bt.name}")

    # -- attributes / calls -------------------------------------------------
    def _attribute(self, e: ast.Attribute, env: dict) -> AV:
        if isinstance(e.value, ast.Name) \
                and e.value.id not in env \
                and e.value.id in self.module_names:
            mod = self.module_names[e.value.id]
            t = _MODULE_CONSTS.get((mod, e.attr))
            if t is not None:
                return _av(t)
            raise Undecidable(f"module attribute {e.value.id}.{e.attr}")
        raise Undecidable(f"attribute .{e.attr} outside the abstract domain")

    def _call(self, e: ast.Call, env: dict) -> AV:
        if e.keywords and any(k.arg is None for k in e.keywords):
            raise Undecidable("**kwargs call")
        fn = e.func
        # str/list/dict method chains
        if isinstance(fn, ast.Attribute):
            if isinstance(fn.value, ast.Name) and fn.value.id not in env \
                    and fn.value.id in self.module_names:
                mod = self.module_names[fn.value.id]
                res = _MODULE_FNS.get((mod, fn.attr))
                if res is not None:
                    for a in e.args:
                        self.eval(a, env)
                    return _av(res)
                raise Undecidable(f"call {fn.value.id}.{fn.attr}() "
                                  "not in the pure-call table")
            recv = self.eval(fn.value, env)
            return self._method(recv, fn.attr, e.args, env)
        if isinstance(fn, ast.Name) and fn.id not in env:
            return self._builtin(fn.id, e.args, env)
        raise Undecidable("call to a computed function")

    def _method(self, recv: AV, name: str, args, env: dict) -> AV:
        rt = recv.base()
        for a in args:
            self.eval(a, env)
        if rt is T.STR:
            if name in _STR_TO_STR:
                return _av(T.STR)
            if name in _STR_TO_I64:
                return _av(T.I64)
            if name in _STR_TO_BOOL:
                return _av(T.BOOL)
            if name in _STR_TO_LIST:
                return AV(T.list_of(T.STR))
            if name == "partition" or name == "rpartition":
                return AV(T.tuple_of(T.STR, T.STR, T.STR))
            raise Undecidable(f"str method .{name}()")
        if isinstance(rt, T.ListType):
            if name in ("index", "count"):
                return _av(T.I64)
            raise Undecidable(f"list method .{name}()")
        if isinstance(rt, T.DictType):
            if name == "get":
                if len(args) >= 2:
                    return self.join_avs(_av(rt.val),
                                         self.eval(args[1], env))
                self.null_join = self.null_join or \
                    ".get() may return None"
                return AV(T.option(rt.val))
            if name == "keys":
                return AV(T.list_of(rt.key))
            if name == "values":
                return AV(T.list_of(rt.val))
            raise Undecidable(f"dict method .{name}()")
        raise Undecidable(f"method .{name}() on {rt.name}")

    def _builtin(self, name: str, args, env: dict) -> AV:
        shadowed = name in self.globals_map
        if shadowed:
            import builtins

            if self.globals_map[name] is not getattr(builtins, name, object()):
                raise Undecidable(f"{name!r} rebound in the UDF's globals")
        # conversions and len() are type-TOTAL: rows where they raise are
        # excluded from the traced schema, so the static result stands even
        # over undecidable arguments
        if name in ("int", "float", "str", "bool", "len", "ord", "repr"):
            for a in args:
                try:
                    self.eval(a, env)
                except Undecidable:
                    pass
            return _av({"int": T.I64, "float": T.F64, "str": T.STR,
                        "bool": T.BOOL, "len": T.I64, "ord": T.I64,
                        "repr": T.STR}[name])
        avs = [self.eval(a, env) for a in args]
        if name == "abs":
            return _av(self._numeric(avs[0].base()))
        if name in ("min", "max"):
            if len(avs) == 1:
                return self._iter_elt(avs[0])
            out = avs[0]
            for a in avs[1:]:
                out = self.join_avs(out, a)
            if out.t is None:
                raise Undecidable(out.why)
            return out
        if name == "round":
            if len(avs) >= 2:
                return _av(self._numeric(avs[0].base()))
            self._numeric(avs[0].base())
            return _av(T.I64)
        if name == "sum":
            elt = self._iter_elt(avs[0])
            base = self._numeric(elt.base())
            if len(avs) >= 2:
                base = self._arith(base, avs[1].base()).t
            return _av(base)
        if name == "chr":
            return _av(T.STR)
        if name == "sorted":
            elt = self._iter_elt(avs[0])
            return AV(T.list_of(elt.use()))
        raise Undecidable(f"call to {name!r} not in the builtin table")

    def _dict_literal(self, e: ast.Dict, env: dict) -> AV:
        if not e.keys:
            return _av(T.EMPTYDICT)
        names: list = []
        ktypes: list = []
        vtypes: list = []
        all_str = True
        for k, v in zip(e.keys, e.values):
            if k is None:
                raise Undecidable("** splat inside dict literal")
            kav = self.eval(k, env)
            vav = self.eval(v, env)
            ktypes.append(kav.use())
            vtypes.append(vav.use())
            if kav.const is not _NO_CONST and isinstance(kav.const, str):
                names.append(kav.const)
            else:
                all_str = False
        kt = ktypes[0]
        for t in ktypes[1:]:
            kt = T.super_type(kt, t)
        record = (tuple(names), tuple(vtypes)) \
            if all_str and len(set(names)) == len(names) else None
        return AV(T.dict_of(kt, _dict_val_super(vtypes)), record=record)


def _dict_val_super(vtypes) -> T.Type:
    """Generic dict value type: super_type fold, mirroring what
    ``infer_type`` (and therefore the trace) computes for dict values."""
    vt = vtypes[0]
    for t in vtypes[1:]:
        vt = T.super_type(vt, t)
    return vt


# ---------------------------------------------------------------------------
# UDF-level entry
# ---------------------------------------------------------------------------

def infer_udf(udf, param_avs: dict) -> Verdict:
    """Infer the return type of a reflected UDFSource whose parameters are
    pre-bound to abstract values (see the operator entries below for the
    binding conventions)."""
    tree = getattr(udf, "tree", None)
    if tree is None or not getattr(udf, "source", ""):
        return Verdict(None, "no retrievable UDF source")
    module_names = {k: v.__name__.split(".")[0]
                    for k, v in getattr(udf, "globals", {}).items()
                    if _is_module(v)}
    return _infer_node(tree, dict(param_avs), udf.globals, module_names)


def infer_tree(node: ast.AST, module_names=None) -> Verdict:
    """Lint-mode inference: no input schema, every parameter is TOP. Only
    input-independent UDFs (constant shapes, conversions, formatting) come
    out exact — honest for a purely syntactic surface."""
    params = _node_params(node)
    if module_names is None:
        module_names = {}
    elif not isinstance(module_names, dict):
        module_names = {n: n for n in module_names}
    binds = {p: AV(None, why="input row type unknown at lint time")
             for p in params}
    return _infer_node(node, binds, {}, module_names)


def _is_module(v) -> bool:
    import types

    return isinstance(v, types.ModuleType)


def _node_params(node) -> tuple:
    a = getattr(node, "args", None)
    if a is None:
        return ()
    return tuple(x.arg for x in
                 list(getattr(a, "posonlyargs", [])) + a.args)


def _infer_node(node: ast.AST, env: dict, globals_map: dict,
                module_names: dict) -> Verdict:
    # a yield/await anywhere makes the whole function a generator/coroutine
    # — the return value is a generator object, NOT the joined yields. Must
    # be checked up front: `yield x` in expression-statement position would
    # otherwise be swallowed as a discarded value and the fall-through path
    # would claim an (unsound) exact NULL. Nested lambdas can't contain
    # yield (SyntaxError) and nested defs abort as statements, so a whole-
    # tree walk is safe.
    for n in ast.walk(node):
        if isinstance(n, (ast.Yield, ast.YieldFrom, ast.Await)):
            return Verdict(None, "generator/async construct")
    a = getattr(node, "args", None)
    if a is not None and (a.vararg or a.kwarg or a.kwonlyargs
                          or getattr(a, "posonlyargs", [])):
        return Verdict(None, "*args/**kwargs/keyword-only parameters")
    interp = _Abs(globals_map, module_names)
    try:
        if isinstance(node, ast.Lambda):
            ret = interp.eval(node.body, env)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if isinstance(node, ast.AsyncFunctionDef):
                return Verdict(None, "async function")
            falls = interp.exec_block(list(node.body), env)
            if falls:
                interp.returns.append(_av(T.NULL, None))
            if not interp.returns:
                return Verdict(None, "function never returns a value")
            ret = interp.returns[0]
            for r in interp.returns[1:]:
                ret = interp.join_avs(ret, r)
            if ret.t is None:
                raise Undecidable(ret.why)
        else:
            return Verdict(None, f"unsupported UDF node "
                                 f"{type(node).__name__}")
        rt = ret.use()
    except Undecidable as e:
        return Verdict(None, e.why)
    except RecursionError:       # pragma: no cover - pathological nesting
        return Verdict(None, "AST too deep")
    if interp.null_join:
        return Verdict(None, interp.null_join, shape=rt)
    if ret.record is not None:
        rt = T.row_of(*ret.record)
    if rt is T.PYOBJECT or rt is T.UNKNOWN:
        return Verdict(None, f"inferred {rt.name}")
    return Verdict(rt)


# ---------------------------------------------------------------------------
# operator-level entry (mirrors plan/logical.py apply_udf_python)
# ---------------------------------------------------------------------------

def _bind_params(udf, schema: T.RowType) -> Optional[dict]:
    """Bind UDF parameters to abstract values the way apply_udf_python
    binds concrete ones: multi-param UDFs spread the row, named rows pass
    the Row itself, single unnamed columns pass the bare value, unnamed
    multi-column rows pass a tuple."""
    from ..runtime.columns import user_columns

    params = _node_params(getattr(udf, "tree", None))
    if getattr(udf, "tree", None) is None:
        return None
    nparams = len(params) if params else 1
    if not params:
        return {}
    if nparams > 1:
        if len(schema.types) == nparams:
            return {p: _av(t) for p, t in zip(params, schema.types)}
        return None
    if user_columns(schema) is not None:
        return {params[0]: AV(schema)}
    if len(schema.types) == 1:
        return {params[0]: _av(schema.types[0])}
    return {params[0]: AV(T.tuple_of(*schema.types))}


def op_static_verdict(op) -> Optional[Verdict]:
    """Per-operator inference verdict against the PARENT schema, memoized
    on the operator (operators are immutable once planned). None for
    operator kinds static typing does not cover (filters pass their schema
    through without sampling anyway; aggregates/joins stay traced)."""
    memo = getattr(op, "_ti_verdict", False)
    if memo is not False:
        return memo
    v = _op_static_verdict_uncached(op)
    try:
        op._ti_verdict = v
    except (AttributeError, TypeError):      # pragma: no cover
        pass
    if v is not None:
        _stamp_report(op, v)
    return v


def _op_static_verdict_uncached(op) -> Optional[Verdict]:
    from ..plan import logical as L

    if not isinstance(op, (L.MapOperator, L.WithColumnOperator,
                           L.MapColumnOperator)):
        return None
    from ..compiler.analyzer import STATS
    from ..runtime import tracing as _tr

    with _tr.span("plan:infer-type", "plan") as _sp:
        try:
            ps = op.parent.schema()
        except Exception as e:
            return Verdict(None, f"parent schema unavailable "
                                 f"({type(e).__name__})")
        if isinstance(op, L.MapColumnOperator):
            if op.column not in (ps.columns or ()):
                v = Verdict(None, f"unknown column {op.column!r}")
            else:
                ci = ps.columns.index(op.column)
                v = infer_udf(op.udf, _binds_or_none(op.udf,
                                                     [ps.types[ci]]))
        else:
            binds = _bind_params(op.udf, ps)
            if binds is None:
                v = Verdict(None, "parameter/row arity mismatch")
            else:
                v = infer_udf(op.udf, binds)
        if v.exact and isinstance(op, L.MapOperator):
            # a map's TOP-LEVEL dict result without a record view (non-
            # constant keys, duplicate keys, captured dicts) cannot be
            # schema'd statically: the trace names output columns from the
            # OBSERVED keys, which are data. A record-view dict already
            # became a RowType in _infer_node; any Dict that survives here
            # is record-less — widen, never guess (soundness contract)
            base = v.type.without_option() if v.type.is_optional() \
                else v.type
            if isinstance(base, T.DictType) or base is T.EMPTYDICT:
                v = Verdict(None, "dict result without a constant key "
                                  "set (column names are data)",
                            shape=v.type)
        if v.exact:
            STATS["inferred_ops"] += 1
        if _sp is not _tr.NOOP:
            _sp.set("op", type(op).__name__).set("exact", v.exact)
            _sp.set("type", v.type.name if v.exact else (v.why or "?"))
    return v


def _binds_or_none(udf, types) -> dict:
    """Single-value binding for mapColumn (the operator calls udf.func on
    the bare cell, not through apply_udf_python)."""
    params = _node_params(getattr(udf, "tree", None))
    if len(params) != 1:
        return {p: AV(None, why="mapColumn UDF must take one parameter")
                for p in params}
    return {params[0]: _av(types[0])}


def _stamp_report(op, v: Verdict) -> None:
    """Expose the verdict on the operator's memoized UDFReport (a per-op
    COPY — reports are memoized per code object and two operators sharing
    a UDF may see different input schemas). Best-effort: lint surfaces
    read it, nothing depends on it."""
    try:
        import dataclasses

        from . import analyzer as az

        entries = az.op_reports(op)
        for i, (attr, rep) in enumerate(entries):
            if attr == "udf":
                entries[i] = (attr, dataclasses.replace(
                    rep,
                    inferred_type=v.type,
                    inferred_why="" if v.exact else (v.why or "undecidable")))
                break
    except Exception:       # pragma: no cover - advisory surface only
        pass


def static_op_schema(op):
    """The operator's exact output RowType when statically decidable under
    the current gate, else None (the caller then runs the sample trace).
    Output shapes mirror the traced ``_infer_schema`` implementations."""
    if not enabled():
        return None
    from ..plan import logical as L

    v = op_static_verdict(op)
    if v is None or not v.exact:
        return None
    t = v.type
    if isinstance(op, L.MapColumnOperator):
        ps = op.parent.schema()
        types = list(ps.types)
        types[ps.columns.index(op.column)] = t
        return T.row_of(ps.columns, types)
    if isinstance(op, L.WithColumnOperator):
        from ..runtime.columns import user_columns

        ps = op.parent.schema()
        if user_columns(ps) is None:
            return None          # the traced path raises; keep its message
        if isinstance(t, T.RowType):
            return None          # withColumn cells hold values, not records
        cols = list(ps.columns)
        types = list(ps.types)
        if op.column in cols:
            types[cols.index(op.column)] = t
        else:
            cols.append(op.column)
            types.append(t)
        return T.row_of(cols, types)
    if isinstance(op, L.MapOperator):
        if isinstance(t, T.RowType):       # dict-literal output: named cols
            return t
        if isinstance(t, T.TupleType):
            return T.row_of([f"_{i}" for i in range(len(t))],
                            list(t.elements))
        return T.row_of(["_0"], [t])
    return None
