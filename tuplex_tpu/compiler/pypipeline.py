"""Per-stage compiled Python fallback pipeline.

The reference generates ONE Python pipeline function per stage and calls it
per exception row (reference: core/src/physical/PythonPipelineBuilder.cc:1-60
generated Row class + per-op try/except chain; ResolveTask.h:31-98 drives
it). Round 1 instead interpreted the operator list per row — isinstance
dispatch, resolver scans, and column-index lookups on every single row made
the slow path ~20x slower than a naive Python loop.

This module is the closure-chain equivalent of the reference's codegen: all
per-op decisions (UDF calling convention, column indices, cell decoders,
resolver lists) are taken ONCE at build time; the returned `pipeline(row)`
touches only prebuilt closures. Exceptions return as plain tuples
(op_id, exc_name, row_value) so this module stays import-light.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from ..core import typesys as T
from ..core.errors import TuplexException
from ..core.row import Row
from ..plan import logical as L

_UNHANDLED = object()


def _make_udf_caller(udf) -> Callable[[Row], Any]:
    """Bind the interpreter calling convention once (mirrors
    L.apply_udf_python exactly)."""
    f = udf.func
    nparams = len(udf.params) if udf.params else 1

    def call(row: Row):
        if nparams > 1 and len(row.values) == nparams:
            return f(*row.values)
        if row.columns is not None:
            return f(row)
        if len(row.values) == 1:
            return f(row.values[0])
        return f(tuple(row.values))

    return call


def _make_cell_decoder(t: T.Type, null_values) -> Callable[[Any], Any]:
    """Per-column general-case decoder (mirrors L.decode_cell_python: parse
    to the normal-case type when possible, else the raw string survives)."""
    nulls = frozenset(null_values)
    base = t.without_option() if t.is_optional() else t

    if base is T.I64:
        def dec(cell):
            if cell is None or not isinstance(cell, str):
                return cell
            if cell in nulls:
                return None
            try:
                return int(cell)
            except ValueError:
                return cell
    elif base is T.F64:
        def dec(cell):
            if cell is None or not isinstance(cell, str):
                return cell
            if cell in nulls:
                return None
            try:
                return float(cell)
            except ValueError:
                return cell
    elif base is T.BOOL:
        def dec(cell):
            if cell is None or not isinstance(cell, str):
                return cell
            if cell in nulls:
                return None
            low = cell.strip().lower()
            if low == "true":
                return True
            if low == "false":
                return False
            return cell
    else:
        def dec(cell):
            if isinstance(cell, str) and cell in nulls:
                return None
            return cell
    return dec


def _build_op(op: L.LogicalOperator):
    """(apply_fn, inject_fn) for one operator. apply_fn(row)->row'|None runs
    the op; inject_fn(v, row)->row'|None wraps a RESOLVER result v the same
    way the op would wrap its own output (mirrors _apply_resolver_python)."""
    if isinstance(op, L.MapOperator):
        call = _make_udf_caller(op.udf)
        cols = op.columns()

        def inject(v, row):
            if isinstance(v, dict):
                return Row(list(v.values()), list(v.keys()))
            return Row.from_value(v, cols)

        def apply(row):
            return inject(call(row), row)

        return apply, inject

    if isinstance(op, L.FilterOperator):
        call = _make_udf_caller(op.udf)

        def inject(v, row):
            return row if v else None

        def apply(row):
            return row if call(row) else None

        return apply, inject

    if isinstance(op, L.WithColumnOperator):
        call = _make_udf_caller(op.udf)
        col = op.column

        def inject(v, row):
            cols = list(row.columns or ())
            vals = list(row.values)
            if col in cols:
                vals[cols.index(col)] = v
            else:
                cols.append(col)
                vals.append(v)
            return Row(vals, cols)

        def apply(row):
            return inject(call(row), row)

        return apply, inject

    if isinstance(op, L.MapColumnOperator):
        f = op.udf.func
        col = op.column
        idx_cache: dict = {}

        def _ci(row):
            cols = row.columns or ()
            ci = idx_cache.get(cols)
            if ci is None:
                ci = list(cols).index(col)
                idx_cache[cols] = ci
            return ci

        def inject(v, row):
            vals = list(row.values)
            vals[_ci(row)] = v
            return Row(vals, row.columns)

        def apply(row):
            vals = list(row.values)
            ci = _ci(row)
            vals[ci] = f(vals[ci])
            return Row(vals, row.columns)

        return apply, inject

    if isinstance(op, L.SelectColumnsOperator):
        out_cols = op.schema().columns
        selected = op.selected
        static_idx = None
        try:
            static_idx = op._resolve_indices()
        except Exception:
            pass
        idx_cache: dict = {}

        def _idx(row):
            if row.columns is None:
                return static_idx
            key = row.columns
            idx = idx_cache.get(key)
            if idx is None:
                cols = list(key)
                idx = [cols.index(c) if isinstance(c, str)
                       else (c if c >= 0 else len(row.values) + c)
                       for c in selected]
                idx_cache[key] = idx
            return idx

        def inject(v, row):
            return Row.from_value(v, out_cols)

        def apply(row):
            return Row([row.values[i] for i in _idx(row)], out_cols)

        return apply, inject

    if isinstance(op, L.RenameColumnOperator):
        out_cols = op.schema().columns

        def inject(v, row):
            return Row.from_value(v, out_cols)

        def apply(row):
            return Row(row.values, out_cols)

        return apply, inject

    if isinstance(op, L.DecodeOperator):
        from ..runtime.columns import user_columns

        decs = [_make_cell_decoder(t, op.null_values)
                for t in op.declared.types]
        out_cols = user_columns(op.declared)

        def inject(v, row):
            return Row.from_value(v, out_cols)

        def apply(row):
            return Row([d(v) for d, v in zip(decs, row.values)], out_cols)

        return apply, inject

    raise TuplexException(f"interpreter: unsupported op {op!r}")


def build_python_pipeline(ops: list) -> Callable[[Row], tuple]:
    """ONE closure per stage: pipeline(row) -> ("ok", Row) | ("drop", None)
    | ("exc", (op_id, exc_name, row_value))."""
    steps = []
    i = 0
    while i < len(ops):
        op = ops[i]
        if isinstance(op, (L.ResolveOperator, L.IgnoreOperator,
                           L.TakeOperator)):
            i += 1
            continue
        resolvers = []
        j = i + 1
        while j < len(ops) and isinstance(
                ops[j], (L.ResolveOperator, L.IgnoreOperator)):
            r = ops[j]
            if isinstance(r, L.IgnoreOperator):
                resolvers.append((r.exc_class, None))
            else:
                resolvers.append((r.exc_class, _make_udf_caller(r.udf)))
            j += 1
        apply_fn, inject_fn = _build_op(op)
        steps.append((apply_fn, inject_fn, isinstance(op, L.FilterOperator),
                      tuple(resolvers), op.id))
        i += 1

    def pipeline(row: Row) -> tuple:
        for apply_fn, inject_fn, is_filter, resolvers, op_id in steps:
            try:
                row2 = apply_fn(row)
            except Exception as e:
                row2 = _UNHANDLED
                for exc_class, res_call in resolvers:
                    if isinstance(e, exc_class):
                        if res_call is None:
                            return ("drop", None)
                        try:
                            row2 = inject_fn(res_call(row), row)
                            break
                        except Exception:
                            pass  # resolver itself raised: try next
                if row2 is _UNHANDLED:
                    return ("exc", (op_id, type(e).__name__, row.unwrap()))
            if row2 is None and is_filter:
                return ("drop", None)
            row = row2
        return ("ok", row)

    return pipeline
