"""Per-stage compiled Python fallback pipeline.

The reference generates ONE Python pipeline function per stage and calls it
per exception row (reference: core/src/physical/PythonPipelineBuilder.cc:1-60
generated Row class + per-op try/except chain; ResolveTask.h:31-98 drives
it). Round 1 instead interpreted the operator list per row — isinstance
dispatch, resolver scans, and column-index lookups on every single row made
the slow path ~20x slower than a naive Python loop.

Two tiers here, both built ONCE per stage:

* source tier (`_try_build_source_pipeline`) — the real PythonPipelineBuilder
  analog: generates one Python function with row fields as plain locals and
  each UDF's dict access rewritten to positional parameters (reference:
  UDF.h:183 rewriteDictAccessInAST), then `exec`s it. No Row objects, no
  per-op list copies on the good-row path; exceptions drop into prebuilt
  per-op resolver helpers.
* closure tier (`_build_closure_pipeline`) — per-op closures chained in a
  loop; used when the stage shape can't be source-specialized (dynamic
  column names, mid-chain Map, odd row arities) and as the per-row escape
  hatch for rows whose shape doesn't match the generated code.

Exceptions return as plain tuples (op_id, exc_name, row_value) so this
module stays import-light.
"""

from __future__ import annotations

import ast
from typing import Any, Callable, Optional

from ..core import typesys as T
from ..core.errors import TuplexException
from ..core.row import Row
from ..plan import logical as L

_UNHANDLED = object()


def _make_udf_caller(udf) -> Callable[[Row], Any]:
    """Bind the interpreter calling convention once (mirrors
    L.apply_udf_python exactly)."""
    f = udf.func
    nparams = len(udf.params) if udf.params else 1

    def call(row: Row):
        if nparams > 1 and len(row.values) == nparams:
            return f(*row.values)
        if row.columns is not None:
            return f(row)
        if len(row.values) == 1:
            return f(row.values[0])
        return f(tuple(row.values))

    return call


def _make_cell_decoder(t: T.Type, null_values) -> Callable[[Any], Any]:
    """Per-column general-case decoder (mirrors L.decode_cell_python: parse
    to the normal-case type when possible, else the raw string survives)."""
    nulls = frozenset(null_values)
    base = t.without_option() if t.is_optional() else t

    if base is T.I64:
        def dec(cell):
            if cell is None or not isinstance(cell, str):
                return cell
            if cell in nulls:
                return None
            try:
                return int(cell)
            except ValueError:
                return cell
    elif base is T.F64:
        def dec(cell):
            if cell is None or not isinstance(cell, str):
                return cell
            if cell in nulls:
                return None
            try:
                return float(cell)
            except ValueError:
                return cell
    elif base is T.BOOL:
        def dec(cell):
            if cell is None or not isinstance(cell, str):
                return cell
            if cell in nulls:
                return None
            low = cell.strip().lower()
            if low == "true":
                return True
            if low == "false":
                return False
            return cell
    else:
        def dec(cell):
            if isinstance(cell, str) and cell in nulls:
                return None
            return cell
    return dec


def _build_op(op: L.LogicalOperator):
    """(apply_fn, inject_fn) for one operator. apply_fn(row)->row'|None runs
    the op; inject_fn(v, row)->row'|None wraps a RESOLVER result v the same
    way the op would wrap its own output (mirrors _apply_resolver_python)."""
    if isinstance(op, L.MapOperator):
        call = _make_udf_caller(op.udf)
        cols = op.columns()

        def inject(v, row):
            if isinstance(v, dict):
                return Row(list(v.values()), list(v.keys()))
            return Row.from_value(v, cols)

        def apply(row):
            return inject(call(row), row)

        return apply, inject

    if isinstance(op, L.FilterOperator):
        call = _make_udf_caller(op.udf)

        def inject(v, row):
            return row if v else None

        def apply(row):
            return row if call(row) else None

        return apply, inject

    if isinstance(op, L.WithColumnOperator):
        call = _make_udf_caller(op.udf)
        col = op.column

        def inject(v, row):
            cols = list(row.columns or ())
            vals = list(row.values)
            if col in cols:
                vals[cols.index(col)] = v
            else:
                cols.append(col)
                vals.append(v)
            return Row(vals, cols)

        def apply(row):
            return inject(call(row), row)

        return apply, inject

    if isinstance(op, L.MapColumnOperator):
        f = op.udf.func
        col = op.column
        idx_cache: dict = {}

        def _ci(row):
            cols = row.columns or ()
            ci = idx_cache.get(cols)
            if ci is None:
                ci = list(cols).index(col)
                idx_cache[cols] = ci
            return ci

        def inject(v, row):
            vals = list(row.values)
            vals[_ci(row)] = v
            return Row(vals, row.columns)

        def apply(row):
            vals = list(row.values)
            ci = _ci(row)
            vals[ci] = f(vals[ci])
            return Row(vals, row.columns)

        return apply, inject

    if isinstance(op, L.SelectColumnsOperator):
        out_cols = op.schema().columns
        selected = op.selected
        static_idx = None
        try:
            static_idx = op._resolve_indices()
        except Exception:
            pass
        idx_cache: dict = {}

        def _idx(row):
            if row.columns is None:
                return static_idx
            key = row.columns
            idx = idx_cache.get(key)
            if idx is None:
                cols = list(key)
                idx = [cols.index(c) if isinstance(c, str)
                       else (c if c >= 0 else len(row.values) + c)
                       for c in selected]
                idx_cache[key] = idx
            return idx

        def inject(v, row):
            return Row.from_value(v, out_cols)

        def apply(row):
            return Row([row.values[i] for i in _idx(row)], out_cols)

        return apply, inject

    if isinstance(op, L.RenameColumnOperator):
        out_cols = op.schema().columns

        def inject(v, row):
            return Row.from_value(v, out_cols)

        def apply(row):
            return Row(row.values, out_cols)

        return apply, inject

    if isinstance(op, L.DecodeOperator):
        from ..runtime.columns import user_columns

        decs = [_make_cell_decoder(t, op.null_values)
                for t in op.declared.types]
        out_cols = user_columns(op.declared)

        def inject(v, row):
            return Row.from_value(v, out_cols)

        def apply(row):
            return Row([d(v) for d, v in zip(decs, row.values)], out_cols)

        return apply, inject

    raise TuplexException(f"interpreter: unsupported op {op!r}")


def build_python_pipeline(ops: list, input_names: Optional[tuple] = None
                          ) -> Callable[[Row], tuple]:
    """ONE function per stage: pipeline(row) -> ("ok", Row) | ("drop", None)
    | ("exc", (op_id, exc_name, row_value)). Tries the generated-source tier
    first (needs the runtime input column names); falls back to closures."""
    closure = _build_closure_pipeline(ops)
    if input_names:
        src = _try_build_source_pipeline(ops, tuple(input_names), closure)
        if src is not None:
            return src
    return closure


def _build_closure_pipeline(ops: list) -> Callable[[Row], tuple]:
    steps = []
    i = 0
    while i < len(ops):
        op = ops[i]
        if isinstance(op, (L.ResolveOperator, L.IgnoreOperator,
                           L.TakeOperator)):
            i += 1
            continue
        resolvers = []
        j = i + 1
        while j < len(ops) and isinstance(
                ops[j], (L.ResolveOperator, L.IgnoreOperator)):
            r = ops[j]
            if isinstance(r, L.IgnoreOperator):
                resolvers.append((r.exc_class, None))
            else:
                resolvers.append((r.exc_class, _make_udf_caller(r.udf)))
            j += 1
        apply_fn, inject_fn = _build_op(op)
        steps.append((apply_fn, inject_fn, isinstance(op, L.FilterOperator),
                      tuple(resolvers), op.id))
        i += 1

    def pipeline(row: Row) -> tuple:
        for apply_fn, inject_fn, is_filter, resolvers, op_id in steps:
            try:
                row2 = apply_fn(row)
            except Exception as e:
                row2 = _UNHANDLED
                for exc_class, res_call in resolvers:
                    if isinstance(e, exc_class):
                        if res_call is None:
                            return ("drop", None)
                        try:
                            row2 = inject_fn(res_call(row), row)
                            break
                        except Exception:
                            pass  # resolver itself raised: try next
                if row2 is _UNHANDLED:
                    return _exc_result(op_id, e, row.unwrap())
            if row2 is None and is_filter:
                return ("drop", None)
            row = row2
        return ("ok", row)

    return pipeline


# ===========================================================================
# source tier — PythonPipelineBuilder.cc analog
# ===========================================================================

class _RowParamRewriter(ast.NodeTransformer):
    """Rewrite `x["col"]` / `x[i]` on the row parameter into positional
    argument names (reference: UDF.h:183 rewriteDictAccessInAST). Any other
    use of the row parameter marks the UDF non-specializable."""

    def __init__(self, param: str, names: tuple):
        self.param = param
        self.names = names
        self.used: dict[int, str] = {}     # column index -> arg name
        self.failed = False

    def _arg_for(self, ci: int) -> ast.Name:
        name = self.used.get(ci)
        if name is None:
            name = f"_a{ci}"
            self.used[ci] = name
        return ast.Name(id=name, ctx=ast.Load())

    def visit_Subscript(self, node: ast.Subscript):
        # match BEFORE generic_visit: descending first would see the row
        # param's Name node and wrongly flag the UDF as non-specializable
        if isinstance(node.value, ast.Name) and node.value.id == self.param \
                and isinstance(node.ctx, ast.Load) \
                and isinstance(node.slice, ast.Constant):
            key = node.slice.value
            if isinstance(key, str) and key in self.names:
                return self._arg_for(self.names.index(key))
            if isinstance(key, int) and not isinstance(key, bool) \
                    and -len(self.names) <= key < len(self.names):
                return self._arg_for(key % len(self.names))
        self.generic_visit(node)
        return node

    def visit_Name(self, node: ast.Name):
        if node.id == self.param:
            self.failed = True  # row escapes (passed whole / reassigned)
        return node

    def _visit_nested_scope(self, node):
        # a nested lambda/def whose parameter shadows the row param creates
        # a NEW binding: its subscripts must NOT be rewritten to the outer
        # row's columns. ast.arg isn't a Name, so visit_Name can't catch it.
        if any(a.arg == self.param for a in
               node.args.posonlyargs + node.args.args + node.args.kwonlyargs):
            self.failed = True
            return node
        if node.args.vararg and node.args.vararg.arg == self.param:
            self.failed = True
            return node
        if node.args.kwarg and node.args.kwarg.arg == self.param:
            self.failed = True
            return node
        self.generic_visit(node)
        return node

    def visit_Lambda(self, node: ast.Lambda):
        return self._visit_nested_scope(node)

    def visit_FunctionDef(self, node):
        return self._visit_nested_scope(node)

    def visit_AsyncFunctionDef(self, node):
        self.failed = True
        return node


_SPEC_COUNTER = [0]


def _specialize_udf(udf, names: tuple):
    """(callable, arg_column_indices) taking the accessed columns
    positionally, or None if the UDF can't be specialized."""
    if not udf.source or udf.tree is None:
        return None
    from .analyzer import analyze_udf

    if analyze_udf(udf).mutates_globals:
        # the analyzer's verdict: a global/closure-mutating UDF must run as
        # the LIVE function object — the rebuilt specialization executes
        # against a COPY of the globals dict, so its writes would silently
        # diverge from interpreter semantics
        return None
    a = udf.tree.args
    if a.vararg or a.kwarg or a.kwonlyargs or a.posonlyargs or a.defaults:
        return None   # exotic signatures keep the generic calling convention
    if getattr(udf.tree, "decorator_list", None):
        return None   # decorators change behavior; the live func must run
    params = udf.params
    if len(params) > 1:
        # multi-param UDF spreads row fields across params already
        if len(params) == len(names):
            return udf.func, list(range(len(names)))
        return None
    if len(params) != 1:
        return None
    tree = udf.tree
    import copy

    body = copy.deepcopy(
        tree.body if isinstance(tree, ast.Lambda) else tree)
    rw = _RowParamRewriter(params[0], names)
    if isinstance(tree, ast.Lambda):
        new_body = rw.visit(body)
        if rw.failed:
            return None
        arg_cis = sorted(rw.used)
        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=rw.used[ci]) for ci in arg_cis],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        fn_ast = ast.Lambda(args=args, body=new_body)
        mod = ast.Expression(body=fn_ast)
        ast.fix_missing_locations(mod)
        code = compile(mod, f"<tpx-spec-{udf.name}>", "eval")
        glb = dict(udf.globals)
        fn = eval(code, glb)  # noqa: S307 — our own rewritten UDF source
        return fn, arg_cis
    # FunctionDef
    new_stmts = [rw.visit(s) for s in body.body]
    if rw.failed:
        return None
    arg_cis = sorted(rw.used)
    _SPEC_COUNTER[0] += 1
    fname = f"_tpx_spec_{_SPEC_COUNTER[0]}"
    args = ast.arguments(
        posonlyargs=[], args=[ast.arg(arg=rw.used[ci]) for ci in arg_cis],
        kwonlyargs=[], kw_defaults=[], defaults=[])
    fn_ast = ast.FunctionDef(name=fname, args=args, body=new_stmts,
                             decorator_list=[], type_params=[])
    mod = ast.Module(body=[fn_ast], type_ignores=[])
    ast.fix_missing_locations(mod)
    code = compile(mod, f"<tpx-spec-{udf.name}>", "exec")
    glb = dict(udf.globals)
    exec(code, glb)  # noqa: S102 — our own rewritten UDF source
    return glb[fname], arg_cis


def _make_resolver_helper(op, resolvers, names: tuple):
    """Exception-path handler for one generated op: tries the attached
    resolvers against a freshly boxed Row (rare path — Row cost fine).
    Returns status codes: 0=resolved value, 1=drop, 2=unhandled."""
    _, inject_fn = _build_op(op)
    res = [(cls, _make_udf_caller(r.udf) if r is not None else None)
           for cls, r in resolvers]

    def handle(e, vals: tuple):
        row = Row(vals, names)
        for exc_class, res_call in res:
            if isinstance(e, exc_class):
                if res_call is None:
                    return 1, None
                try:
                    return 0, inject_fn(res_call(row), row)
                except Exception:
                    pass
        return 2, None

    return handle


def _try_build_source_pipeline(ops: list, input_names: tuple, closure):
    """Generate + exec ONE Python function for the stage; None when the
    stage shape can't be specialized (dynamic names, mid-chain Map, ...).

    Layout: each current column lives in a local `c<slot>`; ops append or
    rewrite slots; the good-row path never builds a Row or copies a list.
    Rows whose arity/names don't match the generated layout delegate to the
    closure tier at entry — exact parity by construction."""
    steps = []
    i = 0
    while i < len(ops):
        op = ops[i]
        if isinstance(op, (L.ResolveOperator, L.IgnoreOperator,
                           L.TakeOperator)):
            i += 1
            continue
        resolvers = []
        j = i + 1
        while j < len(ops) and isinstance(
                ops[j], (L.ResolveOperator, L.IgnoreOperator)):
            r = ops[j]
            resolvers.append((r.exc_class,
                              None if isinstance(r, L.IgnoreOperator) else r))
            j += 1
        steps.append((op, resolvers))
        i += 1

    names = tuple(input_names)
    k_in = len(names)
    env: dict[str, Any] = {"_Row": Row, "_closure": closure,
                           "_DROP": ("drop", None), "_exc": _exc_result,
                           "_IN_NAMES": names}
    src: list[str] = ["def _tpx_pipeline(_row):",
                      "    _v = _row.values",
                      f"    if len(_v) != {k_in} or "
                      "_row.columns != _IN_NAMES:",
                      "        return _closure(_row)"]
    for ci in range(k_in):
        src.append(f"    c{ci} = _v[{ci}]")
    cur = list(range(k_in))     # local slot per current column
    next_slot = k_in

    def row_tuple() -> str:
        """Expression for the current row as a TUPLE of locals."""
        return "(" + ", ".join(f"c{s}" for s in cur) + ("," if len(cur) == 1
                                                        else "") + ")"

    def row_unwrapped() -> str:
        """Expression matching Row.unwrap(): bare value for single column."""
        return f"c{cur[0]}" if len(cur) == 1 else row_tuple()

    def udf_call_expr(si: int, udf) -> Optional[str]:
        """Call expression for a UDF over the current columns; specialized
        to positional locals when possible, else a boxed-Row call."""
        spec = _specialize_udf(udf, names)
        if spec is not None:
            fn, arg_cis = spec
            env[f"_u{si}"] = fn
            return f"_u{si}(" + ", ".join(f"c{cur[ci]}"
                                          for ci in arg_cis) + ")"
        env[f"_u{si}"] = _make_udf_caller(udf)
        env[f"_nm{si}"] = names
        return f"_u{si}(_Row({row_tuple()}, _nm{si}))"

    def emit_handler(si: int, op, resolvers, on_resolved: list[str]):
        """except-block body: resolver cascade then exception record."""
        if resolvers:
            env[f"_h{si}"] = _make_resolver_helper(op, resolvers, names)
            src.append(f"        _st, _x = _h{si}(_e, {row_tuple()})")
            src.append("        if _st == 1:")
            src.append("            return _DROP")
            src.append("        if _st == 2:")
            src.append(f"            return _exc({op.id}, _e, "
                       f"{row_unwrapped()})")
            src.extend(on_resolved)
        else:
            src.append(f"        return _exc({op.id}, _e, "
                       f"{row_unwrapped()})")

    for si, (op, resolvers) in enumerate(steps):
        is_last = si == len(steps) - 1
        if isinstance(op, L.DecodeOperator):
            from ..runtime.columns import user_columns

            out_cols = user_columns(op.declared)
            if out_cols is None or len(out_cols) != len(cur) or \
                    len(op.declared.types) != len(cur):
                return None
            for m, s in enumerate(cur):
                env[f"_d{si}_{m}"] = _make_cell_decoder(
                    op.declared.types[m], op.null_values)
                src.append(f"    c{s} = _d{si}_{m}(c{s})")
            names = tuple(out_cols)
        elif isinstance(op, L.WithColumnOperator):
            call = udf_call_expr(si, op.udf)
            replace = op.column in names
            slot = cur[names.index(op.column)] if replace else next_slot
            inj_idx = names.index(op.column) if replace else len(cur)
            src.append("    try:")
            src.append(f"        c{slot} = {call}")
            src.append("    except Exception as _e:")
            emit_handler(si, op, resolvers,
                         [f"        c{slot} = _x.values[{inj_idx}]"])
            if not replace:
                next_slot += 1
                cur.append(slot)
                names = names + (op.column,)
        elif isinstance(op, L.MapColumnOperator):
            if op.column not in names:
                return None
            ci = names.index(op.column)
            slot = cur[ci]
            env[f"_u{si}"] = op.udf.func
            src.append("    try:")
            src.append(f"        c{slot} = _u{si}(c{slot})")
            src.append("    except Exception as _e:")
            emit_handler(si, op, resolvers,
                         [f"        c{slot} = _x.values[{ci}]"])
        elif isinstance(op, L.FilterOperator):
            call = udf_call_expr(si, op.udf)
            src.append("    try:")
            src.append(f"        if not {call}:")
            src.append("            return _DROP")
            src.append("    except Exception as _e:")
            emit_handler(si, op, resolvers,
                         ["        if _x is None:",
                          "            return _DROP"])
        elif isinstance(op, L.SelectColumnsOperator):
            idx = []
            for c in op.selected:
                if isinstance(c, int) and not isinstance(c, bool):
                    if not -len(cur) <= c < len(cur):
                        return None
                    idx.append(c % len(cur))
                elif isinstance(c, str) and c in names:
                    idx.append(names.index(c))
                else:
                    return None
            # duplicated selections get their OWN slot: later in-place ops
            # (mapColumn / withColumn replace) target the first occurrence
            # only (tuple.index semantics) and must not write through an alias
            seen: set = set()
            new_cur = []
            for i2 in idx:
                s = cur[i2]
                if s in seen:
                    src.append(f"    c{next_slot} = c{s}")
                    s = next_slot
                    next_slot += 1
                seen.add(s)
                new_cur.append(s)
            cur = new_cur
            names = tuple(op.schema().columns)
            if len(names) != len(cur):
                return None
        elif isinstance(op, L.RenameColumnOperator):
            names = tuple(op.schema().columns)
            if len(names) != len(cur):
                return None
        elif isinstance(op, L.MapOperator) and is_last:
            # terminal map: generic result wrapping (dict/tuple/bare)
            call = udf_call_expr(si, op.udf)
            _, inject = _build_op(op)
            env[f"_inj{si}"] = inject
            src.append("    try:")
            src.append(f"        _x = {call}")
            src.append("    except Exception as _e:")
            emit_handler(si, op, resolvers,
                         ["        return (\"ok\", _x)"])
            src.append(f"    return (\"ok\", _inj{si}(_x, None))")
            return _finish_source(src, env)
        else:
            return None   # unsupported op shape for the source tier

    env["_OUT_NAMES"] = names
    src.append(f"    return (\"ok\", _Row({row_tuple()}, _OUT_NAMES))")
    return _finish_source(src, env)


_TRACE_SAMPLE_CAP = 8    # cleaned tracebacks formatted per process (cost cap)
_trace_samples = [0]


def _exc_result(op_id: int, e: BaseException, rowval):
    """Exception row payload; the first few per process carry a cleaned
    traceback (framework frames stripped — utils/repl.py, reference:
    python/tuplex/utils/tracebacks.py) for exception_counts / webui samples."""
    trace = None
    if _trace_samples[0] < _TRACE_SAMPLE_CAP:
        _trace_samples[0] += 1
        from ..utils.repl import clean_udf_traceback

        try:
            trace = clean_udf_traceback(e)
        except Exception:
            trace = None
    return ("exc", (op_id, type(e).__name__, rowval, trace))


def _finish_source(src: list, env: dict):
    code = "\n".join(src)
    try:
        exec(compile(code, "<tpx-pipeline>", "exec"), env)  # noqa: S102
    except SyntaxError:
        return None
    return env["_tpx_pipeline"]
