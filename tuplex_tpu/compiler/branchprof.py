"""Sample-driven branch profiles for speculative if/else pruning.

The reference prunes UDF branches its row sample never takes and lets
violating rows fall to the general/interpreter ladder (reference:
codegen/src/RemoveDeadBranchesVisitor.cc:1-147, fed by TraceVisitor branch
annotations, core/include/TraceVisitor.h:25-80). The emitter here predicates
both arms of every if/else under boolean masks — correct, but every row pays
device compute for arms almost no row takes.

This module produces the evidence: it instruments a copy of the UDF's AST so
every `If`/`IfExp` test routes through a recorder, runs the instrumented
function over the operator's existing sample rows, and reports which arms the
sample observed. The emitter then emits ONLY the observed arm and raises
NORMALCASEVIOLATION for rows that would enter a cold arm (they resolve
exactly on the general tier / interpreter, like every other normal-case
violation).

Profiles are keyed by (node kind, lineno, col_offset) of the ORIGINAL
`udf.tree` nodes — the instrumented tree is a deepcopy, so locations match
without re-parsing (the tree may come from a larger enclosing parse whose
line numbers a re-parse of `udf.source` would not reproduce).
"""

from __future__ import annotations

import ast
import copy
from typing import Callable

_PROFILE_ROW_CAP = 1000


def branch_key(node: ast.AST) -> tuple:
    return (type(node).__name__, node.lineno, node.col_offset)


class _WrapTests(ast.NodeTransformer):
    """Wrap every If/IfExp test in `__tpx_b__(<key index>, test)`."""

    def __init__(self):
        self.keys: list[tuple] = []

    def _wrap(self, node):
        node = self.generic_visit(node)
        idx = len(self.keys)
        self.keys.append(branch_key(node))
        call = ast.Call(func=ast.Name(id="__tpx_b__", ctx=ast.Load()),
                        args=[ast.Constant(value=idx), node.test],
                        keywords=[])
        ast.copy_location(call, node.test)
        node.test = call
        return node

    visit_If = _wrap
    visit_IfExp = _wrap


def _build_instrumented(udf) -> tuple[Callable, dict, list]:
    tree = copy.deepcopy(udf.tree)
    w = _WrapTests()
    tree = w.visit(tree)
    ast.fix_missing_locations(tree)
    hits: dict[int, list[bool]] = {}

    def rec(i, v):
        s = hits.setdefault(i, [False, False])
        s[0 if v else 1] = True
        return v

    g = dict(udf.globals)
    g["__tpx_b__"] = rec
    if isinstance(tree, ast.Lambda):
        expr = ast.Expression(body=tree)
        ast.fix_missing_locations(expr)
        f = eval(compile(expr, "<branchprof>", "eval"), g)
    else:
        mod = ast.Module(body=[tree], type_ignores=[])
        ast.fix_missing_locations(mod)
        exec(compile(mod, "<branchprof>", "exec"), g)
        f = g[tree.name]
    return f, hits, w.keys


_CHEAP_CALLS = {"len", "abs", "min", "max", "ord", "chr", "bool"}


def arm_weight(arm) -> int:
    """Static cost estimate of a branch arm (stmt list or expr): method
    calls / casts are columnar kernels (string scans, parses), loops and
    comprehensions unroll — those make pruning pay. Pure assignments of
    cheap expressions cost nothing under predication, so pruning them only
    buys an error-lattice update."""
    stmts = arm if isinstance(arm, list) else [arm]
    w = 0
    for s in stmts:
        for n in ast.walk(s):
            if isinstance(n, ast.Call):
                f = n.func
                if isinstance(f, ast.Name) and f.id in _CHEAP_CALLS:
                    continue
                w += 1
            elif isinstance(n, (ast.For, ast.While, ast.ListComp,
                                ast.SetComp, ast.DictComp,
                                ast.GeneratorExp)):
                w += 3
    return w


def profile_branches(udf, rows, call: Callable) -> dict:
    """{branch_key: (saw_true, saw_false)} from running the instrumented UDF
    over `rows` via `call(f, row)` (the operator's own calling convention).
    Rows that raise contribute whatever branches they reached before the
    error — same evidence the reference's TraceVisitor collects. Returns {}
    when the UDF has no branches or cannot be instrumented (no pruning)."""
    if not rows:
        return {}
    if not any(isinstance(n, (ast.If, ast.IfExp))
               for n in ast.walk(udf.tree)):
        return {}
    try:
        f, hits, keys = _build_instrumented(udf)
    except Exception:
        return {}
    for r in rows[:_PROFILE_ROW_CAP]:
        try:
            call(f, r)
        except Exception:
            pass
    return {keys[i]: tuple(v) for i, v in hits.items()}
