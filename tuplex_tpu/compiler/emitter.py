"""AST → columnar-jnp abstract interpreter: the compiled fast path.

This is the TPU-native replacement for the reference's LLVM code generator
(reference: codegen/src/BlockGeneratorVisitor.cc — AST to LLVM IR with
exception branches; FunctionRegistry.h:71-205 — builtins/method codegen;
TypeAnnotatorVisitor.cc — type inference). Instead of generating IR we
symbolically execute the UDF's AST over CV column batches inside a jax trace:

  * every expression evaluates to a CV (whole-column value)
  * control flow is predicated: if/else bodies run under boolean masks and
    assignments merge with `where` — no data-dependent Python control flow
    survives into the jaxpr (XLA-friendly by construction)
  * Python exceptions become error-code lattice updates: the first error per
    row wins (matching sequential interpreter semantics), and errored rows
    drop out of the active mask (reference: branch-to-exception-block,
    CodeDefs.h:43 exception_handler_f)
  * constructs outside the supported subset raise NotCompilable — the
    operator then routes ALL rows through the interpreter path (reference:
    fallback mode via cloudpickle, python/tests/test_fallback.py)

Specialization contract: constants (needles, format widths, closure values)
are baked into the trace, so the jit cache must key on them — handled by the
stage builder hashing UDF source + captured globals.
"""

from __future__ import annotations

import ast
import dataclasses
import math as _pymath
from typing import Any, Callable, Optional

from ..core import typesys as T
from ..core.errors import (ExceptionCode, NotCompilable,
                           pack_device_code)
from ..ops import strings as S
from ..runtime.jaxcfg import jnp
from ..utils.reflection import UDFSource, get_udf_source
from .values import CV, _MISSING, const_cv, dtype_for, materialize, null_cv, tuple_cv

# loop bounds: for-loops fully unroll (static trip counts only); while-loops
# unroll to the cap with per-row exit masks — rows still looping at the cap
# raise LOOPCAPEXCEEDED and resolve exactly on the interpreter (reference:
# UnrollLoopsVisitor.cc caps at compile time too)
_FOR_UNROLL_CAP = 256
_WHILE_UNROLL_CAP = 24
_DYN_ITER_CAP = 16     # masked-unroll width for runtime-length iterables


class EmitCtx:
    """Per-stage trace state: batch size, error lattice, active mask."""

    def __init__(self, b: int, rowvalid, seed=None):
        self.b = b
        self.err = jnp.zeros(b, dtype=jnp.int32)
        self.cur_op = -1                  # set per fused op by build_device_fn
        # rows that are real + normal-case; padding/fallback slots never active
        self.active = rowvalid
        # per-partition PRNG seed (0-d uint32, staged as arrays['#seed']) for
        # compiled `random` UDFs; distinct per partition so batches don't
        # replay one sequence (reference: StandardModules.cc:30-129 types the
        # random module; draws are not CPython-sequence-exact there either)
        self.seed = seed
        self._rng_base = None
        self._rng_n = 0

    def next_rng_key(self):
        if self.seed is None:
            raise NotCompilable("random requires a staged #seed")
        from jax import random as jrandom

        if self._rng_base is None:
            self._rng_base = jrandom.key(self.seed)
        k = jrandom.fold_in(self._rng_base, self._rng_n)
        self._rng_n += 1
        return k

    def coded(self, code: ExceptionCode) -> int:
        """Pack (exception class, logical-operator id) into ONE lattice
        value (core.errors.pack_device_code owns the layout). Device
        exceptions become host-attributable with zero extra device ops
        (reference: exception partitions carry (operator id, code) pairs
        from compiled code too)."""
        return pack_device_code(int(code), self.cur_op)

    def raise_where(self, cond, code: ExceptionCode) -> None:
        hit = self.active & cond & (self.err == 0)
        self.err = jnp.where(hit, jnp.int32(self.coded(code)), self.err)
        self.active = self.active & ~hit


class Emitter:
    def __init__(self, ctx: EmitCtx, globals_: dict[str, Any],
                 branch_profile: Optional[dict] = None):
        self.ctx = ctx
        self.globals = globals_
        # sample branch observations for speculative arm pruning
        # (compiler/branchprof.py); None/{} disables speculation
        self.branch_profile = branch_profile or None

    # ------------------------------------------------------------------ UDF
    def eval_udf(self, udf: UDFSource, args: list[CV]) -> CV:
        """Evaluate a UDF body over columnar args; returns the result CV."""
        if udf.source == "":
            raise NotCompilable("no source available for UDF")
        tree = udf.tree
        params = udf.params
        if len(params) != len(args):
            # multi-param UDF over a row: spread fields across params
            if len(args) == 1 and args[0].elts is not None and \
                    len(args[0].elts) == len(params):
                args = list(args[0].elts)
            else:
                raise NotCompilable(
                    f"UDF takes {len(params)} args, got {len(args)}")
        frame = Frame(self, dict(zip(params, args)))
        frame.udf_tree = tree
        if isinstance(tree, ast.Lambda):
            return frame.eval(tree.body)
        assert isinstance(tree, ast.FunctionDef)
        frame.exec_block(tree.body)
        return frame.finalize_return()

    def inline_call(self, func: Callable, args: list[CV]) -> CV:
        """Inline a user helper function referenced from UDF globals
        (reference: ClosureEnvironment — imported/defined symbols)."""
        src = get_udf_source(func)
        if src.source == "":
            raise NotCompilable(f"no source for helper {src.name}")
        sub = Emitter(self.ctx, {**src.globals})
        return sub.eval_udf(src, args)


class Frame:
    """One UDF activation: variable env + predication state."""

    def __init__(self, emitter: Emitter, env: dict[str, CV]):
        self.em = emitter
        self.ctx = emitter.ctx
        self.env = env
        self.mask = None          # branch predicate ([B] bool) or None == all
        self.ret_val: Optional[CV] = None
        self.ret_mask = jnp.zeros(self.ctx.b, dtype=bool)
        # vectorized loop state: one dict per enclosing loop; masks stay
        # None until a row actually breaks/continues/exits so constant
        # propagation survives fully-unrolled loops
        self.loops: list[dict] = []

    # -- masks ---------------------------------------------------------------
    def active(self):
        a = self.ctx.active & ~self.ret_mask
        if self.mask is not None:
            a = a & self.mask
        for lp in self.loops:
            for k in ("brk", "cont", "done"):
                if lp[k] is not None:
                    a = a & ~lp[k]
        return a

    def _assign_pred(self):
        """Predicate under which assignments merge with the old value: branch
        mask plus 'row already left this loop iteration/loop' exclusions."""
        m = self.mask
        for lp in self.loops:
            for k in ("brk", "cont", "done"):
                if lp[k] is not None:
                    m = ~lp[k] if m is None else m & ~lp[k]
        return m

    def raise_where(self, cond, code: ExceptionCode, barrier: bool = True):
        hit = self.active() & cond & (self.ctx.err == 0)
        self.ctx.err = jnp.where(hit, jnp.int32(self.ctx.coded(code)),
                                 self.ctx.err)
        self.ctx.active = self.ctx.active & ~hit
        if not barrier:
            # speculation raises: the condition is an already-materialized
            # branch predicate, not a fused error chain — cutting fusion
            # here would cost more than it saves
            return
        # cut the error lattice's producer chain HERE: lambda UDFs and the
        # fused decode have no statement boundaries, so without this the
        # final #err kLoop fusion re-pulls (and per-element RECOMPUTES)
        # every [B, W] intermediate that fed any error condition — measured
        # ~0.5s of a 1.5s zillow batch on XLA-CPU (CPU-only: see
        # jaxcfg.fusion_barriers_enabled)
        from ..runtime.jaxcfg import stmt_barriers_enabled, lax

        if stmt_barriers_enabled():
            self.ctx.err, self.ctx.active = lax.optimization_barrier(
                (self.ctx.err, self.ctx.active))

    # ===================================================================
    # statements
    # ===================================================================
    def exec_block(self, stmts: list[ast.stmt]) -> None:
        for s in stmts:
            self.exec(s)
            self._fusion_barrier()

    def _fusion_barrier(self) -> None:
        """Materialize the frame state between statements so XLA's producer
        fusion can't inline a whole UDF body into one kLoop fusion that
        recomputes [B, W] string intermediates per output element (measured
        24x slowdown on Zillow extractPrice on XLA-CPU). optimization_barrier
        is free at runtime; fusion still happens within each statement.
        CPU-only (see jaxcfg.fusion_barriers_enabled)."""
        from .values import cv_arrays, cv_rebuild
        from ..runtime.jaxcfg import stmt_barriers_enabled, lax

        if not stmt_barriers_enabled():
            return
        leaves: list = []
        items = list(self.env.items())
        for _, cv in items:
            cv_arrays(cv, leaves)
        rv = self.ret_val
        if rv is not None:
            cv_arrays(rv, leaves)
        state = [self.ctx.err, self.ctx.active, self.ret_mask]
        if self.mask is not None:
            state.append(self.mask)
        loop_slots = []   # (loop dict, key) per materialized loop mask
        for lp in self.loops:
            for k in ("brk", "cont", "done"):
                if lp[k] is not None:
                    loop_slots.append((lp, k))
                    state.append(lp[k])
        n_cv = len(leaves)
        leaves.extend(state)
        if not leaves:
            return
        out = lax.optimization_barrier(tuple(leaves))
        it = iter(out[:n_cv])
        for name, cv in items:
            self.env[name] = cv_rebuild(cv, it)
        if rv is not None:
            self.ret_val = cv_rebuild(rv, it)
        rest = iter(out[n_cv:])
        self.ctx.err, self.ctx.active, self.ret_mask = \
            next(rest), next(rest), next(rest)
        if self.mask is not None:
            self.mask = next(rest)
        for lp, k in loop_slots:
            lp[k] = next(rest)

    def exec(self, node: ast.stmt) -> None:
        m = getattr(self, "exec_" + type(node).__name__, None)
        if m is None:
            raise NotCompilable(f"statement {type(node).__name__}")
        m(node)

    def exec_Return(self, node: ast.Return) -> None:
        val = self.eval(node.value) if node.value is not None else null_cv()
        live = self.active()
        self.ret_val = val if self.ret_val is None else \
            merge_cv(self, live, val, self.ret_val)
        self.ret_mask = self.ret_mask | live

    def finalize_return(self) -> CV:
        if self.ret_val is None:
            return null_cv()
        # rows that fell off the end of the function return None
        # (only matters if some path lacks a return)
        return self.ret_val

    def exec_Assign(self, node: ast.Assign) -> None:
        val = self.eval(node.value)
        if len(node.targets) != 1:
            raise NotCompilable("chained assignment")
        self._assign_target(node.targets[0], val)

    def _assign_target(self, tgt: ast.expr, val: CV) -> None:
        if isinstance(tgt, ast.Name):
            old = self.env.get(tgt.id)
            pred = self._assign_pred()
            if pred is not None and old is not None:
                val = merge_cv(self, pred, val, old)
            self.env[tgt.id] = val
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            if val.elts is None:
                if val.is_const and isinstance(val.const, tuple):
                    val = tuple_cv([const_cv(v) for v in val.const])
                else:
                    raise NotCompilable("unpacking non-tuple")
            if len(tgt.elts) != len(val.elts):
                raise NotCompilable("unpack arity mismatch")
            for t_i, v_i in zip(tgt.elts, val.elts):
                self._assign_target(t_i, v_i)
        else:
            raise NotCompilable(f"assign target {type(tgt).__name__}")

    def exec_AugAssign(self, node: ast.AugAssign) -> None:
        cur = self.eval(node.target)
        val = self.eval(node.value)
        res = self._binop(node.op, cur, val)
        self._assign_target(node.target, res)

    def exec_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is None:
            return
        self._assign_target(node.target, self.eval(node.value))

    def _spec_arms(self, node) -> tuple[bool, bool]:
        """(prune_then, prune_else): arms the operator's sample NEVER took
        (branch speculation, reference RemoveDeadBranchesVisitor.cc:1-147).
        An arm is prunable only with positive evidence the OTHER arm ran —
        a node the sample never reached proves nothing about either arm —
        and only when its body is worth skipping: predicated execution of a
        cheap assignment costs less than the violation bookkeeping."""
        prof = self.em.branch_profile
        if not prof:
            return False, False
        from .branchprof import arm_weight, branch_key

        rec = prof.get(branch_key(node))
        if rec is None:
            return False, False
        saw_t, saw_f = rec
        return (not saw_t and saw_f and arm_weight(node.body) >= 1,
                not saw_f and saw_t and bool(node.orelse)
                and arm_weight(node.orelse) >= 1)

    def exec_If(self, node: ast.If) -> None:
        prune_then, prune_else = self._spec_arms(node)
        cond = self.truthy(self.eval(node.test))
        outer = self.mask
        then_m = cond if outer is None else outer & cond
        else_m = ~cond if outer is None else outer & ~cond
        if prune_then:
            # sample never entered the then-arm: emit only the else-arm;
            # rows taking the cold arm violate the normal case and resolve
            # exactly on the general/interpreter ladder
            self.raise_where(cond, ExceptionCode.NORMALCASEVIOLATION,
                             barrier=False)
            if node.orelse:
                self.mask = else_m
                self.exec_block(node.orelse)
            self.mask = outer
            return
        if prune_else and node.orelse:
            self.raise_where(~cond, ExceptionCode.NORMALCASEVIOLATION,
                             barrier=False)
            self.mask = then_m
            self.exec_block(node.body)
            self.mask = outer
            return
        self.mask = then_m
        self.exec_block(node.body)
        if node.orelse:
            self.mask = else_m
            self.exec_block(node.orelse)
        self.mask = outer

    def exec_Expr(self, node: ast.Expr) -> None:
        # evaluate for side effects (errors); discard value
        self.eval(node.value)

    # -- loops (reference: BlockGeneratorVisitor.cc:5212 NFor, :5608 NWhile,
    # UnrollLoopsVisitor.cc, IteratorContextProxy.cc zip/enumerate) ---------
    _ITER_BUILTINS = ("range", "zip", "enumerate", "reversed")

    def exec_For(self, node: ast.For) -> None:
        # evaluate the iterable ONCE (python does; and its error ops —
        # ascii guards etc. — must not emit twice). Builtin iterator
        # constructors go through the AST-level paths instead.
        is_builtin_call = (
            isinstance(node.iter, ast.Call)
            and isinstance(node.iter.func, ast.Name)
            and node.iter.func.id in self._ITER_BUILTINS
            and node.iter.func.id not in self.env
            and node.iter.func.id not in self.em.globals)
        if is_builtin_call:
            items = self._static_iter_items(node.iter)
            dyn = None if items is not None else \
                self._dynamic_iter(node.iter)
        else:
            v = self.eval(node.iter)
            items = self._cv_iter_items(v)
            dyn = None if items is not None else self._dynamic_iter_cv(v)
        if items is None:
            self._exec_for_dynamic(node, dyn)
            return
        lp = {"brk": None, "cont": None, "done": None}
        self.loops.append(lp)
        try:
            for item in items:
                self._assign_target(node.target, item)
                self.exec_block(node.body)
                lp["cont"] = None        # continue only skips ONE iteration
            brk = lp["brk"]
        finally:
            self.loops.pop()
        self._for_orelse(node, brk)

    def _for_orelse(self, node: ast.For, brk) -> None:
        """python for-else: runs unless the loop broke (per row)."""
        if not node.orelse:
            return
        outer = self.mask
        if brk is not None:
            self.mask = ~brk if outer is None else outer & ~brk
        try:
            self.exec_block(node.orelse)
        finally:
            self.mask = outer

    def _exec_for_dynamic(self, node: ast.For, dyn) -> None:
        """for over a RUNTIME-length iterable — split results, strings, and
        enumerate/zip of those (reference: IteratorContextProxy.cc codegens
        iterator state machines; here the masked-unroll scheme of exec_While
        iterates every row to ITS OWN length). Iteration k deactivates rows
        with count <= k via the loop's `done` mask, so assignments merge and
        errors raise only for rows still iterating; rows longer than the
        unroll width raise LOOPCAPEXCEEDED and resolve exactly on the
        interpreter."""
        if dyn is None:
            raise NotCompilable("for over non-static iterable")
        count, item_at, bound = dyn
        width = self._unroll_width(count, bound)
        # python leaves the loop target unbound when the iterable is empty;
        # a pre-bound name keeps its value (the masked merge reproduces
        # that). For unbound targets the empty rows must interpret — a
        # later read would otherwise see iteration-0 garbage instead of
        # NameError.
        names = [t.id for t in ast.walk(node.target)
                 if isinstance(t, ast.Name)]
        if any(n not in self.env for n in names):
            self.raise_where(count == 0, ExceptionCode.PYTHON_FALLBACK)
        lp = {"brk": None, "cont": None, "done": None, "dyn": True}
        self.loops.append(lp)
        try:
            for k in range(width):
                lp["done"] = count <= k      # rows whose iteration is over
                self._assign_target(node.target, item_at(k))
                self.exec_block(node.body)
                lp["cont"] = None
            brk = lp["brk"]
        finally:
            self.loops.pop()
        self._for_orelse(node, brk)

    def exec_While(self, node: ast.While) -> None:
        """Bounded unrolling with per-row exit masks: rows whose condition
        still holds after the cap raise LOOPCAPEXCEEDED and resolve on the
        interpreter — semantics stay exact, long-looping rows just go slow
        (reference: TypeAnnotator loop-stability + NWhile codegen)."""
        cap = _WHILE_UNROLL_CAP
        lp = {"brk": None, "cont": None, "done": None, "dyn": True}
        self.loops.append(lp)

        def eval_cond():
            """'all' (const-True: every row continues), 'stop' (const-False:
            every active row exits), or a truthy array. Rows observed exiting
            via a false condition accumulate into lp['done'] — they power
            while-else and drop out of active()."""
            cond = self.eval(node.test)
            if cond.is_const:
                if bool(cond.const):
                    return "all"
                exiting = self.active()
                lp["done"] = exiting if lp["done"] is None \
                    else lp["done"] | exiting
                return "stop"
            tr = self.truthy(cond)
            exiting = self.active() & ~tr
            lp["done"] = exiting if lp["done"] is None \
                else lp["done"] | exiting
            return tr

        try:
            for _ in range(cap):
                state = eval_cond()
                if isinstance(state, str) and state == "stop":
                    break
                self.exec_block(node.body)
                lp["cont"] = None
            else:
                # cap reached: rows still looping cannot finish on device
                state = eval_cond()
                if not (isinstance(state, str) and state == "stop"):
                    still = jnp.ones(self.ctx.b, dtype=bool) \
                        if isinstance(state, str) else state
                    self.raise_where(still, ExceptionCode.LOOPCAPEXCEEDED)
            done = lp["done"]
        finally:
            self.loops.pop()
        if node.orelse and done is not None:
            # while-else: ONLY rows that exited via a false condition (a
            # break skips it; const-False exits were folded into `done`)
            outer = self.mask
            self.mask = done if outer is None else outer & done
            try:
                self.exec_block(node.orelse)
            finally:
                self.mask = outer

    def exec_Break(self, node: ast.Break) -> None:
        if not self.loops:
            raise NotCompilable("break outside loop")
        lp = self.loops[-1]
        live = self.active()
        lp["brk"] = live if lp["brk"] is None else lp["brk"] | live

    def exec_Continue(self, node: ast.Continue) -> None:
        if not self.loops:
            raise NotCompilable("continue outside loop")
        lp = self.loops[-1]
        live = self.active()
        lp["cont"] = live if lp["cont"] is None else lp["cont"] | live

    def _static_iter_items(self, node: ast.expr) -> Optional[list[CV]]:
        """The iterable's elements as CVs when the LENGTH is trace-static:
        const str/tuple/list/range, tuple CVs, zip/enumerate/reversed over
        those. Data-dependent lengths can't unroll -> None."""
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and not node.keywords \
                and node.func.id not in self.env \
                and node.func.id not in self.em.globals:
            # keyword forms (enumerate(start=), zip(strict=)) fall through
            # to eval_Call, which rejects keywords -> interpreter
            fname = node.func.id
            if fname == "range":
                args = [self.eval(a) for a in node.args]
                if not all(a.is_const and isinstance(a.const, int)
                           for a in args) or not 1 <= len(args) <= 3:
                    return None
                r = range(*[a.const for a in args])
                if len(r) > _FOR_UNROLL_CAP:
                    raise NotCompilable(
                        f"range({len(r)}) exceeds unroll cap")
                return [const_cv(i) for i in r]
            if fname == "zip":
                subs = [self._static_iter_items(a) for a in node.args]
                if any(s is None for s in subs) or not subs:
                    return None
                return [tuple_cv(list(t)) for t in zip(*subs)]
            if fname == "enumerate":
                if len(node.args) not in (1, 2):
                    return None
                sub = self._static_iter_items(node.args[0])
                if sub is None:
                    return None
                start = 0
                if len(node.args) == 2:
                    s = self.eval(node.args[1])
                    if not (s.is_const and isinstance(s.const, int)):
                        return None
                    start = s.const
                return [tuple_cv([const_cv(i + start), e])
                        for i, e in enumerate(sub)]
            if fname == "reversed":
                sub = self._static_iter_items(node.args[0]) \
                    if len(node.args) == 1 else None
                return None if sub is None else list(reversed(sub))
        try:
            v = self.eval(node)
        except NotCompilable:
            return None
        return self._cv_iter_items(v)

    def _cv_iter_items(self, v: CV) -> Optional[list[CV]]:
        if v.is_const:
            c = v.const
            if isinstance(c, (str, tuple, list, range)):
                if len(c) > _FOR_UNROLL_CAP:
                    raise NotCompilable("iterable exceeds unroll cap")
                return [const_cv(x) for x in c]
            return None
        if v.elts is not None and v.valid is None:
            return list(v.elts)
        return None

    def _dynamic_iter(self, node: ast.expr):
        """(count [B] int32, item_at(k) -> CV, bound | None) for
        RUNTIME-length iterables — the dynamic half of iteration
        (reference: IteratorContextProxy.cc): split results, runtime
        strings (chars), and enumerate/zip mixing those with static
        iterables. `bound` is a trace-time upper limit on count when one
        exists (static zip arm, maxsplit, string width) — the unroll uses
        it instead of the blanket cap. None when the expression isn't
        iterable this way."""
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
                and not node.keywords \
                and node.func.id not in self.env \
                and node.func.id not in self.em.globals:
            fname = node.func.id
            if fname == "enumerate" and len(node.args) in (1, 2):
                sub = self._dynamic_iter(node.args[0])
                if sub is None:
                    return None
                start = 0
                if len(node.args) == 2:
                    s = self.eval(node.args[1])
                    if not (s.is_const and isinstance(s.const, int)):
                        return None
                    start = s.const
                cnt, item, bound = sub
                return (cnt,
                        lambda k: tuple_cv([const_cv(k + start), item(k)]),
                        bound)
            if fname == "zip" and node.args:
                subs = []      # (None, items) static | (count, item_at) dyn
                any_dyn = False
                bound = None
                for a in node.args:
                    d = self._dynamic_iter(a)
                    if d is not None:
                        subs.append((d[0], d[1]))
                        if d[2] is not None:
                            bound = d[2] if bound is None \
                                else min(bound, d[2])
                        any_dyn = True
                        continue
                    st = self._static_iter_items(a)
                    if st is None:
                        return None
                    subs.append((None, st))
                    bound = len(st) if bound is None \
                        else min(bound, len(st))
                if not any_dyn:
                    return None
                cnt = None
                for c, _ in subs:
                    if c is None:
                        continue
                    cnt = c if cnt is None else jnp.minimum(cnt, c)
                for c, items in subs:
                    if c is None:
                        cnt = jnp.minimum(cnt, len(items))

                def zip_item(k, subs=subs):
                    parts = []
                    for c, it in subs:
                        if c is None:       # static list: clipped index
                            parts.append(it[min(k, len(it) - 1)]
                                         if it else const_cv(None))
                        else:
                            parts.append(it(k))
                    return tuple_cv(parts)

                return cnt, zip_item, bound
        try:
            v = self.eval(node)
        except NotCompilable:
            return None
        return self._dynamic_iter_cv(v)

    def _dynamic_iter_cv(self, v: CV):
        """The CV-level half of _dynamic_iter (the iterable is already
        evaluated — exec_For evaluates it exactly once)."""
        if v.kind == "split":
            return self._split_dynamic(v)
        if v.base is T.STR and not v.is_const and v.sbytes is not None:
            # char iteration over a runtime string (byte == codepoint only
            # for ASCII rows; others route via the guard)
            if v.valid is not None:
                self.raise_where(~v.valid, ExceptionCode.TYPEERROR)
            self._ascii_guard(v.sbytes, v.slen)
            sb, sl = v.sbytes, v.slen

            def char_at(k, sb=sb, sl=sl):
                kk = jnp.full(self.ctx.b, k, dtype=jnp.int32)
                bb, bl = S.slice_(sb, sl, kk, kk + 1, out_width=1)
                return CV(t=T.STR, sbytes=bb, slen=bl)

            return sl.astype(jnp.int32), char_at, int(sb.shape[1])
        return None

    def _split_dynamic(self, sv: CV):
        """Piece count + per-piece bounds for a lazy split view, computed
        ONCE with an unrolled find chain shared by every item_at(k)."""
        sb, sl = sv.sbytes, sv.slen
        sep, maxsplit = sv.names
        bound = None if maxsplit is None else maxsplit + 1
        if sep is None:
            cnt = S.ws_token_count(sb, sl).astype(jnp.int32)
            if maxsplit is not None:
                cnt = jnp.minimum(cnt, maxsplit + 1)

            def ws_item(k):
                start, stop, missing = S.ws_token_bounds(sb, sl, k)
                if maxsplit is not None and k == maxsplit:
                    stop = jnp.where(missing, stop, sl)
                bb, bl = S.slice_(sb, sl, start, stop)
                return CV(t=T.STR, sbytes=bb, slen=bl)

            return cnt, ws_item, bound
        m = len(sep)
        cnt = (S.count_const(sb, sl, sep) + 1).astype(jnp.int32)
        if maxsplit is not None:
            cnt = jnp.minimum(cnt, maxsplit + 1)
        chain = _DYN_ITER_CAP if bound is None else min(bound,
                                                        _DYN_ITER_CAP)
        starts = [jnp.zeros(self.ctx.b, dtype=jnp.int32)]
        stops = []
        for k in range(chain):
            nxt = S.find_const(sb, sl, sep, start=starts[k])
            if maxsplit is not None and k == maxsplit:
                stops.append(sl)
            else:
                stops.append(jnp.where(nxt < 0, sl, nxt))
            starts.append(jnp.where(nxt < 0, sl, nxt + m).astype(jnp.int32))

        def sep_item(k):
            if k >= len(stops):     # next() beyond the traced find chain
                raise NotCompilable("iterator past split chain")
            bb, bl = S.slice_(sb, sl, starts[k], stops[k])
            return CV(t=T.STR, sbytes=bb, slen=bl)

        return cnt, sep_item, bound

    # -- comprehensions (reference: BlockGeneratorVisitor.cc:3278
    # NListComprehension) ---------------------------------------------------
    def eval_ListComp(self, node: ast.ListComp) -> CV:
        return self._comprehension(node)

    def eval_GeneratorExp(self, node: ast.GeneratorExp) -> CV:
        return self._comprehension(node)

    def _comprehension(self, node) -> CV:
        if len(node.generators) != 1:
            raise NotCompilable("nested comprehension")
        gen = node.generators[0]
        if getattr(gen, "is_async", 0):
            raise NotCompilable("async comprehension")
        items = self._static_iter_items(gen.iter)
        if items is None:
            if isinstance(node, ast.GeneratorExp):
                # a genexp over a RUNTIME-length iterable has no static
                # shape, but the REDUCERS (sum/any/all/min/max) consume it
                # lazily with masked iteration — hand them the recipe
                dyn = self._dynamic_iter(gen.iter)
                if dyn is not None:
                    # capture the DEFINING env (a helper's genexp must not
                    # rebind free names to the consumer's locals) and a
                    # one-shot cell (python generators exhaust)
                    return CV(t=T.PYOBJECT, kind="dyngen",
                              names=(node, dyn, dict(self.env),
                                     {"consumed": False}))
            raise NotCompilable("comprehension over non-static iterable")
        saved = dict(self.env)
        outs: list[CV] = []
        try:
            for item in items:
                self._assign_target(gen.target, item)
                keep = True
                for cond_node in gen.ifs:
                    cond = self.eval(cond_node)
                    if not cond.is_const:
                        # data-dependent filter => data-dependent ARITY:
                        # no static shape exists for the result
                        raise NotCompilable(
                            "comprehension filter must be trace-constant")
                    if not bool(cond.const):
                        keep = False
                        break
                if keep:
                    outs.append(self.eval(node.elt))
        finally:
            self.env = saved   # py3 comprehension scope: target doesn't leak
        # listcomp results ARE python lists; genexp results are consumable
        # only (returning either must fall back, not decode as a tuple)
        kind = "list" if isinstance(node, ast.ListComp) else "genexp"
        return tuple_cv(outs, kind=kind)

    def eval_DictComp(self, node: ast.DictComp) -> CV:
        """{k: v for ...} with trace-constant string keys becomes a named row
        (same contract as dict literals; reference: BlockGeneratorVisitor
        comprehension + MapOperator named-output semantics)."""
        if len(node.generators) != 1:
            raise NotCompilable("nested comprehension")
        gen = node.generators[0]
        if getattr(gen, "is_async", 0):
            raise NotCompilable("async comprehension")
        items = self._static_iter_items(gen.iter)
        if items is None:
            raise NotCompilable("comprehension over non-static iterable")
        saved = dict(self.env)
        keys: list[str] = []
        vals: list[CV] = []
        try:
            for item in items:
                self._assign_target(gen.target, item)
                keep = True
                for cond_node in gen.ifs:
                    cond = self.eval(cond_node)
                    if not cond.is_const:
                        raise NotCompilable(
                            "comprehension filter must be trace-constant")
                    if not bool(cond.const):
                        keep = False
                        break
                if not keep:
                    continue
                k = self.eval(node.key)
                if not (k.is_const and isinstance(k.const, str)):
                    raise NotCompilable("dict comprehension key must be a "
                                        "trace-constant str")
                v = self.eval(node.value)
                if k.const in keys:          # python: later binding wins
                    vals[keys.index(k.const)] = v
                else:
                    keys.append(k.const)
                    vals.append(v)
        finally:
            self.env = saved
        return tuple_cv(vals, names=keys)

    def exec_Pass(self, node: ast.Pass) -> None:
        pass

    def exec_Assert(self, node: ast.Assert) -> None:
        cond = self.truthy(self.eval(node.test))
        self.raise_where(~cond, ExceptionCode.ASSERTIONERROR)

    def exec_Raise(self, node: ast.Raise) -> None:
        code = ExceptionCode.UNKNOWN
        exc = node.exc
        if isinstance(exc, ast.Call):
            exc = exc.func
        if isinstance(exc, ast.Name):
            from ..core.errors import _PY_TO_CODE

            for cls, c in _PY_TO_CODE.items():
                if cls.__name__ == exc.id:
                    code = c
                    break
        self.raise_where(jnp.ones(self.ctx.b, dtype=bool), code)

    # ===================================================================
    # expressions
    # ===================================================================
    def eval(self, node: ast.expr) -> CV:
        m = getattr(self, "eval_" + type(node).__name__, None)
        if m is None:
            raise NotCompilable(f"expression {type(node).__name__}")
        return m(node)

    def eval_Constant(self, node: ast.Constant) -> CV:
        if node.value is None or isinstance(node.value, (bool, int, float, str)):
            return const_cv(node.value)
        if isinstance(node.value, tuple):
            return const_cv(node.value)
        raise NotCompilable(f"constant {type(node.value).__name__}")

    def eval_Name(self, node: ast.Name) -> CV:
        if node.id in self.env:
            return self.env[node.id]
        if node.id in self.em.globals:
            g = self.em.globals[node.id]
            if isinstance(g, (bool, int, float, str, tuple)) or g is None:
                return const_cv(g)
            return CV(t=T.PYOBJECT, const=g)  # module/function: usable in calls
        raise NotCompilable(f"unknown name {node.id!r}")

    def eval_Tuple(self, node: ast.Tuple) -> CV:
        return tuple_cv([self.eval(e) for e in node.elts])

    def eval_List(self, node: ast.List) -> CV:
        # list literals compile as tuples for CONSUMPTION (indexing/len/
        # iteration/sum agree); kind="list" makes a list-valued RETURN
        # fall back so result typing stays exactly python (list != tuple)
        return tuple_cv([self.eval(e) for e in node.elts], kind="list")

    def eval_Dict(self, node: ast.Dict) -> CV:
        # string-keyed dict literals become named rows (reference: map with
        # dict output keeps column names, MapOperator.cc)
        keys = []
        for k in node.keys:
            if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
                raise NotCompilable("dict literal with non-str-const keys")
            keys.append(k.value)
        vals = [self.eval(v) for v in node.values]
        return tuple_cv(vals, names=keys)

    def eval_BinOp(self, node: ast.BinOp) -> CV:
        left = self.eval(node.left)
        right = self.eval(node.right)
        def _plain_tuple(cv):
            # dict CVs (named) and Option tuples (valid mask) must NOT take
            # the structural fast path: python + raises on dicts, and a
            # None tuple needs its TypeError route
            return cv.elts is not None and cv.names is None \
                and cv.valid is None
        if isinstance(node.op, ast.Add) and _plain_tuple(left) \
                and _plain_tuple(right):
            if (left.kind == "list") != (right.kind == "list"):
                raise NotCompilable("list + tuple")   # TypeError in python
            return tuple_cv(list(left.elts) + list(right.elts),
                            kind=left.kind)
        if isinstance(node.op, ast.Mult) and _plain_tuple(left) \
            and right.is_const and isinstance(right.const, int) \
                and not isinstance(right.const, bool):
            return tuple_cv(list(left.elts) * max(0, right.const),
                            kind=left.kind)
        return self._binop(node.op, left, right)

    def eval_UnaryOp(self, node: ast.UnaryOp) -> CV:
        v = self.eval(node.operand)
        if isinstance(node.op, ast.Not):
            tr = self.truthy(v)
            return CV(t=T.BOOL, data=~tr)
        if isinstance(node.op, ast.USub):
            if v.is_const:
                return const_cv(-v.const)
            v = self._require_numeric(v, "unary -")
            return CV(t=v.t, data=-v.data)
        if isinstance(node.op, ast.UAdd):
            return self._require_numeric(v, "unary +")
        raise NotCompilable("unary op")

    def eval_BoolOp(self, node: ast.BoolOp) -> CV:
        # Python value semantics with short-circuit error masking: operand
        # i+1 only "runs" (raises) where all previous operands passed/failed
        vals = []
        gate = None  # mask under which next operand is evaluated
        is_and = isinstance(node.op, ast.And)
        outer = self.mask
        for i, operand in enumerate(node.values):
            self.mask = gate if gate is not None else outer
            v = self.eval(operand)
            vals.append(v)
            tr = self.truthy(v)
            nxt = tr if is_and else ~tr
            gate = nxt if gate is None else gate & nxt
            if outer is not None:
                gate = gate & outer
        self.mask = outer
        # fold values right-to-left: result = first operand failing the gate
        result = vals[-1]
        for i in range(len(vals) - 2, -1, -1):
            tr = self.truthy(vals[i])
            take_next = tr if is_and else ~tr
            result = merge_cv(self, take_next, result, vals[i])
        return result

    def eval_Compare(self, node: ast.Compare) -> CV:
        left = self.eval(node.left)
        comps = [self.eval(c) for c in node.comparators]
        if left.is_const and all(c.is_const for c in comps):
            # const-fold (unrolled loop counters etc.); raising or exotic
            # compares fall through to the vectorized error-lattice path
            import operator as _o

            table = {ast.Eq: _o.eq, ast.NotEq: _o.ne, ast.Lt: _o.lt,
                     ast.LtE: _o.le, ast.Gt: _o.gt, ast.GtE: _o.ge}
            vals = [left.const] + [c.const for c in comps]
            try:
                ok: Optional[bool] = True
                for op, a, b in zip(node.ops, vals, vals[1:]):
                    f = table.get(type(op))
                    if f is None:
                        ok = None
                        break
                    if not f(a, b):
                        ok = False
                        break
                if ok is not None:
                    return const_cv(bool(ok))
            except Exception:
                pass
        acc = None
        for op, right in zip(node.ops, [*comps]):
            res = self._compare(op, left, right)
            acc = res if acc is None else acc & res
            left = right
        return CV(t=T.BOOL, data=acc)

    def eval_IfExp(self, node: ast.IfExp) -> CV:
        prune_then, prune_else = self._spec_arms(node)
        cond = self.truthy(self.eval(node.test))
        outer = self.mask
        if prune_then:
            self.raise_where(cond, ExceptionCode.NORMALCASEVIOLATION,
                             barrier=False)
            self.mask = ~cond if outer is None else outer & ~cond
            b = self.eval(node.orelse)
            self.mask = outer
            return b
        if prune_else:
            self.raise_where(~cond, ExceptionCode.NORMALCASEVIOLATION,
                             barrier=False)
            self.mask = cond if outer is None else outer & cond
            a = self.eval(node.body)
            self.mask = outer
            return a
        self.mask = cond if outer is None else outer & cond
        a = self.eval(node.body)
        self.mask = ~cond if outer is None else outer & ~cond
        b = self.eval(node.orelse)
        self.mask = outer
        return merge_cv(self, cond, a, b)

    def eval_Subscript(self, node: ast.Subscript) -> CV:
        val = self.eval(node.value)
        if val.kind == "split":
            if isinstance(node.slice, ast.Slice):
                raise NotCompilable("slicing a split result")
            kidx = self.eval(node.slice)
            if not (kidx.is_const and isinstance(kidx.const, int)):
                raise NotCompilable("split index must be constant")
            return self._split_item(val, kidx.const)
        # slicing
        if isinstance(node.slice, ast.Slice):
            return self._slice(val, node.slice)
        idx = self.eval(node.slice)
        # tuple/row indexing
        if val.elts is not None:
            if idx.is_const and isinstance(idx.const, str):
                if val.names is None or idx.const not in val.names:
                    self_names = val.names or ()
                    raise NotCompilable(
                        f"column {idx.const!r} not in {self_names}")
                return val.elts[val.names.index(idx.const)]
            if idx.is_const and isinstance(idx.const, (int, bool)):
                i = int(idx.const)
                if not -len(val.elts) <= i < len(val.elts):
                    raise NotCompilable("tuple index out of range")
                return val.elts[i]
            raise NotCompilable("dynamic tuple index")
        if val.is_const and isinstance(val.const, dict):
            if idx.is_const:
                if idx.const in val.const:
                    return const_cv(val.const[idx.const])
                raise NotCompilable("missing dict key")
            raise NotCompilable("dynamic dict key")
        # string indexing
        if val.base is T.STR:
            val = self._unwrap_option(val, "subscript")
            self._ascii_guard(val.sbytes, val.slen)
            idx = self._require_numeric(idx, "string index")
            idx_arr = self._as_i64(idx)
            ch, cl, oob = S.char_at(val.sbytes, val.slen, idx_arr.astype(jnp.int32))
            self.raise_where(oob, ExceptionCode.INDEXERROR)
            return CV(t=T.STR, sbytes=ch, slen=cl)
        raise NotCompilable(f"subscript on {val.t}")

    def eval_Attribute(self, node: ast.Attribute) -> CV:
        val = self.eval(node.value)
        if val.is_const and val.const is not None and not isinstance(
                val.const, (bool, int, float, str, tuple)):
            # module attribute: math.pi etc.
            obj = val.const
            if hasattr(obj, node.attr):
                attr = getattr(obj, node.attr)
                if isinstance(attr, (bool, int, float, str)):
                    return const_cv(attr)
                return CV(t=T.PYOBJECT, const=attr)
        raise NotCompilable(f"attribute {node.attr}")

    def eval_Call(self, node: ast.Call) -> CV:
        if node.keywords:
            raise NotCompilable("keyword arguments")
        # method call: obj.method(args)
        if isinstance(node.func, ast.Attribute):
            # module functions (math.floor etc.) come through eval_Attribute
            try:
                recv = self.eval(node.func.value)
            except NotCompilable:
                recv = None
            if recv is not None and recv.kind == "match":
                args = [self.eval(a) for a in node.args]
                return self._match_method(recv, node.func.attr, args)
            if recv is not None and recv.is_const and \
                    getattr(recv.const, "__name__", None) == "re" and \
                    node.func.attr in ("search", "match"):
                args = [self.eval(a) for a in node.args]
                return self._re_search(node.func.attr, args)
            if recv is not None and recv.is_const and \
                    getattr(recv.const, "__name__", None) == "re" and \
                    node.func.attr == "sub":
                args = [self.eval(a) for a in node.args]
                return self._re_sub(args)
            if recv is not None and recv.is_const and \
                    getattr(recv.const, "__name__", None) == "random" and \
                    type(recv.const).__name__ == "module":
                args = [self.eval(a) for a in node.args]
                return self._random_fn(node.func.attr, args)
            if recv is not None and recv.base is T.STR:
                args = [self.eval(a) for a in node.args]
                return self._str_method(recv, node.func.attr, args)
            if recv is not None and recv.is_const and recv.const is not None \
                    and not isinstance(recv.const, (bool, int, float, str, tuple)):
                fn = getattr(recv.const, node.func.attr, None)
                if fn is not None:
                    args = [self.eval(a) for a in node.args]
                    import types as _types

                    if isinstance(fn, _types.FunctionType) and \
                            getattr(fn, "__module__", "") != "math":
                        # module-qualified user helper: inline like a bare
                        # name (ClosureEnvironment semantics); stdlib
                        # functions our registry covers (string.capwords)
                        # fall through to their device kernels
                        try:
                            return self.em.inline_call(fn, args)
                        except NotCompilable:
                            pass
                    return self._module_fn(fn, args)
            if recv is not None and recv.elts is not None \
                    and recv.names is not None:
                args = [self.eval(a) for a in node.args]
                return self._dict_method(node, recv, node.func.attr, args)
            if recv is not None and recv.elts is not None \
                    and node.func.attr in ("index", "count"):
                args = [self.eval(a) for a in node.args]
                return self._tuple_method(recv, node.func.attr, args)
            raise NotCompilable(f"method {node.func.attr}")
        if not isinstance(node.func, ast.Name):
            raise NotCompilable("computed call target")
        name = node.func.id
        args = [self.eval(a) for a in node.args]
        # python name resolution order: locals, then globals, THEN builtins —
        # a user-defined sum/len/etc. must win over our builtin emitters
        if name in self.env:
            raise NotCompilable(f"call to local value {name}")
        if name in self.em.globals:
            g = self.em.globals[name]
            if callable(g):
                if g.__module__ in ("math",):
                    return self._module_fn(g, args)
                return self.em.inline_call(g, args)
            raise NotCompilable(f"call to non-callable global {name}")
        builtin = getattr(self, "_builtin_" + name, None)
        if builtin is not None:
            return builtin(args)
        raise NotCompilable(f"call to {name}")

    def _random_fn(self, fname: str, args: list[CV]) -> CV:
        """Compiled `random` module calls (reference: FunctionRegistry
        codegens random.choice; StandardModules.cc:30-129 types the module).
        Draws use jax's counter-based PRNG keyed per (partition seed, call
        site) — deterministic per partition, distinct across partitions, and
        explicitly NOT CPython-Mersenne-sequence-exact (the reference's
        compiled path diverges from CPython sequences the same way)."""
        from jax import random as jrandom

        if fname == "random":
            if args:
                raise NotCompilable("random.random arity")
            u = jrandom.uniform(self.ctx.next_rng_key(), (self.ctx.b,),
                                dtype=jnp.float64)
            return CV(t=T.F64, data=u)
        if fname == "uniform":
            if len(args) != 2:
                raise NotCompilable("random.uniform arity")
            a = self._require_numeric(args[0], "random.uniform")
            b = self._require_numeric(args[1], "random.uniform")
            af = self._cast(a.data, T.F64)
            bf = self._cast(b.data, T.F64)
            u = jrandom.uniform(self.ctx.next_rng_key(), (self.ctx.b,),
                                dtype=jnp.float64)
            # CPython formula: a + (b-a) * random()
            return CV(t=T.F64, data=af + (bf - af) * u)
        if fname in ("randint", "randrange"):
            if fname == "randrange" and len(args) == 1:
                args = [const_cv(0), args[0]]
            if len(args) != 2:
                raise NotCompilable(f"random.{fname} arity")
            for arg in args:
                # CPython raises per-version (ValueError/TypeError) on float
                # bounds; the interpreter tier owns that exactness
                if arg.base not in (T.I64, T.BOOL):
                    raise NotCompilable(f"random.{fname} non-integer bound")
            a = self._as_i64(self._require_numeric(args[0], fname))
            b = self._as_i64(self._require_numeric(args[1], fname))
            hi = b + 1 if fname == "randint" else b    # randint is inclusive
            self.raise_where(jnp.broadcast_to(a >= hi, (self.ctx.b,)),
                             ExceptionCode.VALUEERROR)
            hi_safe = jnp.maximum(hi, a + 1)           # keep errored rows legal
            v = jrandom.randint(self.ctx.next_rng_key(), (self.ctx.b,),
                                a, hi_safe, dtype=jnp.int64)
            return CV(t=T.I64, data=v)
        if fname == "choice":
            if len(args) != 1:
                raise NotCompilable("random.choice arity")
            items = self._cv_iter_items(args[0])
            if items is None:
                raise NotCompilable("random.choice over non-static iterable")
            if not items:
                self.raise_where(jnp.ones(self.ctx.b, dtype=bool),
                                 ExceptionCode.INDEXERROR)
                return const_cv(None)
            idx = jrandom.randint(self.ctx.next_rng_key(), (self.ctx.b,),
                                  0, len(items), dtype=jnp.int32)
            acc = items[-1]
            for i in range(len(items) - 2, -1, -1):
                acc = merge_cv(self, idx == i, items[i], acc)
            return acc
        raise NotCompilable(f"random.{fname}")

    def _re_search(self, fname: str, args: list[CV]) -> CV:
        """Compiled re.search/re.match over a string column (reference:
        FunctionRegistry.h:71-205 codegens re.search; here the pattern
        compiles to whole-column kernel steps — ops/regex.py). Rows whose
        match needs deeper backtracking than the compiled engine explores
        raise PYTHON_FALLBACK and resolve exactly on the interpreter."""
        from ..ops.regex import compile_regex

        if len(args) != 2:
            raise NotCompilable("re.search arity")
        pat, s = args
        if not (pat.is_const and isinstance(pat.const, str)):
            raise NotCompilable("dynamic regex pattern")
        pattern = pat.const
        if fname == "match" and not pattern.startswith("^"):
            pattern = "^" + pattern   # re.match anchors implicitly
        try:
            rx = compile_regex(pattern)   # anchored engine: capture groups
        except NotCompilable:
            rx = None                     # NFA below: boolean-only
        if s.base is not T.STR:
            raise NotCompilable("re.search over non-string")
        if s.valid is not None:
            # python: re.search(p, None) raises TypeError
            self.raise_where(~s.valid, ExceptionCode.TYPEERROR)
        if any(ord(c) > 127 for c in pattern):
            raise NotCompilable("non-ASCII regex pattern")
        # byte-space matching diverges from codepoint semantics on
        # multibyte rows: route them to the interpreter
        s = materialize(s, self.ctx.b)
        self._ascii_guard(s.sbytes, s.slen)
        sb, sl = s.sbytes, s.slen
        if rx is None:
            # unanchored / alternation patterns: exact EXISTENCE via the
            # bit-parallel NFA (ops/nfa.py).
            from ..ops.nfa import compile_nfa

            nfa = compile_nfa(pattern)
            # two-pass capture groups (reference codegens re.search
            # generally, FunctionRegistry.h:184-205): the NFA's min-plus
            # scan finds python's leftmost match START (its boolean is the
            # same exact existence answer, so one scan serves both), then
            # the anchored engine re-runs at that offset for the greedy
            # group spans. The second pass is LAZY — a UDF that only uses
            # the match as a boolean never pays the anchored engine or its
            # fallback routing.
            rx2 = None
            if not nfa.anchored_start and not nfa.nullable \
                    and nfa.n_pos <= nfa._START_MAX_POS:
                try:
                    rx2 = compile_regex("^" + pattern)
                except NotCompilable:
                    rx2 = None
            if rx2 is None:
                # boolean-only: exact existence via the bit-parallel
                # engine; .group() raises NotCompilable and the whole UDF
                # interprets
                return CV(t=T.option(T.tuple_of(T.STR)), elts=(),
                          valid=nfa.match(sb, sl), kind="match")
            matched, start = nfa.match_start(sb, sl)
            cell: list = []

            def _two_pass():
                if not cell:
                    shb, shl = S.slice_(sb, sl, start, sl)
                    am, suspect, gs, ge = rx2.match(shb, shl)
                    elts = []
                    for g in range(rx2.n_groups + 1):
                        bb, bl = S.slice_(shb, shl, gs[g], ge[g])
                        elts.append(CV(t=T.STR, sbytes=bb, slen=bl))
                    # fail-safe: the anchored engine's single-retreat
                    # backtracking may fall short at the found offset —
                    # those rows interpret (raised by the consumer)
                    cell.append((tuple(elts),
                                 matched & (suspect | ~am)))
                return cell[0]

            return CV(t=T.option(T.tuple_of(*[T.STR] *
                                            (rx2.n_groups + 1))),
                      elts=(), valid=matched, kind="match",
                      names=("#lazy_groups", _two_pass))
        matched, suspect, gs, ge = rx.match(sb, sl)
        self.raise_where(suspect & ~matched, ExceptionCode.PYTHON_FALLBACK)
        t_match = T.option(T.tuple_of(*[T.STR] * (rx.n_groups + 1)))
        win = self._GROUP_WIN
        if sb.shape[1] <= win:
            elts = []
            for g in range(rx.n_groups + 1):
                bb, bl = S.slice_(sb, sl, gs[g], ge[g])
                elts.append(CV(t=T.STR, sbytes=bb, slen=bl))
            return CV(t=t_match, elts=tuple(elts), valid=matched,
                      kind="match")
        # wide sources: capture groups slice to _GROUP_WIN instead of the
        # source width — every downstream pass over a group column
        # (parses, compares, output buffers, boxing) then reads 48
        # bytes/row, not W. Rows with a longer group ROUTE in ONE combined
        # raise (fail-safe, same contract as ops.strings._PARSE_WIN;
        # per-group raises fragmented statement fusion 4x). Slicing AND
        # routing are LAZY like the unanchored path: boolean-only
        # consumers keep every row on device. Group 0 (the whole match)
        # keeps full width.
        cell: list = []

        def _groups():
            if not cell:
                over = jnp.zeros(self.ctx.b, dtype=bool)
                for g in range(1, rx.n_groups + 1):
                    over = over | (ge[g] - gs[g] > win)
                elts = []
                for g in range(rx.n_groups + 1):
                    bb, bl = S.slice_(sb, sl, gs[g], ge[g],
                                      out_width=win if g else None)
                    elts.append(CV(t=T.STR, sbytes=bb, slen=bl))
                cell.append((tuple(elts), matched & over))
            return cell[0]

        return CV(t=t_match, elts=(), valid=matched, kind="match",
                  names=("#lazy_groups", _groups))

    _GROUP_WIN = 48

    def _re_sub(self, args: list[CV]) -> CV:
        """Compiled re.sub for the class-run subset ('[class]+' / '\\d+' /
        '\\s+' style — one character class repeated at least once, the
        common data-cleaning shape; reference: FunctionRegistry re.sub).
        Everything else falls back to the interpreter."""
        if len(args) != 3:
            raise NotCompilable("re.sub arity")
        pat, repl, s = args
        if not (pat.is_const and isinstance(pat.const, str)):
            raise NotCompilable("dynamic regex pattern")
        if not (repl.is_const and isinstance(repl.const, str)):
            raise NotCompilable("re.sub dynamic replacement")
        if "\\" in repl.const:
            raise NotCompilable("re.sub backreference replacement")
        if s.valid is not None:
            self.raise_where(~s.valid, ExceptionCode.TYPEERROR)
        s = materialize(s, self.ctx.b)
        rb, rl = self._to_strpair(s)
        self._ascii_guard(rb, rl)
        table = _class_run_table(pat.const)
        if table is not None:
            fb, fl = S.replace_class_runs(rb, rl, table, repl.const)
            return CV(t=T.STR, sbytes=fb, slen=fl)
        return self._re_sub_general(pat.const, repl.const, rb, rl)

    _RE_SUB_MAX_MATCHES = 8

    def _re_sub_general(self, pattern: str, new: str, rb, rl) -> CV:
        """General multi-element re.sub (VERDICT r4 #5; reference codegens
        re.sub generally, FunctionRegistry.h:184-205): python's scan loop —
        find leftmost match, replace, continue at its end — vectorized as a
        bounded unroll. Each round the NFA min-plus scan locates the next
        match start on the remaining suffix, the anchored engine supplies
        the greedy end, and splice_spans assembles the output in one pass.
        Rows with more than _RE_SUB_MAX_MATCHES matches (or needing deeper
        backtracking) route to the interpreter — fail-safe, never wrong."""
        from ..ops.nfa import compile_nfa
        from ..ops.regex import compile_regex

        nfa = compile_nfa(pattern)
        if nfa.anchored_start:
            # ^/\A patterns replace at most the one leftmost match; the
            # suffix-restart loop would wrongly re-anchor every round
            raise NotCompilable("re.sub of anchored pattern")
        if nfa.nullable or not 0 < nfa.n_pos <= nfa._START_MAX_POS:
            raise NotCompilable("re.sub pattern outside compiled bounds")
        rx2 = compile_regex("^" + pattern)   # may raise NotCompilable
        b = self.ctx.b
        zero = jnp.zeros(b, dtype=rl.dtype)
        o = zero
        active = jnp.ones(b, dtype=bool)
        suspect = jnp.zeros(b, dtype=bool)
        starts, ends, valids = [], [], []
        for _ in range(self._RE_SUB_MAX_MATCHES):
            sufb, sufl = S.slice_(rb, rl, o, rl)
            mk, st_rel = nfa.match_start(sufb, sufl)
            mk = mk & active
            shb, shl = S.slice_(sufb, sufl, st_rel, sufl)
            am, susp, gs, ge = rx2.match(shb, shl)
            suspect = suspect | (mk & (susp | ~am))
            st_abs = o + st_rel
            en_abs = st_abs + ge[0]
            starts.append(jnp.where(mk, st_abs, 0).astype(jnp.int32))
            ends.append(jnp.where(mk, en_abs, 0).astype(jnp.int32))
            valids.append(mk)
            o = jnp.where(mk, en_abs, o)
            active = mk
        sufb, sufl = S.slice_(rb, rl, o, rl)
        suspect = suspect | (nfa.match(sufb, sufl) & active)
        self.raise_where(suspect, ExceptionCode.PYTHON_FALLBACK)
        fb, fl = S.splice_spans(rb, rl,
                                jnp.stack(starts, axis=1),
                                jnp.stack(ends, axis=1),
                                jnp.stack(valids, axis=1), new)
        return CV(t=T.STR, sbytes=fb, slen=fl)

    _SPLIT_INDEX_CAP = 32

    def _split_item(self, sv: CV, k: int) -> CV:
        """s.split(sep[, maxsplit])[k] — k-th piece via k unrolled finds
        (sep mode) or token-bound kernels (whitespace mode); rows with
        fewer pieces raise IndexError (python semantics)."""
        sb, sl = sv.sbytes, sv.slen
        sep, maxsplit = sv.names
        if k < 0:
            raise NotCompilable("split negative index")
        if maxsplit is not None and k > maxsplit:
            # len(result) <= maxsplit+1 always: IndexError on every row
            self.raise_where(jnp.ones(self.ctx.b, dtype=bool),
                             ExceptionCode.INDEXERROR)
            return CV(t=T.STR, sbytes=jnp.zeros_like(sb),
                      slen=jnp.zeros_like(sl))
        if sep is None:
            start, stop, missing = S.ws_token_bounds(sb, sl, k)
            if maxsplit is not None and k == maxsplit:
                # remainder piece: from token k's start to end of string
                stop = jnp.where(missing, stop, sl)
            self.raise_where(missing, ExceptionCode.INDEXERROR)
            fb, fl = S.slice_(sb, sl, start, stop)
            return CV(t=T.STR, sbytes=fb, slen=fl)
        m = len(sep)
        if k > self._SPLIT_INDEX_CAP:
            raise NotCompilable(f"split index {k} beyond unroll cap")
        start = jnp.zeros(self.ctx.b, dtype=jnp.int32)
        missing = jnp.zeros(self.ctx.b, dtype=bool)
        for _ in range(k):
            pos = S.find_const(sb, sl, sep, start=start)
            missing = missing | (pos < 0)
            start = jnp.where(pos < 0, start, pos + m)
        nxt = S.find_const(sb, sl, sep, start=start)
        stop = jnp.where(nxt < 0, sl, nxt)
        if maxsplit is not None and k == maxsplit:
            stop = sl   # remainder keeps later separators
        self.raise_where(missing, ExceptionCode.INDEXERROR)
        fb, fl = S.slice_(sb, sl, start, stop)
        return CV(t=T.STR, sbytes=fb, slen=fl)

    def _match_method(self, m: CV, attr: str, args: list[CV]) -> CV:
        if attr != "group":
            raise NotCompilable(f"match.{attr}")
        if len(args) == 0:
            idx = 0
        elif len(args) == 1 and args[0].is_const and \
                isinstance(args[0].const, int):
            idx = args[0].const
        else:
            raise NotCompilable("match.group with non-constant index")
        elts = m.elts
        if not elts and m.names and m.names[0] == "#lazy_groups":
            # unanchored two-pass: the anchored engine runs only here,
            # where groups are actually consumed (+ its fail-safe routing)
            elts, suspect = m.names[1]()
            self.raise_where(suspect, ExceptionCode.PYTHON_FALLBACK)
        if not 0 <= idx < len(elts):
            raise NotCompilable(f"no such regex group {idx}")
        # match is None -> .group raises AttributeError (python semantics)
        self.raise_where(~m.valid, ExceptionCode.ATTRIBUTEERROR)
        return elts[idx]

    def eval_JoinedStr(self, node: ast.JoinedStr) -> CV:
        parts: list[CV] = []
        for v in node.values:
            if isinstance(v, ast.Constant):
                parts.append(const_cv(v.value))
            elif isinstance(v, ast.FormattedValue):
                if v.conversion not in (-1, 115):
                    raise NotCompilable("f-string conversion")
                if v.format_spec is not None:
                    fs = v.format_spec
                    if not (isinstance(fs, ast.JoinedStr)
                            and all(isinstance(x, ast.Constant)
                                    for x in fs.values)):
                        raise NotCompilable("dynamic f-string format spec")
                    spec = "".join(str(x.value) for x in fs.values)
                    parts.append(self._format_method(
                        "{:" + spec + "}", [self.eval(v.value)]))
                else:
                    parts.append(self._to_str(self.eval(v.value)))
            else:
                raise NotCompilable("f-string part")
        out = parts[0] if parts else const_cv("")
        for p in parts[1:]:
            out = self._str_concat(out, p)
        return out

    # ===================================================================
    # helpers
    # ===================================================================
    def truthy(self, v: CV):
        if v.kind == "split":
            if v.names[0] is None:
                # whitespace mode CAN yield zero pieces ("".split() == [])
                return S.ws_token_count(v.sbytes, v.slen) > 0
            # sep mode always yields at least one piece
            return jnp.ones(self.ctx.b, dtype=bool)
        if v.kind == "match":
            # a match object is truthy exactly when the match exists (the
            # NFA path's groupless elts=() must not fall into the tuple
            # branch, where an empty tuple is constant-falsy)
            return v.valid
        if v.is_const:
            return jnp.full(self.ctx.b, bool(v.const), dtype=bool)
        base = v.base
        if base is T.NULL:
            return jnp.zeros(self.ctx.b, dtype=bool)
        if base is T.BOOL:
            tr = v.data
        elif base in (T.I64, T.F64):
            tr = v.data != 0
        elif base is T.STR:
            tr = v.slen > 0
        elif v.elts is not None:
            tr = jnp.full(self.ctx.b, len(v.elts) > 0, dtype=bool)
        else:
            raise NotCompilable(f"truthiness of {v.t}")
        if v.valid is not None:
            tr = tr & v.valid
        return tr

    def _require_numeric(self, v: CV, what: str) -> CV:
        v = self._unwrap_option(v, what)
        if v.t is T.NULL:
            # the TypeError is already flagged under the ACTIVE mask by
            # _unwrap_option; a typed dummy lets dead branches trace on
            # (e.g. `float(x) if x else d` over an all-null column)
            return CV(t=T.I64, data=jnp.zeros(self.ctx.b, dtype=jnp.int64))
        if v.is_const:
            if isinstance(v.const, (bool, int, float)):
                return materialize(v, self.ctx.b)
            raise NotCompilable(f"{what}: not numeric")
        if v.base not in (T.BOOL, T.I64, T.F64):
            raise NotCompilable(f"{what}: {v.t} not numeric")
        return v

    def _unwrap_option(self, v: CV, what: str) -> CV:
        """Using an Option value in a non-None-tolerant op raises TypeError
        for rows where it's None (Python: None + 1 -> TypeError)."""
        if v.t is T.NULL:  # incl. the literal None constant
            self.raise_where(jnp.ones(self.ctx.b, bool), ExceptionCode.TYPEERROR)
            return CV(t=T.NULL)  # non-const marker: callers emit typed dummies
        if v.valid is not None:
            self.raise_where(~v.valid, ExceptionCode.TYPEERROR)
            return CV(t=v.base, data=v.data, sbytes=v.sbytes, slen=v.slen,
                      elts=v.elts, names=v.names)
        return v

    def _as_i64(self, v: CV):
        if v.base is T.BOOL:
            return v.data.astype(jnp.int64)
        return v.data

    def _ascii_guard(self, sbytes, slen):
        """Index-space string ops count BYTES; multibyte UTF-8 rows diverge
        from Python codepoint semantics -> normal-case violation (row re-runs
        on the interpreter, keeping dual-mode exact)."""
        self.raise_where(S.non_ascii_rows(sbytes, slen),
                         ExceptionCode.NORMALCASEVIOLATION)

    # -- arithmetic ---------------------------------------------------------
    def _binop(self, op: ast.operator, a: CV, b: CV) -> CV:
        if a.is_const and b.is_const:
            try:
                return const_cv(_const_binop(op, a.const, b.const))
            except ZeroDivisionError:
                self.raise_where(jnp.ones(self.ctx.b, bool),
                                 ExceptionCode.ZERODIVISIONERROR)
                return const_cv(0)
        # string ops
        if a.base is T.STR or b.base is T.STR or \
                (a.is_const and isinstance(a.const, str)) or \
                (b.is_const and isinstance(b.const, str)):
            return self._str_binop(op, a, b)
        # keep exponent constness visible to _pow before materialization
        b_const_int = b.const if (b.is_const and isinstance(b.const, int)
                                  and not isinstance(b.const, bool)) else None
        a = self._require_numeric(a, "arithmetic")
        b = self._require_numeric(b, "arithmetic")
        if isinstance(op, ast.Pow) and b_const_int is not None:
            if b_const_int >= 0:
                return CV(t=T.I64 if a.base is not T.F64 else T.F64,
                          data=jnp.power(
                              self._as_i64(a) if a.base is not T.F64
                              else a.data, b_const_int))
            # int ** negative-const -> float in Python
            return CV(t=T.F64, data=jnp.power(self._cast(a.data, T.F64),
                                              float(b_const_int)))
        out_t = T.super_type(a.base, b.base)
        if out_t is T.BOOL:
            out_t = T.I64  # bool+bool -> int
        ad, bd = a.data, b.data
        if isinstance(op, ast.Add):
            return CV(t=out_t, data=self._cast(ad, out_t) + self._cast(bd, out_t))
        if isinstance(op, ast.Sub):
            return CV(t=out_t, data=self._cast(ad, out_t) - self._cast(bd, out_t))
        if isinstance(op, ast.Mult):
            return CV(t=out_t, data=self._cast(ad, out_t) * self._cast(bd, out_t))
        if isinstance(op, ast.Div):
            bz = self._cast(bd, T.F64)
            self.raise_where(bz == 0.0, ExceptionCode.ZERODIVISIONERROR)
            safe = jnp.where(bz == 0.0, 1.0, bz)
            return CV(t=T.F64, data=self._cast(ad, T.F64) / safe)
        if isinstance(op, ast.FloorDiv):
            return self._floordiv(a, b, out_t)
        if isinstance(op, ast.Mod):
            return self._mod(a, b, out_t)
        if isinstance(op, ast.Pow):
            return self._pow(a, b)
        if isinstance(op, ast.BitAnd) and out_t is T.I64:
            return CV(t=T.I64, data=self._cast(ad, T.I64) & self._cast(bd, T.I64))
        if isinstance(op, ast.BitOr) and out_t is T.I64:
            return CV(t=T.I64, data=self._cast(ad, T.I64) | self._cast(bd, T.I64))
        if isinstance(op, ast.BitXor) and out_t is T.I64:
            return CV(t=T.I64, data=self._cast(ad, T.I64) ^ self._cast(bd, T.I64))
        raise NotCompilable(f"operator {type(op).__name__}")

    def _cast(self, arr, t: T.Type):
        return arr.astype(dtype_for(t))

    def _floordiv(self, a: CV, b: CV, out_t: T.Type) -> CV:
        zero = self._cast(b.data, out_t) == 0
        self.raise_where(zero, ExceptionCode.ZERODIVISIONERROR)
        bd = jnp.where(zero, self._one(out_t), self._cast(b.data, out_t))
        ad = self._cast(a.data, out_t)
        return CV(t=out_t, data=jnp.floor_divide(ad, bd))

    def _mod(self, a: CV, b: CV, out_t: T.Type) -> CV:
        zero = self._cast(b.data, out_t) == 0
        self.raise_where(zero, ExceptionCode.ZERODIVISIONERROR)
        bd = jnp.where(zero, self._one(out_t), self._cast(b.data, out_t))
        ad = self._cast(a.data, out_t)
        return CV(t=out_t, data=jnp.mod(ad, bd))  # numpy mod == Python %

    def _one(self, t: T.Type):
        return jnp.asarray(1, dtype=dtype_for(t))

    def _pow(self, a: CV, b: CV) -> CV:
        if a.base is T.F64 or b.base is T.F64:
            return CV(t=T.F64,
                      data=jnp.power(self._cast(a.data, T.F64),
                                     self._cast(b.data, T.F64)))
        if b.is_const and isinstance(b.const, int):
            if b.const >= 0:
                return CV(t=T.I64, data=jnp.power(self._as_i64(a), b.const))
            # int ** negative-const -> float in Python
            return CV(t=T.F64, data=jnp.power(self._cast(a.data, T.F64),
                                              float(b.const)))
        bd = self._as_i64(b)
        neg = bd < 0
        # data-dependent result TYPE (int**neg -> float): those rows violate
        # the speculated normal case and re-run on the interpreter
        self.raise_where(neg, ExceptionCode.NORMALCASEVIOLATION)
        return CV(t=T.I64,
                  data=jnp.power(self._as_i64(a), jnp.where(neg, 0, bd)))

    # -- string ops ---------------------------------------------------------
    def _option_eq(self, a: CV, b: CV, raw_eq, op):
        """Validity-aware equality truth table: values equal AND both
        present, OR both None (Python: None == None)."""
        av = a.valid if a.valid is not None else jnp.ones(self.ctx.b, bool)
        bv = b.valid if b.valid is not None else jnp.ones(self.ctx.b, bool)
        a_null = a.t is T.NULL
        b_null = b.t is T.NULL
        if a_null:
            av = jnp.zeros(self.ctx.b, bool)
        if b_null:
            bv = jnp.zeros(self.ctx.b, bool)
        eq = (av & bv & raw_eq) | (~av & ~bv)
        return eq if isinstance(op, ast.Eq) else ~eq

    def _strip_option_strpair(self, v: CV):
        """(bytes, lens) of a possibly-Option str WITHOUT raising for None
        rows (callers gate on validity themselves)."""
        if v.is_const:
            if not isinstance(v.const, str):
                raise NotCompilable("expected str")
            return S.broadcast_const(v.const, self.ctx.b)
        if v.base is not T.STR:
            raise NotCompilable(f"expected str, got {v.t}")
        return v.sbytes, v.slen

    def _to_strpair(self, v: CV):
        """(bytes, lens) for a str CV (materializing consts)."""
        v = self._unwrap_option(v, "string op")
        if v.t is T.NULL:  # error already flagged under the active mask
            return S.broadcast_const("", self.ctx.b)
        return self._strip_option_strpair(v)

    def _str_binop(self, op: ast.operator, a: CV, b: CV) -> CV:
        if isinstance(op, ast.Add):
            return self._str_concat(a, b)
        if isinstance(op, ast.Mod):
            return self._str_format(a, b)
        if isinstance(op, ast.Mult):
            sv, iv = (a, b) if (a.base is T.STR or (
                a.is_const and isinstance(a.const, str))) else (b, a)
            if not (iv.is_const and isinstance(iv.const, int)
                    and not isinstance(iv.const, bool)):
                raise NotCompilable("str * dynamic int")
            n = max(0, iv.const)
            if sv.is_const:
                return const_cv(sv.const * n)
            if n == 0:
                return const_cv("")
            # repeated doubling: O(log n) concats instead of n-1 chained
            # kernels with quadratically growing intermediates
            pows = {1: sv}
            p2 = 1
            while p2 * 2 <= n:
                pows[p2 * 2] = self._str_concat(pows[p2], pows[p2])
                p2 *= 2
            out = None
            rem = n
            for k in sorted(pows, reverse=True):
                while rem >= k:
                    out = pows[k] if out is None else \
                        self._str_concat(out, pows[k])
                    rem -= k
            return out
        raise NotCompilable(f"str operator {type(op).__name__}")

    def _str_concat(self, a: CV, b: CV) -> CV:
        if a.is_const and b.is_const:
            return const_cv(a.const + b.const)
        ab, al = self._to_strpair(a)
        bb, bl = self._to_strpair(b)
        rb, rl = S.concat(ab, al, bb, bl)
        return CV(t=T.STR, sbytes=rb, slen=rl)

    def _str_format(self, fmt: CV, args: CV) -> CV:
        """'%05d' % x — constant format string, limited directives."""
        if not (fmt.is_const and isinstance(fmt.const, str)):
            raise NotCompilable("dynamic format string")
        spec = fmt.const
        arg_list = list(args.elts) if args.elts is not None else [args]
        import re as _re

        # '%%' splits out first so "%%d" stays the literal '%d' instead of
        # consuming an argument (advisor finding, round 1 — CPython treats
        # '%%' as an escape wherever it appears)
        pieces = _re.split(r"(%%|%0?\d*(?:\.\d+)?[dsfxXo])", spec)
        out: Optional[CV] = None
        ai = 0
        for piece in pieces:
            if not piece:
                continue
            if piece == "%%":
                part = const_cv("%")
            elif _re.fullmatch(r"%0?\d*(?:\.\d+)?[dsfxXo]", piece):
                if ai >= len(arg_list):
                    raise NotCompilable("format arity")
                arg = arg_list[ai]
                ai += 1
                kind = piece[-1]
                pad_zero = piece.startswith("%0")
                body = piece[1:-1]
                prec = None
                if "." in body:
                    body, ps_ = body.split(".", 1)
                    prec = int(ps_ or "0")
                width = int(body.lstrip("0") or "0") if body else 0
                if kind == "f":
                    part = self._float_format(arg, 6 if prec is None
                                              else prec, width, pad_zero)
                    out = part if out is None else \
                        self._str_concat(out, part)
                    continue
                if prec is not None:
                    raise NotCompilable(f"format {piece!r}")
                if kind in ("x", "X", "o"):
                    if arg.base is T.F64 or (arg.is_const and
                                             isinstance(arg.const, float)):
                        raise NotCompilable("%x of float")  # TypeError
                    base = 8 if kind == "o" else 16
                    fb, fl = S.int_to_base(self._as_i64(
                        self._require_numeric(arg, "%x")), base,
                        prefix=False)
                    if kind == "X":
                        fb, fl = S.upper(fb, fl)
                    if pad_zero and width > 0:
                        fb, fl = S.zfill(fb, fl, width)
                    elif width > 0:
                        fb, fl = S.pad_left(fb, fl, width, " ")
                    part = CV(t=T.STR, sbytes=fb, slen=fl)
                    out = part if out is None else \
                        self._str_concat(out, part)
                    continue
                if kind == "d":
                    arg = self._require_numeric(arg, "%d")
                    fb, fl = S.format_i64(self._as_i64(arg), width=width,
                                          pad_zero=pad_zero)
                    if width > 0 and not pad_zero:
                        fb, fl = S.pad_left(fb, fl, width, " ")
                    part = CV(t=T.STR, sbytes=fb, slen=fl)
                elif kind == "s":
                    part = self._to_str(arg)
                    if width > 0:
                        pb, pl = self._to_strpair(part)
                        fb, fl = S.pad_left(pb, pl, width, " ")
                        part = CV(t=T.STR, sbytes=fb, slen=fl)
                else:
                    raise NotCompilable(f"format kind {kind!r}")
            else:
                if "%" in piece:
                    # an unrecognized directive (%#x, %e, %-8d, lone %)
                    # must never pass through as literal text
                    raise NotCompilable(f"format {piece!r}")
                part = const_cv(piece)
            out = part if out is None else self._str_concat(out, part)
        if ai != len(arg_list):
            # CPython: TypeError('not all arguments converted ...') — the
            # interpreter keeps exact semantics
            raise NotCompilable("surplus % format arguments")
        return out if out is not None else const_cv("")

    def _format_method(self, spec: str, args: list[CV]) -> CV:
        """'...{}...{:02}...'.format(a, b) with plain / zero-pad int specs
        (reference: FunctionRegistry str.format subset). Anything outside the
        supported subset raises NotCompilable so rows keep exact Python
        semantics via the interpreter."""
        import re as _re

        pieces = _re.split(r"(\{\{|\}\}|\{[^{}]*\})", spec)
        out: Optional[CV] = None
        auto_i = 0
        saw_auto = saw_manual = False
        for piece in pieces:
            if not piece:
                continue
            if piece == "{{":
                part = const_cv("{")
            elif piece == "}}":
                part = const_cv("}")
            elif piece.startswith("{"):
                m = _re.fullmatch(
                    r"\{(\d*)(?::([+]?)(0?)(\d*)(,?)(?:\.(\d+))?"
                    r"([dsf]?))?\}", piece)
                if not m:
                    raise NotCompilable(f"format spec {piece!r}")
                if m.group(1):
                    saw_manual = True
                    idx = int(m.group(1))
                else:
                    saw_auto = True
                    idx = auto_i
                    auto_i += 1
                if saw_auto and saw_manual:
                    # CPython raises ValueError on mixed numbering
                    raise NotCompilable("mixed manual/auto format numbering")
                if idx >= len(args):
                    raise NotCompilable("format arity")
                arg = args[idx]
                plus = m.group(2) == "+"
                zero = m.group(3) == "0"
                width = int(m.group(4)) if m.group(4) else 0
                comma = m.group(5) == ","
                prec = int(m.group(6)) if m.group(6) else None
                kind = m.group(7) or ""
                if comma and (prec is not None or kind not in ("", "d")):
                    raise NotCompilable(f"format spec {piece!r}")
                if comma and zero:
                    # python zero-fills WITH commas ('0,012'): beyond the
                    # grouping kernel
                    raise NotCompilable("comma grouping with zero fill")
                if kind == "f":
                    part = self._float_format(arg, 6 if prec is None
                                              else prec, width, zero,
                                              plus=plus)
                    out = part if out is None else \
                        self._str_concat(out, part)
                    continue
                if prec is not None:
                    # bare '{:.2}' is CPython general format (g-style
                    # sig-digits; ValueError on ints) — not fixed-point
                    raise NotCompilable(f"format spec {piece!r}")
                arg_is_float = arg.base is T.F64 or (
                    arg.is_const and isinstance(arg.const, float))
                if (kind == "d" or comma) and arg_is_float:
                    # CPython: ValueError for :d; ',' on floats groups the
                    # int part (beyond the kernel) — both fall back
                    raise NotCompilable("format d/comma of float")
                is_int = (kind == "d") or (
                    kind == "" and ((arg.base is T.I64 and not arg.is_const)
                                    or (arg.is_const and
                                        isinstance(arg.const, int) and
                                        not isinstance(arg.const, bool))))
                if is_int:
                    na = self._require_numeric(arg, "format int")
                    iv = self._as_i64(na)
                    if plus:
                        # sign first, THEN zero-fill to the total width
                        # (python counts the sign inside the field)
                        fb, fl = self._prepend_plus(*S.format_i64(iv),
                                                    iv >= 0)
                        if zero and width > 0:
                            fb, fl = S.zfill(fb, fl, width)
                    else:
                        fb, fl = S.format_i64(iv, width=0 if comma
                                              else width, pad_zero=zero)
                    if comma:
                        fb, fl = S.group_thousands(fb, fl)
                    if width > 0 and not zero:
                        fb, fl = S.pad_left(fb, fl, width, " ")
                    part = CV(t=T.STR, sbytes=fb, slen=fl)
                elif kind == "d":
                    raise NotCompilable("format d of non-int")
                elif plus or comma:
                    # CPython: ValueError for sign/comma on non-numerics
                    raise NotCompilable("sign/comma flag on non-numeric")
                else:
                    part = self._to_str(arg)
                    if width > 0:
                        # Python left-aligns strings; zero flag fills right
                        pb, pl = self._to_strpair(part)
                        fb, fl = S.pad_right(pb, pl, width,
                                             "0" if zero else " ")
                        part = CV(t=T.STR, sbytes=fb, slen=fl)
            else:
                if "{" in piece or "}" in piece:
                    # CPython raises ValueError on single braces
                    raise NotCompilable("single brace in format string")
                part = const_cv(piece)
            out = part if out is None else self._str_concat(out, part)
        return out if out is not None else const_cv("")

    def _prepend_plus(self, fb, fl, nonneg):
        """'+' before non-negative rows (negatives already carry '-')."""
        pb, pl = S.broadcast_const("+", self.ctx.b)
        return S.concat(pb, jnp.where(nonneg, pl, 0), fb, fl)

    def _float_format(self, arg: CV, prec: int, width: int = 0,
                      pad_zero: bool = False, plus: bool = False) -> CV:
        """%.Nf / {:.Nf} fixed-point rendering; rounding ties and huge
        magnitudes route to the interpreter (CPython renders from the
        exact binary value — scaled integer math can double-round)."""
        from ..core.errors import ExceptionCode

        na = self._require_numeric(arg, "float format")
        fv = self._cast(na.data, T.F64)
        fb, fl, suspect = S.format_f64(fv, prec)
        self.raise_where(suspect, ExceptionCode.NORMALCASEVIOLATION)
        if plus:
            fb, fl = self._prepend_plus(fb, fl, ~jnp.signbit(fv))
        if width > 0:
            if pad_zero:
                fb, fl = S.zfill(fb, fl, width)
            else:
                fb, fl = S.pad_left(fb, fl, width, " ")
        return CV(t=T.STR, sbytes=fb, slen=fl)

    def _to_str(self, v: CV) -> CV:
        if v.is_const:
            return const_cv(str(v.const))
        if v.base is T.STR:
            return v
        if v.base is T.BOOL:
            v2 = self._require_numeric(v, "str()")
            tb, tl = S.broadcast_const("True", self.ctx.b)
            fb2, fl2 = S.broadcast_const("False", self.ctx.b)
            tb, fb2 = S._pad_common(tb, fb2)
            sb = jnp.where(v2.data[:, None], tb, fb2)
            sl = jnp.where(v2.data, tl, fl2)
            return CV(t=T.STR, sbytes=sb.astype(jnp.uint8),
                      slen=sl.astype(jnp.int32))
        if v.base is T.I64:
            v = self._require_numeric(v, "str()")
            fb, fl = S.format_i64(self._as_i64(v))
            return CV(t=T.STR, sbytes=fb, slen=fl)
        raise NotCompilable(f"str() of {v.t}")

    def _slice(self, val: CV, sl: ast.Slice) -> CV:
        if val.base is not T.STR:
            if val.elts is not None:
                # tuple slicing with const bounds
                lo = self._const_or_none(sl.lower)
                hi = self._const_or_none(sl.upper)
                if sl.step is not None:
                    raise NotCompilable("tuple slice step")
                return tuple_cv(list(val.elts)[slice(lo, hi)],
                                kind=val.kind)
            raise NotCompilable(f"slice of {val.t}")
        if sl.step is not None:
            raise NotCompilable("string slice step")
        val = self._unwrap_option(val, "slice")
        self._ascii_guard(val.sbytes, val.slen)
        start = self._index_arr(sl.lower)
        stop = self._index_arr(sl.upper)
        rb, rl = S.slice_(val.sbytes, val.slen, start, stop)
        return CV(t=T.STR, sbytes=rb, slen=rl)

    def _const_or_none(self, node):
        if node is None:
            return None
        v = self.eval(node)
        if v.is_const and isinstance(v.const, int):
            return v.const
        raise NotCompilable("non-constant tuple slice bound")

    def _index_arr(self, node):
        if node is None:
            return None
        v = self._require_numeric(self.eval(node), "slice bound")
        return self._as_i64(v).astype(jnp.int32)

    def _str_method(self, recv: CV, name: str, args: list[CV]) -> CV:
        if recv.is_const and all(a.is_const for a in args):
            try:
                return const_cv(getattr(recv.const, name)(
                    *[a.const for a in args]))
            except Exception:
                pass
        recv = self._unwrap_option(recv, f"str.{name}")
        rb, rl = self._to_strpair(recv)

        def need_const_str(i: int) -> str:
            if i >= len(args) or not (args[i].is_const and
                                      isinstance(args[i].const, str)):
                raise NotCompilable(f"str.{name}: needs constant str arg")
            return args[i].const

        if name == "casefold":
            # ASCII casefold == lower; multibyte rows already routed by the
            # guard below where byte semantics could diverge
            self._ascii_guard(rb, rl)
            fb, fl = S.lower(rb, rl)
            return CV(t=T.STR, sbytes=fb, slen=fl)
        if name in ("removeprefix", "removesuffix"):
            affix = need_const_str(0)
            if not affix:
                return CV(t=T.STR, sbytes=rb, slen=rl)
            m = len(affix.encode())
            if name == "removeprefix":
                hit = S.startswith_const(rb, rl, affix)
                start = jnp.where(hit, m, 0).astype(jnp.int32)
                fb, fl = S.slice_(rb, rl, start, None)
            else:
                hit = S.endswith_const(rb, rl, affix)
                stop = jnp.where(hit, rl - m, rl).astype(jnp.int32)
                fb, fl = S.slice_(rb, rl, None, stop)
            return CV(t=T.STR, sbytes=fb, slen=fl)
        if name in ("partition", "rpartition"):
            self._ascii_guard(rb, rl)
            sep = need_const_str(0)
            if not sep:
                raise NotCompilable("partition with empty separator")
            m = len(sep.encode())
            pos = S.find_const(rb, rl, sep, reverse=name == "rpartition")
            found = pos >= 0
            if name == "partition":
                # not found: (s, '', '')
                head_stop = jnp.where(found, pos, rl).astype(jnp.int32)
                tail_start = jnp.where(found, pos + m, rl).astype(jnp.int32)
            else:
                # not found: ('', '', s)
                head_stop = jnp.where(found, pos, 0).astype(jnp.int32)
                tail_start = jnp.where(found, pos + m,
                                       jnp.zeros_like(rl)).astype(jnp.int32)
            hb, hl = S.slice_(rb, rl, None, head_stop)
            sb2, sl2 = S.broadcast_const(sep, self.ctx.b)
            sl2 = jnp.where(found, sl2, 0)
            tb, tl = S.slice_(rb, rl, tail_start, None)
            return tuple_cv([CV(t=T.STR, sbytes=hb, slen=hl),
                             CV(t=T.STR, sbytes=sb2, slen=sl2),
                             CV(t=T.STR, sbytes=tb, slen=tl)])
        if name in ("lower", "upper", "swapcase"):
            # byte-level case maps cover ASCII only: 'équipe'.upper() must
            # route, not return 'éQUIPE' (review r4)
            self._ascii_guard(rb, rl)
            fb, fl = getattr(S, name)(rb, rl)
            return CV(t=T.STR, sbytes=fb, slen=fl)
        if name in ("strip", "lstrip", "rstrip"):
            self._ascii_guard(rb, rl)  # unicode whitespace divergence
            chars = need_const_str(0) if args else None
            left = name != "rstrip"
            right = name != "lstrip"
            fb, fl = S.strip(rb, rl, chars, left=left, right=right)
            return CV(t=T.STR, sbytes=fb, slen=fl)
        if name in ("find", "rfind", "index", "rindex"):
            self._ascii_guard(rb, rl)  # positions are byte offsets
            needle = need_const_str(0)
            start = None
            if len(args) > 1:
                start = self._as_i64(
                    self._require_numeric(args[1], "find start")
                ).astype(jnp.int32)
            pos = S.find_const(rb, rl, needle, start=start,
                               reverse=name.startswith("r"))
            if name in ("index", "rindex"):
                self.raise_where(pos < 0, ExceptionCode.VALUEERROR)
            return CV(t=T.I64, data=pos.astype(jnp.int64))
        if name == "replace":
            old = need_const_str(0)
            new = need_const_str(1)
            fb, fl = S.replace_const(rb, rl, old, new)
            return CV(t=T.STR, sbytes=fb, slen=fl)
        if name == "startswith":
            return CV(t=T.BOOL, data=S.startswith_const(rb, rl, need_const_str(0)))
        if name == "endswith":
            return CV(t=T.BOOL, data=S.endswith_const(rb, rl, need_const_str(0)))
        if name == "count":
            self._ascii_guard(rb, rl)
            needle = need_const_str(0)
            cnt = S.count_const(rb, rl, needle)
            return CV(t=T.I64, data=cnt.astype(jnp.int64))
        if name in ("isdigit", "isdecimal", "isnumeric", "isalpha",
                    "isalnum", "isspace"):
            self._ascii_guard(rb, rl)
            return CV(t=T.BOOL, data=S.char_class_all(rb, rl, name))
        if name in ("islower", "isupper", "istitle"):
            self._ascii_guard(rb, rl)
            return CV(t=T.BOOL, data=S.case_pred(rb, rl, name))
        if name == "capitalize":
            self._ascii_guard(rb, rl)
            fb, fl = S.capitalize(rb, rl)
            return CV(t=T.STR, sbytes=fb, slen=fl)
        if name == "title":
            self._ascii_guard(rb, rl)
            fb, fl = S.title(rb, rl)
            return CV(t=T.STR, sbytes=fb, slen=fl)
        if name == "format":
            if not (recv.is_const and isinstance(recv.const, str)):
                raise NotCompilable("format on dynamic string")
            return self._format_method(recv.const, args)
        if name == "split":
            self._ascii_guard(rb, rl)
            if len(args) > 2:
                raise NotCompilable("str.split arity")
            maxsplit = None
            if len(args) == 2:
                if not (args[1].is_const and isinstance(args[1].const, int)):
                    raise NotCompilable("str.split dynamic maxsplit")
                maxsplit = args[1].const if args[1].const >= 0 else None
            if not args or (args[0].is_const and args[0].const is None):
                sep = None     # whitespace mode: runs of ws, ends stripped
            else:
                sep = need_const_str(0)
                if sep == "":
                    raise NotCompilable("str.split empty separator")
            # LAZY view (reference: split codegen'd lazily too,
            # FunctionRegistry): only [const_int] and len() force pieces —
            # the result's ARITY is data-dependent, so it can't be a tuple
            return CV(t=T.PYOBJECT, kind="split", names=(sep, maxsplit),
                      sbytes=rb, slen=rl)
        if name == "join":
            if not (recv.is_const and isinstance(recv.const, str)):
                raise NotCompilable("join with dynamic separator")
            if len(args) != 1:
                raise NotCompilable("join takes exactly one argument")
            items = self._cv_iter_items(args[0])
            if items is None:
                raise NotCompilable("join over non-static iterable")
            out: Optional[CV] = None
            sep_cv = const_cv(recv.const)
            for it in items:
                if not (it.base is T.STR or
                        (it.is_const and isinstance(it.const, str))):
                    raise NotCompilable("join of non-str element")
                out = it if out is None else self._str_concat(
                    self._str_concat(out, sep_cv), it)
            return out if out is not None else const_cv("")
        if name in ("center", "ljust", "rjust"):
            # width semantics are per CHARACTER: multibyte rows must take
            # the interpreter path like the other byte-position methods
            self._ascii_guard(rb, rl)
            if not (args and args[0].is_const
                    and isinstance(args[0].const, int)):
                raise NotCompilable(f"str.{name} dynamic width")
            fill = " "
            if len(args) > 1:
                if not (args[1].is_const and isinstance(args[1].const, str)
                        and len(args[1].const.encode()) == 1):
                    raise NotCompilable(f"str.{name} fill char")
                fill = args[1].const
            kern = {"center": S.center, "ljust": S.pad_right,
                    "rjust": S.pad_left}[name]
            fb, fl = kern(rb, rl, args[0].const, fill)
            return CV(t=T.STR, sbytes=fb, slen=fl)
        if name == "zfill":
            if not (args and args[0].is_const and isinstance(args[0].const, int)):
                raise NotCompilable("str.zfill dynamic width")
            fb, fl = S.zfill(rb, rl, args[0].const)
            return CV(t=T.STR, sbytes=fb, slen=fl)
        raise NotCompilable(f"str.{name}")

    # -- dict methods (named-row CVs; reference: FunctionRegistry dict
    # pop/popitem codegen) --------------------------------------------------
    def _dict_method(self, node, recv: CV, name: str, args: list[CV]) -> CV:
        keys = list(recv.names or ())
        if name == "get":
            if not (args and args[0].is_const
                    and isinstance(args[0].const, str)):
                raise NotCompilable("dict.get dynamic key")
            if args[0].const in keys:
                return recv.elts[keys.index(args[0].const)]
            return args[1] if len(args) > 1 else const_cv(None)
        if name == "keys":
            return tuple_cv([const_cv(k) for k in keys])
        if name == "values":
            return tuple_cv(list(recv.elts))
        if name == "items":
            return tuple_cv([tuple_cv([const_cv(k), v])
                             for k, v in zip(keys, recv.elts)])
        if name in ("pop", "popitem"):
            if name == "pop":
                if not (args and args[0].is_const
                        and isinstance(args[0].const, str)):
                    raise NotCompilable("dict.pop dynamic key")
                key = args[0].const
                if key not in keys:
                    if len(args) > 1:
                        return args[1]
                    raise NotCompilable(f"dict.pop missing key {key!r}")
                idx = keys.index(key)
                ret: CV = recv.elts[idx]
            else:
                if args:
                    raise NotCompilable("dict.popitem arity")
                if not keys:
                    raise NotCompilable("popitem on empty dict")
                idx = len(keys) - 1
                ret = tuple_cv([const_cv(keys[idx]), recv.elts[idx]])
            rest = tuple_cv([e for j, e in enumerate(recv.elts) if j != idx],
                            names=[k for j, k in enumerate(keys) if j != idx])
            # mutation is only sound on receivers we can fully account for:
            # a plain un-aliased name (rebind) or a fresh temporary whose
            # value nothing else can observe. Anything else (subscript/
            # attribute receivers, aliased names) must fall back, or the
            # dropped mutation silently diverges from CPython
            tgt = node.func.value
            if isinstance(tgt, ast.Name):
                if self._name_escapes(tgt.id):
                    raise NotCompilable(f"dict.{name} on aliased dict")
                if tgt.id in self.env:
                    self._assign_target(tgt, rest)
            elif not isinstance(tgt, (ast.Dict, ast.DictComp, ast.Call)):
                raise NotCompilable(f"dict.{name} on non-name receiver")
            return ret
        raise NotCompilable(f"dict.{name}")

    def _name_escapes(self, name: str) -> bool:
        """Conservative alias analysis over the UDF AST: may `name`'s value
        be observable through ANOTHER binding? True for any bare-Name read
        that isn't the receiver of a subscript/attribute access — e.g.
        `e = d`, `(d,)`, `f(d)`, `return d`. Mutating through the name is
        only sound when it never escapes (value-semantics env can't model
        shared mutation)."""
        tree = getattr(self, "udf_tree", None)
        if tree is None:
            return True   # no tree to analyze: assume the worst
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Name) and node.id == name
                    and isinstance(node.ctx, ast.Load)):
                continue
            parent = getattr(node, "_tpx_parent", None)
            if parent is None:
                # annotate lazily once per tree
                for p in ast.walk(tree):
                    for ch in ast.iter_child_nodes(p):
                        ch._tpx_parent = p  # type: ignore[attr-defined]
                parent = getattr(node, "_tpx_parent", None)
            if isinstance(parent, (ast.Subscript, ast.Attribute)) and \
                    parent.value is node:
                continue   # d[...] / d.method(...): receiver use, no escape
            return True
        return False

    # -- comparisons --------------------------------------------------------
    def _compare(self, op: ast.cmpop, a: CV, b: CV):
        # None comparisons: x is None / x == None
        if isinstance(op, (ast.Is, ast.IsNot, ast.Eq, ast.NotEq)):
            a_is_none = (a.t is T.NULL) or (a.is_const and a.const is None)
            b_is_none = (b.t is T.NULL) or (b.is_const and b.const is None)
            if a_is_none or b_is_none:
                other = b if a_is_none else a
                if a_is_none and b_is_none:
                    isn = jnp.ones(self.ctx.b, dtype=bool)
                elif other.valid is not None:
                    isn = ~other.valid
                elif other.t is T.NULL:
                    isn = jnp.ones(self.ctx.b, dtype=bool)
                else:
                    isn = jnp.zeros(self.ctx.b, dtype=bool)
                pos = isinstance(op, (ast.Is, ast.Eq))
                return isn if pos else ~isn
        if isinstance(op, (ast.In, ast.NotIn)):
            res = self._contains(a, b)
            return res if isinstance(op, ast.In) else ~res
        # strings
        a_str = a.base is T.STR or (a.is_const and isinstance(a.const, str))
        b_str = b.base is T.STR or (b.is_const and isinstance(b.const, str))
        if a_str and b_str:
            if isinstance(op, (ast.Eq, ast.NotEq)) and \
                    (a.valid is not None or b.valid is not None):
                # Python: None == "x" is False (no TypeError) — keep Option
                # rows on device instead of erroring them to the interpreter
                ab, al = self._strip_option_strpair(a)
                bb, bl = self._strip_option_strpair(b)
                return self._option_eq(a, b, S.equals(ab, al, bb, bl), op)
            ab, al = self._to_strpair(a)
            bb, bl = self._to_strpair(b)
            if isinstance(op, ast.Eq):
                return S.equals(ab, al, bb, bl)
            if isinstance(op, ast.NotEq):
                return ~S.equals(ab, al, bb, bl)
            if isinstance(op, ast.Lt):
                return S.compare_lt(ab, al, bb, bl)
            if isinstance(op, ast.LtE):
                return S.compare_lt(ab, al, bb, bl, or_equal=True)
            if isinstance(op, ast.Gt):
                return S.compare_lt(bb, bl, ab, al)
            if isinstance(op, ast.GtE):
                return S.compare_lt(bb, bl, ab, al, or_equal=True)
            raise NotCompilable("string comparison op")
        if a_str != b_str:
            # str vs non-str: values never equal, but None == None is True
            # when both sides are Option/None
            if isinstance(op, (ast.Eq, ast.NotEq)):
                return self._option_eq(a, b,
                                       jnp.zeros(self.ctx.b, dtype=bool), op)
            self.raise_where(jnp.ones(self.ctx.b, bool), ExceptionCode.TYPEERROR)
            return jnp.zeros(self.ctx.b, dtype=bool)
        if isinstance(op, (ast.Eq, ast.NotEq)) and \
                (a.valid is not None or b.valid is not None):
            a2 = CV(t=a.base, data=a.data) if a.valid is not None else a
            b2 = CV(t=b.base, data=b.data) if b.valid is not None else b
            an = self._require_numeric(a2, "comparison")
            bn = self._require_numeric(b2, "comparison")
            return self._option_eq(a, b, an.data == bn.data, op)
        an = self._require_numeric(a, "comparison")
        bn = self._require_numeric(b, "comparison")
        ad, bd = an.data, bn.data
        if isinstance(op, ast.Eq):
            return ad == bd
        if isinstance(op, ast.NotEq):
            return ad != bd
        if isinstance(op, ast.Lt):
            return ad < bd
        if isinstance(op, ast.LtE):
            return ad <= bd
        if isinstance(op, ast.Gt):
            return ad > bd
        if isinstance(op, ast.GtE):
            return ad >= bd
        raise NotCompilable(f"comparison {type(op).__name__}")

    def _contains(self, needle: CV, hay: CV):
        # 'x' in s  (constant needle, columnar haystack)
        if hay.base is T.STR or (hay.is_const and isinstance(hay.const, str)):
            if needle.is_const and isinstance(needle.const, str):
                hb, hl = self._to_strpair(hay)
                return S.contains_const(hb, hl, needle.const)
            raise NotCompilable("dynamic needle for `in`")
        items = None
        if hay.is_const and isinstance(hay.const,
                                       (tuple, list, set, frozenset, dict)):
            # iteration order gives dict KEYS — python `in` semantics
            items = [const_cv(v) for v in hay.const]
        elif hay.elts is not None:
            # dict CV: python `in` tests KEYS (which are static strs)
            items = [const_cv(k) for k in hay.names] \
                if hay.names is not None else list(hay.elts)
        if items is not None:
            acc = jnp.zeros(self.ctx.b, dtype=bool)
            for e in items:
                acc = acc | self._compare(ast.Eq(), needle, e)
            return acc
        raise NotCompilable(f"`in` over {hay.t}")

    # -- builtins -----------------------------------------------------------
    def _builtin_int(self, args: list[CV]) -> CV:
        if not args:
            return const_cv(0)
        v = args[0]
        if len(args) > 1:
            if not (args[1].is_const and isinstance(args[1].const, int)
                    and 2 <= args[1].const <= 36):
                raise NotCompilable("int(x, base) dynamic base")
            if not (v.base is T.STR or (v.is_const and
                                        isinstance(v.const, str))):
                raise NotCompilable("int(x, base) of non-string")
            if v.is_const:
                try:
                    return const_cv(int(v.const, args[1].const))
                except ValueError:
                    pass   # every row raises: keep python semantics below
            rb, rl = self._to_strpair(v)
            self._ascii_guard(rb, rl)
            val, bad, ovf = S.parse_int_base(rb, rl, args[1].const)
            self.raise_where(bad, ExceptionCode.VALUEERROR)
            self.raise_where(ovf & ~bad, ExceptionCode.NORMALCASEVIOLATION)
            return CV(t=T.I64, data=val)
        if v.is_const:
            try:
                return const_cv(int(v.const))
            except (ValueError, TypeError):
                pass
        v = self._unwrap_option(v, "int()")
        if v.t is T.NULL:
            return CV(t=T.I64, data=jnp.zeros(self.ctx.b, dtype=jnp.int64))
        if v.base is T.STR:
            val, bad, route = S.parse_i64(v.sbytes, v.slen)
            self.raise_where(bad, ExceptionCode.VALUEERROR)
            # valid python int, unrepresentable in i64: interpreter row
            self.raise_where(route, ExceptionCode.NORMALCASEVIOLATION)
            return CV(t=T.I64, data=val)
        if v.base is T.F64:
            return CV(t=T.I64, data=jnp.trunc(v.data).astype(jnp.int64))
        if v.base in (T.I64, T.BOOL):
            return CV(t=T.I64, data=self._as_i64(v))
        raise NotCompilable(f"int() of {v.t}")

    def _builtin_float(self, args: list[CV]) -> CV:
        if not args:
            return const_cv(0.0)
        v = args[0]
        if v.is_const:
            try:
                return const_cv(float(v.const))
            except (ValueError, TypeError):
                pass
        v = self._unwrap_option(v, "float()")
        if v.t is T.NULL:  # error already flagged; dummy keeps tracing
            return CV(t=T.F64, data=jnp.zeros(self.ctx.b, dtype=jnp.float64))
        if v.base is T.STR:
            val, bad, route = S.parse_f64(v.sbytes, v.slen)
            self.raise_where(bad, ExceptionCode.VALUEERROR)
            # inf/nan literals parse fine in CPython: interpreter row
            self.raise_where(route, ExceptionCode.NORMALCASEVIOLATION)
            return CV(t=T.F64, data=val)
        if v.base in (T.I64, T.BOOL, T.F64):
            return CV(t=T.F64, data=self._cast(
                v.data if v.base is not T.BOOL else v.data.astype(jnp.int64),
                T.F64))
        raise NotCompilable(f"float() of {v.t}")

    def _builtin_str(self, args: list[CV]) -> CV:
        if not args:
            return const_cv("")
        return self._to_str(args[0])

    def _builtin_bool(self, args: list[CV]) -> CV:
        if not args:
            return const_cv(False)
        return CV(t=T.BOOL, data=self.truthy(args[0]))

    def _builtin_len(self, args: list[CV]) -> CV:
        if args and args[0].kind == "split":
            sv = args[0]
            sep, maxsplit = sv.names
            if sep is None:
                cnt = S.ws_token_count(sv.sbytes, sv.slen)
            else:
                cnt = S.count_const(sv.sbytes, sv.slen, sep) \
                    .astype(jnp.int64) + 1
            if maxsplit is not None:
                cnt = jnp.minimum(cnt, maxsplit + 1)
            return CV(t=T.I64, data=cnt.astype(jnp.int64))
        v = args[0]
        if v.is_const:
            try:
                return const_cv(len(v.const))
            except TypeError:
                pass  # e.g. None: falls through to the unwrap error path
        if v.elts is not None:
            return const_cv(len(v.elts))
        v = self._unwrap_option(v, "len()")
        if v.t is T.NULL:
            return CV(t=T.I64, data=jnp.zeros(self.ctx.b, dtype=jnp.int64))
        if v.base is T.STR:
            self._ascii_guard(v.sbytes, v.slen)
            return CV(t=T.I64, data=v.slen.astype(jnp.int64))
        raise NotCompilable(f"len() of {v.t}")

    def _builtin_abs(self, args: list[CV]) -> CV:
        v = self._require_numeric(args[0], "abs()")
        return CV(t=v.base if v.base is not T.BOOL else T.I64,
                  data=jnp.abs(self._as_i64(v) if v.base is T.BOOL else v.data))

    def _builtin_round(self, args: list[CV]) -> CV:
        v = self._require_numeric(args[0], "round()")
        nd = 0
        if len(args) > 1:
            if not (args[1].is_const and isinstance(args[1].const, int)):
                raise NotCompilable("round() dynamic ndigits")
            nd = args[1].const
        if v.base in (T.I64, T.BOOL):
            return CV(t=T.I64, data=self._as_i64(v))
        scaled = v.data * (10.0 ** nd)
        r = jnp.round(scaled)  # banker's rounding — matches Python round()
        if len(args) > 1:
            return CV(t=T.F64, data=r / (10.0 ** nd))
        return CV(t=T.I64, data=r.astype(jnp.int64))

    def _tuple_method(self, recv: CV, name: str, args: list[CV]) -> CV:
        """tuple.index / tuple.count over static elements (unrolled
        equality tests; index raises ValueError rows when absent)."""
        if len(args) != 1:
            raise NotCompilable(f"tuple.{name} arity")
        needle = args[0]
        eqs = [self._compare(ast.Eq(), needle, e) for e in recv.elts]
        if name == "count":
            cnt = jnp.zeros(self.ctx.b, dtype=jnp.int64)
            for eq in eqs:
                cnt = cnt + eq.astype(jnp.int64)
            return CV(t=T.I64, data=cnt)
        idx = jnp.full(self.ctx.b, -1, dtype=jnp.int64)
        for i in range(len(eqs) - 1, -1, -1):
            idx = jnp.where(eqs[i], i, idx)
        self.raise_where(idx < 0, ExceptionCode.VALUEERROR)
        return CV(t=T.I64, data=jnp.maximum(idx, 0))

    def _int_to_base(self, args: list[CV], base: int, what: str) -> CV:
        if len(args) != 1:
            raise NotCompilable(f"{what} arity")
        v = args[0]
        if not (v.base is T.I64 or v.base is T.BOOL or
                (v.is_const and isinstance(v.const, int))):
            raise NotCompilable(f"{what} of non-int")   # python: TypeError
        if v.is_const:
            # const fold (also: arbitrary-precision consts never reach the
            # i64 kernel)
            return const_cv({16: hex, 8: oct, 2: bin}[base](v.const))
        fb, fl = S.int_to_base(self._as_i64(
            self._require_numeric(v, what)), base)
        return CV(t=T.STR, sbytes=fb, slen=fl)

    def _builtin_hex(self, args: list[CV]) -> CV:
        return self._int_to_base(args, 16, "hex")

    def _builtin_oct(self, args: list[CV]) -> CV:
        return self._int_to_base(args, 8, "oct")

    def _builtin_bin(self, args: list[CV]) -> CV:
        return self._int_to_base(args, 2, "bin")

    def _builtin_divmod(self, args: list[CV]) -> CV:
        if len(args) != 2:
            raise NotCompilable("divmod arity")
        return tuple_cv([self._binop(ast.FloorDiv(), args[0], args[1]),
                         self._binop(ast.Mod(), args[0], args[1])])

    def _builtin_ord(self, args: list[CV]) -> CV:
        if len(args) != 1:
            raise NotCompilable("ord arity")
        v = args[0]
        if v.is_const and isinstance(v.const, str):
            if len(v.const) != 1:
                raise NotCompilable("ord of non-1-char constant")
            return const_cv(ord(v.const))
        rb, rl = self._to_strpair(v)
        self._ascii_guard(rb, rl)
        # TypeError rows where len != 1 (python raises TypeError)
        self.raise_where(rl != 1, ExceptionCode.TYPEERROR)
        return CV(t=T.I64, data=rb[:, 0].astype(jnp.int64))

    def _builtin_chr(self, args: list[CV]) -> CV:
        if len(args) != 1:
            raise NotCompilable("chr arity")
        v = self._require_numeric(args[0], "chr")
        if v.base is T.F64 or (v.is_const and isinstance(v.const, float)):
            raise NotCompilable("chr of float")   # python: TypeError
        code = self._as_i64(v)
        # ValueError outside unicode range; non-ASCII routes (byte matrix
        # is utf-8; multibyte encoding of one codepoint stays interpreter)
        self.raise_where((code < 0) | (code > 0x10FFFF),
                         ExceptionCode.VALUEERROR)
        self.raise_where(code > 127, ExceptionCode.NORMALCASEVIOLATION)
        b = jnp.clip(code, 0, 127).astype(jnp.uint8)[:, None]
        return CV(t=T.STR, sbytes=b, slen=jnp.ones(self.ctx.b,
                                                   dtype=jnp.int32))

    def _builtin_iter(self, args: list[CV]) -> CV:
        """iter(x) with STATIC consumption: each next() call site advances
        a trace-time cursor (reference: IteratorContextProxy.cc's iterator
        state machines; the per-call-site cursor is the vectorized analog
        for straight-line consumption)."""
        if len(args) != 1:
            raise NotCompilable("iter arity")
        v = args[0]
        cell = {"pos": 0}
        items = self._cv_iter_items(v)
        if items is not None:
            return CV(t=T.PYOBJECT, kind="iter",
                      names=("#static", tuple(items), cell))
        if v.kind == "split":
            cnt, item_at, _ = self._split_dynamic(v)
            return CV(t=T.PYOBJECT, kind="iter",
                      names=("#dyn", (cnt, item_at), cell))
        raise NotCompilable("iter over unsupported value")

    def _builtin_next(self, args: list[CV]) -> CV:
        if len(args) not in (1, 2):
            raise NotCompilable("next arity")
        it = args[0]
        if it.kind != "iter":
            raise NotCompilable("next over non-iterator")
        # consumption must be uniform across rows: under an if-branch mask,
        # after a possible early return, or inside a loop with per-row
        # exit/break masks, the trace-time cursor would advance for rows
        # python skips (review r4: `if a == 'x': next(it)` silently
        # misaligned the cursor) -> interpreter
        if self.mask is not None or self.ret_val is not None:
            raise NotCompilable("next under row-divergent control flow")
        if any(lp.get("dyn") or lp["brk"] is not None
               or lp["cont"] is not None for lp in self.loops):
            raise NotCompilable("next under row-divergent control flow")
        tag, src, cell = it.names
        k = cell["pos"]
        cell["pos"] = k + 1
        default = args[1] if len(args) == 2 else None
        if tag == "#static":
            if k < len(src):
                return src[k]
            if default is None:
                self.raise_where(jnp.ones(self.ctx.b, dtype=bool),
                                 ExceptionCode.STOPITERATION)
                return const_cv(None)
            return default
        cnt, item_at = src
        if k >= _DYN_ITER_CAP:
            raise NotCompilable("next past dynamic iterator cap")
        has_k = cnt > k
        val = item_at(k)
        if default is None:
            self.raise_where(~has_k, ExceptionCode.STOPITERATION)
            return val
        return merge_cv(self, has_k, val, default)

    def _builtin_sorted(self, args: list[CV]) -> CV:
        """sorted() over a static iterable via a compare-exchange network
        (vectorized bubble network: k(k-1)/2 predicated swaps — data-
        dependent orderings can't reorder a traced program, so every lane
        carries its own permutation through merge_cv)."""
        if len(args) != 1:
            raise NotCompilable("sorted arity")
        items = self._cv_iter_items(args[0])
        if items is None:
            raise NotCompilable("sorted over non-static iterable")
        vals = list(items)
        k = len(vals)
        if k > 8:
            raise NotCompilable("sorted over >8 elements")
        for i in range(k):
            for j in range(k - 1 - i):
                lt = self._compare(ast.Lt(), vals[j + 1], vals[j])
                a, b = vals[j], vals[j + 1]
                vals[j] = merge_cv(self, lt, b, a)
                vals[j + 1] = merge_cv(self, lt, a, b)
        return tuple_cv(vals, kind="list")

    def _unroll_width(self, count, bound) -> int:
        """Masked-unroll width for a runtime-length iterable: the static
        bound when one exists, else the cap — rows iterating past it raise
        LOOPCAPEXCEEDED and resolve exactly on the interpreter. Shared by
        dynamic for-loops and genexp reductions."""
        width = _DYN_ITER_CAP if bound is None else min(bound,
                                                        _DYN_ITER_CAP)
        if bound is None or bound > _DYN_ITER_CAP:
            self.raise_where(count > width, ExceptionCode.LOOPCAPEXCEEDED)
        return width

    def _dyn_genexp_steps(self, v: CV):
        """Iterate a dyngen CV (lazy genexp over a runtime-length iterable,
        _comprehension): yields (value CV, active-mask) per unrolled step,
        with loop masks arranged so element-expression errors raise only
        for rows still iterating AND passing the filters (reference:
        IteratorContextProxy-driven reductions). Element expressions
        evaluate under the genexp's DEFINING env; a second consumption
        refuses to compile (python generators exhaust — re-tracing would
        double-count)."""
        node, (count, item_at, bound), def_env, cell = v.names
        if cell["consumed"]:
            raise NotCompilable("generator consumed twice")
        cell["consumed"] = True
        gen = node.generators[0]
        width = self._unroll_width(count, bound)
        saved = self.env
        self.env = dict(def_env)
        lp = {"brk": None, "cont": None, "done": None, "dyn": True}
        self.loops.append(lp)
        steps = []
        try:
            for k in range(width):
                lp["done"] = count <= k
                lp["cont"] = None
                self._assign_target(gen.target, item_at(k))
                mask = count > k
                for cond_node in gen.ifs:
                    ctr = self.truthy(self.eval(cond_node))
                    mask = mask & ctr
                    # rows failing the filter skip the element expression
                    # (its errors must not fire for them)
                    drop = self.active() & ~ctr
                    lp["cont"] = drop if lp["cont"] is None \
                        else lp["cont"] | drop
                val = self.eval(node.elt)
                steps.append((val, mask))
        finally:
            self.loops.pop()
            self.env = saved
        return steps

    def _builtin_sum(self, args: list[CV]) -> CV:
        if len(args) not in (1, 2):
            raise NotCompilable("sum() arity")
        start: CV = args[1] if len(args) == 2 else const_cv(0)
        if start.base is T.STR or (start.is_const
                                   and isinstance(start.const, str)):
            # python forbids sum() over strings (TypeError): the
            # interpreter path reproduces the exact error — applies to the
            # dyngen branch too (review r4: it silently concatenated)
            raise NotCompilable("sum() can't sum strings")
        if args[0].kind == "dyngen":
            steps = self._dyn_genexp_steps(args[0])
            acc = start
            for val, mask in steps:
                acc = merge_cv(self, mask,
                               self._binop(ast.Add(), acc, val), acc)
            return acc
        items = self._cv_iter_items(args[0])
        if items is None:
            raise NotCompilable("sum over non-static iterable")
        acc = start
        for it in items:
            acc = self._binop(ast.Add(), acc, it)
        return acc

    def _builtin_any(self, args: list[CV]) -> CV:
        return self._any_all(args, any_mode=True)

    def _builtin_all(self, args: list[CV]) -> CV:
        return self._any_all(args, any_mode=False)

    def _any_all(self, args: list[CV], any_mode: bool) -> CV:
        if len(args) != 1:
            raise NotCompilable("any/all arity")
        if args[0].kind == "dyngen":
            steps = self._dyn_genexp_steps(args[0])
            acc = jnp.full(self.ctx.b, not any_mode, dtype=bool)
            for val, mask in steps:
                t = self.truthy(val)
                acc = (acc | (mask & t)) if any_mode \
                    else (acc & (~mask | t))
            return CV(t=T.BOOL, data=acc)
        items = self._cv_iter_items(args[0])
        if items is None:
            raise NotCompilable("any/all over non-static iterable")
        if all(it.is_const for it in items):
            # const-fold so while/comprehension conditions stay trace-static
            vals = [it.const for it in items]
            return const_cv(any(vals) if any_mode else all(vals))
        acc = self.truthy(items[0])
        for it in items[1:]:
            tr = self.truthy(it)
            acc = (acc | tr) if any_mode else (acc & tr)
        return CV(t=T.BOOL, data=acc)

    def _builtin_min(self, args: list[CV]) -> CV:
        return self._minmax(args, jnp.minimum)

    def _builtin_max(self, args: list[CV]) -> CV:
        return self._minmax(args, jnp.maximum)

    def _minmax(self, args: list[CV], fn) -> CV:
        if len(args) == 1 and args[0].kind == "dyngen":
            steps = self._dyn_genexp_steps(args[0])
            want_min = fn is jnp.minimum
            acc: Optional[CV] = None
            seen = jnp.zeros(self.ctx.b, dtype=bool)
            for val, mask in steps:
                if acc is None:
                    acc, seen = val, mask
                    continue
                res = self._compare(ast.Lt() if want_min else ast.Gt(),
                                    val, acc)
                cmp = self.truthy(res) if isinstance(res, CV) else res
                acc = merge_cv(self, mask & (~seen | cmp), val, acc)
                seen = seen | mask
            if acc is None:     # zero-width unroll: every row is empty
                self.raise_where(jnp.ones(self.ctx.b, dtype=bool),
                                 ExceptionCode.VALUEERROR)
                return const_cv(None)
            # python: min()/max() of an EMPTY iterable raises ValueError
            self.raise_where(~seen, ExceptionCode.VALUEERROR)
            return acc
        if len(args) == 1:
            items = self._cv_iter_items(args[0])
            if not items:
                raise NotCompilable("min/max over non-static iterable")
            args = items
        if any(a.base is T.STR or (a.is_const and isinstance(a.const, str))
               for a in args):
            want_min = fn is jnp.minimum
            out = args[0]
            for b in args[1:]:
                lt = self._compare(ast.Lt(), b, out)   # raw [B] bool
                out = merge_cv(self, lt if want_min else ~lt, b, out)
            return out
        vs = [self._require_numeric(a, "min/max") for a in args]
        out_t = vs[0].base
        for v in vs[1:]:
            out_t = T.super_type(out_t, v.base)
        acc = self._cast(vs[0].data, out_t)
        for v in vs[1:]:
            acc = fn(acc, self._cast(v.data, out_t))
        return CV(t=out_t, data=acc)

    # -- math module --------------------------------------------------------
    _MATH_UNARY = {
        "floor": (jnp.floor, T.I64), "ceil": (jnp.ceil, T.I64),
        "sqrt": (jnp.sqrt, T.F64), "sin": (jnp.sin, T.F64),
        "cos": (jnp.cos, T.F64), "tan": (jnp.tan, T.F64),
        "exp": (jnp.exp, T.F64), "log": (jnp.log, T.F64),
        "log2": (jnp.log2, T.F64), "log10": (jnp.log10, T.F64),
        "fabs": (jnp.abs, T.F64), "trunc": (jnp.trunc, T.I64),
        "radians": (jnp.radians, T.F64), "degrees": (jnp.degrees, T.F64),
        "isnan": (jnp.isnan, T.BOOL), "isinf": (jnp.isinf, T.BOOL),
        "atan": (jnp.arctan, T.F64), "asin": (jnp.arcsin, T.F64),
        "acos": (jnp.arccos, T.F64), "sinh": (jnp.sinh, T.F64),
        "cosh": (jnp.cosh, T.F64), "tanh": (jnp.tanh, T.F64),
        "expm1": (jnp.expm1, T.F64), "log1p": (jnp.log1p, T.F64),
    }

    def _module_fn(self, fn, args: list[CV]) -> CV:
        mod = getattr(fn, "__module__", None)
        name = getattr(fn, "__name__", None)
        if mod == "math" and name in self._MATH_UNARY:
            jfn, out_t = self._MATH_UNARY[name]
            v = self._require_numeric(args[0], f"math.{name}")
            res = jfn(self._cast(v.data, T.F64))
            if out_t is T.I64:
                return CV(t=T.I64, data=res.astype(jnp.int64))
            if out_t is T.BOOL:
                return CV(t=T.BOOL, data=res)
            return CV(t=T.F64, data=res)
        if mod == "string" and name == "capwords":
            rb, rl = self._to_strpair(args[0])
            self._ascii_guard(rb, rl)  # unicode whitespace divergence
            fb, fl = S.capwords(rb, rl)
            return CV(t=T.STR, sbytes=fb, slen=fl)
        if mod == "math" and name in self._MATH_BINARY:
            jfn = self._MATH_BINARY[name]
            a = self._require_numeric(args[0], f"math.{name}")
            b = self._require_numeric(args[1], f"math.{name}")
            bd = self._cast(b.data, T.F64)
            if name == "fmod":
                # math.fmod(x, 0.0) raises ValueError in CPython; jnp.fmod
                # would silently emit NaN
                self.raise_where(bd == 0.0, ExceptionCode.VALUEERROR)
            return CV(t=T.F64, data=jfn(self._cast(a.data, T.F64), bd))
        if mod == "math" and name == "isclose":
            if len(args) != 2:
                raise NotCompilable("math.isclose arity")
            a = self._cast(self._require_numeric(args[0], "isclose").data,
                           T.F64)
            c = self._cast(self._require_numeric(args[1], "isclose").data,
                           T.F64)
            tol = 1e-09 * jnp.maximum(jnp.abs(a), jnp.abs(c))
            # CPython order: a == b short-circuits True (equal infinities
            # are close); any remaining infinity is False (the formula's
            # inf tolerance would otherwise accept everything)
            finite = ~(jnp.isinf(a) | jnp.isinf(c))
            return CV(t=T.BOOL,
                      data=(a == c) | (finite & (jnp.abs(a - c) <= tol)))
        raise NotCompilable(f"module fn {mod}.{name}")

    _MATH_BINARY = {
        "pow": jnp.power, "fmod": jnp.fmod, "hypot": jnp.hypot,
        "copysign": jnp.copysign, "atan2": jnp.arctan2,
    }


# ---------------------------------------------------------------------------
# CV merging (predicated phi nodes)
# ---------------------------------------------------------------------------

def merge_cv(frame: Frame, mask, a: CV, b: CV) -> CV:
    """where(mask, a, b) over CVs, unifying types (the phi node of the
    predicated control flow; reference analog: TypeAnnotator's if-branch
    type unification)."""
    b_ = frame.ctx.b
    if a.is_const and b.is_const and a.const == b.const and \
            type(a.const) is type(b.const):
        return a
    # None joins: produce Option
    a_null = a.t is T.NULL
    b_null = b.t is T.NULL
    if a_null and b_null:
        return null_cv()
    if a_null or b_null:
        other = b if a_null else a
        other_m = materialize(other, b_) if other.is_const else other
        ov = other_m.valid if other_m.valid is not None \
            else jnp.ones(b_, dtype=bool)
        # valid exactly where the non-null side is selected and itself valid
        sel_other = ~mask if a_null else mask
        new_valid = sel_other & ov
        return CV(t=T.option(other_m.base), data=other_m.data,
                  valid=new_valid, sbytes=other_m.sbytes, slen=other_m.slen,
                  elts=other_m.elts, names=other_m.names)
    am = materialize(a, b_) if a.is_const else a
    bm = materialize(b, b_) if b.is_const else b
    # tuples
    if am.elts is not None and bm.elts is not None:
        if len(am.elts) != len(bm.elts):
            raise NotCompilable("merging tuples of different arity")
        if am.kind != bm.kind:   # list vs tuple branches: per-row TYPE
            raise NotCompilable("merging list and tuple")
        elts = tuple(merge_cv(frame, mask, x, y)
                     for x, y in zip(am.elts, bm.elts))
        valid = None
        if am.valid is not None or bm.valid is not None:
            av = am.valid if am.valid is not None else jnp.ones(b_, bool)
            bv = bm.valid if bm.valid is not None else jnp.ones(b_, bool)
            valid = jnp.where(mask, av, bv)
        return tuple_cv(elts, names=am.names or bm.names, valid=valid,
                        kind=am.kind)
    at, bt = am.base, bm.base
    # strings
    if at is T.STR and bt is T.STR:
        ab, al = am.sbytes, am.slen
        bb2, bl = bm.sbytes, bm.slen
        ab, bb2 = S._pad_common(ab, bb2)
        sb = jnp.where(mask[:, None], ab, bb2)
        sl = jnp.where(mask, al, bl)
        valid = _merge_valid(mask, am, bm, b_)
        t = T.option(T.STR) if valid is not None else T.STR
        return CV(t=t, sbytes=sb, slen=sl, valid=valid)
    # numerics
    if at.is_numeric() and bt.is_numeric():
        out_t = T.super_type(at, bt)
        data = jnp.where(mask,
                         am.data.astype(dtype_for(out_t)),
                         bm.data.astype(dtype_for(out_t)))
        valid = _merge_valid(mask, am, bm, b_)
        t = T.option(out_t) if valid is not None else out_t
        return CV(t=t, data=data, valid=valid)
    raise NotCompilable(f"cannot merge {a.t} and {b.t}")


def _merge_valid(mask, am: CV, bm: CV, b_: int):
    if am.valid is None and bm.valid is None:
        return None
    av = am.valid if am.valid is not None else jnp.ones(b_, dtype=bool)
    bv = bm.valid if bm.valid is not None else jnp.ones(b_, dtype=bool)
    return jnp.where(mask, av, bv)


def _const_binop(op: ast.operator, a, b):
    import operator as _op

    table = {
        ast.Add: _op.add, ast.Sub: _op.sub, ast.Mult: _op.mul,
        ast.Div: _op.truediv, ast.FloorDiv: _op.floordiv, ast.Mod: _op.mod,
        ast.Pow: _op.pow, ast.BitAnd: _op.and_, ast.BitOr: _op.or_,
        ast.BitXor: _op.xor, ast.LShift: _op.lshift, ast.RShift: _op.rshift,
    }
    fn = table.get(type(op))
    if fn is None:
        raise NotCompilable(f"const op {type(op).__name__}")
    return fn(a, b)


def _class_run_table(pattern: str):
    """[256] bool table when `pattern` is exactly one character class
    repeated 1+ times ('[0-9]+', '\\s+', 'x+', '[^a-z]+'); else None."""
    import re as _pyre

    try:
        from re import _parser as _sre
    except ImportError:                      # pragma: no cover - py<3.11
        import sre_parse as _sre             # type: ignore

    import numpy as np

    from ..ops.regex import _byte_in_spec, _in_spec

    if any(ord(c) > 127 for c in pattern):
        return None
    try:
        tree = _sre.parse(pattern)
    except Exception:
        return None
    if tree.state.flags & ~_pyre.UNICODE.value:
        return None
    terms = list(tree)
    if len(terms) != 1:
        return None
    op, av = terms[0]
    # exactly class+ / literal+ — a bare class (no repeat) replaces EACH
    # char, and {2,} must not match length-1 runs: both diverge from the
    # run-collapsing kernel, so only MAX_REPEAT(1, MAXREPEAT) qualifies
    if str(op) != "MAX_REPEAT":
        return None
    lo, hi, body = av
    if lo != 1 or str(hi) != "MAXREPEAT" or len(body) != 1:
        return None
    op, av = list(body)[0]
    spec = None
    if str(op) == "IN":
        spec = _in_spec(av)
    elif str(op) == "LITERAL":
        spec = (("lit", av),)
    if spec is None:
        return None
    tab = np.zeros(256, dtype=bool)
    for c in range(256):
        if _byte_in_spec(c, spec):
            tab[c] = True
    return tab
