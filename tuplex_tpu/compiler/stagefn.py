"""Bridging columnar batches <-> emitter CVs and building fused stage fns.

This is the TransformStage/StageBuilder analog (reference:
core/src/physical/StageBuilder.cc generateFastCodePath — assembles the fused
per-row pipeline; here we assemble a fused per-BATCH jax function that the
backend jits once per (stage, schema, bucket-spec)).
"""

from __future__ import annotations

from typing import Any, Optional

from ..core import typesys as T
from ..core.errors import NotCompilable
from ..runtime.jaxcfg import jnp
from .values import CV, tuple_cv


def require_traceable(ops, speculate: bool = True) -> None:
    """Consume the plan-time traceability verdict (compiler/analyzer.py):
    raise NotCompilable BEFORE any emitter work when a fused UDF is
    statically known untraceable. With `speculate` on, findings inside
    if-arms are left to the trace (branch pruning may remove them)."""
    from .analyzer import op_analysis

    for op in ops:
        rep = op_analysis(op)
        f = rep.routing_finding(speculate) if rep is not None else None
        if f is not None:
            raise NotCompilable(
                f"UDF {rep.name} statically untraceable: {f.reason} "
                f"({rep.loc(f)})")


def partition_avals(part, bucket_mode: str = "q8"):
    """Abstract (ShapeDtypeStruct) mirror of ``columns.stage_partition``
    for `part` — the exact avals its dispatch batch will have, computed
    without copying a byte. Feeds the ahead-of-time compile pool
    (exec/compilequeue): compiling against these avals means the real
    dispatch finds its executable already built. None when a leaf has no
    device layout."""
    import numpy as np

    from ..runtime import columns as C
    from ..runtime.jaxcfg import jax

    b = C.bucket_size(part.num_rows, bucket_mode)
    avals: dict = {}
    for path, leaf in part.leaves.items():
        ks = C._leaf_keys(path, leaf)
        if ks is None:
            return None                     # host-only ObjectLeaf
        if not ks:
            continue                        # NullLeaf: layout-free
        if isinstance(leaf, C.NumericLeaf):
            avals[path] = jax.ShapeDtypeStruct((b,), leaf.data.dtype)
        else:
            wb = C.bucket_size(max(leaf.width, 1), bucket_mode, minimum=8)
            avals[path + "#bytes"] = jax.ShapeDtypeStruct((b, wb), np.uint8)
            avals[path + "#len"] = jax.ShapeDtypeStruct(
                (b,), leaf.lengths.dtype)
        if path + "#valid" in ks:
            avals[path + "#valid"] = jax.ShapeDtypeStruct((b,), np.bool_)
    avals["#rowvalid"] = jax.ShapeDtypeStruct((b,), np.bool_)
    avals["#seed"] = jax.ShapeDtypeStruct((), np.uint32)
    return avals


def restage_avals(out_avals: dict, bucket_mode: str = "q8"):
    """Predicted input avals of the NEXT stage, given this stage's
    ``jax.eval_shape`` output avals: control keys drop, data keys re-stage
    at the same batch size (exact when every input row emits one output
    row — the chain stops at filters/limits upstream), and str widths
    re-bucket from the TRACE width (partition_from_result_arrays keeps the
    device array's byte width, so the next staging pads to
    bucket(trace_width) — predictable without looking at content). None
    when the layout can't be predicted (compacted outputs, structural
    markers)."""
    from ..runtime import columns as C
    from ..runtime.jaxcfg import jax

    import numpy as np

    if "#rowidx" in out_avals:
        return None        # compaction: output batch size is data-dependent
    avals: dict = {}
    b = None
    for k, v in out_avals.items():
        if k.startswith("#"):
            continue       # '#err'/'#keep'/fold lattice: not re-staged
        if k.endswith(("#null", "#unit", "#opt")):
            return None    # structural markers re-stage under other keys
        if k.endswith("#bytes"):
            wb = C.bucket_size(max(int(v.shape[1]), 1), bucket_mode,
                               minimum=8)
            avals[k] = jax.ShapeDtypeStruct((v.shape[0], wb), v.dtype)
        else:
            avals[k] = jax.ShapeDtypeStruct(v.shape, v.dtype)
        b = int(v.shape[0])
    if not avals or b is None:
        return None
    avals["#rowvalid"] = jax.ShapeDtypeStruct((b,), np.bool_)
    avals["#seed"] = jax.ShapeDtypeStruct((), np.uint32)
    return avals


def leaf_cv(arrays: dict, path: str, t: T.Type) -> CV:
    """CV view over a staged leaf (see runtime.columns.stage_partition)."""
    base = t.without_option() if t.is_optional() else t
    opt = t.is_optional()
    valid = arrays.get(path + "#valid") if opt else None
    if isinstance(base, T.TupleType):
        elts = []
        if opt:
            tvalid = arrays[path + "#opt"]
            valid = tvalid if valid is None else valid & tvalid
        for i, e in enumerate(base.elements):
            elts.append(leaf_cv(arrays, f"{path}.{i}", T.option(e) if opt else e))
        return tuple_cv(elts, valid=valid)
    if base is T.STR:
        return CV(t=t, sbytes=arrays[path + "#bytes"], slen=arrays[path + "#len"],
                  valid=valid)
    if base is T.NULL:
        return CV(t=T.NULL, const=None)
    if base is T.EMPTYTUPLE:
        return tuple_cv([], valid=valid)
    if base in (T.BOOL, T.I64, T.F64):
        return CV(t=t, data=arrays[path], valid=valid)
    raise NotCompilable(f"column type {t} has no device layout")


def input_row_cv(arrays: dict, schema: T.RowType) -> CV:
    """The row value passed to the first UDF: single unnamed column -> bare
    value; otherwise a named row tuple (dict-style access resolves on names)."""
    from ..runtime.columns import user_columns

    cvs = [leaf_cv(arrays, str(i), t) for i, t in enumerate(schema.types)]
    cols = user_columns(schema)
    if len(cvs) == 1 and cols is None:
        return cvs[0]
    return tuple_cv(cvs, names=cols)


def result_arrays(cv: CV, b: int) -> tuple[dict, T.Type]:
    """Flatten a stage RESULT into row-layout arrays: a plain tuple result
    spreads into columns 0..k-1; anything else is the single column 0 (same
    convention as runtime.columns.schema_for_result_type)."""
    from .values import materialize

    cv = materialize(cv, b) if cv.is_const else cv

    def _has_list(v) -> bool:
        if v.kind in ("list", "genexp"):
            return True
        return v.elts is not None and any(_has_list(e) for e in v.elts)

    if _has_list(cv):
        # list/generator results must keep python's types: interpreter path
        from ..core.errors import NotCompilable

        raise NotCompilable("list-valued result")
    if cv.elts is not None and cv.valid is None:
        out: dict[str, Any] = {}
        for i, e in enumerate(cv.elts):
            sub, _ = cv_output_arrays(e, b, str(i))
            out.update(sub)
        return out, cv.t
    return cv_output_arrays(cv, b, "0")


def cv_output_arrays(cv: CV, b: int, prefix: str = "") -> tuple[dict, T.Type]:
    """Flatten a result CV into named output arrays + its row-able type.

    Output keys mirror the staged-input convention so results can be rebuilt
    into Partitions (runtime.columns layout).
    """
    from .values import materialize

    cv = materialize(cv, b) if cv.is_const else cv
    out: dict[str, Any] = {}
    t = cv.t
    base = cv.base
    if cv.elts is not None:
        opt = cv.valid is not None
        if opt:
            out[prefix + "#opt"] = cv.valid
        if not cv.elts:  # empty tuple: keep a structural marker
            out[prefix + "#unit"] = jnp.zeros(b, dtype=bool)
            et = T.EMPTYTUPLE
            return out, (T.option(et) if opt else et)
        ts = []
        for i, e in enumerate(cv.elts):
            sub, et = cv_output_arrays(e, b, f"{prefix}.{i}" if prefix else str(i))
            out.update(sub)
            ts.append(et)
        tt = T.tuple_of(*ts)
        return out, (T.option(tt) if opt else tt)
    if base is T.STR:
        out[prefix + "#bytes"] = cv.sbytes
        out[prefix + "#len"] = cv.slen
        if cv.valid is not None:
            out[prefix + "#valid"] = cv.valid
        return out, t
    if base is T.NULL:
        # structural marker so the column survives the round trip
        out[prefix + "#null"] = jnp.zeros(b, dtype=bool)
        return out, T.NULL
    if base is T.EMPTYTUPLE:
        out[prefix + "#unit"] = jnp.zeros(b, dtype=bool)
        if cv.valid is not None:
            out[prefix + "#valid"] = cv.valid
        return out, t
    if base in (T.BOOL, T.I64, T.F64):
        out[prefix] = cv.data
        if cv.valid is not None:
            out[prefix + "#valid"] = cv.valid
        return out, t
    raise NotCompilable(f"output type {t} has no columnar layout")
