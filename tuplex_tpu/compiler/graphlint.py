"""Jaxpr-plane static analysis: pre-submission compile-hazard vetting.

Both existing static passes stop at the Python layer (analyzer.py lints
UDF ASTs, typeinfer.py runs abstract types); the compile plane hands
every stage jaxpr to XLA blind, so pathological graphs are only
*survived* — 300 s deadline, SIGKILL, whole-stage tier degrade — never
predicted or avoided. This pass closes that gap: a cheap walk over a
stage's ClosedJaxpr (post-trace, pre-``lowered.compile()``) producing a
:class:`GraphReport` with

* an eqn census by primitive family,
* a static intermediate-buffer peak estimate from eqn avals (a sound
  upper bound on simultaneously-live temporaries, checked against the
  MemoryManager budget at plan time, before HBM ever sees the stage),
* dtype-creep (8-byte intermediates dominating a graph traced from
  32-bit inputs) and implicit-broadcast blowup findings,
* scatter/gather/one-hot/concat **compaction-chain** detection, and
* a weighted hazard score (predicted XLA:CPU compile seconds) with
  per-construct weights calibrated against measured compile times —
  the same observations plan/splittuner.py fits its op-count power law
  to, broken down by primitive family instead of op count alone.

The load-bearing output is the ``wedge``-severity rule. Round 17
bisected the flights airport build-side stage (3 ops / 2.2k eqns,
>20 min / >120 GB on XLA:CPU — ROADMAP residue (c)) eqn-span by
eqn-span under the fork-isolated compiler:

* every prefix that leaves the assembled row buffers as computation
  ROOTS compiles in < 2 s;
* adding ANY post-assembly consumer of the wide row state — the
  terminal 26..28-operand ``optimization_barrier`` *or* the two-eqn
  row-valid epilogue — wedges the compile (kill at 45-120 s, > 20 min
  unattended);
* the trigger survives removing every scatter (a gather-based
  ``_scatter_cols`` rewrite still wedges), removing the terminal
  barrier alone, and splitting the wide barrier into per-leaf barriers,
  so no single eqn is at fault: XLA:CPU's fusion/emission pass goes
  superlinear on the *combination* of a dense string-compaction graph
  and a wide multi-string-column row materialization.

Measured over every stage of the five bundled pipelines (zillow,
flights, tpch, nyc311, logs — both the plan-time probe-shape trace and
the jaxprs the compile plane actually submits in production runs,
ground-truthed against forked deadline-killed XLA:CPU compiles),
exactly one structural signature separates the wedging stages from the
clean ones:

    eqns/op >= 300  AND  scatter+cumsum >= 10  AND  str row buffers >= 4

Two stages carry it, and both are measured wedges: the airport build
side (961 eqns/op, 12 compaction eqns, 7 str buffers) and the flights
probe-side mega-segment (394 eqns/op, 30 cumsum eqns, 5 str buffers —
its production compile blows even a 300 s deadline). Every clean stage
misses at least one axis with margin: the densest clean stages
(logs_strip at 1140 eqns/op, logs_regex at 1060) have ZERO compaction
eqns; the most compaction-heavy high-density clean stages (tpch q1/q6/
q19 at 6 compaction eqns) sit 40 % under the compaction floor with at
most 3 str buffers; the most compaction-heavy stage overall, flights[1]
with scatter=4 cumsum=4, sits at 77 eqns/op — 4x under the density
floor. That conjunction is pinned as rule ``wide-str-compaction`` and
test-enforced as both a zero-false-positive gate over all five
pipelines and a fires-on-airport regression.

Disabled (``TUPLEX_GRAPHLINT=0`` env kill switch, mirroring
devprof/excprof) every hook is one module-flag check — no trace, no
walk, no allocation.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field, replace
from typing import Optional

# ---------------------------------------------------------------------------
# enable gate (mirrors runtime/devprof: process-wide, env kill switch wins)
# ---------------------------------------------------------------------------


def _env_disabled() -> bool:
    return os.environ.get("TUPLEX_GRAPHLINT", "").strip().lower() \
        in ("0", "false", "off")


_enabled = not _env_disabled()


def enabled() -> bool:
    return _enabled


def enable(on: bool = True) -> None:
    """Process-wide gate. TUPLEX_GRAPHLINT=0 wins over any option-driven
    enable (A/B overhead timing, pathological-graph archaeology)."""
    global _enabled
    _enabled = bool(on) and not _env_disabled()


# hazard-score veto threshold (predicted compile seconds). 60 s sits
# a 2.6x margin above the worst CLEAN bundled stage (zillow[0] at
# 22.9 s) — by default only a wedge-severity finding (score forced to
# 1e9) crosses it, so vetting changes nothing on healthy plans.
_DEFAULT_THRESHOLD = 60.0
_threshold = _DEFAULT_THRESHOLD


def hazard_threshold() -> float:
    return _threshold


def set_hazard_threshold(value: float) -> None:
    """<= 0 disables the score veto (wedge findings still veto)."""
    global _threshold
    _threshold = float(value)


def apply_options(options) -> None:
    """Wire the process gate from ContextOptions. Like devprof, the
    ``tuplex.tpu.graphlint`` option turns vetting ON, never off — the
    gate is process-wide and another live Context may depend on it."""
    if options.get_bool("tuplex.tpu.graphlint", True):
        enable(True)
    set_hazard_threshold(options.get_float(
        "tuplex.tpu.hazardThreshold", _DEFAULT_THRESHOLD))


# ---------------------------------------------------------------------------
# primitive families + calibrated per-family compile-cost weights
# ---------------------------------------------------------------------------

# family -> estimated XLA:CPU compile seconds PER EQN. Calibrated by
# least-squares over the round-17 stage corpus (19 stages, forked
# compiles, probe shapes): clean stages run ~1.5-2.5 ms/eqn flat, with
# gather/sort/scatter/while carrying the residual above the flat rate.
# These seed splittuner's per-family residual fit (see
# CompileModel.family_weights) and are intentionally conservative — the
# score exists to rank and to veto, not to schedule.
FAMILY_WEIGHTS = {
    "scatter": 0.060,
    "gather": 0.012,
    "cumsum": 0.020,
    "sort": 0.050,
    "while": 0.080,
    "concat": 0.010,
    "onehot": 0.008,       # iota/eq one-hot expansions
    "broadcast": 0.003,
    "reduce": 0.004,
    "convert": 0.002,
    "control": 0.006,      # pjit/cond/custom-call bodies
    "elementwise": 0.0015,
}

_FAMILY_OF = {
    "scatter": "scatter", "scatter-add": "scatter",
    "gather": "gather", "dynamic_slice": "gather",
    "dynamic_update_slice": "scatter", "take_along_axis": "gather",
    "cumsum": "cumsum", "cumlogsumexp": "cumsum", "cummax": "cumsum",
    "cummin": "cumsum", "cumprod": "cumsum",
    "sort": "sort",
    "while": "while", "scan": "while",
    "concatenate": "concat", "pad": "concat",
    "iota": "onehot",
    "broadcast_in_dim": "broadcast", "reshape": "broadcast",
    "squeeze": "broadcast", "rev": "broadcast", "transpose": "broadcast",
    "convert_element_type": "convert", "bitcast_convert_type": "convert",
    "pjit": "control", "cond": "control", "custom_jvp_call": "control",
    "custom_vjp_call": "control", "remat": "control",
    "optimization_barrier": "control", "custom_call": "control",
}
for _p in ("reduce_sum", "reduce_max", "reduce_min", "reduce_and",
           "reduce_or", "reduce_prod", "argmax", "argmin",
           "reduce_precision"):
    _FAMILY_OF[_p] = "reduce"


def family_of(prim_name: str) -> str:
    return _FAMILY_OF.get(prim_name, "elementwise")


# wide-str-compaction thresholds (see module docstring for the corpus
# margins backing each number)
WEDGE_MIN_EQNS_PER_OP = 300
WEDGE_MIN_COMPACTION = 10      # scatter + cumsum eqns
WEDGE_MIN_STR_BUFS = 4         # >=2-d uint8 leaves in the row state

# dtype-creep / broadcast-blowup thresholds
_CREEP_MIN_COUNT = 50          # 8-byte-valued eqns before we bother
_CREEP_MIN_FRACTION = 0.25
_BLOWUP_RATIO = 64             # out.size / max(in.size) per broadcast
_BLOWUP_MIN_COUNT = 4


# ---------------------------------------------------------------------------
# report types
# ---------------------------------------------------------------------------


@dataclass
class Finding:
    """One named rule hit. ``severity``: info < warn < wedge. A wedge
    finding means "statically known to stall this platform's compiler"
    and forces the hazard score past any threshold."""

    rule: str
    severity: str
    message: str
    eqn_span: Optional[tuple] = None   # (first, last) top-level eqn idx

    def line(self) -> str:
        span = (f" [eqns {self.eqn_span[0]}..{self.eqn_span[1]}]"
                if self.eqn_span else "")
        return f"[{self.severity}] {self.rule}: {self.message}{span}"


@dataclass
class GraphReport:
    """Static analysis of one stage jaxpr (see module docstring)."""

    n_eqns: int = 0
    n_ops: int = 1
    census: dict = field(default_factory=dict)     # primitive -> count
    families: dict = field(default_factory=dict)   # family -> count
    peak_bytes: int = 0            # static live-set peak at traced shapes
    peak_fixed_bytes: int = 0      # peak share that does NOT scale w/ rows
    peak_row_bytes: int = 0        # peak share per traced row (scales)
    input_row_bytes: int = 0       # bytes per row across the INPUT avals
    traced_rows: int = 0           # leading batch dim of the traced avals
    str_bufs: int = 0              # >=2-d uint8 buffers in the outvars
    hazard_score: float = 0.0      # predicted compile seconds (see WEIGHTS)
    findings: list = field(default_factory=list)
    elapsed_ms: float = 0.0

    @property
    def wedge(self) -> bool:
        return any(f.severity == "wedge" for f in self.findings)

    def worst_severity(self) -> str:
        rank = {"info": 0, "warn": 1, "wedge": 2}
        worst = ""
        for f in self.findings:
            if not worst or rank.get(f.severity, 0) > rank.get(worst, 0):
                worst = f.severity
        return worst

    def peak_bytes_at(self, rows: int) -> int:
        """Scale the static peak to a target batch-row count. Sound as
        long as only leading-batch-dim buffers grow with rows (true for
        the columnar layout: every [B]/[B, W] leaf scales, consts and
        scalars don't)."""
        if self.traced_rows <= 0:
            return self.peak_bytes
        return self.peak_fixed_bytes + self.peak_row_bytes * max(rows, 0)

    def op_costs(self) -> list:
        """Per-op hazard costs for splittuner's split-point placement:
        the census-weighted cost spread uniformly over the stage's ops
        (the jaxpr does not delimit op boundaries, so the spread is the
        least-surprising sound choice; a wedge finding concentrates its
        weight instead so the split isolates SOMETHING rather than
        nothing)."""
        n = max(self.n_ops, 1)
        per = self.hazard_score / n
        return [per] * n

    def lines(self) -> list:
        """Human-readable summary block (lint / explain / compilestats)."""
        fams = ", ".join(f"{k}={v}" for k, v in sorted(
            self.families.items(), key=lambda kv: -kv[1]) if v)
        out = [
            f"eqns={self.n_eqns} ops={self.n_ops} "
            f"hazard={self.hazard_score:.2f}s peak={self.peak_bytes}B "
            f"(+{self.peak_row_bytes}B/row)",
            f"families: {fams}" if fams else "families: (empty)",
        ]
        out.extend(f.line() for f in self.findings)
        return out


# ---------------------------------------------------------------------------
# the analysis pass
# ---------------------------------------------------------------------------


def _aval_nbytes(aval) -> int:
    try:
        size = 1
        for d in aval.shape:
            size *= int(d)
        return size * aval.dtype.itemsize
    except Exception:
        return 0


def _walk_census(jaxpr, census: dict) -> int:
    """Full census including nested jaxprs (pjit/cond/while bodies);
    returns total eqn count."""
    total = 0
    stack = [jaxpr]
    while stack:
        jx = stack.pop()
        for eq in jx.eqns:
            census[eq.primitive.name] = census.get(eq.primitive.name, 0) + 1
            total += 1
            for p in eq.params.values():
                if hasattr(p, "jaxpr"):
                    stack.append(p.jaxpr)
                elif isinstance(p, (list, tuple)):
                    for pp in p:
                        if hasattr(pp, "jaxpr"):
                            stack.append(pp.jaxpr)
    return total


def _static_peak(jaxpr, traced_rows: int):
    """Sound upper bound on simultaneously-live intermediate bytes: walk
    top-level eqns in program order with last-use liveness (a buffer is
    allocated at its defining eqn and freed after its last consumer).
    XLA will fuse much of this away — that is why it is an UPPER bound;
    it cannot under-report, which is the property the plan-time
    memory_budget check needs. Returns (peak, fixed_peak, per_row_peak)
    split by whether the leading dim equals the traced batch rows."""
    last_use: dict = {}
    for i, eq in enumerate(jaxpr.eqns):
        for v in eq.invars:
            if hasattr(v, "aval") and type(v).__name__ != "Literal":
                last_use[id(v)] = i
    for v in jaxpr.outvars:
        if hasattr(v, "aval") and type(v).__name__ != "Literal":
            last_use[id(v)] = len(jaxpr.eqns)

    live = 0
    live_row = 0
    peak = 0
    peak_fixed = 0
    peak_row = 0
    expiring: dict = {}
    for i, eq in enumerate(jaxpr.eqns):
        for v in eq.outvars:
            aval = getattr(v, "aval", None)
            if aval is None:
                continue
            nb = _aval_nbytes(aval)
            scales = bool(aval.shape) and traced_rows > 0 \
                and aval.shape[0] == traced_rows
            live += nb
            if scales:
                live_row += nb
            end = last_use.get(id(v), i)  # unused: dies immediately
            expiring.setdefault(end, []).append((nb, scales))
        if live > peak:
            peak = live
            peak_row = live_row
            peak_fixed = live - live_row
        for nb, scales in expiring.pop(i, ()):
            live -= nb
            if scales:
                live_row -= nb
    per_row = peak_row // max(traced_rows, 1)
    return peak, peak_fixed, per_row


def _str_buf_count(jaxpr) -> int:
    """Count distinct >=2-d uint8 buffers in the stage's live row state:
    the widest optimization_barrier (operator-boundary materialization)
    when present, else the outvars."""
    best = None
    best_w = -1
    for eq in jaxpr.eqns:
        if eq.primitive.name == "optimization_barrier" \
                and len(eq.invars) > best_w:
            best_w = len(eq.invars)
            best = eq.invars
    if best is None:
        best = jaxpr.outvars
    n = 0
    for v in best:
        aval = getattr(v, "aval", None)
        if aval is not None and getattr(aval, "dtype", None) is not None \
                and aval.dtype.name == "uint8" and len(aval.shape) >= 2:
            n += 1
    return n


def _find_spans(jaxpr, names) -> Optional[tuple]:
    """(first, last) top-level eqn index whose primitive is in names."""
    first = last = None
    for i, eq in enumerate(jaxpr.eqns):
        if eq.primitive.name in names:
            if first is None:
                first = i
            last = i
    return None if first is None else (first, last)


def analyze(closed_jaxpr, *, n_ops: int = 1, platform: str = "",
            traced_rows: int = 0) -> Optional[GraphReport]:
    """Run the pass over a ClosedJaxpr. Returns None when the gate is
    off (the zero-alloc disabled path — callers treat None as "no
    findings, no veto"). ``platform`` guards the CPU-only wedge rule;
    ``traced_rows`` is the leading batch dim of the traced avals (8 for
    the plan-time probe shapes) and drives the per-row peak split."""
    if not _enabled:
        return None
    t0 = time.perf_counter()
    jaxpr = getattr(closed_jaxpr, "jaxpr", closed_jaxpr)

    census: dict = {}
    n_eqns = _walk_census(jaxpr, census)
    families: dict = {}
    for prim, cnt in census.items():
        fam = family_of(prim)
        families[fam] = families.get(fam, 0) + cnt

    if traced_rows <= 0:
        for v in jaxpr.invars:
            aval = getattr(v, "aval", None)
            if aval is not None and getattr(aval, "shape", ()):
                traced_rows = int(aval.shape[0])
                break
    peak, peak_fixed, per_row = _static_peak(jaxpr, traced_rows)
    str_bufs = _str_buf_count(jaxpr)
    in_row = 0
    if traced_rows > 0:
        for v in jaxpr.invars:
            aval = getattr(v, "aval", None)
            if aval is not None and getattr(aval, "shape", ()) \
                    and aval.shape[0] == traced_rows:
                in_row += _aval_nbytes(aval)
        in_row //= traced_rows

    report = GraphReport(
        n_eqns=n_eqns, n_ops=max(n_ops, 1), census=census,
        families=families, peak_bytes=peak, peak_fixed_bytes=peak_fixed,
        peak_row_bytes=per_row, input_row_bytes=in_row,
        traced_rows=traced_rows, str_bufs=str_bufs)

    score = sum(FAMILY_WEIGHTS.get(f, 0.0015) * c
                for f, c in families.items())
    compaction = census.get("scatter", 0) + census.get("cumsum", 0)
    eqns_per_op = n_eqns / max(n_ops, 1)

    # ---- named rules -------------------------------------------------
    is_cpu = (platform or "").startswith("cpu")
    if is_cpu and eqns_per_op >= WEDGE_MIN_EQNS_PER_OP \
            and compaction >= WEDGE_MIN_COMPACTION \
            and str_bufs >= WEDGE_MIN_STR_BUFS:
        span = _find_spans(jaxpr, ("scatter", "cumsum"))
        report.findings.append(Finding(
            "wide-str-compaction", "wedge",
            f"{eqns_per_op:.0f} eqns/op with {compaction} "
            f"scatter/cumsum compaction eqns over {str_bufs} string "
            f"row buffers — XLA:CPU fusion emission goes superlinear "
            f"on this shape (round-17 bisection: any post-assembly "
            f"consumer of the assembled row wedges the compile)",
            eqn_span=span))

    if compaction >= 2:
        span = _find_spans(jaxpr, ("scatter", "cumsum"))
        report.findings.append(Finding(
            "compaction-chain", "info",
            f"{census.get('scatter', 0)} scatter + "
            f"{census.get('cumsum', 0)} cumsum eqns "
            f"(string compaction / positional rewrite chain)",
            eqn_span=span))
    onehot = census.get("iota", 0)
    if onehot >= 2 and census.get("concatenate", 0) >= 2:
        report.findings.append(Finding(
            "onehot-concat-chain", "info",
            f"{onehot} iota + {census.get('concatenate', 0)} concatenate "
            f"eqns (one-hot index assembly feeding scatter/gather)"))

    # dtype creep: 8-byte eqn outputs dominating the graph
    wide = 0
    for_eqns = 0
    for eq in jaxpr.eqns:
        for v in eq.outvars:
            aval = getattr(v, "aval", None)
            if aval is None or getattr(aval, "dtype", None) is None:
                continue
            for_eqns += 1
            if aval.dtype.itemsize >= 8:
                wide += 1
    if wide >= _CREEP_MIN_COUNT and for_eqns \
            and wide / for_eqns >= _CREEP_MIN_FRACTION:
        report.findings.append(Finding(
            "dtype-creep-64bit", "info",
            f"{wide}/{for_eqns} eqn outputs are 8-byte (i64/f64) — "
            f"check for implicit Python-int/float promotion widening "
            f"intermediates"))

    # implicit-broadcast blowup: broadcasts that multiply element count
    blowups = 0
    worst_ratio = 0.0
    for eq in jaxpr.eqns:
        if eq.primitive.name != "broadcast_in_dim":
            continue
        try:
            out_sz = 1
            for d in eq.outvars[0].aval.shape:
                out_sz *= int(d)
            in_sz = 1
            for d in getattr(eq.invars[0], "aval", None).shape:
                in_sz *= int(d)
            ratio = out_sz / max(in_sz, 1)
        except Exception:
            continue
        if ratio >= _BLOWUP_RATIO:
            blowups += 1
            worst_ratio = max(worst_ratio, ratio)
    if blowups >= _BLOWUP_MIN_COUNT:
        report.findings.append(Finding(
            "broadcast-blowup", "info",
            f"{blowups} broadcasts expand element count >= "
            f"{_BLOWUP_RATIO}x (worst {worst_ratio:.0f}x) — implicit "
            f"outer-product-shaped intermediates"))

    if report.wedge:
        score = max(score, 1e9)   # a wedge outranks any threshold
    report.hazard_score = score
    report.elapsed_ms = (time.perf_counter() - t0) * 1e3
    return report


# ---------------------------------------------------------------------------
# stage-level convenience (plan plane, CLI, smoke gate)
# ---------------------------------------------------------------------------


def analyze_stage(stage, platform: str = "") -> Optional[GraphReport]:
    """Trace ``stage``'s device fn at the plan-time probe shapes and run
    the pass. Returns None when the gate is off, the stage has no
    columnar input, it is already interpreter-pinned, or the trace
    fails (the compile plane will vet the real traced jaxpr anyway)."""
    if not _enabled:
        return None
    from ..plan.physical import abstract_batch_arrays

    if getattr(stage, "force_interpret", False):
        return None
    arrays = abstract_batch_arrays(stage.input_schema)
    if arrays is None:
        return None
    try:
        from ..runtime.jaxcfg import jax

        if not platform:
            platform = jax.default_backend()
        fn = stage.build_device_fn(stage.input_schema)
        closed = jax.make_jaxpr(fn)(arrays)
    except Exception:
        return None
    rows = 0
    for v in arrays.values():
        if getattr(v, "shape", ()):
            rows = int(v.shape[0])
            break
    return analyze(closed, n_ops=len(getattr(stage, "ops", ()) or ()) or 1,
                   platform=platform, traced_rows=rows)


# ---------------------------------------------------------------------------
# plan-time vet memo (plan/physical._vet_stage)
# ---------------------------------------------------------------------------
# Drivers (and the test suite) re-plan the same pipeline shapes over and
# over; the probe trace behind analyze_stage costs ~300 ms where a plan
# without it costs ~7 ms. Verdicts are therefore memoized on the stage
# fingerprint — the compile plane's content address, which by
# construction captures everything that shapes the jaxpr (op sources,
# schemas, speculation state, codegen options). The backend is fixed per
# process (jaxcfg), so the fingerprint alone is a sufficient key.

_VET_MEMO: dict = {}
_VET_MEMO_CAP = 512
_MISS = object()


def vet_memo_get(fp: str):
    """(hit, report). The returned report is a copy with a fresh
    findings list (plan-plane annotations like ``static-peak-memory``
    must stay per-plan) and ``elapsed_ms`` 0.0 — a memo hit ran no walk,
    so it must not bill one to the stage's graphlint_ms."""
    rep = _VET_MEMO.get(fp, _MISS)
    if rep is _MISS:
        return False, None
    if rep is None:
        return True, None
    return True, replace(rep, findings=list(rep.findings), elapsed_ms=0.0)


def vet_memo_put(fp: str, report: Optional[GraphReport]) -> None:
    if len(_VET_MEMO) >= _VET_MEMO_CAP:   # unbounded plans, bounded memo
        _VET_MEMO.clear()
    _VET_MEMO[fp] = None if report is None else \
        replace(report, findings=list(report.findings))
