"""Closed-loop re-specialization: sense drift, rebuild, canary, hot-swap.

PR 13 landed the SENSING half of adaptive serving: ``runtime/excprof``
watches each tenant's live exception traffic against the plan-time
baseline and fires ``respecialize_recommended`` when the distribution
drifts. Until now nothing acted on it — a tenant whose data drifted just
decayed into the resolve tiers forever. This module is the ACTING half,
a per-tenant state machine the job service owns:

* **trigger** — a controller thread polls the drift signal (debounced:
  ``tuplex.serve.respecDebounce`` consecutive recommendations; per-tenant
  ``respecCooldownS`` between attempts), so one noisy window never spends
  a background compile.
* **re-speculate from LIVE evidence** — the candidate plan is rebuilt
  from the tenant's last request spec, but specialized for the traffic
  the service actually OBSERVED rather than the stale plan-time sample:
  exception codes seen live fold into the stage inventory
  (``TransformStage.extra_expected_codes`` — they widen the resolve
  preallocation and the drift baseline instead of reading as
  out-of-inventory drift forever), and a stage whose pruned cold arm is
  provably being hit (observed NORMALCASEVIOLATION traffic + captured
  deviant-row samples) is re-compiled WITHOUT branch speculation so
  those rows return to the compiled path. Every candidate stage carries
  a per-generation ``respec_salt`` so baselines and jit-cache entries
  never alias across generations (the XLA executable itself still dedups
  content-addressed in exec/compilequeue).
* **background compile** — candidate stages compile via the compile
  queue's ``background_lane()``: a separate low-priority pool, so a
  foreground job's compile never finds its slot occupied by a candidate.
  The whole phase is bounded by ``respecCompileDeadlineS``; a candidate
  that cannot compile in time is quarantined, never promoted.
* **canary** — the tenant's next job shadow-executes the candidate on a
  bounded fraction of its partitions (``respecCanaryFrac``), cross-checks
  output row counts and exception counts against the incumbent run of
  the SAME partitions, and the job's own results always come from the
  incumbent. Canary rows are excprof-suppressed — the probe must not
  read as drift.
* **promote / rollback** — a passing canary hot-swaps the tenant's
  active overlay atomically at the job boundary (jobs admitted AFTER the
  swap rebuild under the new generation; in-flight jobs keep the
  generation pinned at admission), and re-anchors the tenant's excprof
  window to the observed distribution — the re-specialized plan's normal
  case IS the live traffic, so the drift score recovers without a
  restart. The incumbent is retained as a fallback rung: a promoted
  candidate that fails at run time (blown compile deadline at dispatch)
  restarts the whole stage on the incumbent configuration
  (exec/local ``_TierRestart`` — rows are never split across plan
  generations mid-stage) and the tenant is demoted for future jobs.
* **quarantine** — a failed/regressing candidate writes a
  content-addressed ``.respecquar`` marker (the unified compilequeue
  marker helper, provenance-stamped) keyed by the candidate's SIGNATURE
  (incumbent stage keys + overlay content, generation-independent), with
  an exponential cooldown — a poisoned respec cannot flap.

Observability rides along: ``serve_respec_*`` counters (xferstats →
/metrics), per-tenant generation gauges, a ``respec`` health check
(degraded while self-healing is blocked: drift-recommended but
quarantined, or a candidate stuck compiling), ``respec:compile /
canary / promote / rollback`` spans, and per-tenant lifecycle events in
the history recorder (the dashboard's "respecialize recommended" badge
becomes a lifecycle). ``runtime/faults`` checkpoints sit in the
candidate compile (``respec:…-compile``) and the canary dispatch
(``respec:…-canary``) so ``scripts/chaos_bench.py`` can prove the
rollback story end to end.
"""

from __future__ import annotations

import hashlib
import math
import threading
import time
from collections import deque
from typing import Any, Optional

from ..runtime import excprof, faults, telemetry
from ..runtime import tracing as TR
from ..runtime import xferstats
from ..utils.logging import get_logger

log = get_logger("tuplex_tpu.serve.respec")

#: candidate lifecycle states
COMPILING = "compiling"
READY = "ready"
CANARY = "canary"

_HISTORY_CAP = 64


class _TenantState:
    """Controller-internal per-tenant record (all mutation under the
    controller lock)."""

    __slots__ = ("tenant", "gen", "overlay", "prev_overlay", "candidate",
                 "last_entries", "last_options", "avals", "schema",
                 "debounce", "cooldown_until", "quar", "history",
                 "promotions", "quarantines", "rollbacks")

    def __init__(self, tenant: str):
        self.tenant = tenant
        self.gen = 0                   # active plan generation
        self.overlay: Optional[dict] = None       # active overlay (gen>0)
        self.prev_overlay: Optional[dict] = None  # incumbent, for rollback
        self.candidate: Optional[dict] = None
        self.last_entries: Optional[list] = None  # wire-safe stage entries
        self.last_options: dict = {}
        self.avals = None              # stage-0 dispatch avals (hint)
        self.schema = None
        self.debounce = 0
        self.cooldown_until = 0.0
        self.quar: dict = {}           # sig -> (count, last epoch secs):
                                       # the in-process quarantine record
                                       # must carry its own timestamp —
                                       # with no cache dir there is no
                                       # marker to date the backoff from,
                                       # and an undated quarantine would
                                       # never expire
        self.history: deque = deque(maxlen=_HISTORY_CAP)
        self.promotions = 0
        self.quarantines = 0
        self.rollbacks = 0


class RespecController:
    """See module docstring. One instance per JobService; every public
    method is safe to call from scheduler/worker threads."""

    def __init__(self, service, options):
        self.service = service
        o = options
        self.check_s = max(0.01, o.get_float("tuplex.serve.respecCheckS",
                                             1.0))
        self.debounce_n = max(1, o.get_int("tuplex.serve.respecDebounce",
                                           2))
        self.cooldown_s = max(0.0, o.get_float(
            "tuplex.serve.respecCooldownS", 120.0))
        self.canary_frac = min(1.0, max(0.0, o.get_float(
            "tuplex.serve.respecCanaryFrac", 0.25)))
        self.compile_deadline_s = max(0.1, o.get_float(
            "tuplex.serve.respecCompileDeadlineS", 120.0))
        self.quarantine_s = max(0.0, o.get_float(
            "tuplex.serve.respecQuarantineS", 300.0))
        self._lock = threading.Lock()
        self._states: dict[str, _TenantState] = {}
        self._backend = None           # lazy LocalBackend for bg compiles
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="tpx-respec")
        self._register_telemetry()
        self._thread.start()

    # ------------------------------------------------------------------
    # telemetry
    # ------------------------------------------------------------------
    def _register_telemetry(self) -> None:
        if not telemetry.enabled():
            return
        telemetry.set_gauge(
            "serve_respec_candidates",
            lambda: sum(1 for s in list(self._states.values())
                        if s.candidate is not None), owner=self)
        telemetry.set_gauge(
            "serve_respec_promoted_tenants",
            lambda: sum(1 for s in list(self._states.values())
                        if s.gen > 0), owner=self)
        telemetry.register_health_check("respec", self._health_check,
                                        owner=self)

    def _health_check(self):
        """Self-healing health: degraded while the loop is BLOCKED — a
        tenant the drift detector wants re-specialized sits in a
        quarantine cooldown (we cannot heal it), or a candidate compile
        has run past twice its deadline (stuck background lane)."""
        now = time.monotonic()
        blocked: list = []
        stuck: list = []
        with self._lock:
            states = list(self._states.items())
        for tenant, st in states:
            cand = st.candidate
            if cand is not None and cand["state"] == COMPILING \
                    and now - cand["t_start"] > 2 * self.compile_deadline_s:
                stuck.append(tenant)
            if st.quar and now < st.cooldown_until:
                try:
                    if excprof.respecialize_recommended(tenant):
                        blocked.append(tenant)
                except Exception:
                    pass
        if stuck:
            return (telemetry.DEGRADED,
                    f"candidate compile stuck past "
                    f"{2 * self.compile_deadline_s:.0f}s for "
                    f"tenant(s) {', '.join(sorted(stuck))}")
        if blocked:
            return (telemetry.DEGRADED,
                    f"tenant(s) {', '.join(sorted(blocked))} drifted but "
                    f"their respecialization is quarantined "
                    f"(self-healing blocked)")
        return (telemetry.OK, None)

    def _gauge_tenant(self, tenant: str) -> None:
        if not telemetry.enabled():
            return
        telemetry.set_gauge(
            "serve_respec_generation",
            lambda t=tenant: self._gen_of(t), owner=self, tenant=tenant)

    def _gen_of(self, tenant: str) -> int:
        st = self._states.get(tenant)
        return st.gen if st is not None else 0

    def _event(self, tenant: str, phase: str, **fields) -> None:
        """One lifecycle transition: history deque + recorder row +
        tenant log line (the dashboard renders the deque per tenant)."""
        st = self._states.get(tenant)
        if st is not None:
            st.history.append({"t": time.time(), "phase": phase,
                               **fields})
        r = getattr(self.service, "recorder", None)
        if r is not None and getattr(r, "enabled", False):
            try:
                r.respec_event(tenant, phase, **fields)
            except Exception:   # dashboard rows are advisory
                pass

    # ------------------------------------------------------------------
    # service integration points
    # ------------------------------------------------------------------
    def _state(self, tenant: str, create: bool = True) \
            -> Optional[_TenantState]:
        with self._lock:
            st = self._states.get(tenant)
            if st is None and create:
                st = self._states[tenant] = _TenantState(tenant)
                self._gauge_tenant(tenant)
            return st

    def pin(self, record) -> None:
        """Pin the tenant's ACTIVE plan generation onto the job record
        BEFORE its runner is built: the overlay object travels with the
        record, so retries rebuild under the same generation and a
        promotion mid-job only affects jobs admitted after the swap
        (the hot-swap atomicity contract)."""
        st = self._state(record.request.tenant)
        with self._lock:
            record.respec_gen = st.gen
            record.respec_overlay = st.overlay
        record._respec_ctrl = self

    def note_admitted(self, record) -> None:
        """Post-admission hook: remember the tenant's latest wire-safe
        request (the respeculation substrate) and, when a validated
        candidate is waiting, claim THIS job as its canary."""
        req = record.request
        st = self._state(req.tenant)
        with self._lock:
            if req.wire_safe():
                st.last_entries = list(req.stages)
                st.last_options = dict(req.options or {})
            cand = st.candidate
            if cand is not None and cand["state"] == READY \
                    and cand.get("canary_job") is None:
                cand["canary_job"] = record.id
                cand["state"] = CANARY
                record.respec_canary = cand
        if getattr(record, "respec_canary", None) is not None:
            xferstats.bump("serve_respec_canaries", 1, tag=req.tenant)
            TR.instant("respec:canary-claim", "respec",
                       {"tenant": req.tenant, "job": record.id,
                        "gen": record.respec_canary["gen"]})
            self._event(req.tenant, "canary-start",
                        gen=record.respec_canary["gen"], job=record.id)
            log.info("respec[%s]: job %s canaries candidate gen %d",
                     req.tenant, record.id,
                     record.respec_canary["gen"])

    def note_input(self, tenant: str, avals, schema) -> None:
        """Stage-0 dispatch avals of a live job (tiny ShapeDtypeStructs):
        the background compile replays them through the backend's
        precompile walk so the candidate executables are warm before the
        canary ever dispatches."""
        st = self._state(tenant)
        with self._lock:
            st.avals = avals
            st.schema = schema

    def note_tenant_retired(self, tenant: str) -> None:
        """The service evicted the tenant's last retained record: drop
        the controller state (a returning tenant recalibrates from
        scratch, consistent with its excprof window being dropped). The
        on-disk quarantine markers persist — flap protection survives
        tenant churn and process restarts."""
        with self._lock:
            dropped = self._states.pop(tenant, None)
        if dropped is not None:
            # the per-tenant generation gauge dies with the state: a
            # churning tenant population must not accumulate one dead
            # gauge per tenant ever seen (the same leak class the
            # excprof drop_scope satellite fixes)
            telemetry.remove_gauge("serve_respec_generation",
                                   tenant=tenant)

    def stop(self) -> None:
        self._stop.set()
        telemetry.drop_owner(self)
        self._thread.join(timeout=2.0)

    # ------------------------------------------------------------------
    # overlay plumbing (runner side)
    # ------------------------------------------------------------------
    def overlay_job(self, runner) -> None:
        """Apply the record's pinned overlay to a freshly rebuilt stage
        list (called from _JobRunner.__init__ — admission time AND retry
        rebuilds, so one job never mixes plan generations)."""
        record = runner.record
        ov = getattr(record, "respec_overlay", None)
        if not ov:
            return
        tenant = record.request.tenant
        notify = self._make_notify(tenant, ov)
        for si, stage in enumerate(runner.stages):
            entry = runner.entries[si] if si < len(runner.entries) else {}
            if isinstance(entry, dict) and "spec" in entry:
                apply_overlay_to_stage(stage, ov, si, notify=notify)

    def _make_notify(self, tenant: str, overlay: dict):
        def _notify(cause):
            self.note_runtime_failure(tenant, overlay, cause)
        return _notify

    def note_runtime_failure(self, tenant: str, overlay: dict,
                             cause) -> None:
        """The exec/local fallback rung fired: a stage running under
        `overlay` failed at run time and already restarted on the
        retained incumbent. Demote the tenant (future jobs rebuild on
        the incumbent) and quarantine the candidate signature."""
        st = self._state(tenant, create=False)
        demoted = False
        with self._lock:
            if st is not None and st.overlay is not None \
                    and st.overlay.get("gen") == overlay.get("gen"):
                st.overlay = st.prev_overlay
                st.prev_overlay = None
                st.gen += 1      # generations only move forward — the
                st.rollbacks += 1  # rollback IS a new (incumbent-shaped)
                demoted = True     # generation, never an alias of gen N
        if not demoted:
            return
        xferstats.bump("serve_respec_rollbacks", 1, tag=tenant)
        TR.instant("respec:rollback", "respec",
                   {"tenant": tenant, "gen": overlay.get("gen"),
                    "cause": str(cause)[:120]})
        self._event(tenant, "rollback", gen=overlay.get("gen"),
                    cause=str(cause)[:200])
        log.warning("respec[%s]: generation %s failed at run time (%s); "
                    "rolled back onto the incumbent",
                    tenant, overlay.get("gen"), cause)
        self._quarantine_sig(tenant, overlay.get("sig", ""),
                             f"runtime failure after promotion: {cause}")

    # ------------------------------------------------------------------
    # the controller loop
    # ------------------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.wait(self.check_s):
            try:
                self._tick()
            except Exception:   # pragma: no cover - loop must survive
                log.exception("respec tick failed")

    def _tick(self) -> None:
        if not excprof.enabled():
            return
        now = time.monotonic()
        with self._lock:
            states = list(self._states.items())
        for tenant, st in states:
            cand = st.candidate
            if cand is not None:
                # compile watchdog: a candidate stuck in its compile
                # phase past the deadline is quarantined here even if
                # the build thread itself is wedged (an injected hang,
                # a pathological trace) — the tick is the guarantee
                if cand["state"] == COMPILING \
                        and now - cand["t_start"] > self.compile_deadline_s:
                    self._quarantine(tenant, cand,
                                     f"candidate compile exceeded "
                                     f"{self.compile_deadline_s:g}s")
                continue
            if st.last_entries is None or now < st.cooldown_until:
                continue
            try:
                recommended = excprof.respecialize_recommended(tenant)
            except Exception:
                recommended = False
            with self._lock:
                st.debounce = st.debounce + 1 if recommended else 0
                fire = st.debounce >= self.debounce_n
                if fire:
                    st.debounce = 0
                    st.candidate = {
                        "gen": st.gen + 1, "state": COMPILING,
                        "t_start": now, "t_trigger": now,
                        "overlay": None, "sig": "", "checks": [],
                        "failed": None, "canary_job": None}
                    cand = st.candidate
            if fire:
                xferstats.bump("serve_respec_triggered", 1, tag=tenant)
                TR.instant("respec:trigger", "respec",
                           {"tenant": tenant, "gen": cand["gen"],
                            "drift": round(excprof.drift_score(tenant),
                                           3)})
                self._event(tenant, "trigger", gen=cand["gen"],
                            drift=round(excprof.drift_score(tenant), 3))
                log.info("respec[%s]: drift tripped — building candidate "
                         "generation %d", tenant, cand["gen"])
                t = threading.Thread(
                    target=self._build_candidate, args=(tenant, cand),
                    daemon=True, name=f"tpx-respec-build-{tenant[:12]}")
                t.start()

    # ------------------------------------------------------------------
    # candidate construction + background compile
    # ------------------------------------------------------------------
    def _job_options(self, st: _TenantState):
        from ..core.options import ContextOptions

        opts = ContextOptions(self.service.options.to_dict())
        if st.last_options:
            opts.update(st.last_options)
        opts.set("tuplex.backend", "local")
        opts.set("tuplex.webui.enable", False)
        return opts

    def _rebuild(self, entries, options, overlay: Optional[dict]):
        """Spec entries -> TransformStage list, with `overlay` applied
        (the same rebuild path every job runner uses, so stage keys —
        deterministic stage-local op ids — match the live jobs')."""
        from ..exec.serverless import rebuild_stage

        stages = []
        for si, entry in enumerate(entries):
            if not isinstance(entry, dict) or "spec" not in entry:
                stages.append(None)
                continue
            stage = rebuild_stage(entry["spec"], options,
                                  files=entry.get("files"))
            if overlay:
                apply_overlay_to_stage(stage, overlay, si)
            stages.append(stage)
        return stages

    def _derive_overlay(self, st: _TenantState, inc_stages,
                        gen: int) -> dict:
        """Re-speculate from the LIVE evidence: the observed per-stage
        code distribution (excprof cumulative stage reports under the
        incumbent keys) and the captured deviant-row samples decide, per
        stage, (a) which observed codes the new plan should EXPECT and
        (b) whether branch speculation went stale (observed
        NORMALCASEVIOLATION traffic on a speculation-pruned stage →
        compile the cold arms back in).

        Bounded approximation: the cumulative stage reports aggregate by
        stage KEY, and at generation 0 isomorphic tenants share keys
        (the per-generation salt only diverges after a first promotion)
        — so another tenant's traffic can widen this candidate's
        inventory or force its de-speculation. Both stay CORRECT
        (expecting extra codes widens preallocation; un-pruning costs
        only specialization), and a candidate is only ever built for a
        tenant whose OWN window tripped drift; per-tenant per-stage code
        accounting in excprof would remove the approximation."""
        from ..core.errors import ExceptionCode as EC

        observed = excprof.reports()
        samples = excprof.samples()
        scope = excprof.scope_report(st.tenant)
        overlay: dict = {
            "gen": gen, "tenant": st.tenant,
            "salt": f"{st.tenant}:g{gen}",
            "anchor_rate": float(scope.get("ewma_rate") or 0.0),
            "stages": {},
        }
        for si, stage in enumerate(inc_stages):
            if stage is None:
                continue
            rep = observed.get(stage.key())
            if not rep:
                continue
            obs_codes = sorted({int(code) for (code, _op)
                                in rep.get("codes", {})})
            try:
                base = {int(c) for c in stage.possible_exception_codes()}
            except Exception:
                base = set()
            cfg: dict = {}
            extra = [c for c in obs_codes if c not in base]
            if extra:
                cfg["extra_codes"] = extra
            if int(EC.NORMALCASEVIOLATION) in obs_codes:
                try:
                    pruned = stage.speculation_pruned()
                except Exception:
                    pruned = False
                if pruned:
                    # deviant-row samples captured for the violation are
                    # the evidence the cold arm is live traffic now, not
                    # a one-off — either way the non-speculating compile
                    # is the safe respeculation
                    cfg["speculate"] = False
                    cfg["ncv_samples"] = len(samples.get(
                        (stage.key(), int(EC.NORMALCASEVIOLATION)), []))
            if cfg:
                overlay["stages"][si] = cfg
        return overlay

    @staticmethod
    def _signature(inc_stages, overlay: dict) -> str:
        """Generation-INDEPENDENT content address of a candidate: the
        incumbent stage keys it grew from + the overlay's structural
        content. The same poisoned respeculation re-derived later (gen
        3, gen 4, …) hashes identically, so its quarantine marker keeps
        matching — no flapping."""
        h = hashlib.sha256()
        h.update(str(overlay.get("tenant", "")).encode())
        for stage in inc_stages:
            if stage is not None:
                h.update(stage.key().encode())
        for si in sorted(overlay.get("stages", {})):
            cfg = overlay["stages"][si]
            h.update(f"{si}:{sorted(cfg.get('extra_codes', []))}"
                     f":{cfg.get('speculate')}".encode())
        return h.hexdigest()[:24]

    def _quar_base(self, sig: str) -> Optional[str]:
        from ..runtime.jaxcfg import aot_cache_dir

        d = aot_cache_dir()
        if not d:
            return None
        import os

        return os.path.join(d, f"respec-{sig}")

    def _quarantined_until(self, st: _TenantState, sig: str) -> float:
        """Expiry (epoch seconds) of a candidate signature's quarantine,
        from the in-process (count, stamped-at) record and/or the
        cross-process ``.respecquar`` marker — whichever is later. 0.0
        when never quarantined."""
        from ..exec import compilequeue as CQ

        rec = CQ.read_marker(self._quar_base(sig), "respecquar")
        local = st.quar.get(sig)
        if rec is None and local is None:
            return 0.0
        count = max(local[0] if local else 0,
                    int(rec.get("count", 1)) if rec else 0)
        created = max(local[1] if local else 0.0,
                      float(rec.get("created", 0.0)) if rec else 0.0)
        if created <= 0.0:
            return 0.0          # undatable verdict: never block forever
        backoff = self.quarantine_s * (2 ** max(0, count - 1))
        return created + backoff

    def _build_candidate(self, tenant: str, cand: dict) -> None:
        from ..exec import compilequeue as CQ

        st = self._state(tenant, create=False)
        if st is None:
            return
        try:
            with TR.span("respec:compile", "respec") as sp:
                sp.set("tenant", tenant[:16]).set("gen", cand["gen"])
                # chaos checkpoint: an injected hang here is a wedged
                # candidate build — the tick watchdog quarantines it at
                # the compile deadline while this thread sleeps it off
                faults.maybe("respec", point="compile")
                with self._lock:
                    if st.candidate is not cand or cand["failed"]:
                        return   # the tick watchdog quarantined us while
                                 # we were wedged — do no further work
                    entries = list(st.last_entries or [])
                    active = st.overlay
                    avals, schema = st.avals, st.schema
                opts = self._job_options(st)
                inc_stages = self._rebuild(entries, opts, active)
                overlay = self._derive_overlay(st, inc_stages,
                                               cand["gen"])
                sig = self._signature(inc_stages, overlay)
                overlay["sig"] = sig
                cand["overlay"] = overlay
                cand["sig"] = sig
                sp.set("sig", sig[:12])
                until = self._quarantined_until(st, sig)
                if time.time() < until:
                    self._abandon(tenant, cand,
                                  f"candidate {sig[:12]} is quarantined "
                                  f"for {until - time.time():.0f}s more")
                    return
                cand_stages = self._rebuild(entries, opts, overlay)
                n_compiled = self._compile_stages(cand_stages, avals,
                                                  schema, cand)
                sp.set("stages", sum(1 for s in cand_stages
                                     if s is not None))
                sp.set("compiled", n_compiled)
            with self._lock:
                if st.candidate is not cand or cand["failed"]:
                    return      # watchdog quarantined us mid-build
                cand["state"] = READY
                cand["t_ready"] = time.monotonic()
            xferstats.bump("serve_respec_compiles", 1, tag=tenant)
            self._event(tenant, "candidate-ready", gen=cand["gen"],
                        sig=cand["sig"][:12], compiled=n_compiled)
            log.info("respec[%s]: candidate gen %d ready (%d background "
                     "compile(s)); awaiting canary", tenant,
                     cand["gen"], n_compiled)
        except Exception as e:   # noqa: BLE001 - any failure quarantines
            self._quarantine(tenant, cand,
                             f"candidate build failed: "
                             f"{type(e).__name__}: {e}")

    def _compile_stages(self, cand_stages, avals, schema,
                        cand: dict) -> int:
        """Compile the candidate stage set on the BACKGROUND lane and
        wait (bounded by what is left of the compile deadline). Without
        an aval hint the stages are trace-validated only — the first
        canary dispatch compiles them, still content-addressed."""
        from ..exec import compilequeue as CQ

        live = [s for s in cand_stages if s is not None]
        if not live:
            raise RuntimeError("no spec-rebuilt stage to respecialize")
        if avals is None or schema is None:
            for s in live:      # no hint: validate the builds trace-side
                s.build_device_fn(schema if schema is not None else None)
            return 0
        backend = self._bg_backend()
        with CQ.background_lane():
            futs = backend._precompile_avals(cand_stages, avals, schema)
        deadline = cand["t_start"] + self.compile_deadline_s
        for f in futs:
            left = deadline - time.monotonic()
            if left <= 0:
                raise CQ.CompileTimeout(
                    f"candidate compile phase exceeded "
                    f"{self.compile_deadline_s:g}s")
            f.result(timeout=left)      # raises the compile's own error
        return len(futs)

    def _bg_backend(self):
        if self._backend is None:
            from ..exec.local import LocalBackend

            self._backend = LocalBackend(self._job_options(
                _TenantState("")))
        return self._backend

    # ------------------------------------------------------------------
    # canary (called from _JobRunner.step, on the scheduler thread)
    # ------------------------------------------------------------------
    def canary_stage(self, runner, si: int, stage, inputs,
                     incumbent_res) -> None:
        """Shadow-execute the candidate's stage `si` on a bounded
        fraction of the SAME input partitions the incumbent just
        processed, and cross-check exception count + output row count.
        The job's own results are untouched (they came from the
        incumbent); excprof recording is suppressed so probe rows never
        read as tenant drift."""
        record = runner.record
        cand = getattr(record, "respec_canary", None)
        if cand is None or cand.get("failed") \
                or cand.get("state") != CANARY:
            return
        entry = runner.entries[si] if si < len(runner.entries) else {}
        if not isinstance(entry, dict) or "spec" not in entry:
            return              # live stages cannot be respecialized
        if not isinstance(inputs, list) or not inputs:
            return
        if any(getattr(p, "device_batch", None) is not None
               for p in inputs):
            # device-resident handoff views are one-shot and their
            # buffers may be donated by the incumbent dispatch that just
            # consumed them — a shadow re-execution here could read dead
            # device memory and quarantine a HEALTHY candidate. Host-
            # backed partitions re-stage from host leaves (the same
            # contract the tier-restart replay relies on); these don't.
            return
        tenant = record.request.tenant
        try:
            overlay = cand["overlay"]
            cache = getattr(record, "_respec_canary_stages", None)
            if cache is None:
                cache = record._respec_canary_stages = {}
            cstage = cache.get(si)
            if cstage is None:
                from ..exec.serverless import rebuild_stage

                cstage = rebuild_stage(entry["spec"], runner.options,
                                       files=entry.get("files"))
                apply_overlay_to_stage(cstage, overlay, si)
                cache[si] = cstage
            k = max(1, int(math.ceil(self.canary_frac * len(inputs))))
            k = min(k, len(inputs))
            sub = inputs[:k]
            with TR.span("respec:canary", "respec") as sp:
                sp.set("tenant", tenant[:16]).set("gen", cand["gen"])
                sp.set("stage", si).set("partitions", k)
                with excprof.suppressed():
                    faults.maybe("respec", point="canary")
                    cres = runner.backend.execute_any(cstage, sub,
                                                      runner.ctx)
                    if k == len(inputs):
                        base_rows = incumbent_res.metrics.get("rows_out",
                                                              0)
                        base_exc = len(incumbent_res.exceptions)
                    else:
                        ires = runner.backend.execute_any(stage, sub,
                                                          runner.ctx)
                        base_rows = ires.metrics.get("rows_out", 0)
                        base_exc = len(ires.exceptions)
                crows = cres.metrics.get("rows_out", 0)
                cexc = len(cres.exceptions)
                ok = (crows == base_rows and cexc <= base_exc)
                if getattr(cstage, "_respec_revert", None) is None:
                    # the tier ladder's fallback rung fired DURING the
                    # shadow run: the "candidate" result above is really
                    # the incumbent re-run (the candidate could not even
                    # compile) — an incumbent-vs-incumbent comparison
                    # must never pass the canary
                    ok = False
                    cand["failed"] = (
                        f"candidate fell back to the incumbent during "
                        f"its own canary at stage {si} (compile "
                        f"deadline) — nothing canary-able to promote")
                sp.set("ok", int(ok))
            cand["checks"].append(
                {"stage": si, "partitions": k, "rows": crows,
                 "rows_incumbent": base_rows, "exceptions": cexc,
                 "exceptions_incumbent": base_exc, "ok": ok})
            if not ok:
                cand["failed"] = (
                    f"canary mismatch at stage {si}: candidate "
                    f"{crows} rows / {cexc} exception(s) vs incumbent "
                    f"{base_rows} / {base_exc}")
        except Exception as e:   # noqa: BLE001 - canary failure is data
            cand["checks"].append({"stage": si, "ok": False,
                                   "error": f"{type(e).__name__}: {e}"})
            cand["failed"] = (f"canary dispatch failed at stage {si}: "
                              f"{type(e).__name__}: {e}")

    def finish_job(self, record, ok: bool) -> None:
        """Job-boundary verdict for a canary job: promote a candidate
        whose every stage cross-check passed on a successful job;
        quarantine anything else. Jobs that never touched a canary are
        no-ops."""
        cand = getattr(record, "respec_canary", None)
        if cand is None:
            return
        record.respec_canary = None
        tenant = record.request.tenant
        st = self._state(tenant, create=False)
        if st is None:
            return
        with self._lock:
            if st.candidate is not cand:
                return          # already quarantined (watchdog raced us)
        if ok and not cand.get("failed") and cand["checks"] \
                and all(c.get("ok") for c in cand["checks"]):
            self._promote(tenant, st, cand)
        elif ok and not cand.get("failed") and not cand["checks"]:
            # the claimed job had no canary-able stage execution (e.g.
            # every stage rode live): release the claim for the next job
            with self._lock:
                cand["state"] = READY
                cand["canary_job"] = None
            self._event(tenant, "canary-skipped", gen=cand["gen"])
        else:
            reason = cand.get("failed") or \
                ("canary job failed" if not ok else "canary checks failed")
            self._quarantine(tenant, cand, reason)

    # ------------------------------------------------------------------
    # promote / quarantine
    # ------------------------------------------------------------------
    def _promote(self, tenant: str, st: _TenantState, cand: dict) -> None:
        now = time.monotonic()
        with self._lock:
            if st.candidate is not cand:
                return
            st.prev_overlay = st.overlay
            st.overlay = cand["overlay"]
            st.gen = cand["gen"]
            st.candidate = None
            st.cooldown_until = now + self.cooldown_s
            st.debounce = 0
            st.promotions += 1
        # the re-specialized plan's normal case IS the observed traffic:
        # re-anchor the tenant's drift window (and the process-global
        # one — its expectation moved with the tenant's) so the score
        # recovers without waiting out the EWMA, and WITHOUT a restart.
        # Bounded approximation on the GLOBAL window: adopting the
        # current global rate can also absorb another still-drifting
        # tenant's contribution, quieting the global-scope gauge early —
        # but never the health signal, because the exception_drift check
        # takes the WORST score across ALL windows and that tenant's own
        # window keeps tripping until it is healed too.
        excprof.reanchor(tenant, rate=cand["overlay"].get("anchor_rate"))
        excprof.reanchor(None)
        promote_s = now - cand["t_trigger"]
        xferstats.bump("serve_respec_promotions", 1, tag=tenant)
        telemetry.observe("serve_respec_promote_seconds", promote_s,
                          tenant=tenant)
        TR.instant("respec:promote", "respec",
                   {"tenant": tenant, "gen": cand["gen"],
                    "promote_s": round(promote_s, 3),
                    "checks": len(cand["checks"])})
        self._event(tenant, "promote", gen=cand["gen"],
                    sig=cand["sig"][:12],
                    promote_s=round(promote_s, 3),
                    checks=len(cand["checks"]))
        log.info("respec[%s]: promoted generation %d after %d canary "
                 "check(s) (%.2fs trigger-to-promote); incumbent "
                 "retained as the fallback rung",
                 tenant, cand["gen"], len(cand["checks"]), promote_s)

    def _abandon(self, tenant: str, cand: dict, reason: str) -> None:
        """Drop a candidate WITHOUT a new quarantine mark (it is already
        quarantined — re-marking would double the backoff per check)."""
        st = self._state(tenant, create=False)
        if st is None:
            return
        now = time.monotonic()
        with self._lock:
            if st.candidate is cand:
                st.candidate = None
            until = self._quarantined_until(st, cand.get("sig", ""))
            st.cooldown_until = max(
                st.cooldown_until,
                now + max(self.cooldown_s, until - time.time()))
        self._event(tenant, "abandoned", gen=cand["gen"], reason=reason)
        log.info("respec[%s]: %s", tenant, reason)

    def _quarantine(self, tenant: str, cand: dict, reason: str) -> None:
        from ..exec import compilequeue as CQ

        st = self._state(tenant, create=False)
        if st is None:
            return
        sig = cand.get("sig", "")
        now = time.monotonic()
        with self._lock:
            if st.candidate is cand:
                st.candidate = None
            elif cand.get("state") == "quarantined":
                return          # double fire (watchdog + build thread)
            cand["state"] = "quarantined"
            cand["failed"] = cand.get("failed") or reason
            prev = st.quar.get(sig) if sig else None
            count = (prev[0] if prev else 0) + 1
            if sig:
                st.quar[sig] = (count, time.time())
            st.quarantines += 1
            backoff = self.quarantine_s * (2 ** max(0, count - 1))
            st.cooldown_until = max(st.cooldown_until, now + backoff)
            st.debounce = 0
        if sig:
            CQ.write_marker(self._quar_base(sig), "respecquar",
                            reason=reason, tenant=tenant,
                            gen=cand.get("gen"), count=count,
                            backoff_s=backoff)
        xferstats.bump("serve_respec_quarantined", 1, tag=tenant)
        TR.instant("respec:rollback", "respec",
                   {"tenant": tenant, "gen": cand.get("gen"),
                    "reason": reason[:120], "quarantine_s": backoff})
        self._event(tenant, "quarantine", gen=cand.get("gen"),
                    sig=sig[:12], reason=reason[:200],
                    backoff_s=backoff)
        log.warning("respec[%s]: candidate gen %s quarantined (%s); "
                    "cooldown %.0fs", tenant, cand.get("gen"), reason,
                    backoff)

    def _quarantine_sig(self, tenant: str, sig: str, reason: str) -> None:
        """Quarantine by signature alone (post-promotion rollback: there
        is no candidate object anymore, the overlay WAS active)."""
        from ..exec import compilequeue as CQ

        st = self._state(tenant, create=False)
        if st is None or not sig:
            return
        now = time.monotonic()
        with self._lock:
            prev = st.quar.get(sig)
            count = (prev[0] if prev else 0) + 1
            st.quar[sig] = (count, time.time())
            st.quarantines += 1
            backoff = self.quarantine_s * (2 ** max(0, count - 1))
            st.cooldown_until = max(st.cooldown_until, now + backoff)
        CQ.write_marker(self._quar_base(sig), "respecquar",
                        reason=reason, tenant=tenant, count=count,
                        backoff_s=backoff)
        xferstats.bump("serve_respec_quarantined", 1, tag=tenant)

    # ------------------------------------------------------------------
    # readouts
    # ------------------------------------------------------------------
    def tenant_report(self, tenant: str) -> dict:
        """One tenant's lifecycle readout (dashboard/excprof event rows +
        tests): generation, candidate state, counts, bounded history."""
        st = self._state(tenant, create=False)
        if st is None:
            return {"generation": 0, "state": "idle", "promotions": 0,
                    "quarantines": 0, "rollbacks": 0, "history": []}
        with self._lock:
            cand = st.candidate
            return {
                "generation": st.gen,
                "state": cand["state"] if cand is not None else
                ("promoted" if st.overlay is not None else "idle"),
                "candidate_gen": cand["gen"] if cand is not None else None,
                "promotions": st.promotions,
                "quarantines": st.quarantines,
                "rollbacks": st.rollbacks,
                "history": list(st.history),
            }


# ---------------------------------------------------------------------------
# overlay application (stage side — also used by exec/local's revert)
# ---------------------------------------------------------------------------

def apply_overlay_to_stage(stage, overlay: dict, si: int,
                           notify=None) -> None:
    """Mutate one freshly rebuilt TransformStage to its re-specialized
    generation: the per-generation key salt, the live-observed expected
    codes and (where the respeculation decided so) the non-speculating
    compile. The ORIGINAL values are retained on the stage
    (``_respec_revert``) — exec/local's tier ladder restores them, whole
    stage from partition 0, if the generation fails at run time."""
    revert = {
        "respec_salt": stage.respec_salt,
        "extra_expected_codes": stage.extra_expected_codes,
        "speculate_branches": stage.speculate_branches,
    }
    stage.respec_salt = overlay.get("salt", "")
    cfg = (overlay.get("stages") or {}).get(si) \
        or (overlay.get("stages") or {}).get(str(si)) or {}
    if cfg.get("extra_codes"):
        stage.extra_expected_codes = tuple(
            sorted(set(int(c) for c in cfg["extra_codes"])))
    if cfg.get("speculate") is not None:
        stage.speculate_branches = bool(cfg["speculate"])
    for memo in ("_resolve_plan_memo",):
        if hasattr(stage, memo):
            try:
                delattr(stage, memo)
            except AttributeError:
                pass
    stage.respec_generation = int(overlay.get("gen", 0))
    stage._respec_revert = revert
    if notify is not None:
        stage._respec_notify = notify


__all__ = ["RespecController", "apply_overlay_to_stage",
           "COMPILING", "READY", "CANARY"]
