"""Scratch-dir wire protocol for the job service (submit / poll / fetch).

The same filesystem handshake ``exec/worker.py --serve`` established for
warm workers, lifted to whole pipelines: a client drops an atomic
``request.pkl`` under the service root's ``inbox/``, the service loop
(``python -m tuplex_tpu serve <root>``) admits it into a ``JobService``,
streams state into ``status.json``, and writes the terminal
``response.pkl`` atomically — completion is signalled solely by that
rename, never by process liveness. No sockets: the root can live on any
shared filesystem, and a crashed client leaves nothing wedged.

Layout under the service root:

    inbox/<job>/request.pkl      client -> service (atomic rename)
    inbox/<job>/status.json      service -> client (overwritten per poll)
    inbox/<job>/response.pkl     service -> client (atomic, terminal)
    inbox/<job>/journal.json     service-side state journal (atomic):
                                 admitted/running/terminal transitions +
                                 the crash-requeue count. A restarted
                                 service over the same root reads it to
                                 requeue in-flight jobs exactly once,
                                 keep completed responses fetchable, and
                                 fail poison jobs (in flight through
                                 more than tuplex.serve.retryCount
                                 crashes) cleanly instead of crash-
                                 looping on them
    metrics.prom                 Prometheus text drop (runtime/telemetry,
                                 rewritten every tuplex.serve.metricsPromS
                                 seconds — the pull-telemetry leg of the
                                 wire protocol for clients with no port)
    metrics.port                 bound /metrics HTTP port, written once
                                 when tuplex.serve.metricsPort >= 0
    STOP                         touch to shut the service loop down
"""

from __future__ import annotations

import json
import os
import pickle
import time
import uuid
from typing import Optional

from ..utils.logging import get_logger
from .jobs import (DONE, FAILED, RUNNING, JobRejected, JobRequest,
                   QueueFull, cleanup_request_scratch)
from .service import JobService

log = get_logger("tuplex_tpu.serve")

_TERMINAL = (DONE, FAILED, "rejected", "cancelled")


def _atomic_write(path: str, data: bytes) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as fp:
        fp.write(data)
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# client side
# ---------------------------------------------------------------------------

def submit(root: str, request: JobRequest,
           jid: Optional[str] = None) -> str:
    """Drop a request into the service inbox; returns the job dir name.
    Only wire-safe requests travel (every stage by spec — live stage
    objects are an in-process construct).

    `jid` is an optional idempotency key: resubmitting under a jid whose
    request already landed is a no-op (the first request stands and its
    status/response stay authoritative), so a client that crashed
    between submit and fetch can blindly resubmit-then-fetch without
    ever running the job twice."""
    if not request.wire_safe():
        # the request dies here: its staged input parts must die with it
        cleanup_request_scratch(request.stages)
        raise JobRejected(
            "request carries live stage objects (join/aggregate tier); "
            "only spec-serialized pipelines can travel the wire protocol")
    jid = jid or uuid.uuid4().hex[:12]
    jdir = os.path.join(root, "inbox", jid)
    os.makedirs(jdir, exist_ok=True)
    req_path = os.path.join(jdir, "request.pkl")
    if os.path.exists(req_path):
        # duplicate submission: idempotent — the first request stands.
        # Release the NEW request's staged scratch (it would leak), but
        # never an indir the standing request also references (a caller
        # resubmitting the SAME request object must not have its staged
        # input deleted out from under the admitted job).
        keep: set = set()
        try:
            with open(req_path, "rb") as fp:
                standing = pickle.load(fp)
            keep = {e.get("indir") for e in standing.stages
                    if isinstance(e, dict)}
        except Exception:
            keep = {e.get("indir") for e in request.stages
                    if isinstance(e, dict)}   # unreadable: clean nothing
        cleanup_request_scratch(
            [e for e in request.stages
             if isinstance(e, dict) and e.get("indir")
             and e["indir"] not in keep])
        return jid
    _atomic_write(req_path, pickle.dumps(request))
    return jid


def poll(root: str, jid: str) -> dict:
    """Latest status record for a submitted job ({} before the service
    first sees it)."""
    path = os.path.join(root, "inbox", jid, "status.json")
    try:
        with open(path) as fp:
            return json.load(fp)
    except (OSError, json.JSONDecodeError):
        return {}


def fetch(root: str, jid: str, timeout: float = 600.0,
          poll_s: float = 0.1) -> dict:
    """Block until the job's terminal response lands; returns the response
    dict ({"ok": bool, "rows": [...], "metrics": {...}} or
    {"ok": False, "error": ...}). TimeoutError past `timeout`.

    The reader trusts ONLY complete atomic renames: a torn/partial
    ``response.pkl`` (a crashed writer's leftovers, a network filesystem
    exposing a rename mid-flight) is treated as not-yet-arrived and
    polling continues — the real response can still land over it via
    ``os.replace`` — instead of surfacing a confusing unpickling error
    to the caller."""
    resp = os.path.join(root, "inbox", jid, "response.pkl")
    deadline = time.monotonic() + timeout
    saw_torn = False
    while True:
        if os.path.exists(resp):
            try:
                with open(resp, "rb") as fp:
                    return pickle.load(fp)
            except (OSError, EOFError, pickle.UnpicklingError,
                    IndexError):
                saw_torn = True     # partial bytes: keep polling
            # ImportError/AttributeError from a COMPLETE pickle are
            # version skew between client and service, not a torn write
            # — surface them instead of polling out the whole timeout
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"no response for job {jid} after {timeout:.0f}s"
                + (" (a torn/partial response.pkl was present — the "
                   "writer likely crashed mid-write and never replaced "
                   "it atomically)" if saw_torn else ""))
        time.sleep(poll_s)


# ---------------------------------------------------------------------------
# service side (the `python -m tuplex_tpu serve` loop)
# ---------------------------------------------------------------------------

def _read_journal(jdir: str) -> dict:
    try:
        with open(os.path.join(jdir, "journal.json")) as fp:
            return json.load(fp)
    except (OSError, json.JSONDecodeError):
        return {}


def _write_journal(jdir: str, state: str, cache: Optional[dict] = None,
                   **fields) -> None:
    """Atomically journal a job-state transition. `cache` (jdir -> last
    journal dict) avoids a read-modify-write per poll: only CHANGES hit
    the filesystem, and the persistent ``requeues`` counter survives
    every rewrite."""
    prev = (cache.get(jdir) if cache is not None else None) \
        or _read_journal(jdir)
    rec = {"requeues": int(prev.get("requeues", 0)), "state": state}
    rec.update(fields)
    if cache is not None:
        old = cache.get(jdir)
        if old is not None and \
                {k: v for k, v in old.items() if k != "updated"} == rec:
            return
    out = dict(rec)
    out["updated"] = time.time()
    try:
        _atomic_write(os.path.join(jdir, "journal.json"),
                      json.dumps(out).encode())
        if cache is not None:
            cache[jdir] = out
    except OSError:     # journal is the recovery substrate, writes are
        pass            # still best-effort per tick — the next one retries


def _recover_inbox(inbox: str, requeue_budget: int) -> tuple:
    """Crash recovery over a pre-existing service root, run once before
    the loop starts. Returns (finished_dirs, n_requeued, n_failed).

    * a dir with ``response.pkl`` is DONE — its result stays fetchable
      and it is never re-admitted (duplicate submissions under that jid
      are idempotently ignored);
    * a dir journaled admitted/running was IN FLIGHT when the previous
      service process died: bump its crash-requeue count and let the
      normal admission scan requeue it (exactly once per restart);
    * a job already requeued more than `requeue_budget` times is a
      poison job (it keeps being in flight when the service dies):
      terminal-fail it cleanly instead of crash-looping on it."""
    finished: set = set()
    requeued = failed = 0
    try:
        names = sorted(os.listdir(inbox))
    except OSError:
        return finished, requeued, failed
    for d in names:
        jdir = os.path.join(inbox, d)
        if not os.path.isdir(jdir):
            continue
        if os.path.exists(os.path.join(jdir, "response.pkl")):
            finished.add(d)
            continue
        j = _read_journal(jdir)
        if j.get("state") not in ("admitted", "running", "recovered"):
            continue        # never admitted: the normal scan handles it
        requeues = int(j.get("requeues", 0)) + 1
        if requeues > max(1, requeue_budget):
            msg = (f"job was in flight through {requeues - 1} service "
                   f"crash(es) (tuplex.serve.retryCount); failing "
                   f"cleanly instead of requeueing again")
            try:      # terminal: release the request's staged input
                with open(os.path.join(jdir, "request.pkl"), "rb") as fp:
                    cleanup_request_scratch(pickle.load(fp).stages)
            except Exception:   # unreadable request: nothing staged to
                pass            # find — the dir itself stays diagnosable
            _atomic_write(os.path.join(jdir, "response.pkl"),
                          pickle.dumps({"ok": False, "state": FAILED,
                                        "error": msg}))
            _write_status(jdir, FAILED, {"error": msg})
            _write_journal(jdir, FAILED, requeues=requeues)
            finished.add(d)
            failed += 1
            log.warning("recovery: poison job %s failed cleanly "
                        "(%d crash requeues)", d, requeues - 1)
        else:
            _write_journal(jdir, "recovered", requeues=requeues)
            requeued += 1
            log.info("recovery: requeueing in-flight job %s "
                     "(crash requeue %d/%d)", d, requeues,
                     max(1, requeue_budget))
    return finished, requeued, failed


def _write_status(jdir: str, handle_or_state,
                  extra: Optional[dict] = None,
                  cache: Optional[dict] = None):
    if isinstance(handle_or_state, str):
        rec = {"state": handle_or_state}
    else:
        h = handle_or_state
        # plain record reads only — JobHandle.stats would lock (and reap)
        # the running job's MemoryManager 10x/second per job just to
        # report a turn counter
        rec = {"state": h.state, "job": h.id, "tenant": h.tenant,
               "turns": h._rec.stats.get("turns", 0)}
    if extra:
        rec.update(extra)
    payload = json.dumps(rec)
    # the poll loop calls this every iteration; only CHANGES hit the
    # filesystem (the protocol targets shared/network filesystems where
    # a rename per 0.1s poll per job is real churn)
    if cache is not None and cache.get(jdir) == payload:
        return
    try:
        _atomic_write(os.path.join(jdir, "status.json"), payload.encode())
        if cache is not None:
            cache[jdir] = payload
    except OSError:
        pass


def _finish(jdir: str, handle, jcache: Optional[dict] = None) -> None:
    if handle.state == DONE:
        resp = {"ok": True, "rows": handle._rec.result_rows,
                "metrics": handle.metrics.as_dict(),
                "counters": handle.counters(),
                "stats": handle.stats,
                "attempts": handle.attempts(),
                # latency-budget vector (runtime/critpath): wire clients
                # get the same per-bucket attribution + critical path an
                # in-process JobHandle.latency_budget() reads
                "latency_budget": handle.latency_budget(),
                "exception_counts": {}}
        for e in handle.exceptions():
            resp["exception_counts"][e.exc_name] = \
                resp["exception_counts"].get(e.exc_name, 0) + 1
    else:
        resp = {"ok": False, "state": handle.state,
                "error": handle.error or handle.state,
                "attempts": handle.attempts()}
    _atomic_write(os.path.join(jdir, "response.pkl"), pickle.dumps(resp))
    # journal AFTER the response rename: a crash between the two leaves
    # an admitted/running journal next to a response — recovery treats
    # the response as authoritative, so the job is still terminal
    _write_journal(jdir, handle.state, jcache,
                   attempts=len(handle.attempts()))


def service_loop(root: str, options=None, *, poll_s: float = 0.1,
                 service: Optional[JobService] = None,
                 max_idle_s: float = 0.0) -> int:
    """Run the file-protocol front end over a JobService until
    ``<root>/STOP`` appears (or `max_idle_s` of quiet, when positive —
    tests use it). Returns the number of jobs served."""
    from ..runtime import telemetry

    svc = service if service is not None else JobService(options)
    inbox = os.path.join(root, "inbox")
    os.makedirs(inbox, exist_ok=True)
    stop_file = os.path.join(root, "STOP")
    # pull telemetry: an HTTP /metrics + /healthz endpoint when a port is
    # configured (metricsPort >= 0; 0 = pick a free one, announced via
    # <root>/metrics.port), and a periodic metrics.prom text drop either
    # way — the no-socket leg of the wire protocol
    metrics_srv = None
    prom_path = os.path.join(root, "metrics.prom")
    port_path = os.path.join(root, "metrics.port")
    # a previous run's announcement is a lie the moment this loop owns
    # the root: remove it BEFORE deciding whether to serve, so a restart
    # without a port (or a failed bind) never points clients at a dead
    # or recycled socket
    try:
        os.unlink(port_path)
    except OSError:
        pass
    prom_every = svc.options.get_float("tuplex.serve.metricsPromS", 5.0)
    last_prom = 0.0
    if telemetry.enabled():
        port = svc.options.get_int("tuplex.serve.metricsPort", -1)
        if port >= 0:
            try:
                metrics_srv, url = telemetry.start_metrics_server(port)
            except OSError as e:
                log.warning("metrics server failed to bind: %s", e)
            else:
                try:
                    with open(port_path, "w") as fp:
                        fp.write(str(metrics_srv.server_address[1]))
                    log.info("metrics at %smetrics, health at %shealthz",
                             url, url)
                except OSError as e:
                    # the server IS up but undiscoverable: a --metrics-port
                    # 0 client can never find it, so take it back down
                    # rather than leak a silently unreachable endpoint
                    log.warning("could not announce metrics port in %s "
                                "(%s); shutting the metrics server down",
                                port_path, e)
                    metrics_srv.shutdown()
                    metrics_srv = None
    tracked: dict = {}          # jid dir -> (jdir, handle)
    waiting: dict = {}          # jid dir -> first queue-full timestamp
    status_cache: dict = {}     # jdir -> last status json written
    journal_cache: dict = {}    # jdir -> last journal dict written
    # crash recovery BEFORE the first scan: completed jobs stay fetchable
    # (and are never re-admitted), jobs that were in flight when a
    # previous service process died over this root are requeued exactly
    # once, poison jobs are failed cleanly
    finished, n_requeued, n_poisoned = _recover_inbox(
        inbox, svc.retry_count)
    # crash-recovery observability: the requeue/poison outcomes used to
    # exist only in the per-job journals — export them as counters (the
    # xferstats bridge puts `tuplex_serve_recovered_jobs_total` /
    # `tuplex_serve_poison_jobs_total` on /metrics) and as a health-check
    # detail so the /healthz payload states what the last restart did
    from ..runtime import xferstats

    if n_requeued:
        xferstats.bump("serve_recovered_jobs", n_requeued, tag="requeued")
    if n_poisoned:
        xferstats.bump("serve_poison_jobs", n_poisoned, tag="poisoned")
    if telemetry.enabled():
        recovery_detail = (
            f"last start over this root: {n_requeued} in-flight job(s) "
            f"requeued, {n_poisoned} poison job(s) failed cleanly, "
            f"{len(finished)} finished response(s) kept"
            if (n_requeued or n_poisoned)
            else "no crash recovery needed at start")
        telemetry.register_health_check(
            "serve_recovery",
            lambda d=recovery_detail: (telemetry.OK, d), owner=svc)
    served = 0
    last_activity = time.monotonic()
    log.info("job service listening on %s (slots=%d, depth=%d)%s",
             root, svc.slots, svc.queue_depth,
             f" — recovered root: {n_requeued} requeued, "
             f"{n_poisoned} poison-failed, {len(finished)} kept"
             if (n_requeued or n_poisoned) else "")

    def _reject_dir(d, jdir, msg, stages=None):
        if stages is not None:
            cleanup_request_scratch(stages)
        _atomic_write(os.path.join(jdir, "response.pkl"),
                      pickle.dumps({"ok": False, "state": "rejected",
                                    "error": msg}))
        _write_status(jdir, "rejected", {"error": msg})
        _write_journal(jdir, "rejected", journal_cache)
        status_cache.pop(jdir, None)
        waiting.pop(d, None)
        finished.add(d)

    try:
        while not os.path.exists(stop_file):
            progressed = False
            names = sorted(os.listdir(inbox))
            # a client that removed its job dir releases our memory of it
            # (bounds `finished`/`waiting` over a long-lived service, and
            # keeps a vanished waiting dir from pinning max_idle_s open)
            name_set = set(names)
            finished &= name_set
            for d in list(waiting):
                if d not in name_set:
                    waiting.pop(d, None)
            for d in names:
                jdir = os.path.join(inbox, d)
                if d in tracked or d in finished:
                    continue
                req_path = os.path.join(jdir, "request.pkl")
                if not os.path.exists(req_path):
                    continue
                try:
                    with open(req_path, "rb") as fp:
                        req = pickle.load(fp)
                    # zero-wait admission: the poll thread must never
                    # block on a full queue (frozen statuses, deferred
                    # STOP). Queue-full retries ride the poll loop until
                    # the service's admission timeout, THEN reject.
                    handle = svc.submit(req, timeout=0,
                                        cleanup_on_reject=False)
                except QueueFull:
                    first = waiting.setdefault(d, time.monotonic())
                    if time.monotonic() - first \
                            >= svc.admission_timeout_s:
                        progressed = True
                        # this is the client-visible rejection (the
                        # zero-wait probes above deliberately don't
                        # count): feed the health/counter accounting
                        svc.note_rejection()
                        # the probe submits used timeout=0; report the
                        # wait the client ACTUALLY got
                        _reject_dir(
                            d, jdir,
                            f"admission queue full — timed out after "
                            f"{svc.admission_timeout_s:.0f}s "
                            f"(tuplex.serve.admissionTimeoutS)",
                            stages=req.stages)
                    else:
                        _write_status(jdir, "waiting", cache=status_cache)
                    continue
                except JobRejected as e:
                    progressed = True
                    _reject_dir(d, jdir, str(e), stages=req.stages)
                    continue
                except Exception as e:   # unreadable request
                    progressed = True
                    _reject_dir(d, jdir, f"bad request: {e}")
                    continue
                progressed = True
                waiting.pop(d, None)
                tracked[d] = (jdir, handle)
                _write_status(jdir, handle, cache=status_cache)
                # journal the admission BEFORE this tick returns: a crash
                # from here on leaves an admitted/running record the next
                # service over this root requeues exactly once
                _write_journal(jdir, "admitted", journal_cache,
                               job=handle.id)
                from ..runtime import faults

                faults.maybe("serve", point="after-admit")
            for d in list(tracked):
                jdir, handle = tracked[d]
                _write_status(jdir, handle, cache=status_cache)
                if handle.state == RUNNING:
                    _write_journal(jdir, "running", journal_cache,
                                   job=handle.id)
                if handle.state in _TERMINAL:
                    _finish(jdir, handle, journal_cache)
                    del tracked[d]
                    status_cache.pop(jdir, None)
                    journal_cache.pop(jdir, None)
                    finished.add(d)
                    served += 1
                    progressed = True
            if telemetry.enabled() and prom_every > 0 \
                    and time.monotonic() - last_prom >= prom_every:
                last_prom = time.monotonic()
                try:
                    telemetry.write_prom(prom_path)
                except OSError:   # telemetry drop is advisory
                    pass
            if progressed or tracked or waiting:
                last_activity = time.monotonic()
            elif max_idle_s > 0 and \
                    time.monotonic() - last_activity > max_idle_s:
                break
            time.sleep(poll_s)
    finally:
        if telemetry.enabled():
            try:            # final drop: the terminal aggregate survives
                telemetry.write_prom(prom_path)
            except OSError:
                pass
        if metrics_srv is not None:
            metrics_srv.shutdown()
            try:                   # the port dies with the server
                os.unlink(port_path)
            except OSError:
                pass
        if service is None:
            svc.close()
    return served
