"""Scratch-dir wire protocol for the job service (submit / poll / fetch).

The same filesystem handshake ``exec/worker.py --serve`` established for
warm workers, lifted to whole pipelines: a client drops an atomic
``request.pkl`` under the service root's ``inbox/``, the service loop
(``python -m tuplex_tpu serve <root>``) admits it into a ``JobService``,
streams state into ``status.json``, and writes the terminal
``response.pkl`` atomically — completion is signalled solely by that
rename, never by process liveness. No sockets: the root can live on any
shared filesystem, and a crashed client leaves nothing wedged.

Layout under the service root:

    inbox/<job>/request.pkl      client -> service (atomic rename)
    inbox/<job>/status.json      service -> client (overwritten per poll)
    inbox/<job>/response.pkl     service -> client (atomic, terminal)
    metrics.prom                 Prometheus text drop (runtime/telemetry,
                                 rewritten every tuplex.serve.metricsPromS
                                 seconds — the pull-telemetry leg of the
                                 wire protocol for clients with no port)
    metrics.port                 bound /metrics HTTP port, written once
                                 when tuplex.serve.metricsPort >= 0
    STOP                         touch to shut the service loop down
"""

from __future__ import annotations

import json
import os
import pickle
import time
import uuid
from typing import Optional

from ..utils.logging import get_logger
from .jobs import (DONE, FAILED, JobRejected, JobRequest, QueueFull,
                   cleanup_request_scratch)
from .service import JobService

log = get_logger("tuplex_tpu.serve")

_TERMINAL = (DONE, FAILED, "rejected", "cancelled")


def _atomic_write(path: str, data: bytes) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as fp:
        fp.write(data)
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# client side
# ---------------------------------------------------------------------------

def submit(root: str, request: JobRequest) -> str:
    """Drop a request into the service inbox; returns the job dir name.
    Only wire-safe requests travel (every stage by spec — live stage
    objects are an in-process construct)."""
    if not request.wire_safe():
        # the request dies here: its staged input parts must die with it
        cleanup_request_scratch(request.stages)
        raise JobRejected(
            "request carries live stage objects (join/aggregate tier); "
            "only spec-serialized pipelines can travel the wire protocol")
    jid = uuid.uuid4().hex[:12]
    jdir = os.path.join(root, "inbox", jid)
    os.makedirs(jdir, exist_ok=True)
    _atomic_write(os.path.join(jdir, "request.pkl"),
                  pickle.dumps(request))
    return jid


def poll(root: str, jid: str) -> dict:
    """Latest status record for a submitted job ({} before the service
    first sees it)."""
    path = os.path.join(root, "inbox", jid, "status.json")
    try:
        with open(path) as fp:
            return json.load(fp)
    except (OSError, json.JSONDecodeError):
        return {}


def fetch(root: str, jid: str, timeout: float = 600.0,
          poll_s: float = 0.1) -> dict:
    """Block until the job's terminal response lands; returns the response
    dict ({"ok": bool, "rows": [...], "metrics": {...}} or
    {"ok": False, "error": ...}). TimeoutError past `timeout`."""
    resp = os.path.join(root, "inbox", jid, "response.pkl")
    deadline = time.monotonic() + timeout
    while not os.path.exists(resp):
        if time.monotonic() > deadline:
            raise TimeoutError(f"no response for job {jid} "
                               f"after {timeout:.0f}s")
        time.sleep(poll_s)
    with open(resp, "rb") as fp:
        return pickle.load(fp)


# ---------------------------------------------------------------------------
# service side (the `python -m tuplex_tpu serve` loop)
# ---------------------------------------------------------------------------

def _write_status(jdir: str, handle_or_state,
                  extra: Optional[dict] = None,
                  cache: Optional[dict] = None):
    if isinstance(handle_or_state, str):
        rec = {"state": handle_or_state}
    else:
        h = handle_or_state
        # plain record reads only — JobHandle.stats would lock (and reap)
        # the running job's MemoryManager 10x/second per job just to
        # report a turn counter
        rec = {"state": h.state, "job": h.id, "tenant": h.tenant,
               "turns": h._rec.stats.get("turns", 0)}
    if extra:
        rec.update(extra)
    payload = json.dumps(rec)
    # the poll loop calls this every iteration; only CHANGES hit the
    # filesystem (the protocol targets shared/network filesystems where
    # a rename per 0.1s poll per job is real churn)
    if cache is not None and cache.get(jdir) == payload:
        return
    try:
        _atomic_write(os.path.join(jdir, "status.json"), payload.encode())
        if cache is not None:
            cache[jdir] = payload
    except OSError:
        pass


def _finish(jdir: str, handle) -> None:
    if handle.state == DONE:
        resp = {"ok": True, "rows": handle._rec.result_rows,
                "metrics": handle.metrics.as_dict(),
                "counters": handle.counters(),
                "stats": handle.stats,
                "exception_counts": {}}
        for e in handle.exceptions():
            resp["exception_counts"][e.exc_name] = \
                resp["exception_counts"].get(e.exc_name, 0) + 1
    else:
        resp = {"ok": False, "state": handle.state,
                "error": handle.error or handle.state}
    _atomic_write(os.path.join(jdir, "response.pkl"), pickle.dumps(resp))


def service_loop(root: str, options=None, *, poll_s: float = 0.1,
                 service: Optional[JobService] = None,
                 max_idle_s: float = 0.0) -> int:
    """Run the file-protocol front end over a JobService until
    ``<root>/STOP`` appears (or `max_idle_s` of quiet, when positive —
    tests use it). Returns the number of jobs served."""
    from ..runtime import telemetry

    svc = service if service is not None else JobService(options)
    inbox = os.path.join(root, "inbox")
    os.makedirs(inbox, exist_ok=True)
    stop_file = os.path.join(root, "STOP")
    # pull telemetry: an HTTP /metrics + /healthz endpoint when a port is
    # configured (metricsPort >= 0; 0 = pick a free one, announced via
    # <root>/metrics.port), and a periodic metrics.prom text drop either
    # way — the no-socket leg of the wire protocol
    metrics_srv = None
    prom_path = os.path.join(root, "metrics.prom")
    port_path = os.path.join(root, "metrics.port")
    # a previous run's announcement is a lie the moment this loop owns
    # the root: remove it BEFORE deciding whether to serve, so a restart
    # without a port (or a failed bind) never points clients at a dead
    # or recycled socket
    try:
        os.unlink(port_path)
    except OSError:
        pass
    prom_every = svc.options.get_float("tuplex.serve.metricsPromS", 5.0)
    last_prom = 0.0
    if telemetry.enabled():
        port = svc.options.get_int("tuplex.serve.metricsPort", -1)
        if port >= 0:
            try:
                metrics_srv, url = telemetry.start_metrics_server(port)
            except OSError as e:
                log.warning("metrics server failed to bind: %s", e)
            else:
                try:
                    with open(port_path, "w") as fp:
                        fp.write(str(metrics_srv.server_address[1]))
                    log.info("metrics at %smetrics, health at %shealthz",
                             url, url)
                except OSError as e:
                    # the server IS up but undiscoverable: a --metrics-port
                    # 0 client can never find it, so take it back down
                    # rather than leak a silently unreachable endpoint
                    log.warning("could not announce metrics port in %s "
                                "(%s); shutting the metrics server down",
                                port_path, e)
                    metrics_srv.shutdown()
                    metrics_srv = None
    tracked: dict = {}          # jid dir -> (jdir, handle)
    waiting: dict = {}          # jid dir -> first queue-full timestamp
    finished: set = set()
    status_cache: dict = {}     # jdir -> last status json written
    served = 0
    last_activity = time.monotonic()
    log.info("job service listening on %s (slots=%d, depth=%d)",
             root, svc.slots, svc.queue_depth)

    def _reject_dir(d, jdir, msg, stages=None):
        if stages is not None:
            cleanup_request_scratch(stages)
        _atomic_write(os.path.join(jdir, "response.pkl"),
                      pickle.dumps({"ok": False, "state": "rejected",
                                    "error": msg}))
        _write_status(jdir, "rejected", {"error": msg})
        status_cache.pop(jdir, None)
        waiting.pop(d, None)
        finished.add(d)

    try:
        while not os.path.exists(stop_file):
            progressed = False
            names = sorted(os.listdir(inbox))
            # a client that removed its job dir releases our memory of it
            # (bounds `finished`/`waiting` over a long-lived service, and
            # keeps a vanished waiting dir from pinning max_idle_s open)
            name_set = set(names)
            finished &= name_set
            for d in list(waiting):
                if d not in name_set:
                    waiting.pop(d, None)
            for d in names:
                jdir = os.path.join(inbox, d)
                if d in tracked or d in finished:
                    continue
                req_path = os.path.join(jdir, "request.pkl")
                if not os.path.exists(req_path):
                    continue
                try:
                    with open(req_path, "rb") as fp:
                        req = pickle.load(fp)
                    # zero-wait admission: the poll thread must never
                    # block on a full queue (frozen statuses, deferred
                    # STOP). Queue-full retries ride the poll loop until
                    # the service's admission timeout, THEN reject.
                    handle = svc.submit(req, timeout=0,
                                        cleanup_on_reject=False)
                except QueueFull:
                    first = waiting.setdefault(d, time.monotonic())
                    if time.monotonic() - first \
                            >= svc.admission_timeout_s:
                        progressed = True
                        # this is the client-visible rejection (the
                        # zero-wait probes above deliberately don't
                        # count): feed the health/counter accounting
                        svc.note_rejection()
                        # the probe submits used timeout=0; report the
                        # wait the client ACTUALLY got
                        _reject_dir(
                            d, jdir,
                            f"admission queue full — timed out after "
                            f"{svc.admission_timeout_s:.0f}s "
                            f"(tuplex.serve.admissionTimeoutS)",
                            stages=req.stages)
                    else:
                        _write_status(jdir, "waiting", cache=status_cache)
                    continue
                except JobRejected as e:
                    progressed = True
                    _reject_dir(d, jdir, str(e), stages=req.stages)
                    continue
                except Exception as e:   # unreadable request
                    progressed = True
                    _reject_dir(d, jdir, f"bad request: {e}")
                    continue
                progressed = True
                waiting.pop(d, None)
                tracked[d] = (jdir, handle)
                _write_status(jdir, handle, cache=status_cache)
            for d in list(tracked):
                jdir, handle = tracked[d]
                _write_status(jdir, handle, cache=status_cache)
                if handle.state in _TERMINAL:
                    _finish(jdir, handle)
                    del tracked[d]
                    status_cache.pop(jdir, None)
                    finished.add(d)
                    served += 1
                    progressed = True
            if telemetry.enabled() and prom_every > 0 \
                    and time.monotonic() - last_prom >= prom_every:
                last_prom = time.monotonic()
                try:
                    telemetry.write_prom(prom_path)
                except OSError:   # telemetry drop is advisory
                    pass
            if progressed or tracked or waiting:
                last_activity = time.monotonic()
            elif max_idle_s > 0 and \
                    time.monotonic() - last_activity > max_idle_s:
                break
            time.sleep(poll_s)
    finally:
        if telemetry.enabled():
            try:            # final drop: the terminal aggregate survives
                telemetry.write_prom(prom_path)
            except OSError:
                pass
        if metrics_srv is not None:
            metrics_srv.shutdown()
            try:                   # the port dies with the server
                os.unlink(port_path)
            except OSError:
                pass
        if service is None:
            svc.close()
    return served
