"""Job-service runtime: concurrent multi-tenant pipelines on one warm TPU.

Public surface:

* ``JobService`` — the long-lived scheduler (serve/service.py).
* ``JobRequest`` / ``request_from_dataset`` — submissions built from the
  serverless stage-spec serialization (serve/jobs.py).
* ``JobHandle`` — caller-side state/result/metrics view.
* ``client`` — the scratch-dir wire protocol + the
  ``python -m tuplex_tpu serve`` loop (serve/client.py).
* ``RespecController`` — closed-loop self-healing (serve/respec.py):
  background re-specialization keyed off the exception-plane drift
  signal, canary validation, guarded hot-swap, automatic rollback.
* ``Context.submit(ds)`` (api/context.py) is the one-liner entry point.

Observability: the service feeds per-tenant latency histograms, queue/
slot/memory gauges and health checks into ``runtime/telemetry`` —
scraped via ``--metrics-port`` (/metrics + /healthz), the periodic
``<root>/metrics.prom`` drop, or ``Metrics.export_prometheus()``;
``scripts/serve_bench.py`` measures concurrent-vs-serial p99.
"""

from .jobs import (CANCELLED, DONE, FAILED, QUEUED, REJECTED, RUNNING,
                   JobFailed, JobHandle, JobRejected, JobRequest,
                   QueueFull, request_from_dataset)
from .respec import RespecController
from .service import JobService

__all__ = [
    "JobService", "JobRequest", "JobHandle", "JobRejected", "JobFailed",
    "QueueFull", "request_from_dataset", "RespecController", "QUEUED",
    "RUNNING", "DONE", "FAILED", "REJECTED", "CANCELLED",
]
