"""Job-service data model: requests, handles, and the per-job runner.

A submitted pipeline travels as a ``JobRequest`` whose stages are the SAME
stage-spec serialization the serverless fan-out ships to workers
(exec/serverless.serialize_stage / rebuild_stage) — UDF sources + captured
globals + authoritative schemas, with file sources referenced by path and
memory sources staged to the scratch dir as native-format parts (the
exec/worker.py staged-parts protocol). That makes a request picklable end
to end, so the same object serves the in-process ``Context.submit()`` path
and the scratch-dir wire protocol (serve/client.py).

Stages the spec can't carry (joins, aggregates — the driver-side merge
tier in the serverless analog) ride as LIVE stage objects for in-process
submissions; the wire client rejects them.

Each admitted job gets its own ``_JobRunner``: a private LocalBackend over
the SHARED warm device whose MemoryManager budget is the job's memory
budget (runtime/spill.py enforces it by LRU spill — a budget-blowing job
degrades to disk instead of OOM-ing the process), while every stage
executable still dedups process-wide through exec/compilequeue's
content-addressed store — N isomorphic jobs cost ~1 compile set.
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

from ..core.errors import TuplexException
from ..utils.logging import get_logger

log = get_logger("tuplex_tpu.serve")


class JobRejected(TuplexException):
    """Admission refused (queue full past the admission timeout, memory
    budget above the service cap, unshippable wire request...). The
    message states the reason — rejection is part of the protocol, never
    a silent drop."""


class QueueFull(JobRejected):
    """The depth-bound admission queue had no slot within the allowed
    wait. Distinguished from terminal rejections because it is the one
    RETRYABLE kind — the wire loop polls with a zero wait and retries
    until the admission timeout instead of blocking its poll thread."""


class JobFailed(TuplexException):
    """Raised by ``JobHandle.result()`` when the job's execution failed."""


def transient_failure(exc: BaseException) -> bool:
    """Whether a job failure is worth RETRYING (the serve retry ladder's
    one classification decision). Transient = the run environment broke —
    a killed/deadlined compile, a device or dispatch runtime error, an
    injected transient fault, I/O flaking — so a fresh attempt on the
    same warm device can succeed. Deterministic = the job itself is wrong
    (user-code exceptions the resolvers didn't absorb, malformed
    requests, plan errors): retrying burns device time to fail
    identically, so it short-circuits with the clear error instead.

    Unknown exception types default to DETERMINISTIC: a retry loop that
    guesses "transient" on everything turns every poison job into
    retryCount poison jobs."""
    from ..exec.compilequeue import CompileTimeout
    from ..runtime.faults import FaultInjected

    if isinstance(exc, FaultInjected):
        return exc.transient
    if isinstance(exc, CompileTimeout):
        return True
    if isinstance(exc, (FileNotFoundError, PermissionError,
                        IsADirectoryError, NotADirectoryError)):
        return False            # bad paths/permissions recur identically
    if isinstance(exc, (ConnectionError, BrokenPipeError, TimeoutError,
                        OSError)):
        return True             # I/O flaking: a fresh attempt can win
    if isinstance(exc, TuplexException):
        return False            # framework-classified user/plan errors
    name = type(exc).__name__
    if name in ("XlaRuntimeError", "RuntimeError", "InternalError"):
        msg = str(exc)
        return any(p in msg for p in (
            "RESOURCE_EXHAUSTED", "DEADLINE", "UNAVAILABLE", "INTERNAL",
            "ABORTED", "device", "Device", "dispatch"))
    return False


#: job lifecycle states
QUEUED = "queued"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
REJECTED = "rejected"
CANCELLED = "cancelled"


@dataclass
class JobRequest:
    """One pipeline submission. ``stages`` entries are dicts with one of:

    * ``{"spec": <serialize_stage dict>, "files": [...] | None}`` — a
      transform stage over a file source (or a mid-pipeline stage:
      ``files`` None and no ``indir``);
    * ``{"spec": ..., "indir": path}`` — first stage whose memory input
      was staged to scratch as native-format parts;
    * ``{"live": <stage object>}`` — in-process only (joins/aggregates).
    """

    stages: list
    name: str = "job"
    tenant: str = "default"
    options: dict = field(default_factory=dict)   # per-job option overrides
    memory_budget: Optional[int] = None           # bytes; None -> service
                                                  # default (tuplex.serve.
                                                  # jobMemory)
    weight: Optional[int] = None                  # DRR weight; None -> the
                                                  # tenant's configured one
    collect: bool = True                          # materialize result rows

    def wire_safe(self) -> bool:
        """Whether every stage travels by spec (picklable wire form)."""
        return all("live" not in e for e in self.stages)


class JobHandle:
    """Caller-side view of a submitted job (the Lambda 'invocation id'
    analog). Thread-safe: state flips under the service condition, waits
    ride the same condition."""

    def __init__(self, record, service):
        self._rec = record
        self._svc = service

    # -- identity ----------------------------------------------------------
    @property
    def id(self) -> str:
        return self._rec.id

    @property
    def tenant(self) -> str:
        return self._rec.request.tenant

    @property
    def name(self) -> str:
        return self._rec.request.name

    # -- state -------------------------------------------------------------
    @property
    def state(self) -> str:
        return self._rec.state

    @property
    def error(self) -> Optional[str]:
        return self._rec.error

    @property
    def metrics(self):
        """Per-job api.Metrics — stage records land here, never on another
        tenant's object."""
        return self._rec.metrics

    @property
    def stats(self) -> dict:
        """Scheduler-side accounting: turns consumed, global turn at
        completion, queue wait seconds, and the job's memory footprint
        against its budget (its own MemoryManager — runtime/spill.py)."""
        out = dict(self._rec.stats)
        runner = self._rec.runner
        if runner is not None:
            mm = runner.backend.mm
            out["resident_bytes"] = mm.resident_bytes()
            out["budget_bytes"] = mm.budget
            out.update(mm.metrics())
        return out

    def counters(self) -> dict:
        """This job's scoped xferstats family (bumps made on its
        executing thread: d2h/h2d/spill plus inline-dispatch compile
        counters) — isolated from other tenants. Snapshotted onto the
        record at completion (the live registry entry is released so the
        service doesn't grow per job served)."""
        return self._rec._counters()

    def trace_events(self) -> list:
        """This job's span stream (runtime/tracing events recorded under
        its stream tag). Empty unless tracing is enabled."""
        from ..runtime import tracing

        return tracing.events_for_stream(self._rec.id)

    def exceptions(self) -> list:
        return list(self._rec.exceptions)

    def exc_profile(self) -> dict:
        """The TENANT's live exception-plane readout (runtime/excprof,
        scoped like the xferstats counter families): cumulative exception
        rate, resolve-tier mix, the EWMA-vs-baseline drift score and the
        respecialize recommendation. Tenant-wide by design — drift is a
        property of the tenant's traffic distribution, not of one job."""
        from ..runtime import excprof

        return excprof.scope_report(self._rec.request.tenant)

    def attempts(self) -> list:
        """The retry ladder's audit trail: one record per FAILED attempt
        ({attempt, error, transient, action, backoff_s, t}). Empty for a
        job that succeeded first try."""
        return [dict(a) for a in self._rec.attempts]

    def latency_budget(self) -> dict:
        """The job's latency-budget vector (runtime/critpath): its
        end-to-end wall attributed into the canonical exclusive buckets
        (admission/queue waits, compile split, h2d/device/d2h, resolve
        tiers, merge, scheduler/other) with an honest ``unattributed``
        remainder, plus the swept critical path. Empty until the job is
        terminal or when critpath is disabled."""
        return dict(self._rec.latency_budget or {})

    # -- completion --------------------------------------------------------
    def wait(self, timeout: Optional[float] = None) -> str:
        """Block until the job reaches a terminal state (or `timeout`
        elapses); returns the state either way."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._svc._cond:
            while self._rec.state in (QUEUED, RUNNING):
                left = None if deadline is None \
                    else deadline - time.monotonic()
                if left is not None and left <= 0:
                    break
                self._svc._cond.wait(0.2 if left is None
                                     else min(0.2, left))
        return self._rec.state

    def result(self, timeout: Optional[float] = None):
        """The job's output rows (``collect=True`` requests). Raises
        JobFailed on failure, TimeoutError if still running at
        `timeout`."""
        state = self.wait(timeout)
        if state in (QUEUED, RUNNING):
            raise TimeoutError(f"job {self.id} still {state}")
        if state != DONE:
            raise JobFailed(
                f"job {self.id} {state}: {self._rec.error or 'unknown'}")
        return self._rec.result_rows


class JobRecord:
    """Service-internal per-job state (the handle wraps it)."""

    def __init__(self, request: JobRequest, weight: int):
        from ..api.metrics import Metrics

        self.id = uuid.uuid4().hex[:12]
        self.request = request
        self.state = QUEUED
        self.error: Optional[str] = None
        self.metrics = Metrics()
        # this job's metrics report ITS scoped counter family, never the
        # process-global registry (no cross-tenant bleed in responses)
        self.metrics.counters_source = self._counters
        self.exceptions: list = []
        self.result_rows: Optional[list] = None
        self.runner: Optional[_JobRunner] = None
        self.final_counters: Optional[dict] = None
        self.latency_budget: Optional[dict] = None   # runtime/critpath
                                            # bucket vector, stamped at
                                            # the terminal turn
        self.weight = max(1, int(weight))
        self.burst = 0                      # consecutive steps this round
        self.attempt = 0                    # completed FAILED attempts
        self.attempts: list = []            # one dict per failed attempt
                                            # (error, transient verdict,
                                            # backoff, action) — the retry
                                            # ladder's audit trail
        self.stats: dict = {"turns": 0, "finished_turn": None,
                            "queued_s": None, "wall_s": None,
                            "attempts": 0}
        self.t_submit = time.perf_counter()
        self.t_start: Optional[float] = None
        self.t_enqueue: Optional[float] = None   # last ready-queue append
                                                 # (stage-queue-wait metric)

    def _counters(self) -> dict:
        """The job's scoped xferstats family — live while running, the
        completion snapshot afterwards (the registry entry is released at
        the terminal turn)."""
        if self.final_counters is not None:
            return dict(self.final_counters)
        from ..runtime import xferstats

        return xferstats.scoped(self.id)

    def reset_for_retry(self) -> None:
        """Clear the per-ATTEMPT result state before a retry replays the
        job from stage 0: stage metrics, exception rows and result rows
        belong to the aborted attempt — keeping them would double-count
        them into the final response (the attempts audit trail and the
        scoped counter family deliberately persist across attempts)."""
        from ..api.metrics import Metrics

        self.metrics = Metrics()
        self.metrics.counters_source = self._counters
        self.exceptions = []
        self.result_rows = None


class _RunnerCtx:
    """Duck-typed context for source loading + stage execution inside the
    service (the exec/worker.py _Ctx pattern): options_store + backend is
    all the executors read."""

    def __init__(self, options_store, backend):
        self.options_store = options_store
        self.backend = backend
        self.recorder = None


class _JobRunner:
    """Executes one job stage-at-a-time. ``step()`` is the scheduler's
    fairness unit: one stage dispatch onto the warm device per call, so a
    long job's stage list interleaves with other tenants instead of
    monopolizing the chip."""

    def __init__(self, record: JobRecord, service_options,
                 default_budget: int):
        from ..core.options import ContextOptions
        from ..exec.local import LocalBackend

        req = record.request
        opts = ContextOptions(service_options.to_dict())
        if req.options:
            opts.update(req.options)
        # jobs are leaves of the service: no nested fan-out, no UI
        opts.set("tuplex.backend", "local")
        opts.set("tuplex.webui.enable", False)
        budget = req.memory_budget if req.memory_budget else default_budget
        if budget and budget > 0:
            # the per-job memory budget IS the backend MemoryManager
            # budget: partitions past it spill via the runtime/spill.py
            # LRU evictor (degrade to disk, never OOM the shared process)
            opts.set("tuplex.executorMemory", int(budget))
        self.record = record
        self.options = opts
        self.backend = LocalBackend(opts)
        self.ctx = _RunnerCtx(opts, self.backend)
        self.entries = list(req.stages)
        self.stages = [self._rebuild(e) for e in self.entries]
        if not self.stages:
            raise TuplexException("job has no stages")
        # re-specialization hot-swap (serve/respec): the record carries
        # the plan generation PINNED AT ADMISSION — applied here, at
        # every rebuild (retries included), so one job never mixes plan
        # generations and a promotion mid-flight only affects jobs
        # admitted after the swap
        ctrl = getattr(record, "_respec_ctrl", None)
        if ctrl is not None:
            ctrl.overlay_job(self)
        self.si = 0
        self.partitions: Any = []

    # ------------------------------------------------------------------
    def _rebuild(self, entry: dict):
        if "live" in entry:
            return entry["live"]
        from ..exec.serverless import rebuild_stage

        return rebuild_stage(entry["spec"], self.options,
                             files=entry.get("files"))

    def _load_input(self, entry: dict, stage):
        from ..api.dataset import _source_partitions

        indir = entry.get("indir")
        if indir:
            from ..io.tuplexfmt import TuplexFileSourceOperator

            src = TuplexFileSourceOperator(self.options, indir)
            return src.load_partitions(self.ctx)
        if getattr(stage, "source", None) is not None:
            return _source_partitions(self.ctx, stage, lazy=False)
        return self.partitions      # mid-pipeline: previous stage's output

    # ------------------------------------------------------------------
    def step(self) -> bool:
        """Run ONE stage; returns True when the job is complete."""
        from ..plan.physical import consumer_kind

        stage = self.stages[self.si]
        entry = self.entries[self.si]
        ctrl = getattr(self.record, "_respec_ctrl", None)
        if self.si == 0 or entry.get("indir") \
                or getattr(stage, "source", None) is not None:
            self.partitions = self._load_input(entry, stage)
            if self.si == 0:
                # whole-plan AOT prewarm on the shared compile pool —
                # admission-to-first-dispatch overlaps the compiles
                pre = getattr(self.backend, "precompile_plan", None)
                if pre is not None:
                    try:
                        pre(self.stages, self.partitions)
                    except Exception:
                        pass
                if ctrl is not None:
                    # aval hint for background candidate compiles: the
                    # stage-0 dispatch shapes, a few ShapeDtypeStructs —
                    # never a partition reference (that would pin memory)
                    try:
                        from ..compiler import stagefn as SF

                        first = self.partitions[0] \
                            if isinstance(self.partitions, list) \
                            and self.partitions else None
                        if first is not None:
                            ctrl.note_input(
                                self.record.request.tenant,
                                SF.partition_avals(
                                    first, self.backend.bucket_mode),
                                first.schema)
                    except Exception:   # hint is best-effort
                        pass
        consumer = consumer_kind(self.stages, self.si)
        canary_inputs = self.partitions \
            if ctrl is not None \
            and getattr(self.record, "respec_canary", None) is not None \
            else None
        res = self.backend.execute_any(stage, self.partitions, self.ctx,
                                       intermediate=consumer)
        if canary_inputs is not None:
            # canary: shadow-execute the candidate generation on a
            # bounded fraction of the SAME inputs; the job's results
            # below stay 100% incumbent (never mixed across generations)
            ctrl.canary_stage(self, self.si, stage, canary_inputs, res)
        self.partitions = res.partitions
        self.record.metrics.record_stage(res.metrics)
        self.record.exceptions.extend(res.exceptions)
        self.si += 1
        return self.si >= len(self.stages)

    def finalize(self) -> None:
        rec = self.record
        if rec.request.collect:
            from ..runtime.columns import partition_to_pylist

            rows: list = []
            for p in self.partitions or []:
                self.backend.touch_partition(p)
                rows.extend(partition_to_pylist(p))
            rec.result_rows = rows
        else:
            rec.result_rows = []
        # drop the columnar partitions (and their spill files, via the
        # weakref finalizers): the record retains only the materialized
        # rows — terminal records live for the retention window and must
        # not pin a second copy of every job's output
        self.partitions = []

    def mm_metrics(self) -> dict:
        return self.backend.mm.metrics()

    def cleanup(self) -> None:
        """Remove the request's staged input parts (one-shot by contract;
        a long-lived service must not accumulate dead scratch). Best
        effort — the job's outcome is already decided."""
        cleanup_request_scratch(self.entries)


def cleanup_request_scratch(entries) -> None:
    """rmtree every staged 'indir' of a request's stage entries (requests
    are one-shot: once rejected or finished, the staged parts are dead)."""
    import shutil

    for entry in entries or []:
        indir = entry.get("indir") if isinstance(entry, dict) else None
        if indir:
            shutil.rmtree(indir, ignore_errors=True)


# ---------------------------------------------------------------------------
# request construction
# ---------------------------------------------------------------------------

def request_from_dataset(dataset, name: str = "job",
                         tenant: str = "default",
                         memory_budget: Optional[int] = None,
                         weight: Optional[int] = None,
                         options: Optional[dict] = None,
                         scratch_dir: Optional[str] = None) -> JobRequest:
    """Plan a DataSet's chain and package it as a JobRequest.

    Transform stages serialize via exec/serverless.serialize_stage; a
    memory-source first stage has its partitions staged to `scratch_dir`
    as native-format parts (the worker staged-parts protocol), so the
    request pickles whole. Join/aggregate stages (driver-tier in the
    serverless analog) ride live — in-process submissions only.
    """
    import os

    from ..exec.serverless import NotShippable, serialize_stage
    from ..plan import logical as L
    from ..plan.physical import TransformStage, plan_stages

    context = dataset._context
    stages = plan_stages(dataset._op, context.options_store)
    scratch = scratch_dir or os.path.join(
        context.options_store.get_str("tuplex.scratchDir",
                                      "/tmp/tuplex_tpu"),
        "serve", uuid.uuid4().hex[:12])
    entries: list = []
    for si, st in enumerate(stages):
        if not isinstance(st, TransformStage) \
                or getattr(st, "fold_op", None) is not None:
            # join/aggregate tiers and fused-fold stages ride live (the
            # spec doesn't carry a fold — same gate as the serverless
            # fan_out); in-process submissions only
            entries.append({"live": st})
            continue
        try:
            spec = serialize_stage(st)
        except NotShippable as e:
            log.info("stage %d not spec-serializable (%s); riding live",
                     si, e)
            entries.append({"live": st})
            continue
        src = st.source
        if src is None:
            entries.append({"spec": spec})
        elif spec["source"] is None:
            # memory / directory input: stage the partitions to scratch
            # (reference: uploads to the S3 scratch dir before invoking)
            if isinstance(src, L.ParallelizeOperator) \
                    or hasattr(src, "load_partitions"):
                from ..api.dataset import _source_partitions
                from ..io.tuplexfmt import write_partitions_tuplex

                parts = _source_partitions(context, st, lazy=False)
                indir = os.path.join(scratch, f"in-{si:03d}")
                write_partitions_tuplex(indir, list(parts),
                                        backend=context.backend)
                entries.append({"spec": spec, "indir": indir})
            else:
                entries.append({"live": st})
        else:
            files = list(getattr(src, "files", []) or []) or None
            entries.append({"spec": spec, "files": files})
    return JobRequest(stages=entries, name=name, tenant=tenant,
                      memory_budget=memory_budget, weight=weight,
                      options=dict(options or {}))
