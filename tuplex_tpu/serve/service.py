"""The long-lived job service: admission, fair scheduling, warm device.

One ``JobService`` owns the process's warm accelerator and runs forever,
absorbing pipeline submissions from many tenants (ROADMAP "Job-service
runtime"; the architectural successor of one-shot ``Context`` execution).
Three mechanisms carry the multi-tenant contract:

* **bounded admission with backpressure** — at most ``tuplex.serve.
  queueDepth`` jobs may be queued+running; a submit past that blocks up to
  ``tuplex.serve.admissionTimeoutS`` seconds, then rejects with a clear
  ``JobRejected`` (the caller can retry/shed; the service never builds an
  unbounded backlog). A memory budget above ``tuplex.serve.maxJobMemory``
  rejects immediately.
* **deficit-weighted round-robin scheduling** — the unit of dispatch is
  ONE STAGE of one job (``_JobRunner.step``). Each scheduler slot
  (``tuplex.serve.slots``, default 1 — one in-flight device dispatch per
  slot) pops the next ready job, runs one stage, and requeues it; a
  tenant with weight w gets w consecutive stage dispatches per cycle
  (``tuplex.serve.tenantWeights`` = "tenantA:2,tenantB:1"). A short job
  queued behind a long one therefore completes after O(its own stages)
  turns, never after the long job's full stage list.
* **shared compile plane, isolated everything else** — all jobs share the
  process-wide compile queue + content-addressed AOT artifact cache
  (exec/compilequeue): N isomorphic jobs cost ~1 compile set, joined
  in-flight when concurrent. Each job keeps its OWN LocalBackend whose
  MemoryManager budget is the job's memory budget (spill-degrade under
  pressure), its own api.Metrics, a tagged span stream
  (runtime/tracing.set_stream) and a scoped counter family
  (runtime/xferstats.set_scope) — nothing of one tenant's telemetry
  bleeds into another's.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from ..core.options import ContextOptions
from ..runtime import faults, telemetry
from ..utils.logging import get_logger
from .jobs import (CANCELLED, DONE, FAILED, QUEUED, RUNNING, JobHandle,
                   JobRecord, JobRejected, JobRequest, QueueFull,
                   _JobRunner, transient_failure)

log = get_logger("tuplex_tpu.serve")


def _parse_weights(s: str) -> dict:
    """"a:2,b:1" -> {"a": 2, "b": 1}; malformed entries are skipped."""
    out: dict = {}
    for part in (s or "").split(","):
        part = part.strip()
        if not part or ":" not in part:
            continue
        k, _, v = part.partition(":")
        try:
            out[k.strip()] = max(1, int(v))
        except ValueError:
            continue
    return out


class JobService:
    """See module docstring. ``autostart=False`` lets tests (and the CLI
    loop) admit a batch of jobs before the first scheduler turn — the
    fairness order is then deterministic from turn 0."""

    def __init__(self, options: Optional[ContextOptions] = None, *,
                 autostart: bool = True, recorder=None):
        self.options = options if options is not None else ContextOptions()
        o = self.options
        self.queue_depth = max(1, o.get_int("tuplex.serve.queueDepth", 64))
        self.admission_timeout_s = o.get_float(
            "tuplex.serve.admissionTimeoutS", 30.0)
        self.slots = max(1, o.get_int("tuplex.serve.slots", 1))
        self.default_budget = o.get_size("tuplex.serve.jobMemory", 256 << 20)
        self.max_job_memory = o.get_size("tuplex.serve.maxJobMemory", 0)
        self.tenant_weights = _parse_weights(
            o.get_str("tuplex.serve.tenantWeights", ""))
        self.retain_jobs = max(1, o.get_int("tuplex.serve.retainJobs", 256))
        # job-level retry ladder: transient failures (device/dispatch
        # runtime errors, compile deadlines — jobs.transient_failure)
        # requeue from stage 0 with exponential backoff; deterministic
        # failures short-circuit. The wire loop reuses retry_count as the
        # crash-requeue budget (serve/client journal recovery).
        self.retry_count = max(0, o.get_int("tuplex.serve.retryCount", 2))
        self.retry_backoff_s = max(0.0, o.get_float(
            "tuplex.serve.retryBackoffS", 0.5))
        self._delayed: list = []          # (due_monotonic, JobRecord)
        self.recorder = recorder          # history.JobRecorder (optional)
        self._cond = threading.Condition()
        self._ready: deque = deque()      # runnable JobRecords (DRR order)
        self._records: dict = {}          # id -> JobRecord (bounded: the
                                          # newest retain_jobs TERMINAL
                                          # records; live jobs always kept)
        self._terminal: deque = deque()   # terminal ids, oldest first
        self._open = 0                    # queued + running jobs
        self._turn = 0                    # global stage-dispatch counter
        self._stop = False
        self._threads: list = []
        self._started = False
        self._busy = 0                    # slots currently inside a turn
        # monotonic stamp of the last QueueFull rejection; -inf = never
        # (0.0 would read as "recent" on a freshly booted clock)
        self._last_reject_t = float("-inf")
        self._last_turn_done_t = time.monotonic()
        telemetry.apply_options(o)
        from ..runtime import devprof, excprof

        devprof.apply_options(o)   # serve CLI builds options Context-less
        excprof.apply_options(o)   # exception-plane drift knobs + health
        from ..runtime import critpath

        critpath.apply_options(o)  # latency-budget plane: SLOs, burn-rate
        # health, per-tenant baseline budget vectors (tuplex.serve.sloMs /
        # tenantSlos / sloBurnWindowS / sloTarget + tuplex.tpu.critpath*)
        from ..compiler import graphlint

        graphlint.apply_options(o)   # pre-submission jaxpr vetting
        self._register_telemetry(o)
        # closed-loop self-healing (serve/respec): watch each tenant's
        # drift signal, re-speculate in the background, canary, hot-swap
        self.respec = None
        if o.get_bool("tuplex.serve.respec", True) and excprof.enabled():
            from .respec import RespecController

            self.respec = RespecController(self, o)
        if autostart:
            self.start()

    # ------------------------------------------------------------------
    def _register_telemetry(self, o) -> None:
        """Sampled gauges + health checks for the always-on serve-layer
        telemetry (runtime/telemetry). Everything is owner-scoped to this
        service so close() drops the callbacks; reads are lock-free
        single-attribute loads (a scrape must never contend with the
        scheduler)."""
        if not telemetry.enabled():
            return
        self._health_saturation = o.get_float(
            "tuplex.serve.healthSaturation", 0.9)
        self._health_wedged_s = o.get_float(
            "tuplex.serve.healthWedgedCompileS", 300.0)
        self._health_starvation_s = o.get_float(
            "tuplex.serve.healthStarvationS", 120.0)
        g = telemetry.set_gauge
        g("serve_queue_ready_jobs", lambda: len(self._ready), owner=self)
        g("serve_open_jobs", lambda: self._open, owner=self)
        g("serve_queue_depth_limit", self.queue_depth, owner=self)
        g("serve_slots", self.slots, owner=self)
        g("serve_slots_busy", lambda: self._busy, owner=self)
        g("serve_admission_saturation",
          lambda: self._open / self.queue_depth, owner=self)
        g("serve_resident_bytes", self._resident_bytes, owner=self)
        g("serve_turns", lambda: self._turn, owner=self)
        g("serve_retry_backlog", lambda: len(self._delayed), owner=self)
        telemetry.register_health_check(
            "serve_admission", self._check_admission, owner=self)
        telemetry.register_health_check(
            "serve_slots", self._check_slots, owner=self)
        telemetry.register_health_check(
            "compile_watchdog", self._check_compile, owner=self)

    def note_rejection(self) -> None:
        """Account one CLIENT-VISIBLE admission rejection (the unhealthy
        health signal + the serve_rejected_jobs counter). Called for
        timed-out blocking submits and by the wire loop when a polled
        request exhausts the admission window — never for its zero-wait
        probes."""
        self._last_reject_t = time.monotonic()
        from ..runtime import xferstats

        xferstats.bump("serve_rejected_jobs", 1, tag="queue_full")

    def _resident_bytes(self) -> int:
        """Summed MemoryManager footprint of the live jobs (each job's
        private backend; terminal records dropped their runner output)."""
        total = 0
        with self._cond:
            recs = [r for r in self._records.values()
                    if r.state in (QUEUED, RUNNING)]
        for r in recs:
            runner = r.runner
            if runner is not None:
                try:
                    total += runner.backend.mm.resident_bytes()
                except Exception:
                    pass
        return total

    # -- health checks (runtime/telemetry state machine inputs) ----------
    def _check_admission(self):
        sat = self._open / self.queue_depth
        if sat >= 1.0 \
                and time.monotonic() - self._last_reject_t < 60.0:
            return (telemetry.UNHEALTHY,
                    f"admission queue full ({self._open}/"
                    f"{self.queue_depth}) and rejecting submissions")
        if sat >= self._health_saturation:
            return (telemetry.DEGRADED,
                    f"admission queue at {sat:.0%} "
                    f"({self._open}/{self.queue_depth})")
        return (telemetry.OK, None)

    def _check_slots(self):
        """Slot starvation: runnable jobs are waiting but no scheduler
        turn has completed for a while — every slot is stuck inside one
        dispatch (a wedged compile, a pathological stage)."""
        if not self._ready or not self._started:
            return (telemetry.OK, None)
        stalled = time.monotonic() - self._last_turn_done_t
        if self._busy >= self.slots and stalled > self._health_starvation_s:
            state = telemetry.UNHEALTHY \
                if stalled > 4 * self._health_starvation_s \
                else telemetry.DEGRADED
            return (state,
                    f"{len(self._ready)} ready job(s), all {self.slots} "
                    f"slot(s) busy, no turn finished in {stalled:.0f}s")
        return (telemetry.OK, None)

    def _check_compile(self):
        from ..exec import compilequeue as CQ

        age = CQ.pending_info()["inflight_oldest_age_seconds"]
        if age > 3 * self._health_wedged_s:
            return (telemetry.UNHEALTHY,
                    f"oldest in-flight compile {age:.0f}s old")
        if age > self._health_wedged_s:
            return (telemetry.DEGRADED,
                    f"oldest in-flight compile {age:.0f}s old "
                    f"(wedged-compile watchdog)")
        return (telemetry.OK, None)

    # ------------------------------------------------------------------
    def start(self) -> None:
        with self._cond:
            if self._started or self._stop:
                return
            self._started = True
            for i in range(self.slots):
                t = threading.Thread(target=self._worker, daemon=True,
                                     name=f"tpx-serve-{i}")
                t.start()
                self._threads.append(t)

    def close(self, timeout: float = 5.0) -> None:
        """Stop the scheduler. Unfinished jobs flip to CANCELLED so no
        waiter blocks forever."""
        with self._cond:
            self._stop = True
            cancelled = []
            for rec in self._records.values():
                if rec.state in (QUEUED, RUNNING):
                    rec.state = CANCELLED
                    rec.error = "service closed"
                    cancelled.append(rec)
            self._ready.clear()
            self._delayed.clear()    # backoff waiters die with the service
            self._open = 0
            self._cond.notify_all()
        telemetry.drop_owner(self)   # gauges/checks close over this object
        if self.respec is not None:
            self.respec.stop()
        for t in self._threads:
            t.join(timeout=timeout)
        # a worker outliving its join timeout may still be mid-step: in
        # that case only QUEUED jobs' scratch is safe to sweep — a
        # running job's staged input must not be rmtree'd under its
        # final step (the dangling daemon thread dies with the process)
        workers_alive = any(t.is_alive() for t in self._threads)
        self._threads = []
        from ..runtime import xferstats

        for rec in cancelled:
            if not workers_alive or rec.t_start is None:
                try:
                    rec.runner.cleanup()
                except Exception:
                    pass
            # cancelled jobs never reach the terminal turn: release their
            # scoped counter families here
            if rec.final_counters is None:
                rec.final_counters = xferstats.drop_scope(rec.id)

    # ------------------------------------------------------------------
    def submit(self, request: JobRequest, *,
               timeout: Optional[float] = None,
               cleanup_on_reject: bool = True) -> JobHandle:
        """Admit one job. Blocks while the queue is at depth (up to the
        admission timeout), then rejects — backpressure, not backlog.
        `timeout` overrides tuplex.serve.admissionTimeoutS (the wire loop
        passes 0 and retries so its poll thread never blocks);
        `cleanup_on_reject=False` leaves the request's staged scratch for
        the caller to release once it gives up retrying."""
        from .jobs import cleanup_request_scratch

        def _reject(exc):
            if cleanup_on_reject:
                cleanup_request_scratch(request.stages)
            raise exc

        if self.max_job_memory > 0 and request.memory_budget \
                and request.memory_budget > self.max_job_memory:
            _reject(JobRejected(
                f"job memory budget {request.memory_budget} exceeds "
                f"tuplex.serve.maxJobMemory={self.max_job_memory}; "
                f"lower the budget or raise the service cap"))
        weight = request.weight if request.weight \
            else self.tenant_weights.get(request.tenant, 1)
        rec = JobRecord(request, weight)
        if self.respec is not None:
            # pin the tenant's ACTIVE plan generation before the runner
            # builds: a promotion that lands mid-admission (or between
            # retries) must not change THIS job's generation
            self.respec.pin(rec)
        wait_s = self.admission_timeout_s if timeout is None else timeout
        t_admit0 = time.monotonic()
        deadline = t_admit0 + max(0.0, wait_s)
        # shed load BEFORE paying for the job: wait for a queue slot
        # first, build the runner (outside the lock — spec rebuild is
        # pure, and a bad request must fail the submitter, not the
        # scheduler), then take the slot — looping if it was snatched
        # while we built. Overload rejections therefore cost nothing but
        # the wait; a rejected job never reaches _run_turn, so its staged
        # scratch is released here.
        while True:
            with self._cond:
                while not self._stop \
                        and self._open >= self.queue_depth:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        # zero-wait probes (the wire loop polls with
                        # timeout=0 and does its OWN rejection accounting
                        # after the full admission window) must not read
                        # as ~10 rejections/second per waiting client
                        if wait_s > 0:
                            self.note_rejection()
                        _reject(QueueFull(
                            f"admission queue full ({self._open}/"
                            f"{self.queue_depth} jobs) — timed out "
                            f"after {wait_s:.0f}s "
                            f"(tuplex.serve.admissionTimeoutS)"))
                    self._cond.wait(min(0.1, left))
                if self._stop:
                    _reject(JobRejected("service is closed"))
                if rec.runner is not None:
                    self._open += 1
                    self._records[rec.id] = rec
                    rec.t_enqueue = time.perf_counter()
                    self._ready.append(rec)
                    self._cond.notify_all()
                    break
            try:
                rec.runner = _JobRunner(rec, self.options,
                                        self.default_budget)
            except Exception as e:
                if cleanup_on_reject:
                    cleanup_request_scratch(request.stages)
                raise JobRejected(
                    f"job rejected at admission: "
                    f"{type(e).__name__}: {e}") from e
        if self.respec is not None:
            # post-admission: remember the wire-safe request (the
            # respeculation substrate) and claim the canary if a
            # validated candidate is waiting for this tenant
            self.respec.note_admitted(rec)
        telemetry.observe("serve_admission_wait_seconds",
                          time.monotonic() - t_admit0,
                          tenant=request.tenant)
        self._record_event(rec, "job_start",
                           action=f"serve:{request.name}",
                           tenant=request.tenant,
                           stages=[type(s).__name__
                                   for s in rec.runner.stages])
        log.info("admitted job %s (%s/%s): %d stage(s), weight %d",
                 rec.id, request.tenant, request.name,
                 len(rec.runner.stages), rec.weight)
        return JobHandle(rec, self)

    # convenience: plan + submit a DataSet in one call
    def submit_dataset(self, dataset, **kw) -> JobHandle:
        from .jobs import request_from_dataset

        return self.submit(request_from_dataset(dataset, **kw))

    # ------------------------------------------------------------------
    def jobs(self) -> list:
        with self._cond:
            return [JobHandle(r, self) for r in self._records.values()]

    def stats(self) -> dict:
        with self._cond:
            states: dict = {}
            for r in self._records.values():
                states[r.state] = states.get(r.state, 0) + 1
            return {"jobs": len(self._records), "open": self._open,
                    "turns": self._turn, "states": states,
                    "queue_depth": self.queue_depth, "slots": self.slots}

    # ------------------------------------------------------------------
    def _record_event(self, rec: JobRecord, event: str, **fields) -> None:
        r = self.recorder
        if r is None or not getattr(r, "enabled", False):
            return
        try:
            r.serve_job_event(rec.id, event, **fields)
        except Exception:   # dashboard rows are advisory
            pass

    # ------------------------------------------------------------------
    def _worker(self) -> None:
        while True:
            with self._cond:
                while not self._stop:
                    # promote retry-backoff waiters whose delay elapsed
                    # (the 0.2s condition poll bounds the promotion lag)
                    if self._delayed:
                        now = time.monotonic()
                        due = [x for x in self._delayed if x[0] <= now]
                        for x in due:
                            self._delayed.remove(x)
                            self._ready.append(x[1])
                    if self._ready:
                        break
                    self._cond.wait(0.2)
                if self._stop:
                    return
                rec = self._ready.popleft()
                self._busy += 1
                if rec.state == QUEUED:
                    rec.state = RUNNING
                    rec.t_start = time.perf_counter()
                    rec.stats["queued_s"] = rec.t_start - rec.t_submit
            if rec.t_enqueue is not None:
                qw = time.perf_counter() - rec.t_enqueue
                telemetry.observe("serve_stage_queue_wait_seconds", qw,
                                  tenant=rec.request.tenant)
                # cumulative stage-queue wait ALSO rides the record: the
                # latency-budget plane (runtime/critpath) attributes it
                # as the queue_wait bucket — span gaps alone cannot tell
                # a DRR requeue from an unattributed stall
                if rec.t_start is not None \
                        and rec.t_enqueue > rec.t_start:
                    rec.stats["stage_queue_s"] = \
                        rec.stats.get("stage_queue_s", 0.0) + qw
            self._run_turn(rec)

    def _note_attempt(self, rec: JobRecord, err: BaseException) -> bool:
        """Record one failed attempt on the job's audit trail (and its
        tenant span stream — the caller still has the stream set) and
        decide whether the retry ladder takes it: transient failures
        retry up to tuplex.serve.retryCount with exponential backoff,
        deterministic ones short-circuit with the clear error."""
        from ..runtime import tracing

        transient = False
        try:
            transient = transient_failure(err)
        except Exception:       # classifier must never mask the failure
            pass
        will_retry = transient and rec.attempt < self.retry_count \
            and not self._stop
        backoff = self.retry_backoff_s * (2 ** rec.attempt) \
            if will_retry else 0.0
        entry = {"attempt": rec.attempt + 1,
                 "error": f"{type(err).__name__}: {err}",
                 "transient": transient,
                 "action": "retry" if will_retry else "fail",
                 "backoff_s": round(backoff, 3),
                 "t": time.time()}
        rec.attempts.append(entry)
        rec.stats["attempts"] = len(rec.attempts)
        tracing.instant("serve:attempt-failed", "serve", {
            "attempt": entry["attempt"], "transient": transient,
            "action": entry["action"], "error": entry["error"][:120]})
        self._record_event(rec, "job_retry" if will_retry else "job_fail",
                           attempt=entry["attempt"],
                           transient=transient,
                           backoff_s=entry["backoff_s"],
                           tenant=rec.request.tenant,
                           error=entry["error"])
        return will_retry

    def _run_turn(self, rec: JobRecord) -> None:
        """One scheduler turn: one stage dispatch of `rec`, telemetry
        scoped to the job, then DRR requeue / completion under the lock.
        A failed turn consults the retry ladder BEFORE going terminal:
        transient failures requeue the job from stage 0 after its
        exponential backoff (the slot frees immediately — backoff never
        blocks a worker)."""
        from ..runtime import critpath, excprof, tracing, xferstats

        done = False
        err: Optional[BaseException] = None
        retrying = False
        tracing.set_stream(rec.id)
        xferstats.set_scope(rec.id)
        # exception-plane scope is the TENANT, not the job: drift is a
        # property of a tenant's traffic distribution across jobs
        excprof.set_scope(rec.request.tenant)
        t_disp0 = time.perf_counter()
        try:
            faults.maybe("serve", point="step")   # chaos checkpoint: an
            # injected raise classifies exactly like a real step failure
            done = rec.runner.step()
            if done:
                rec.runner.finalize()
        except BaseException as e:   # noqa: BLE001 - job dies, service lives
            err = e
            retrying = self._note_attempt(rec, e)
        finally:
            tracing.set_stream(None)
            xferstats.set_scope(None)
            excprof.set_scope(None)
        now = time.perf_counter()
        telemetry.observe("serve_dispatch_seconds", now - t_disp0,
                          tenant=rec.request.tenant)
        wall = now - (rec.t_start or rec.t_submit)
        if retrying:
            rec.attempt += 1
            backoff = rec.attempts[-1]["backoff_s"]
            xferstats.bump("serve_job_retries", 1, tag=rec.request.tenant)
            log.warning("job %s attempt %d failed (%s); retrying in %.2gs",
                        rec.id, rec.attempt, rec.attempts[-1]["error"],
                        backoff)
            try:
                # fresh runner: the retry replays the job from stage 0
                # over the ORIGINAL request (its staged scratch is only
                # cleaned at the true terminal turn); the aborted
                # attempt's metrics/exceptions/rows are dropped so the
                # final response never double-counts them
                rec.reset_for_retry()
                rec.runner = _JobRunner(rec, self.options,
                                        self.default_budget)
            except Exception as e2:   # rebuild failed: terminal after all
                retrying = False
                err = e2
                rec.attempts[-1]["action"] = "fail"
        if retrying:
            with self._cond:
                self._turn += 1
                self._busy -= 1
                self._last_turn_done_t = time.monotonic()
                rec.stats["turns"] += 1
                if rec.state == CANCELLED or self._stop:
                    # close() raced the retry: keep the CANCELLED verdict
                    if rec.final_counters is None:
                        rec.final_counters = xferstats.drop_scope(rec.id)
                    self._cond.notify_all()
                    return
                # the slot frees NOW; the job re-enters the ready queue
                # once its backoff elapses (worker-loop promotion)
                self._delayed.append((time.monotonic() + backoff, rec))
                self._cond.notify_all()
            return
        if err is not None or done:
            if self.respec is not None:
                # job boundary = canary verdict boundary: promote or
                # quarantine the candidate this job carried (no-op for
                # non-canary jobs)
                try:
                    self.respec.finish_job(rec, ok=(done
                                                    and err is None))
                except Exception:   # controller must never fail a job
                    log.exception("respec finish_job failed")
            try:
                rec.runner.cleanup()
            except Exception:
                pass
            # the end-to-end latency the p99 harness measures: admission
            # to terminal, queue waits included (never just device time)
            telemetry.observe("serve_job_latency_seconds",
                              now - rec.t_submit,
                              tenant=rec.request.tenant)
            xferstats.bump("serve_jobs_finished", 1,
                           tag="failed" if err is not None else "done")
            # embed the job's tenant-tagged span stream into the history
            # file so `python -m tuplex_tpu trace` replays serve jobs too
            # (before the state flip: a waiter that sees DONE must find
            # the rows already written)
            evts = tracing.events_for_stream(rec.id) \
                if tracing.enabled() else []
            if evts:
                r = self.recorder
                if r is not None and getattr(r, "enabled", False):
                    try:
                        r.serve_job_spans(rec.id, evts,
                                          tenant=rec.request.tenant)
                    except Exception:   # dashboard rows are advisory
                        pass
            # latency-budget plane (runtime/critpath): sweep the job's
            # span stream into the canonical exclusive bucket vector,
            # fold the tenant's EWMA baseline + SLO burn windows, and
            # surface the blame verdict — whyslow, the dashboard budget
            # panel and the serve:slow-job instant all read THIS record
            if critpath.enabled():
                try:
                    budget = critpath.analyze_events(
                        evts,
                        wall_s=now - rec.t_submit,
                        queued_s=float(rec.stats.get("queued_s") or 0.0),
                        stage_queue_s=float(
                            rec.stats.get("stage_queue_s") or 0.0),
                        t0_us=tracing.to_trace_us(rec.t_start)
                        if rec.t_start is not None and evts else None,
                        t1_us=tracing.to_trace_us(now) if evts else None)
                    verdict = critpath.record_job(
                        rec.request.tenant, rec.id, budget,
                        failed=err is not None)
                    rec.latency_budget = budget
                    if budget is not None:
                        if verdict.get("slow"):
                            tracing.instant("serve:slow-job", "serve", {
                                "job": rec.id,
                                "tenant": rec.request.tenant,
                                "wall_ms": round(
                                    budget["wall_s"] * 1e3, 1),
                                "baseline_ms": round(
                                    (verdict.get("baseline_wall_s")
                                     or 0.0) * 1e3, 1),
                                "blame": verdict.get("blame"),
                                "delta_ms": round(
                                    verdict.get("delta_s", 0.0) * 1e3,
                                    1)})
                        self._record_event(
                            rec, "critpath", tenant=rec.request.tenant,
                            wall_s=budget["wall_s"],
                            dominant=budget["dominant"],
                            unattributed_frac=budget[
                                "unattributed_frac"],
                            coverage_frac=budget["coverage_frac"],
                            degraded=budget["degraded"],
                            buckets=budget["buckets"],
                            path=budget["path"][:32],
                            slow=bool(verdict.get("slow")),
                            blame=verdict.get("blame"),
                            slo_ms=verdict.get("slo_ms"),
                            slo_ok=verdict.get("slo_ok"),
                            baseline=critpath.tenant_report(
                                rec.request.tenant)["baseline"])
                except Exception:   # budget rows are advisory
                    pass
            # snapshot the job's scoped counter family onto the record and
            # release the registry entry (a service that lives for
            # thousands of jobs must not keep one family per job)
            rec.final_counters = xferstats.drop_scope(rec.id)
            # exception-plane row for the dashboard drift panel: the
            # tenant's cumulative exception rate, resolve-tier mix and
            # the drift/respecialize readout at this job's terminal turn
            if excprof.enabled():
                try:
                    exr = excprof.scope_report(rec.request.tenant)
                    if self.respec is not None:
                        # the "respecialize recommended" badge becomes a
                        # lifecycle: the tenant's generation + candidate
                        # state ride the drift panel row
                        rr = self.respec.tenant_report(rec.request.tenant)
                        exr["respec_generation"] = rr["generation"]
                        exr["respec_state"] = rr["state"]
                        exr["respec_promotions"] = rr["promotions"]
                        exr["respec_quarantines"] = rr["quarantines"]
                    self._record_event(
                        rec, "excprof", tenant=rec.request.tenant,
                        **{k: v for k, v in exr.items()
                           if isinstance(v, (int, float, str, dict))})
                except Exception:   # dashboard rows are advisory
                    pass
        # history rows land BEFORE the state flip wakes any waiter: a
        # client that sees DONE must find the job_done row already written
        if err is not None:
            rec.error = f"{type(err).__name__}: {err}"
            self._record_event(rec, "job_done", rows=0,
                               wall_s=round(wall, 4),
                               tenant=rec.request.tenant,
                               exception_counts={},
                               error=rec.error)
            log.warning("job %s failed: %s", rec.id, rec.error)
        elif done:
            counts: dict = {}
            for e in rec.exceptions:
                counts[e.exc_name] = counts.get(e.exc_name, 0) + 1
            self._record_event(
                rec, "stage", no=len(rec.metrics.stages),
                kind="serve", metrics={
                    k: v for k, v in rec.metrics.as_dict().items()
                    if isinstance(v, (int, float))})
            self._record_event(rec, "job_done",
                               rows=len(rec.result_rows or []),
                               wall_s=round(wall, 4),
                               tenant=rec.request.tenant,
                               exception_counts=counts)
            log.info("job %s done: %d rows, %d turn(s), %.3fs",
                     rec.id, len(rec.result_rows or []),
                     rec.stats["turns"] + 1, wall)
        retired_tenants: set = set()
        with self._cond:
            self._turn += 1
            self._busy -= 1
            self._last_turn_done_t = time.monotonic()
            rec.stats["turns"] += 1
            if rec.state == CANCELLED or self._stop:
                # close() raced this turn: the job was already flipped to
                # CANCELLED (and _open zeroed) — a waiter may have seen
                # that state, so never overwrite it or touch the
                # admission counters; just release the job's scope
                if rec.final_counters is None:
                    rec.final_counters = xferstats.drop_scope(rec.id)
                self._cond.notify_all()
                return
            if err is not None or done:
                rec.state = FAILED if err is not None else DONE
                rec.stats["finished_turn"] = self._turn
                rec.stats["wall_s"] = wall
                self._open -= 1
                # bounded retention: the service index keeps only the
                # newest retain_jobs terminal records (and their
                # materialized result rows) — a caller-held JobHandle
                # keeps its own record alive regardless; only the
                # service-wide pin is released
                self._terminal.append(rec.id)
                evicted: set = set()
                while len(self._terminal) > self.retain_jobs:
                    old = self._records.pop(self._terminal.popleft(),
                                            None)
                    if old is not None:
                        evicted.add(old.request.tenant)
                if evicted:
                    live = {r.request.tenant
                            for r in self._records.values()}
                    retired_tenants = evicted - live
            else:
                # deficit-weighted RR: a tenant with weight w keeps the
                # slot for w consecutive stage dispatches, then yields
                rec.burst += 1
                rec.t_enqueue = time.perf_counter()
                if rec.burst < rec.weight:
                    self._ready.appendleft(rec)
                else:
                    rec.burst = 0
                    self._ready.append(rec)
            self._cond.notify_all()
        if retired_tenants:
            # tenant retirement: the service no longer holds ANY record
            # for these tenants — release their per-tenant exception-
            # plane drift windows (runtime/excprof grows one window per
            # scope forever otherwise: the long-lived-serve state leak
            # under a churning tenant population) and the respec
            # controller state (quarantine markers persist on disk)
            for t in retired_tenants:
                excprof.drop_scope(t)
                critpath.drop_tenant(t)
                if self.respec is not None:
                    self.respec.note_tenant_retired(t)
