"""Central jax import + config. Import jax ONLY through here inside the
framework so x64 is enabled before any trace happens.

Python ints are i64 in the reference's type system (TypeSystem.h); on TPU
i64 is emulated but the hot arithmetic is mostly i32-safe — the emitter
narrows where value ranges allow (future work, tuplex.tpu.* options).
"""

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

__all__ = ["jax", "jnp", "lax"]
