"""Central jax import + config. Import jax ONLY through here inside the
framework so x64 is enabled before any trace happens.

Python ints are i64 in the reference's type system (TypeSystem.h); on TPU
i64 is emulated but the hot arithmetic is mostly i32-safe — the emitter
narrows where value ranges allow (future work, tuplex.tpu.* options).
"""

import os

import jax

jax.config.update("jax_enable_x64", True)

# Persistent XLA compile cache: the fused-stage executables are expensive to
# build on the TPU service (~6 min for the 7.3k-op Zillow stage via the
# tunnel) but perfectly cacheable — identical HLO hits the on-disk cache in
# milliseconds across processes. Reference analog: LLVMOptimizer caches per
# (stage, schema) in-process only; on TPU the compile is remote so a disk
# cache is the right redesign.
def _host_tag() -> str:
    """Cache-partition tag for this host's CPU. XLA:CPU AOT results encode
    target machine features; loading artifacts compiled on a different
    machine warns about SIGILL risk (observed with a shared cache dir:
    +prefer-no-scatter/+avx512* mismatches). TPU artifacts are host-neutral
    but live happily in the per-host partition too."""
    import hashlib
    import platform

    tag = platform.machine()
    try:
        with open("/proc/cpuinfo") as fp:
            for line in fp:
                if line.startswith("flags"):
                    tag += hashlib.sha256(line.encode()).hexdigest()[:8]
                    break
    except OSError:
        pass
    # axon sessions remote-compile EVERYTHING (PALLAS_AXON_REMOTE_COMPILE),
    # including XLA:CPU executables built on the service machine's ISA
    # (+prefer-no-scatter/+avx512* artifacts observed) — those must never
    # land in the cache partition that plain local-CPU sessions load from
    # (SIGILL risk, seen round 4). Keyed on the EFFECTIVE platform list:
    # CPU-forced processes (tests, bench cpu child) set jax_platforms="cpu"
    # before importing the framework and compile locally.
    try:
        platforms = jax.config.jax_platforms or ""
    except AttributeError:
        platforms = os.environ.get("JAX_PLATFORMS", "")
    if "axon" in platforms:
        tag += "_axon"
    return tag


_cache_dir = os.environ.get(
    "TUPLEX_COMPILE_CACHE",
    os.path.join(os.path.expanduser("~"), ".cache",
                 f"jax_comp_cache_{_host_tag()}"))
if _cache_dir and _cache_dir != "0":
    try:
        os.makedirs(_cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # pragma: no cover - cache is best-effort
        pass

import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

__all__ = ["jax", "jnp", "lax", "fusion_barriers_enabled"]


def fusion_barriers_enabled() -> bool:
    """Whether stage traces insert lax.optimization_barrier between operators
    / statements / error-lattice updates.

    XLA-CPU's producer fusion inlines whole UDF bodies into one kLoop fusion
    that RECOMPUTES [B, W] string intermediates per output element (measured
    24x on Zillow extractPrice), so barriers are load-bearing there. XLA-TPU
    fuses loop nests without that pathology — and the barriers sent the
    TPU-tunnel compile from ~6 min to >15 min wedged — so they default off
    everywhere except CPU. Override: TUPLEX_FUSION_BARRIERS=0/1."""
    import os

    mode = os.environ.get("TUPLEX_FUSION_BARRIERS", "auto")
    if mode in ("0", "1"):
        return mode == "1"
    return jax.default_backend() == "cpu"


def device_handoff_enabled(consumer: str = "stage") -> bool:
    """Whether intermediate stage outputs keep a device-resident gathered
    view for downstream re-staging (skips host pad/copy + H2D — the analog
    of the reference passing hash intermediates by pointer as stage
    globals, LocalBackend.cc:903-908). Default: off on CPU (host staging IS
    device memory there; the extra device gather would be pure overhead),
    on everywhere else.

    `consumer` names WHO drains the view — "stage" (a downstream
    TransformStage re-stages it), "join" (the probe side of a JoinStage
    gathers from it), or "agg" (an AggregateStage evaluates fold exprs over
    it). Round 5 gated joins and aggregates off entirely, which is exactly
    the boundary that made q19/flights/nyc311 round-trip per stage; the
    per-consumer knobs exist so a regressing consumer can be switched off
    without losing the others. TUPLEX_DEVICE_HANDOFF=0/1 overrides all
    consumers (tests force it on under the CPU platform);
    TUPLEX_DEVICE_HANDOFF_STAGE / _JOIN / _AGG=0/1 override one."""
    import os

    per = os.environ.get(f"TUPLEX_DEVICE_HANDOFF_{consumer.upper()}")
    if per in ("0", "1"):
        return per == "1"
    mode = os.environ.get("TUPLEX_DEVICE_HANDOFF", "auto")
    if mode in ("0", "1"):
        return mode == "1"
    return jax.default_backend() != "cpu"


def varlen_wire_enabled() -> bool:
    """Whether packed stage outputs ship str leaves as a varlen segment
    (per-row lengths + contiguous payload of ACTUAL bytes) instead of the
    zero-padded [B, W] matrices. The padded matrices are ~170 B/row on
    zillow against ~30 B of real content, and the D2H tunnel runs at
    ~50 MB/s — shipping content-sized payloads is the same offsets+payload
    layout the reference serializer uses on disk (Serializer.h:104-138)
    applied to the transfer wire. Only meaningful where packing is active
    (the varlen segment rides PackedOuts). TUPLEX_VARLEN_WIRE=0/1
    overrides; default on."""
    import os

    mode = os.environ.get("TUPLEX_VARLEN_WIRE", "auto")
    if mode in ("0", "1"):
        return mode == "1"
    return True


def device_handoff_budget_bytes() -> int:
    """Cap on device memory pinned by handoff views per stage. Views are
    one-shot (released at consumption), but ALL of a stage's outputs hold
    views until the next stage drains them — without a cap a large
    intermediate dataset would pin O(dataset) HBM. Default: 25% of the
    device's reported bytes_limit, else 1 GiB. TUPLEX_DEVICE_HANDOFF_MB
    overrides."""
    import os

    mb = os.environ.get("TUPLEX_DEVICE_HANDOFF_MB")
    if mb is not None:
        try:
            return int(float(mb) * (1 << 20))
        except ValueError:
            pass
    try:
        stats = jax.devices()[0].memory_stats() or {}
        limit = int(stats.get("bytes_limit", 0))
        if limit > 0:
            return limit // 4
    except Exception:
        pass
    return 1 << 30


def stmt_barriers_enabled() -> bool:
    """Statement-level barriers inside UDF bodies (finer than the per-
    operator barriers in the stage loop). Separately switchable so the
    granularity tradeoff (materialized bandwidth vs recompute) can be
    tuned per platform. TUPLEX_STMT_BARRIERS=0/1 overrides."""
    import os

    mode = os.environ.get("TUPLEX_STMT_BARRIERS", "auto")
    if mode in ("0", "1"):
        return mode == "1"
    return fusion_barriers_enabled()


def shard_map_compat(fn, mesh, in_specs, out_specs, check: bool = False):
    """``jax.shard_map`` across jax versions: the top-level export (and its
    ``check_vma`` kwarg) only exists on newer jax; older releases ship it
    as ``jax.experimental.shard_map`` with ``check_rep``. Import jax's
    shard_map ONLY through here (same rule as the jax import itself)."""
    try:
        from jax import shard_map as _sm  # jax >= 0.6

        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_vma=check)
    except ImportError:
        from jax.experimental.shard_map import shard_map as _sm

        return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=check)


def aot_cache_enabled() -> bool:
    """Content-addressed AOT executable reuse (exec/compilequeue.py): stage
    executables serialize to disk keyed on (canonical jaxpr fingerprint,
    platform/ISA, avals, donation/packing flags, mesh epoch) so a second
    process re-running the same pipeline deserializes instead of compiling.
    This sits ABOVE jax's own persistent compilation cache: that one still
    re-runs the XLA pipeline front-end per process; this one skips the
    compile call entirely (the hit/miss counters in compilequeue.STATS are
    the proof). TUPLEX_AOT_CACHE=0 disables; =<path> relocates the store."""
    return os.environ.get("TUPLEX_AOT_CACHE", "") != "0"


def aot_cache_dir() -> str:
    """On-disk artifact directory for serialized stage executables.
    Partitioned by the same host-ISA tag as the XLA compile cache (XLA:CPU
    artifacts encode machine features; loading cross-ISA risks SIGILL —
    same rationale as _host_tag above)."""
    v = os.environ.get("TUPLEX_AOT_CACHE", "")
    if v == "0":
        return ""
    d = v or os.path.join(os.path.expanduser("~"), ".cache",
                          f"tuplex_aot_{_host_tag()}")
    try:
        os.makedirs(d, exist_ok=True)
    except OSError:
        return ""
    return d


def aot_platform_tag() -> str:
    """Platform component of the AOT fingerprint: effective backend +
    host-ISA tag + x64 mode + jax version. Anything that changes what a
    compiled executable MEANS must appear here."""
    return "/".join((jax.default_backend(), _host_tag(),
                     f"x64={int(bool(jax.config.jax_enable_x64))}",
                     f"jax={jax.__version__}"))


def donation_enabled() -> bool:
    """Whether stage dispatch donates its input device buffers to XLA
    (halves per-stage HBM residency: the staged input is dead the moment
    the kernel reads it — every consumer re-stages from host leaves or a
    one-shot handoff view). Off on CPU, where XLA does not support
    donation and would warn per call. TUPLEX_DONATE=0/1 overrides (tests
    force it on under the CPU platform to exercise the path)."""
    import os

    mode = os.environ.get("TUPLEX_DONATE", "auto")
    if mode in ("0", "1"):
        return mode == "1"
    return jax.default_backend() not in ("cpu",)
