"""Process-wide structured tracing: nested, thread-aware spans.

The reference ships a live history server because dual-mode pipelines fail
in TIME, not just in counts — a job that "works" may be losing its wall
clock to compile-queue waits, D2H materialization, or the interpreter
resolve tier. Per-stage sums (api/metrics.py) can't show that; this module
records WHERE the seconds went as a span timeline:

  * ``span(name, cat)`` is a context manager (and ``traced()`` a
    decorator) that records one closed interval per entered span. Spans
    nest naturally — a per-thread stack tracks depth, and concurrent
    threads (the compile pool, source prefetch) interleave without locks
    on the hot path.
  * storage is a RING BUFFER (``TUPLEX_TRACE_BUFFER`` events, default
    65536): a long job keeps the most recent window instead of growing
    without bound. deque.append is atomic under the GIL, so recording
    takes no lock.
  * disabled (the default) the whole thing is one module-flag check:
    ``span()`` returns a shared no-op singleton — no allocation, no
    timestamp, no buffer write. Enable via the ``tuplex.tpu.trace``
    option or ``TUPLEX_TRACE=1``.
  * spans export as Chrome trace-event JSON (``export_chrome_trace`` /
    ``Metrics.export_trace``) openable in Perfetto or chrome://tracing —
    "X" complete events with ph/ts/dur/pid/tid, per-thread lanes named
    after the python thread, span attributes under ``args``.
  * multihost: every process records its own stream; ``set_host(idx)``
    keys the stream's pid lane by the jax process index and
    ``dump_jsonl``/``merge_jsonl`` let the driver merge per-host streams
    into one timeline (each host's lane keeps its own clock epoch; within
    a host, relative timing is exact).

The timebase is ``time.perf_counter`` relative to module import, reported
in microseconds (the Chrome trace unit).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque
from typing import Any, Optional

_t0 = time.perf_counter()


def _env_enabled() -> bool:
    return os.environ.get("TUPLEX_TRACE", "0").strip().lower() \
        not in ("", "0", "false", "off")


def _capacity() -> int:
    try:
        return max(256, int(os.environ.get("TUPLEX_TRACE_BUFFER", "65536")))
    except ValueError:
        return 65536


_enabled = _env_enabled()
_events: "deque[dict]" = deque(maxlen=_capacity())
_tls = threading.local()
_host_pid: Optional[int] = None        # multihost lane (jax process index)
_tid_names: dict[int, str] = {}        # tid -> thread name (export metadata)


def enabled() -> bool:
    return _enabled


def enable(on: bool = True) -> None:
    """Turn recording on/off process-wide. Turning off keeps already
    recorded events (export still works); ``clear()`` drops them."""
    global _enabled
    _enabled = bool(on)


def clear() -> None:
    _events.clear()
    _tid_names.clear()


def set_host(idx: int) -> None:
    """Key this process's span stream by a host index (multihost: the jax
    process index) so merged traces show one lane per host."""
    global _host_pid
    _host_pid = int(idx)


def set_stream(tag: Optional[str]) -> None:
    """Tag every span recorded by THIS thread with a stream id. The job
    service (serve/) sets the running job's id around each scheduler step,
    so concurrent tenants sharing one process separate into per-job span
    streams without per-tenant ring buffers. None clears the tag."""
    _tls.stream = None if tag is None else str(tag)


def current_stream() -> Optional[str]:
    return getattr(_tls, "stream", None)


def events_for_stream(tag: str) -> list:
    """Spans recorded under ``set_stream(tag)`` — one tenant's slice of
    the shared ring buffer (serve/: per-job Metrics/trace isolation)."""
    return [e for e in events() if e.get("stream") == tag]


def now_us() -> float:
    """Microseconds since the trace epoch (module import)."""
    return (time.perf_counter() - _t0) * 1e6


def to_trace_us(perf_s: float) -> float:
    """Convert a raw ``time.perf_counter()`` reading to microseconds on
    the trace clock. Both clocks share the perf_counter timebase, so
    scheduler stamps (JobRecord.t_submit/t_start) and span timestamps
    become directly comparable — runtime/critpath uses this to bound a
    job's running window on the span timeline."""
    return (perf_s - _t0) * 1e6


class _NoopSpan:
    """Shared do-nothing span for the disabled path: entering, exiting and
    setting attributes all fall through. One module-level instance — a
    disabled ``span()`` call allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, key, value):
        return self


NOOP = _NoopSpan()


class _Span:
    __slots__ = ("name", "cat", "args", "_ts", "_depth")

    def __init__(self, name: str, cat: str, args: Optional[dict]):
        self.name = name
        self.cat = cat
        self.args = args
        self._ts = 0.0
        self._depth = 0

    def set(self, key: str, value: Any) -> "_Span":
        """Attach one attribute (rendered under ``args`` in the export).
        Callable mid-span — cache hit/miss verdicts land on the span that
        covered the lookup."""
        a = self.args
        if a is None:
            a = self.args = {}
        a[key] = value
        return self

    def __enter__(self) -> "_Span":
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        self._depth = len(stack)
        stack.append(self)
        self._ts = now_us()
        return self

    def __exit__(self, et, ev, tb) -> bool:
        dur = now_us() - self._ts
        stack = getattr(_tls, "stack", None)
        if stack and stack[-1] is self:
            stack.pop()
        elif stack and self in stack:          # pragma: no cover - misuse
            stack.remove(self)
        if et is not None:
            self.set("error", et.__name__)
        tid = threading.get_ident()
        if tid not in _tid_names:
            _tid_names[tid] = threading.current_thread().name
        rec = {
            "name": self.name, "cat": self.cat,
            "ts": self._ts, "dur": dur,
            "tid": tid, "depth": self._depth,
            "args": self.args,
        }
        st = current_stream()
        if st is not None:
            rec["stream"] = st
        _events.append(rec)
        return False


def span(name: str, cat: str = "exec", args: Optional[dict] = None):
    """Open a span. ``with tracing.span("stage:dispatch", "exec") as sp:``
    — the span closes (and is recorded) when the block exits; ``sp.set``
    attaches attributes. When tracing is disabled this returns a shared
    no-op object: zero allocation, zero bookkeeping."""
    if not _enabled:
        return NOOP
    return _Span(name, cat, args)


def traced(name: Optional[str] = None, cat: str = "exec"):
    """Decorator form: the wrapped call body becomes one span."""
    def deco(fn):
        import functools

        sname = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*a, **kw):
            if not _enabled:
                return fn(*a, **kw)
            with _Span(sname, cat, None):
                return fn(*a, **kw)
        return wrapper
    return deco


def instant(name: str, cat: str = "exec",
            args: Optional[dict] = None) -> None:
    """Record a zero-duration marker (Chrome 'i' instant event)."""
    if not _enabled:
        return
    tid = threading.get_ident()
    if tid not in _tid_names:
        _tid_names[tid] = threading.current_thread().name
    ev = {"name": name, "cat": cat, "ts": now_us(), "dur": None,
          "tid": tid,
          "depth": len(getattr(_tls, "stack", ())), "args": args}
    st = current_stream()
    if st is not None:
        ev["stream"] = st
    _events.append(ev)


def complete(name: str, cat: str, ts_us: float, dur_us: float,
             args: Optional[dict] = None) -> None:
    """Record an interval with EXPLICIT timestamps — for waits measured
    across threads (a pool job's queue wait starts on the submitting
    thread and ends on the worker) where a context manager can't
    bracket the gap."""
    if not _enabled:
        return
    tid = threading.get_ident()
    if tid not in _tid_names:
        _tid_names[tid] = threading.current_thread().name
    ev = {"name": name, "cat": cat, "ts": float(ts_us),
          "dur": float(dur_us), "tid": tid,
          "depth": len(getattr(_tls, "stack", ())), "args": args}
    st = current_stream()
    if st is not None:
        ev["stream"] = st
    _events.append(ev)


_NULL_CM = contextlib.nullcontext()   # shared, stateless


def device_annotation(name: str):
    """``jax.profiler.TraceAnnotation`` bracketing a device-side region so
    our host spans line up inside XLA device profiles
    (``tuplex.tpu.profileDir``). No-op (shared null context — zero
    allocation, like NOOP) when tracing is off or the profiler API is
    unavailable — annotation must never fail a dispatch."""
    if not _enabled:
        return _NULL_CM
    try:
        from jax.profiler import TraceAnnotation

        return TraceAnnotation(name)
    except Exception:   # pragma: no cover - profiler API drift
        return _NULL_CM


# ---------------------------------------------------------------------------
# export
# ---------------------------------------------------------------------------

def events() -> list[dict]:
    """Snapshot of the recorded span records (ring-buffer order: oldest
    first). Each record: name/cat/ts/dur(us)/tid/depth/args.

    Recording stays lock-free, so a compile-pool (or abandoned deadline-
    compile) thread can append mid-snapshot — deques raise RuntimeError on
    mutation during iteration; retry until a consistent pass succeeds."""
    while True:
        try:
            return list(_events)
        except RuntimeError:       # pragma: no cover - needs a mid-iter race
            continue


def events_since(ts_us: float) -> list[dict]:
    """Spans that STARTED at or after `ts_us` (history per-job slicing)."""
    return [e for e in events() if e["ts"] >= ts_us]


def _chrome_event(e: dict, pid: int) -> dict:
    out = {"name": e["name"], "cat": e.get("cat") or "exec",
           "ph": "X" if e.get("dur") is not None else "i",
           "ts": round(float(e["ts"]), 3),
           "pid": pid, "tid": e.get("tid", 0)}
    if e.get("dur") is not None:
        out["dur"] = round(float(e["dur"]), 3)
    else:
        out["s"] = "t"                      # instant scope: thread
    if e.get("args"):
        out["args"] = e["args"]
    if e.get("stream") is not None:
        # per-tenant stream tag (serve/): copy-on-write so the recorded
        # event's args dict is never mutated by the export
        out["args"] = dict(out.get("args") or {}, stream=e["stream"])
    return out


def chrome_events(evts: Optional[list] = None,
                  pid: Optional[int] = None) -> list[dict]:
    """Convert span records to Chrome trace-event dicts, prefixed with
    process/thread name metadata events so Perfetto labels the lanes."""
    if evts is None:
        evts = events()
    p = pid if pid is not None \
        else (_host_pid if _host_pid is not None else os.getpid())
    out: list[dict] = [{
        "name": "process_name", "ph": "M", "pid": p, "tid": 0,
        "args": {"name": f"tuplex_tpu host{_host_pid}"
                 if _host_pid is not None else "tuplex_tpu"}}]
    # .copy() is atomic under the GIL — a concurrent thread closing its
    # FIRST span inserts here, and plain .items() iteration would raise
    for tid, tname in _tid_names.copy().items():
        out.append({"name": "thread_name", "ph": "M", "pid": p,
                    "tid": tid, "args": {"name": tname}})
    out.extend(_chrome_event(e, p) for e in evts)
    return out


def export_chrome_trace(path: str, extra_events: Optional[list] = None) -> str:
    """Write the recorded spans as a Chrome trace-event JSON file (the
    ``{"traceEvents": [...]}`` object form) loadable in Perfetto /
    chrome://tracing. `extra_events` (already chrome-shaped dicts — e.g.
    other hosts' streams via ``load_jsonl``) merge into the same file."""
    evs = chrome_events()
    if extra_events:
        evs.extend(extra_events)
    obj = {"traceEvents": evs, "displayTimeUnit": "ms"}
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fp:
        json.dump(obj, fp)
    os.replace(tmp, path)
    return path


def dump_jsonl(path: str) -> str:
    """Write this process's span stream as JSON-lines of chrome-shaped
    events (one event per line; a multihost worker dumps its stream here
    for the driver to merge)."""
    with open(path, "w") as fp:
        for e in chrome_events():
            fp.write(json.dumps(e) + "\n")
    return path


def load_jsonl(path: str) -> list[dict]:
    out = []
    with open(path) as fp:
        for line in fp:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out


def merge_jsonl(paths: list, out_path: str) -> str:
    """Driver-side merge: this process's spans + every per-host stream
    (``dump_jsonl`` files) into one Chrome trace. Lanes separate by pid
    (the host index), so cross-host skew never corrupts within-host
    nesting."""
    extra: list[dict] = []
    for p in paths:
        try:
            extra.extend(load_jsonl(p))
        except OSError:
            continue
    return export_chrome_trace(out_path, extra_events=extra)
