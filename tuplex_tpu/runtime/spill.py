"""Partition spill-to-disk under memory pressure.

Reference semantics: core/include/Partition.h:207-214 swapOut/swapIn +
Executor.h:179 evictLRUPartition — partitions beyond the executor memory
budget write their buffers to scratchDir and reload transparently on access.

A Partition's leaves serialize to one .npz file; the MemoryManager tracks
registered partitions via WEAK references (dropped partitions unregister
automatically and their spill files are deleted by a finalizer), keeps byte
accounting incrementally, and evicts LRU past the budget. Host-boxed
fallback values stay in memory (small by the normal-case contract).
"""

from __future__ import annotations

import os
import threading
import uuid
import weakref
from collections import OrderedDict
from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..utils.logging import get_logger
from . import columns as C
from . import tracing, xferstats

log = get_logger("spill")


def _leaves_to_npz_dict(part: C.Partition) -> dict:
    out: dict = {}
    for path, leaf in part.leaves.items():
        key = path.replace("#", "%23")
        if isinstance(leaf, C.NumericLeaf):
            out[f"n!{key}!data"] = leaf.data
            if leaf.valid is not None:
                out[f"n!{key}!valid"] = leaf.valid
        elif isinstance(leaf, C.StrLeaf):
            out[f"s!{key}!bytes"] = leaf.bytes
            out[f"s!{key}!len"] = leaf.lengths
            if leaf.valid is not None:
                out[f"s!{key}!valid"] = leaf.valid
        elif isinstance(leaf, C.NullLeaf):
            out[f"z!{key}!n"] = np.asarray([leaf.n])
        # ObjectLeaf stays in memory (pickling arbitrary objects not worth it)
    return out


def load_leaves_npz(src) -> dict:
    """npz image (path or open binary file) -> leaf dict; the read half of
    _leaves_to_npz_dict. Shared by local spill files and the tuplexfile
    format's remote-scheme reads (io/tuplexfmt.py)."""
    leaves: dict = {}
    with np.load(src) as z:
        names = set(z.files)
        seen: set = set()
        for f in names:
            kind, key, _ = f.split("!", 2)
            if key in seen:
                continue
            path = key.replace("%23", "#")
            if kind == "n":
                leaves[path] = C.NumericLeaf(
                    z[f"n!{key}!data"],
                    z[f"n!{key}!valid"] if f"n!{key}!valid" in names
                    else None)
            elif kind == "s":
                leaves[path] = C.StrLeaf(
                    z[f"s!{key}!bytes"], z[f"s!{key}!len"],
                    z[f"s!{key}!valid"] if f"s!{key}!valid" in names
                    else None)
            elif kind == "z":
                leaves[path] = C.NullLeaf(int(z[f"z!{key}!n"][0]))
            seen.add(key)
    return leaves


class SpilledPartition:
    """Disk image of a partition's array leaves."""

    def __init__(self, path: str, obj_leaves: dict):
        self.path = path
        self.obj_leaves = obj_leaves  # ObjectLeafs kept live

    def load(self) -> dict:
        leaves = load_leaves_npz(self.path)
        leaves.update(self.obj_leaves)
        return leaves

    def delete(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass


@dataclass
class _Entry:
    ref: "weakref.ref[C.Partition]"
    nbytes: int        # bytes currently resident (0 while spilled)


class MemoryManager:
    """LRU partition eviction against a byte budget (reference:
    Executor::evictLRUPartition + BitmapAllocator pressure)."""

    def __init__(self, budget_bytes: int, scratch_dir: str):
        self.budget = budget_bytes
        self.scratch = os.path.join(scratch_dir, f"spill-{os.getpid()}")
        self._entries: "OrderedDict[int, _Entry]" = OrderedDict()
        self._inmem = 0
        self._lock = threading.Lock()
        self._dead: list[int] = []  # filled by weakref callbacks, no lock
        self._pinned: set[int] = set()  # never evicted (in active use)
        self.swap_out_count = 0
        self.swap_in_count = 0
        self.swapped_bytes = 0

    # ------------------------------------------------------------------
    def register(self, part: C.Partition) -> None:
        with self._lock:
            self._reap_locked()  # BEFORE membership: ids get reused after GC
            pid = id(part)
            if pid in self._entries:
                self._entries.move_to_end(pid)
                return
            nb = part.nbytes()

            # callbacks may fire while WE hold the lock (a strong ref
            # dropped inside eviction): never lock here — just enqueue
            def on_dead(_ref, mm=self, key=pid):
                mm._dead.append(key)  # list.append is atomic

            self._entries[pid] = _Entry(weakref.ref(part, on_dead), nb)
            self._inmem += nb
            self._evict_locked(exclude=pid)

    def touch(self, part: C.Partition) -> None:
        """Mark recently used; swap back in if spilled."""
        with self._lock:
            self._reap_locked()
            pid = id(part)
            if pid in self._entries:
                self._entries.move_to_end(pid)
            if getattr(part, "_spilled", None) is not None:
                self._swap_in_locked(part)

    def pin(self, part: C.Partition) -> None:
        """Exclude from eviction while another thread may touch/register
        (prefetch makes mm calls concurrent: touch-then-use isn't atomic
        across threads). Always pair with unpin."""
        with self._lock:
            self._pinned.add(id(part))
            if getattr(part, "_spilled", None) is not None:
                self._swap_in_locked(part)

    def unpin(self, part: C.Partition) -> None:
        with self._lock:
            self._pinned.discard(id(part))

    def _reap_locked(self) -> None:
        while self._dead:
            key = self._dead.pop()
            e = self._entries.pop(key, None)
            if e is not None:
                self._inmem -= e.nbytes

    # ------------------------------------------------------------------
    def _evict_locked(self, exclude: int = -1) -> None:
        """`exclude`: the entry being registered/loaded RIGHT NOW — even a
        partition bigger than the whole budget must stay resident while its
        caller reads it."""
        if self.budget <= 0:
            return
        for pid, entry in list(self._entries.items()):
            if self._inmem <= self.budget:
                break
            if pid == exclude or pid in self._pinned:
                continue
            part = entry.ref()
            if part is None or entry.nbytes == 0 or \
                    getattr(part, "_spilled", None) is not None:
                continue
            self._swap_out_locked(part, entry)

    def _swap_out_locked(self, part: C.Partition, entry: _Entry) -> None:
        os.makedirs(self.scratch, exist_ok=True)
        path = os.path.join(self.scratch, f"p{uuid.uuid4().hex}.npz")
        arrays = _leaves_to_npz_dict(part)
        obj = {p: l for p, l in part.leaves.items()
               if isinstance(l, C.ObjectLeaf)}
        np.savez(path, **arrays)
        sp = SpilledPartition(path, obj)
        self.swap_out_count += 1
        self.swapped_bytes += entry.nbytes
        xferstats.bump("spill_bytes", entry.nbytes, tag="swap_out")
        tracing.instant("mm:swap-out", "mem",
                        {"rows": part.num_rows, "bytes": entry.nbytes})
        self._inmem -= entry.nbytes
        entry.nbytes = 0
        part._spilled = sp  # type: ignore[attr-defined]
        # orphaned spill files are removed when the partition is GC'd
        part._spill_fin = weakref.finalize(part, sp.delete)  # type: ignore[attr-defined]
        part.leaves = {}
        # a device-resident view pins device memory: a partition under
        # memory pressure must not keep one
        if getattr(part, "device_batch", None) is not None:
            part.device_batch = None
        log.debug("swapped out partition (%d rows) to %s", part.num_rows, path)

    def _swap_in_locked(self, part: C.Partition) -> None:
        sp = part._spilled  # type: ignore[attr-defined]
        with tracing.span("mm:swap-in", "mem") as _sp:
            part.leaves = sp.load()
            _sp.set("rows", part.num_rows)
        part._spilled = None  # type: ignore[attr-defined]
        sp.delete()
        self.swap_in_count += 1
        entry = self._entries.get(id(part))
        nb = part.nbytes()
        if entry is not None:
            entry.nbytes = nb
        self._inmem += nb
        self._evict_locked(exclude=id(part))

    def ensure_loaded(self, part: C.Partition) -> C.Partition:
        self.touch(part)
        return part

    def resident_bytes(self) -> int:
        """Bytes currently resident across registered partitions — the
        quantity the LRU evictor holds under ``budget``. The job service
        reports it per tenant (each job's runner owns its own manager,
        so this IS the job's resident footprint)."""
        with self._lock:
            self._reap_locked()
            return self._inmem

    def metrics(self) -> dict:
        return {"swap_out": self.swap_out_count, "swap_in": self.swap_in_count,
                "swapped_bytes": self.swapped_bytes}

    def metrics_snapshot(self) -> tuple:
        return (self.swap_out_count, self.swap_in_count, self.swapped_bytes)

    def metrics_delta(self, snap: tuple) -> dict:
        return {"swap_out": self.swap_out_count - snap[0],
                "swap_in": self.swap_in_count - snap[1],
                "swapped_bytes": self.swapped_bytes - snap[2]}
