"""Device-plane cost attribution: XLA cost/memory analysis + measured
device time + roofline readouts.

Every observability layer so far is host-side wall clock (spans, serve
histograms); nothing answers "what does this compiled stage cost ON THE
DEVICE" — FLOPs, bytes moved, peak memory, achieved utilization — which
is exactly the signal a cost-based plan optimizer needs. Three pieces:

* **StageCost** — harvested once per compiled executable from XLA's own
  ``compiled.cost_analysis()`` / ``compiled.memory_analysis()`` (guarded
  per backend: XLA:CPU returns partial dicts on some versions, TPU
  plugins may return nothing). The compile queue calls ``note_compiled``
  at its publish chokepoint, so AOT hits, dedup hits and subprocess
  handbacks all land here; the record is persisted as a ``<fp>.cost.json``
  sidecar NEXT TO the content-addressed executable artifact, so a warm
  second process recovers the analysis with zero recompiles — the AOT
  store becomes a queryable cost database, not a pile of opaque blobs.
* **measured device time** — the dispatch path (exec/local) blocks each
  launched partition until ready and records the launch→ready delta per
  stage, split cold (first call: includes the compile/AOT-load wait) vs
  warm. Samples land in telemetry histograms
  (``device_dispatch_seconds{stage,state}``) and a per-stage accumulator
  consumed into stage metrics; the warm median also feeds the split
  tuner's per-boundary cost model (plan/splittuner.record_device_dispatch)
  — the first REAL device-cost feature in the split decision.
* **roofline** — a small per-platform peak table (TPU generations from
  published specs; CPU a labeled estimate) turns flops/bytes/seconds
  into achieved FLOP/s, achieved bytes/s, arithmetic intensity and
  fraction-of-attainable-peak per stage, plus peak-memory vs the job's
  MemoryManager budget.

Disabled (``TUPLEX_DEVPROF=0`` env kill switch) the record path is one
module-flag check — no allocation, no lock, no block_until_ready (the
same zero-overhead contract tracing/telemetry pin, test-asserted). Note
the ENABLED path deliberately blocks each dispatch until the device
finishes: that is what "measured device time" means, and it trades a
little dispatch/merge overlap for attribution (steady-state zillow on
CPU measures within noise; kill the switch for maximum-overlap runs).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from dataclasses import asdict, dataclass
from typing import Optional

# ---------------------------------------------------------------------------
# enable gate (mirrors runtime/telemetry: process-wide, env kill switch wins)
# ---------------------------------------------------------------------------


def _env_disabled() -> bool:
    return os.environ.get("TUPLEX_DEVPROF", "").strip().lower() \
        in ("0", "false", "off")


_enabled = not _env_disabled()


def enabled() -> bool:
    return _enabled


def enable(on: bool = True) -> None:
    """Process-wide gate. TUPLEX_DEVPROF=0 wins over any option-driven
    enable (A/B overhead timing, maximum-overlap production runs)."""
    global _enabled
    _enabled = bool(on) and not _env_disabled()


def apply_options(options) -> None:
    """Wire the process gate from ContextOptions. Like telemetry, the
    ``tuplex.tpu.devprof`` option turns attribution ON, never off — the
    gate is process-wide and another live Context may depend on it; the
    only OFF switches are the env kill switch and an explicit
    ``devprof.enable(False)``."""
    if options.get_bool("tuplex.tpu.devprof", True):
        enable(True)


# ---------------------------------------------------------------------------
# StageCost: the per-executable analysis record
# ---------------------------------------------------------------------------


@dataclass
class StageCost:
    """XLA's static cost/memory analysis for ONE compiled executable
    (per-execution numbers: one dispatch of one partition batch).
    ``partial`` marks records where one of the two analyses was
    unavailable; a missing record altogether means the backend returned
    nothing (compilestats flags those explicitly)."""

    flops: float = 0.0
    bytes_accessed: float = 0.0
    transcendentals: float = 0.0
    argument_bytes: int = 0
    output_bytes: int = 0
    temp_bytes: int = 0
    generated_code_bytes: int = 0
    backend: str = ""
    partial: bool = False

    @property
    def peak_bytes(self) -> int:
        """Peak device-memory footprint of one execution: arguments +
        outputs + XLA temp allocations + generated code. XLA does not
        expose a liveness-exact peak through this API; the sum is the
        upper bound the runtime actually reserves."""
        return (self.argument_bytes + self.output_bytes + self.temp_bytes
                + self.generated_code_bytes)

    def to_dict(self) -> dict:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "StageCost":
        fields = {k: d[k] for k in cls.__dataclass_fields__ if k in d}
        return cls(**fields)


def harvest(compiled) -> Optional[StageCost]:
    """Pull XLA's cost + memory analysis off a compiled executable,
    tolerating every observed shape of the API: ``cost_analysis()``
    returning a dict, a list of per-device dicts, ``None``, or raising
    (some PJRT plugins); ``memory_analysis()`` likewise. Returns None
    only when NEITHER analysis yields anything — the "backend returned
    nothing" case the CLI flags."""
    ca: Optional[dict] = None
    try:
        raw = compiled.cost_analysis()
        if isinstance(raw, (list, tuple)):
            raw = raw[0] if raw else None
        if isinstance(raw, dict) and raw:
            ca = raw
    except Exception:
        ca = None
    ma = None
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    if ca is None and ma is None:
        return None
    try:
        import jax

        backend = jax.default_backend()
    except Exception:   # pragma: no cover - no backend yet
        backend = ""
    cost = StageCost(backend=backend, partial=(ca is None or ma is None))
    if ca is not None:
        cost.flops = float(ca.get("flops", 0.0) or 0.0)
        cost.bytes_accessed = float(ca.get("bytes accessed", 0.0) or 0.0)
        cost.transcendentals = float(ca.get("transcendentals", 0.0) or 0.0)
    if ma is not None:
        for attr, field in (("argument_size_in_bytes", "argument_bytes"),
                            ("output_size_in_bytes", "output_bytes"),
                            ("temp_size_in_bytes", "temp_bytes"),
                            ("generated_code_size_in_bytes",
                             "generated_code_bytes")):
            try:
                setattr(cost, field, int(getattr(ma, attr, 0) or 0))
            except Exception:
                pass
    return cost


# ---------------------------------------------------------------------------
# sidecar persistence (alongside the content-addressed AOT artifact)
# ---------------------------------------------------------------------------


def _sidecar_path(fp: str) -> Optional[str]:
    if not fp:
        return None
    from .jaxcfg import aot_cache_dir

    d = aot_cache_dir()
    return os.path.join(d, fp + ".cost.json") if d else None


def store_cost(fp: str, cost: StageCost) -> None:
    """Persist the analysis next to ``<fp>.aot`` so a warm process (AOT
    hit, zero compiles) recovers it without re-analyzing."""
    path = _sidecar_path(fp)
    if path is None:
        return
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as f:
            json.dump(cost.to_dict(), f)
        os.replace(tmp, path)
    except OSError:   # pragma: no cover - sidecar is best-effort
        pass


def load_cost(fp: str) -> Optional[StageCost]:
    path = _sidecar_path(fp)
    if path is None or not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            return StageCost.from_dict(json.load(f))
    except Exception:   # pragma: no cover - corrupt sidecar = miss
        return None


# ---------------------------------------------------------------------------
# in-process registry: fingerprint -> cost, stage tag -> {fp: cost}
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_BY_FP: dict[str, Optional[StageCost]] = {}    # None = analysis unavailable
_BY_TAG: dict[str, dict] = {}                  # tag -> {fp_or_'': cost|None}
_MAX_ENTRIES = 4096


def note_compiled(tag: str, fp: str, compiled) -> None:
    """Publish chokepoint hook (exec/compilequeue): associate `tag` (the
    stage cache key) with `fp`'s analysis — loading the sidecar on an AOT
    hit, harvesting (and persisting) on a fresh compile or handback. A
    backend that returns nothing is recorded as None so the stage reads
    as "analysis unavailable" rather than silently blank."""
    if not _enabled:
        return
    with _LOCK:
        have = fp in _BY_FP if fp else False
        cost = _BY_FP.get(fp) if have else None
    if not have:
        cost = load_cost(fp) if fp else None
        freshly_harvested = False
        if cost is None:
            cost = harvest(compiled)
            freshly_harvested = cost is not None
        if fp and freshly_harvested:
            store_cost(fp, cost)
    with _LOCK:
        if fp:
            _BY_FP[fp] = cost
            while len(_BY_FP) > _MAX_ENTRIES:
                _BY_FP.pop(next(iter(_BY_FP)))
        if tag:
            _BY_TAG.setdefault(tag, {})[fp] = cost
            while len(_BY_TAG) > _MAX_ENTRIES:
                _BY_TAG.pop(next(iter(_BY_TAG)))


def note_tag(tag: str, fp: str) -> None:
    """Dedup-hit association: the executable (and its cost) already
    exist; only the tag->fp edge is new."""
    if not _enabled or not tag or not fp:
        return
    with _LOCK:
        if fp in _BY_FP:
            _BY_TAG.setdefault(tag, {})[fp] = _BY_FP[fp]


def cost_for_tag(tag: str) -> Optional[StageCost]:
    """The stage's dominant executable's analysis: a tag may map to
    several fingerprints (packed main fn, ragged-tail shapes, general
    tier, cpu pin) — the max-flops record is the one dispatch spends its
    time in."""
    with _LOCK:
        recs = [c for c in _BY_TAG.get(tag, {}).values() if c is not None]
    if not recs:
        return None
    return max(recs, key=lambda c: (c.flops, c.bytes_accessed))


def tag_seen(tag: str) -> bool:
    """True when at least one executable compiled under `tag` (even if
    its backend returned no analysis)."""
    with _LOCK:
        return tag in _BY_TAG


# ---------------------------------------------------------------------------
# measured device time per dispatch
# ---------------------------------------------------------------------------

#: one stage-label truncation for EVERY exposition surface (histogram
#: labels, gauge labels) so a PromQL join across the devprof families
#: matches — stage.key() is 16 hex chars, so 16 keeps it whole
STAGE_LABEL_LEN = 16

# (owner, tag) -> accumulator, consumed per stage execution. The owner
# half (the dispatching backend's id) keeps CONCURRENT serve jobs
# running isomorphic stages — identical stage.key() by design, that is
# what compile-sharing means — from pooling samples into one window and
# having whichever job finishes first steal the others' report.
_DISP: dict[tuple, dict] = {}
_WARM_KEEP = 64                     # bounded warm-sample window per stage
_tuner_fed: set = set()             # tags already fed to the split tuner


def block_ready(outs) -> None:
    """Wait until a dispatch's device work is done — by POLLING
    ``Array.is_ready()``, never ``jax.block_until_ready``. The
    distinction is load-bearing: block_until_ready touches the result
    buffers, and on XLA:CPU with input donation forced on
    (TUPLEX_DONATE=1 — a config jax itself doesn't support on CPU) that
    touch non-deterministically corrupted stage outputs (missing filter
    survivors, garbage '#keep' lattices; reproduced only via
    block_until_ready — an is_ready poll or a plain sleep over the same
    window is clean). Polling observes completion without touching
    buffer internals, at ±0.2 ms precision — noise next to the
    histogram's ±12% buckets. Handles the packed wire's PackedOuts
    (buf/vbuf/extras attributes — not a pytree) and plain pytrees.
    Best-effort: a failure here must never kill the dispatch."""
    try:
        import jax

        buf = getattr(outs, "buf", None)
        if buf is not None:
            outs = (buf, getattr(outs, "vbuf", None),
                    getattr(outs, "extras", None))
        for leaf in jax.tree_util.tree_leaves(outs):
            ready = getattr(leaf, "is_ready", None)
            if ready is None:
                continue
            while not ready():
                time.sleep(0.0002)
    except Exception:   # pragma: no cover - attribution is best-effort
        pass


def record_dispatch(tag: str, seconds: float, cold: bool = False,
                    rows: int = 0, owner: int = 0) -> None:
    """One launched-partition sample: launch→ready seconds. `cold` marks
    the first call of an input spec (includes the compile / AOT-load /
    dedup wait — minutes on a cold tunnel) so roofline math prefers
    warm samples (see stage_report for the cold-only fallback). `owner`
    scopes the accumulator to the dispatching backend so concurrent
    jobs sharing a stage key don't pool windows."""
    if not _enabled or not tag or seconds < 0:
        return
    from . import telemetry

    telemetry.observe("device_dispatch_seconds", seconds,
                      stage=tag[:STAGE_LABEL_LEN],
                      state="cold" if cold else "warm")
    with _LOCK:
        key = (owner, tag)
        acc = _DISP.get(key)
        if acc is None:
            # bounded like every other registry here: a stage that
            # dispatches but dies before its stage_report consume (job
            # crash/interrupt) must not leak its window forever in a
            # long-lived serve process
            while len(_DISP) >= _MAX_ENTRIES:
                _DISP.pop(next(iter(_DISP)))
            acc = _DISP[key] = {"device_s": 0.0, "cold_s": 0.0, "n": 0,
                                "cold_n": 0, "rows": 0, "warm": [],
                                "min_s": math.inf}
        acc["device_s"] += seconds
        acc["n"] += 1
        acc["rows"] += int(rows)
        if seconds < acc["min_s"]:
            acc["min_s"] = seconds
        if cold:
            acc["cold_s"] += seconds
            acc["cold_n"] += 1
        elif len(acc["warm"]) < _WARM_KEEP:
            acc["warm"].append(seconds)


# ---------------------------------------------------------------------------
# platform peaks + roofline math
# ---------------------------------------------------------------------------


@dataclass
class Peaks:
    flops_per_s: float
    bytes_per_s: float
    name: str = ""
    kind: str = "estimate"      # "table" (published spec) | "estimate"


#: published per-chip peaks (dense compute, HBM bandwidth) by device-kind
#: substring; matched case-insensitively against jax's device_kind
_TPU_PEAKS = (
    ("v6e", 918e12, 1640e9),
    ("v5p", 459e12, 2765e9),
    ("v5e", 197e12, 819e9),
    ("v5 lite", 197e12, 819e9),
    ("v4", 275e12, 1228e9),
    ("v3", 123e12, 900e9),
    ("v2", 46e12, 700e9),
)

_peaks_cache: Optional[Peaks] = None


def platform_peaks() -> Peaks:
    """Peak FLOP/s + memory bytes/s for the default device.
    TUPLEX_DEVPROF_PEAKS="<flops>,<bytes_per_s>" overrides (roofline
    calibration on unlisted hardware); TPU generations come from the
    published spec table; CPU is a labeled ESTIMATE (cores x 3 GHz x 16
    f32 FMA lanes, ~25 GB/s stream bandwidth) — good enough to rank
    stages, not to certify utilization."""
    global _peaks_cache
    if _peaks_cache is not None:
        return _peaks_cache
    env = os.environ.get("TUPLEX_DEVPROF_PEAKS", "")
    if env:
        try:
            f, b = (float(x) for x in env.split(",")[:2])
            _peaks_cache = Peaks(f, b, name="env", kind="override")
            return _peaks_cache
        except ValueError:
            pass
    kind_s = ""
    backend = "cpu"
    try:
        import jax

        dev = jax.devices()[0]
        backend = dev.platform
        kind_s = str(getattr(dev, "device_kind", "")).lower()
    except Exception:   # pragma: no cover - no backend yet
        pass
    if backend != "cpu":
        for sub, f, b in _TPU_PEAKS:
            if sub in kind_s:
                _peaks_cache = Peaks(f, b, name=kind_s, kind="table")
                return _peaks_cache
        # unknown accelerator: conservative v2-class floor, labeled
        _peaks_cache = Peaks(46e12, 700e9, name=kind_s or backend,
                             kind="estimate")
        return _peaks_cache
    cores = os.cpu_count() or 1
    _peaks_cache = Peaks(cores * 3.0e9 * 16, 25e9,
                         name=f"cpu x{cores}", kind="estimate")
    return _peaks_cache


def roofline(flops: float, nbytes: float, seconds: float,
             peaks: Optional[Peaks] = None) -> dict:
    """The classic roofline readout for one execution: achieved FLOP/s
    and bytes/s, arithmetic intensity (flops/byte), the attainable peak
    ``min(peak_flops, intensity * peak_bw)`` and the achieved fraction of
    it, clamped to (0, 1]. A flop-free stage (pure data movement) reads
    off the bandwidth roof instead. Empty dict when `seconds` (or both
    numerators) is unusable."""
    if seconds <= 0 or not math.isfinite(seconds):
        return {}
    peaks = peaks or platform_peaks()
    out: dict = {}
    if flops > 0:
        ach_f = flops / seconds
        out["achieved_flops_per_s"] = ach_f
        if nbytes > 0:
            intensity = flops / nbytes
            out["arithmetic_intensity"] = intensity
            attain = min(peaks.flops_per_s, intensity * peaks.bytes_per_s)
        else:
            attain = peaks.flops_per_s
        out["attainable_flops_per_s"] = attain
        out["roofline_frac"] = min(1.0, ach_f / attain) if attain > 0 \
            else 0.0
    if nbytes > 0:
        ach_b = nbytes / seconds
        out["achieved_bytes_per_s"] = ach_b
        if flops <= 0:
            out["arithmetic_intensity"] = 0.0
            out["roofline_frac"] = min(1.0, ach_b / peaks.bytes_per_s) \
                if peaks.bytes_per_s > 0 else 0.0
    return out


# ---------------------------------------------------------------------------
# the per-stage report (consumed into stage metrics)
# ---------------------------------------------------------------------------

_REPORTS: dict[str, dict] = {}          # tag -> last report (exposition)
_MAX_REPORTS = 256


def stage_report(tag: str, mm_budget: int = 0,
                 owner: int = 0) -> Optional[dict]:
    """Consume the stage's dispatch window and combine it with the
    executable's StageCost into FLAT NUMERIC metrics (they ride the
    stage metrics dict through Metrics.stage_breakdown unchanged):

    device_s / device_cold_s / device_dispatches, flops / device_bytes
    (analysis x dispatch count), hbm_peak (per-execution peak footprint),
    roofline_frac (warm-median seconds vs the platform roof; a stage
    dispatched only cold falls back to the SMALLEST sample — still
    compile/load-inclusive, so it UNDERSTATES utilization — warm runs
    self-correct it), and hbm_budget_frac when the MemoryManager budget
    is known. Also updates the bounded exposition snapshot (telemetry
    /metrics gauges) and feeds the warm median to the split tuner once
    per stage per process."""
    if not _enabled or not tag:
        return None
    with _LOCK:
        acc = _DISP.pop((owner, tag), None)
    if acc is None or acc["n"] == 0:
        return None
    cost = cost_for_tag(tag)
    rep: dict = {
        "device_s": acc["device_s"],
        "device_cold_s": acc["cold_s"],
        "device_dispatches": acc["n"],
    }
    warm = sorted(acc["warm"])
    warm_med = warm[len(warm) // 2] if warm else 0.0
    if warm_med > 0 and tag not in _tuner_fed:
        _tuner_fed.add(tag)
        try:        # the first real device-cost feature in the tuner
            from ..plan.splittuner import model_for

            model_for().record_device_dispatch(warm_med)
        except Exception:   # pragma: no cover - model is best-effort
            pass
    if cost is not None:
        rep["flops"] = cost.flops * acc["n"]
        rep["device_bytes"] = cost.bytes_accessed * acc["n"]
        rep["hbm_peak"] = cost.peak_bytes
        # cold-only fallback: the smallest observed sample is the least
        # compile/load-inflated one (a mean over cold samples would bury
        # the execution under the compile wait entirely)
        rl = roofline(cost.flops, cost.bytes_accessed,
                      warm_med if warm_med > 0 else acc["min_s"])
        if "roofline_frac" in rl:
            rep["roofline_frac"] = rl["roofline_frac"]
        if "arithmetic_intensity" in rl:
            rep["arithmetic_intensity"] = rl["arithmetic_intensity"]
        if "achieved_flops_per_s" in rl:
            rep["achieved_flops_per_s"] = rl["achieved_flops_per_s"]
        if mm_budget > 0:
            # vs the JOB's MemoryManager budget (tuplex.executorMemory /
            # the serve per-job memory cap) — a capacity-planning signal,
            # not a device-HBM measurement on CPU backends
            rep["hbm_budget_frac"] = cost.peak_bytes / mm_budget
    with _LOCK:
        _REPORTS[tag] = dict(rep)
        while len(_REPORTS) > _MAX_REPORTS:
            _REPORTS.pop(next(iter(_REPORTS)))
    _index_update(tag, rep, cost)
    return rep


def reports() -> dict:
    """Last report per stage tag (the /metrics exposition source)."""
    with _LOCK:
        return {t: dict(r) for t, r in _REPORTS.items()}


# ---------------------------------------------------------------------------
# the persistent stage index (compilestats' plan-time lookup)
# ---------------------------------------------------------------------------

_INDEX_NAME = "devprof_stages.json"
_INDEX_MAX = 512
#: min seconds between full index rewrites per process — the index is a
#: read-parse-rewrite of one JSON file, so a busy serve loop must not
#: pay O(index) disk I/O on every stage consume. A tag not yet in the
#: index always writes through (first measurement beats freshness).
_INDEX_WRITE_EVERY_S = 5.0
_index_last_write = 0.0
_index_known: set = set()           # tags this process already indexed


def _index_path() -> Optional[str]:
    from .jaxcfg import aot_cache_dir

    d = aot_cache_dir()
    return os.path.join(d, _INDEX_NAME) if d else None


def _index_update(tag: str, rep: dict, cost: Optional[StageCost]) -> None:
    """Fold one stage report into the on-disk tag index. ``stage.key()``
    is content-derived (ops + UDF sources + schema), so a later
    ``compilestats`` run planning the same script computes the same tag
    and finds the measured record without executing anything."""
    path = _index_path()
    if path is None:
        return
    global _index_last_write
    now = time.monotonic()
    if tag in _index_known \
            and now - _index_last_write < _INDEX_WRITE_EVERY_S:
        return          # refresh later; the in-memory report is current
    try:
        idx = load_stage_index()
        entry = {"updated": time.time(),
                 "device_s_per_dispatch":
                     rep["device_s"] / max(1, rep["device_dispatches"]),
                 "device_dispatches": rep["device_dispatches"],
                 "roofline_frac": rep.get("roofline_frac"),
                 "analysis": cost.to_dict() if cost is not None else None}
        idx[tag] = entry
        if len(idx) > _INDEX_MAX:
            for k, _ in sorted(idx.items(),
                               key=lambda kv: kv[1].get("updated", 0)) \
                    [: len(idx) - _INDEX_MAX]:
                idx.pop(k, None)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(idx, f)
        os.replace(tmp, path)      # atomic; cross-process last-writer-
        _index_known.add(tag)      # wins is acceptable for a best-
        _index_last_write = now    # effort measurement index
    except Exception:   # pragma: no cover - index is best-effort
        pass


def load_stage_index() -> dict:
    """tag -> {device_s_per_dispatch, analysis|None, ...} from the cache
    dir (empty when nothing ever ran)."""
    path = _index_path()
    if path is None or not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            d = json.load(f)
        return d if isinstance(d, dict) else {}
    except Exception:   # pragma: no cover - corrupt index = empty
        return {}


# ---------------------------------------------------------------------------
# lifecycle (tests)
# ---------------------------------------------------------------------------


def clear() -> None:
    global _peaks_cache, _index_last_write
    with _LOCK:
        _BY_FP.clear()
        _BY_TAG.clear()
        _DISP.clear()
        _REPORTS.clear()
    _tuner_fed.clear()
    _index_known.clear()
    _index_last_write = 0.0
    _peaks_cache = None


# human-readable helpers — ONE threshold ladder for every surface that
# prints flops/bytes counts (compilestats, the dashboard device table)

def fmt_eng(v: float, unit: str = "") -> str:
    """Engineering notation: 1.2G / 3.4M / 5.6k; with a unit the number
    gets a separating space ("1.2 GFLOP")."""
    v = float(v)
    sep = " " if unit else ""
    for prefix, div in (("G", 1e9), ("M", 1e6), ("k", 1e3)):
        if abs(v) >= div:
            return f"{v / div:.1f}{sep}{prefix}{unit}"
    return f"{v:.0f}{sep}{unit}"


def fmt_flops(v: float) -> str:
    return fmt_eng(v, "FLOP")


def fmt_bytes(v: float) -> str:
    return fmt_eng(v, "B")
