"""Single-buffer transfer packing for stage dispatch.

The tunneled PJRT data plane pays a fixed per-buffer cost in both
directions: staging the zillow batch (~60 leaf arrays, 24 MB) measured
113 MB/s against 290-830 MB/s for one contiguous buffer, and fetching the
~43 output arrays (17 MB) ran at 56 MB/s (tpu_diag/count_dispatches.py on
the live v5e). Packing every leaf into ONE uint8 buffer per direction —
with the unpack/pack bitcasts fused into the stage executable — collapses
those per-buffer round-trips into one H2D and one D2H.

Reference analog: the C++ runtime ships whole partitions as single memory
blocks (tuplex/core/include/Partition.h) rather than per-column buffers;
this is the same idea applied to the PJRT transfer layer.

Host side packs with numpy views (memcpy only); device side slices +
bitcast_convert_type inside the jit, so XLA sees static offsets and the
donated input buffer can be reused for the output.
"""

from __future__ import annotations

import numpy as np

from .jaxcfg import jax, jnp

# segment alignment inside the packed buffer: large enough that every
# element-typed view of a segment is aligned (max itemsize 8; 64 also
# keeps cache-line alignment), small enough that a many-leaf stage does
# not bleed KBs of padding per partition (512 cost ~18 KB/partition on
# zillow's ~35 output leaves)
_ALIGN = 64


def packing_enabled() -> bool:
    """Default: pack on accelerator backends (the per-buffer RPC tax is a
    tunnel/PCIe property); CPU 'transfers' are pointer handoffs where the
    extra memcpy is pure loss. TUPLEX_PACK_TRANSFERS=0/1 overrides (tests
    force it on under CPU for parity coverage)."""
    import os

    mode = os.environ.get("TUPLEX_PACK_TRANSFERS", "auto")
    if mode in ("0", "1"):
        return mode == "1"
    return jax.default_backend() != "cpu"


def _pad(nb: int) -> int:
    return -(-nb // _ALIGN) * _ALIGN


def _packable(dtype) -> bool:
    """Dtypes the in-executable bitcasts handle on every backend. 64-bit
    ints split into u32 halves arithmetically (the XLA-TPU x64 legalizer
    has no rule for 64-bit bitcast-convert inside large stage graphs, and
    f64<->int bitcasts fail outright on the current TPU stack — probed on
    the live chip); f64 and anything exotic transfer per-leaf instead."""
    return np.dtype(dtype) in (np.dtype(np.uint8), np.dtype(np.bool_),
                               np.dtype(np.int8), np.dtype(np.int16),
                               np.dtype(np.uint16), np.dtype(np.int32),
                               np.dtype(np.uint32), np.dtype(np.float32),
                               np.dtype(np.int64), np.dtype(np.uint64))


# wire-dtype markers beyond plain numpy dtype strs:
#   "b1"     1-D bool bitpacked little-endian, 8 rows/byte (both directions)
#   "lo4i"   i64 shipped as its low u32 word; high words ride the varlen
#   "lo4u"   u64 same — payload carries (rare) rows whose high word isn't
#            the low word's sign/zero extension (output direction only)
#   "pb<N>"  '#rowidx' as a survivor bitmap over the padded input size N:
#            the compaction contract (physical.py) keeps the indices
#            ascending+unique with sentinel N for dead tail slots, so a
#            bit per INPUT row reconstructs them exactly (output only)
_BITS = "b1"
_LO32 = {"<i8": "lo4i", "<u8": "lo4u"}


def _wire_nbytes(shape, wdt: str) -> int:
    n = int(np.prod(shape)) if shape else 1
    if wdt == _BITS:
        return (n + 7) // 8
    if wdt in ("lo4i", "lo4u"):
        return n * 4
    if wdt.startswith("pb"):
        return (int(wdt[2:]) + 7) // 8
    return n * np.dtype(wdt).itemsize


def _wire_dtype(k: str, dtype, arrays, check_values: bool = False) -> str:
    """Transfer dtype (str, possibly a marker) for a leaf.

    * 1-D bool leaves bitpack 8 rows/byte ('#keep', '#rowvalid', Option
      validity — an 8x cut on every boolean lattice column).
    * A '#len' column is bounded by its sibling byte matrix's padded
      width, so it narrows to u16 (or u8 when the width fits a byte) and
      re-widens on arrival. ('#err' is NOT narrowed: it packs
      class|op_id<<8 and operator ids come from a session-global counter,
      so values exceed u16.)
    * '#rowidx' values are bounded by the padded INPUT size (sentinel
      included), visible statically as '#err'.shape — u16 when it fits.

    check_values (host pack path only — device values are traced):
    the len<=padded-width invariant is enforced nowhere upstream, so a
    '*#len' leaf carrying values past the narrowed range (or a negative
    sentinel) would silently wrap on the wire; such leaves fall back to
    their declared dtype (ADVICE round 5)."""
    dt = np.dtype(dtype)
    a = arrays.get(k)
    if dt == np.dtype(np.bool_) and getattr(a, "ndim", 0) == 1:
        return _BITS
    if dt == np.dtype(np.int32) and k.endswith("#len"):
        sib = arrays.get(k[:-4] + "#bytes")
        if sib is not None and getattr(sib, "ndim", 0) == 2 \
                and sib.shape[1] < (1 << 16):
            narrow = np.uint8 if sib.shape[1] <= 0xFF else np.uint16
            if check_values:
                av = np.asarray(a)
                if av.size and (int(av.max()) > int(np.iinfo(narrow).max)
                                or int(av.min()) < 0):
                    return dt.str
            return np.dtype(narrow).str
    if dt == np.dtype(np.int32) and k == "#rowidx":
        err = arrays.get("#err")
        b_in = err.shape[0] if err is not None \
            and getattr(err, "ndim", 0) == 1 else None
        if b_in is not None and not check_values:
            # device direction: the compaction contract (ascending,
            # unique, sentinel=b_in) is structural — a bit per input row
            return f"pb{b_in}"
        if b_in is not None and b_in < (1 << 16):
            av = np.asarray(a)
            if not av.size or (int(av.max()) < (1 << 16)
                               and int(av.min()) >= 0):
                return np.dtype(np.uint16).str
    return dt.str


def _host_spec(arrays: dict, check_values: bool = True):
    """Deterministic layout: (key, shape, dtype_str, offset, wire_nbytes,
    wire_dtype_str). ``check_values=False`` computes the layout from
    shapes/dtypes alone (ShapeDtypeStruct avals work) — the PREDICTED spec
    the AOT prewarm compiles against; it matches the dispatch-time spec
    whenever the '#len' narrowing invariant holds (the normal case — a
    violating partition just compiles its own wide-layout variant)."""
    spec = []
    off = 0
    for k in sorted(arrays):
        a = arrays[k]
        if not _packable(a.dtype):
            continue
        wd = _wire_dtype(k, a.dtype, arrays, check_values=check_values)
        nb = _wire_nbytes(a.shape, wd)
        spec.append((k, tuple(a.shape), np.dtype(a.dtype).str, off, nb, wd))
        off += _pad(nb)
    return tuple(spec), off


def _pack_host(arrays: dict, spec, total: int) -> np.ndarray:
    buf = np.zeros(total, dtype=np.uint8)
    for k, shape, dt, off, nb, wdt in spec:
        if not nb:
            continue
        a = np.ascontiguousarray(arrays[k])
        if wdt == _BITS:
            bits = np.packbits(a.astype(np.bool_).reshape(-1),
                               bitorder="little")
            buf[off:off + nb] = bits
            continue
        if wdt != dt:
            a = np.ascontiguousarray(a.astype(np.dtype(wdt)))
        buf[off:off + nb] = a.view(np.uint8).reshape(-1)
    return buf


def _unpack_host(buf: np.ndarray, spec) -> dict:
    out = {}
    for k, shape, dt, off, nb, wdt in spec:
        dtype = np.dtype(dt)
        n = int(np.prod(shape)) if shape else 1
        if not nb:
            out[k] = np.zeros(shape, dtype=dtype)
            continue
        if wdt == _BITS:
            seg = np.frombuffer(buf, dtype=np.uint8, count=nb, offset=off)
            out[k] = np.unpackbits(seg, bitorder="little")[:n] \
                .astype(np.bool_).reshape(shape)
            continue
        if wdt in ("lo4i", "lo4u"):
            lo = np.frombuffer(buf, dtype=np.uint32, count=n, offset=off)
            # sign/zero-extend the low word; rows whose high word differs
            # are patched from the varlen payload (_unpack_varlen)
            out[k] = (lo.astype(np.int32).astype(np.int64)
                      if wdt == "lo4i"
                      else lo.astype(np.uint64)).astype(dtype) \
                .reshape(shape)
            continue
        if wdt.startswith("pb"):
            b_in = int(wdt[2:])
            seg = np.frombuffer(buf, dtype=np.uint8, count=nb, offset=off)
            pos = np.nonzero(
                np.unpackbits(seg, bitorder="little")[:b_in])[0]
            arr = np.full(n, b_in, dtype=dtype)   # sentinel tail slots
            arr[:min(len(pos), n)] = pos[:n]
            out[k] = arr.reshape(shape)
            continue
        wdtype = np.dtype(wdt)
        # zero-copy views: offsets are _ALIGN-ed so every element aligns
        arr = np.frombuffer(buf, dtype=wdtype,
                            count=nb // wdtype.itemsize,
                            offset=off).reshape(shape)
        out[k] = arr.astype(dtype) if wdtype != dtype else arr
    return out


def _device_unpack(buf, spec):
    """Traced: one u8 buffer -> dict of typed arrays (static slices +
    bitcasts; XLA fuses these into the stage executable). 64-bit ints
    combine from u32 halves arithmetically — no 64-bit bitcast reaches
    the TPU x64 legalizer."""
    out = {}
    for k, shape, dt, off, nb, wdt in spec:
        seg = buf[off:off + nb]
        if wdt == _BITS:
            n = int(np.prod(shape)) if shape else 1
            bits = (seg[:, None] >> jnp.arange(8, dtype=jnp.uint8)) \
                & jnp.uint8(1)
            out[k] = bits.reshape(-1)[:n].astype(jnp.bool_).reshape(shape)
            continue
        dtype = np.dtype(wdt)
        if dtype == np.uint8:
            arr = seg.reshape(shape)
        elif dtype == np.bool_:
            arr = seg.reshape(shape).astype(jnp.bool_)
        elif dtype.itemsize == 8:
            halves = jax.lax.bitcast_convert_type(
                seg.reshape(tuple(shape) + (2, 4)), jnp.uint32)
            lo = halves[..., 0].astype(jnp.uint64)
            hi = halves[..., 1].astype(jnp.uint64)
            arr = (lo | (hi << jnp.uint64(32))).astype(jnp.dtype(dt))
        else:
            it = dtype.itemsize
            arr = jax.lax.bitcast_convert_type(
                seg.reshape(tuple(shape) + (it,)), jnp.dtype(dtype))
        if dtype != np.dtype(dt) and arr.dtype != np.dtype(dt):
            arr = arr.astype(jnp.dtype(dt))     # re-widen narrowed wires
        out[k] = arr
    return out


def _device_pack(outs: dict, skip=(), lo32: dict | None = None):
    """Traced: dict of packable arrays -> (u8 buffer, spec). Keys in
    `skip` ride the varlen payload but stay visible here so wire
    narrowing still sees its siblings; keys in `lo32` ship only their low
    u32 word here (high words ride the varlen payload)."""
    lo32 = lo32 or {}
    segs = []
    spec = []
    off = 0
    for k in sorted(outs):
        if k in skip:
            continue
        v = jnp.asarray(outs[k])
        orig_dt = np.dtype(v.dtype).str
        if k in lo32:
            wd = _LO32[orig_dt]
            u = jax.lax.bitcast_convert_type(lo32[k], jnp.uint8).reshape(-1)
        else:
            wd = _wire_dtype(k, np.dtype(v.dtype), outs)
            if wd == _BITS:
                u = _bitpack_dev(v)
            elif wd.startswith("pb"):
                b_in = int(wd[2:])
                bm = jnp.zeros(b_in, jnp.bool_).at[v].set(True, mode="drop")
                u = _bitpack_dev(bm)
            else:
                if np.dtype(wd) != np.dtype(v.dtype):
                    v = v.astype(jnp.dtype(wd))     # narrowed wire dtype
                if v.dtype == jnp.uint8:
                    u = v.reshape(-1)
                elif v.dtype == jnp.bool_:
                    u = v.astype(jnp.uint8).reshape(-1)
                elif v.dtype.itemsize == 8:
                    w = v.astype(jnp.uint64) if v.dtype == jnp.int64 else v
                    lo = (w & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
                    hi = (w >> jnp.uint64(32)).astype(jnp.uint32)
                    halves = jnp.stack([lo, hi], axis=-1)
                    u = jax.lax.bitcast_convert_type(
                        halves, jnp.uint8).reshape(-1)
                else:
                    u = jax.lax.bitcast_convert_type(
                        v, jnp.uint8).reshape(-1)
        nb = int(u.shape[0])
        pad = _pad(nb) - nb
        if pad:
            u = jnp.pad(u, (0, pad))
        segs.append(u)
        spec.append((k, tuple(v.shape), orig_dt, off, nb, wd))
        off += _pad(nb)
    buf = jnp.concatenate(segs) if segs else jnp.zeros(0, jnp.uint8)
    return buf, tuple(spec)


def _varlen_str_keys(outs: dict) -> tuple:
    """Output keys eligible for the varlen string wire: 2-D u8 '#bytes'
    matrices with an int '#len' sibling (the StrLeaf layout). Sorted so
    the device payload order and the host re-derivation agree byte for
    byte."""
    ks = []
    for k in sorted(outs):
        if not k.endswith("#bytes"):
            continue
        v = outs[k]
        lk = k[:-6] + "#len"
        if getattr(v, "ndim", 0) == 2 and np.dtype(v.dtype) == np.uint8 \
                and lk in outs \
                and np.dtype(outs[lk].dtype).kind in "iu":
            ks.append(k)
    return tuple(ks)


def _varlen_i64_keys(outs: dict, str_keys: tuple) -> tuple:
    """1-D 64-bit leaves whose high words ride the varlen payload (the
    low word ships fixed as u32). On data like zillow the values fit 32
    bits almost everywhere, so this halves every i64 column."""
    skip = set(str_keys)
    return tuple(k for k in sorted(outs)
                 if k not in skip
                 and getattr(outs[k], "ndim", 0) == 1
                 and np.dtype(outs[k].dtype) in (np.dtype(np.int64),
                                                 np.dtype(np.uint64)))


def _live_masks(args, outs):
    """(live_slot, live_input) bool masks — rows the host merge can ever
    read from the fast-path outputs (rowvalid & keep & err==0, mapped
    through '#rowidx' for compacted outputs). Dead rows' varlen bytes are
    suppressed: padding/filtered/errored slots would otherwise ship
    garbage content over the ~50 MB/s tunnel. None when the outputs don't
    carry the stage lattice (non-stage uses of the packer)."""
    keep = outs.get("#keep")
    err = outs.get("#err")
    if keep is None or err is None or getattr(keep, "ndim", 0) != 1 \
            or getattr(err, "shape", None) != keep.shape:
        return None, None
    live = keep & (err == 0)
    rv = args.get("#rowvalid") if isinstance(args, dict) else None
    if rv is not None and getattr(rv, "shape", None) == live.shape:
        live = live & rv
    rowidx = outs.get("#rowidx")
    if rowidx is None or getattr(rowidx, "ndim", 0) != 1:
        return live, live
    b_in = live.shape[0]
    ri = jnp.clip(rowidx, 0, b_in - 1)
    live_slot = live[ri] & (rowidx < b_in)
    return live_slot, live


def _bitpack_dev(v):
    """Traced: 1-D bool -> little-endian bitpacked u8[ceil(n/8)]."""
    n = int(v.shape[0])
    nb8 = (n + 7) // 8
    b = v.astype(jnp.int32)
    if nb8 * 8 != n:
        b = jnp.pad(b, (0, nb8 * 8 - n))
    return (b.reshape(nb8, 8) << jnp.arange(8, dtype=jnp.int32)) \
        .sum(axis=1).astype(jnp.uint8)


def _u32_bytes(v):
    return jax.lax.bitcast_convert_type(v.astype(jnp.uint32), jnp.uint8)


def _device_pack_varlen(entries: list):
    """Traced: scatter every varlen entry's actual row bytes into ONE
    contiguous payload buffer. entries: (kind, key, mat u8 [B, w],
    lens i32 [B], dt_str). Capacity is the static worst case so the
    executable is shape-stable; the host fetches only payload[:total]
    after re-deriving the per-row lengths from the fixed buffer."""
    lens = [e[3].astype(jnp.int64) for e in entries]
    all_lens = jnp.concatenate(lens)
    offs = jnp.cumsum(all_lens) - all_lens          # exclusive cumsum
    cap = _pad(sum(int(e[2].shape[0] * e[2].shape[1]) for e in entries))
    payload = jnp.zeros(max(cap, 1), jnp.uint8)
    vspec = []
    row0 = 0
    for (kind, k, mat, ln, dt), ln64 in zip(entries, lens):
        b, w = mat.shape
        o = offs[row0:row0 + b]
        idx = o[:, None] + jnp.arange(w, dtype=o.dtype)[None, :]
        m = jnp.arange(w, dtype=jnp.int32)[None, :] < \
            ln.astype(jnp.int32)[:, None]
        idx = jnp.where(m, idx, cap)                # OOB -> dropped
        payload = payload.at[idx.reshape(-1)].set(
            mat.reshape(-1), mode="drop")
        vspec.append((kind, k, (b, w), dt))
        row0 += b
    return payload, tuple(vspec)


def _build_varlen(args, outs, pack_outs):
    """Assemble the varlen plan inside the trace. Mutates pack_outs
    (masked lens, synthetic '#need' bitmaps) and returns
    (entries, skip_keys, lo32)."""
    entries = []
    skip = set()
    lo32 = {}
    live_slot, live_in = _live_masks(args, pack_outs)
    str_keys = _varlen_str_keys(pack_outs)
    if live_slot is not None:
        # ship the liveness mask (bitpacked) so the host derives the same
        # layout lengths WITHOUT altering the '#len' leaves — dead slots
        # (padding/filtered/errored; unread by every consumer, the merge
        # gathers only rowvalid & keep & err==0 rows) contribute zero
        # payload bytes instead of garbage content
        pack_outs["#live"] = live_slot
    # -- str leaves: actual bytes instead of padded [B, W] matrices ------
    for bk in str_keys:
        lk = bk[:-6] + "#len"
        mat = jnp.asarray(pack_outs[bk])
        b, w = mat.shape
        ln = jnp.clip(jnp.asarray(pack_outs[lk]).astype(jnp.int32)
                      .reshape(-1), 0, w)
        if live_slot is not None and live_slot.shape == ln.shape:
            ln = ln * live_slot
        entries.append(("str", bk, mat, ln, "|u1"))
        skip.add(bk)
    # -- 64-bit leaves: low word u32, high words varlen ------------------
    for k in _varlen_i64_keys(pack_outs, tuple(skip)):
        v = jnp.asarray(pack_outs[k])
        dt = np.dtype(v.dtype)
        w64 = v.astype(jnp.uint64)
        lo = (w64 & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
        hi = (w64 >> jnp.uint64(32)).astype(jnp.uint32)
        sext = ((lo.astype(jnp.int32) >> 31).astype(jnp.uint32)
                if dt == np.dtype(np.int64) else jnp.uint32(0))
        need = hi != sext
        if live_slot is not None and live_slot.shape == need.shape:
            # liveness known: the low words ride the payload too, so dead
            # slots ship zero bytes instead of 4 garbage ones
            need = need & live_slot
            entries.append(("lo32v", k, _u32_bytes(lo),
                            live_slot.astype(jnp.int32) * 4, dt.str))
            skip.add(k)
        else:
            lo32[k] = lo                    # low word fixed-buffer u32
        pack_outs[k + "#need"] = need       # 1-D bool -> bitpacked wire
        entries.append(("hi32", k, _u32_bytes(hi),
                        need.astype(jnp.int32) * 4, dt.str))
    # -- '#err': zero-dominated lattice -> sparse nonzero codes ----------
    err = pack_outs.get("#err")
    if err is not None and getattr(err, "ndim", 0) == 1 \
            and np.dtype(err.dtype) == np.dtype(np.int32):
        ev = jnp.asarray(err)
        need = ev != 0
        rv = args.get("#rowvalid") if isinstance(args, dict) else None
        if rv is not None and getattr(rv, "shape", None) == need.shape:
            need = need & rv                # padding rows' codes are noise
        pack_outs["#err#need"] = need
        entries.append(("sparse32", "#err", _u32_bytes(ev),
                        need.astype(jnp.int32) * 4, "<i4"))
        skip.add("#err")
    return entries, tuple(sorted(skip)), lo32


class PackedOuts:
    """Async handle for a packed stage result: one fixed-layout device
    buffer + layout, an optional varlen payload buffer (str leaves as
    actual bytes), plus any per-leaf arrays whose dtype can't ride the
    buffer (f64)."""

    __slots__ = ("buf", "spec", "extras", "vbuf", "vspec")

    def __init__(self, buf, spec, extras=None, vbuf=None, vspec=()):
        self.buf = buf
        self.spec = spec
        self.extras = extras or {}
        self.vbuf = vbuf
        self.vspec = tuple(vspec or ())

    def to_host(self) -> dict:
        import os
        import time

        from . import tracing as TR
        from . import xferstats

        t0 = time.perf_counter()
        with TR.span("d2h:packed-fetch", "xfer") as _sp:
            host = np.asarray(jax.device_get(self.buf))
            out = _unpack_host(host, self.spec)
            fetched = host.nbytes
            if self.vspec:
                with TR.span("d2h:varlen-unpack", "xfer") as _vsp:
                    vb = self._unpack_varlen(out)
                    _vsp.set("bytes", vb)
                fetched += vb
            if self.extras:
                ex = jax.device_get(self.extras)
                fetched += sum(np.asarray(v).nbytes for v in ex.values())
                out.update(ex)
            _sp.set("bytes", fetched)
        xferstats.note_d2h(fetched, tag="packed_fetch")
        if os.environ.get("TUPLEX_PACK_DEBUG"):
            import sys

            print(f"[pack] d2h {fetched >> 20}MB ({len(self.vspec)} varlen"
                  f"+{len(self.extras)}x) "
                  f"{time.perf_counter() - t0:.3f}s", file=sys.stderr,
                  flush=True)
        return out

    def _unpack_varlen(self, out: dict) -> int:
        """Fetch payload[:total] and rebuild every varlen entry in place
        — str byte matrices, i64 high words, sparse '#err' codes. The
        per-row lengths re-derive deterministically from the fixed buffer
        (shipped lens / '#need' bitmaps), so no offsets travel. Returns
        bytes fetched."""
        from .columns import varlen_to_matrix

        live = out.pop("#live", None)
        lens = {}
        total = 0
        for kind, k, (b, w), dt in self.vspec:
            if kind == "str":
                ln = np.clip(np.asarray(out[k[:-6] + "#len"],
                                        dtype=np.int64).reshape(-1), 0, w)
                if live is not None and live.shape == ln.shape:
                    ln = ln * live
            elif kind == "lo32v":
                ln = np.asarray(live, dtype=np.int64) * 4
            else:
                ln = np.asarray(out[k + "#need"],
                                dtype=np.int64).reshape(-1) * 4
            lens[(kind, k)] = ln
            total += int(ln.sum())
        cap = int(self.vbuf.shape[0])
        want = min(_pad(total), cap) if total else 0
        payload = np.asarray(jax.device_get(self.vbuf[:want])) if want \
            else np.zeros(0, np.uint8)
        off = 0
        for kind, k, (b, w), dt in self.vspec:
            ln = lens[(kind, k)]
            offs = off + np.concatenate(
                [[0], np.cumsum(ln, dtype=np.int64)])[:-1]
            mat = varlen_to_matrix(payload, offs, ln, w)
            off += int(ln.sum())
            if kind == "str":
                out[k] = mat
                continue
            words = np.ascontiguousarray(
                np.ascontiguousarray(mat).view("<u4")[:, 0])
            if kind == "lo32v":
                # dead rows carried no bytes -> lo 0 -> value 0 (unread)
                out[k] = (words.astype(np.int32).astype(np.int64)
                          if np.dtype(dt) == np.dtype(np.int64)
                          else words.astype(np.uint64)).astype(np.dtype(dt))
                continue
            need = np.asarray(out.pop(k + "#need"), dtype=np.bool_)
            if kind == "sparse32":
                out[k] = np.where(need, words.view("<i4"),
                                  0).astype(np.dtype(dt))
            else:   # hi32: patch the rows whose high word isn't the
                    # low word's sign/zero extension
                base = np.asarray(out[k]).view(np.uint64)
                lo = base & np.uint64(0xFFFFFFFF)
                full = lo | (words.astype(np.uint64) << np.uint64(32))
                out[k] = np.where(need, full,
                                  base).view(np.dtype(dt))
        return payload.nbytes


class PackedStageFn:
    """Drop-in for jit(raw_fn): __call__(arrays_dict) -> PackedOuts.

    One compiled executable per input layout (same granularity as jit's
    shape retrace). The output layout is recorded as a trace side effect.

    With the varlen wire (runtime/jaxcfg.varlen_wire_enabled) str '#bytes'
    outputs leave the fixed buffer and ship as one contiguous payload of
    actual row bytes — on zillow that's the difference between ~170 B/row
    of padding and ~30 B of content over a ~50 MB/s tunnel."""

    def __init__(self, raw_fn, donate: bool, tag: str = "", n_ops: int = 0,
                 deadline=None):
        from .jaxcfg import varlen_wire_enabled

        self._raw = raw_fn
        self._donate = donate
        self._varlen = varlen_wire_enabled()
        self._fns: dict = {}
        self._tag = tag          # compile-seconds attribution (stage key)
        self._n_ops = n_ops      # feeds the stage-split tuner curve
        self._deadline = deadline   # compile deadline (CompileTimeout)

    def _make_entry(self, spec, ekey):
        """Build (and cache) the per-layout compiled entry: the traced
        closure that unpacks `spec`, runs the stage, and re-packs —
        shared verbatim by dispatch (__call__) and the AOT prewarm
        (``warm``), so both produce the SAME jaxpr and therefore the same
        content address in exec/compilequeue."""
        cell: dict = {}

        def traced(buf, extras):
            args = _device_unpack(buf, spec)
            args.update(extras)
            outs = self._raw(args)
            pack_outs = {k: v for k, v in outs.items()
                         if _packable(jnp.asarray(v).dtype)}
            extra_outs = {k: v for k, v in outs.items()
                          if k not in pack_outs}
            entries, vskip, lo32 = (
                _build_varlen(args, outs, pack_outs)
                if self._varlen else ([], (), {}))
            obuf, ospec = _device_pack(pack_outs, skip=vskip,
                                       lo32=lo32)
            vbuf, vspec = (_device_pack_varlen(entries) if entries
                           else (jnp.zeros(0, jnp.uint8), ()))
            cell["ospec"] = ospec
            cell["vspec"] = vspec
            return obuf, vbuf, extra_outs

        # content-addressed AOT route (exec/compilequeue): the trace —
        # which records ospec/vspec into `cell` as a side effect — runs
        # on every path (fingerprinting always traces); only the XLA
        # compile is skipped on a fingerprint or disk-artifact hit
        from ..exec.compilequeue import aot_jit

        fn = aot_jit(traced, donate=self._donate, salt="pack",
                     tag=self._tag, n_ops=self._n_ops,
                     deadline=self._deadline)
        entry = (fn, cell, traced)
        self._fns[(spec, ekey)] = entry
        return entry

    def warm(self, avals: dict):
        """Ahead-of-time compile against PREDICTED avals (the precompile
        driver's chained shape walk): derive the wire-buffer layout from
        the leaf avals alone and queue the packed executable's compile on
        the pool, so a varlen-wire stage finds its executable already
        built (or on disk) at first dispatch instead of compiling inline.
        Returns the pool Future, or None when the layout has no packable
        leaves. Speculative by construction: a value-dependent '#len'
        narrowing miss only wastes one background compile."""
        from ..exec import compilequeue as CQ

        spec, total = _host_spec(avals, check_values=False)
        if not spec:
            return None
        extras = {k: v for k, v in avals.items()
                  if not _packable(np.dtype(v.dtype))}
        ekey = tuple(sorted((k, tuple(v.shape), np.dtype(v.dtype).str)
                            for k, v in extras.items()))
        entry = self._fns.get((spec, ekey))
        if entry is None:
            entry = self._make_entry(spec, ekey)
        buf_aval = jax.ShapeDtypeStruct((total,), np.uint8)
        ex_avals = {k: jax.ShapeDtypeStruct(tuple(v.shape),
                                            np.dtype(v.dtype))
                    for k, v in extras.items()}
        return CQ.submit_compile(
            entry[2], (buf_aval, ex_avals),
            donate_argnums=(0,) if self._donate else (), salt="pack",
            tag=self._tag, n_ops=self._n_ops, deadline_s=self._deadline)

    def note_async_defect(self) -> bool:
        """Forward the async deserialize-defect verdict (see
        AotJit.note_async_defect) to every per-spec AOT route this
        packed fn built; True when any entry was pinned to the plain
        in-process jit."""
        hit = False
        for fn, _cell, _traced in self._fns.values():
            noted = getattr(fn, "note_async_defect", None)
            if noted is not None and noted():
                hit = True
        return hit

    def __call__(self, arrays: dict):
        spec, total = _host_spec(arrays)
        extras_in = {k: v for k, v in arrays.items()
                     if not _packable(v.dtype)}
        ekey = tuple(sorted((k, tuple(v.shape), v.dtype.str)
                            for k, v in extras_in.items()))
        entry = self._fns.get((spec, ekey))
        if entry is None:
            entry = self._make_entry(spec, ekey)
        fn, cell = entry[0], entry[1]
        import os

        if os.environ.get("TUPLEX_PACK_DEBUG"):
            import sys
            import time

            t0 = time.perf_counter()
            buf = _pack_host(arrays, spec, total)
            t1 = time.perf_counter()
            dbuf, vbuf, extra_outs = fn(jax.device_put(buf), extras_in)
            jax.block_until_ready(dbuf)
            print(f"[pack] host-pack {total >> 20}MB {t1 - t0:.3f}s; "
                  f"h2d+exec {time.perf_counter() - t1:.3f}s",
                  file=sys.stderr, flush=True)
            return PackedOuts(dbuf, cell["ospec"], extra_outs,
                              vbuf, cell["vspec"])
        from . import tracing as TR
        from . import xferstats

        h2d_bytes = 0
        with TR.span("h2d:packed-upload", "xfer") as _sp:
            buf = _pack_host(arrays, spec, total)
            h2d_bytes = buf.nbytes + sum(np.asarray(v).nbytes
                                         for v in extras_in.values())
            _sp.set("bytes", h2d_bytes)
            # explicit placement: measured 871 MB/s vs 534 MB/s letting
            # the jit call transfer its numpy argument over the tunnel
            dev = jax.device_put(buf)
        xferstats.note_h2d(h2d_bytes, tag="packed_dispatch")
        dbuf, vbuf, extra_outs = fn(dev, extras_in)
        return PackedOuts(dbuf, cell["ospec"], extra_outs,
                          vbuf, cell["vspec"])
