"""Single-buffer transfer packing for stage dispatch.

The tunneled PJRT data plane pays a fixed per-buffer cost in both
directions: staging the zillow batch (~60 leaf arrays, 24 MB) measured
113 MB/s against 290-830 MB/s for one contiguous buffer, and fetching the
~43 output arrays (17 MB) ran at 56 MB/s (tpu_diag/count_dispatches.py on
the live v5e). Packing every leaf into ONE uint8 buffer per direction —
with the unpack/pack bitcasts fused into the stage executable — collapses
those per-buffer round-trips into one H2D and one D2H.

Reference analog: the C++ runtime ships whole partitions as single memory
blocks (tuplex/core/include/Partition.h) rather than per-column buffers;
this is the same idea applied to the PJRT transfer layer.

Host side packs with numpy views (memcpy only); device side slices +
bitcast_convert_type inside the jit, so XLA sees static offsets and the
donated input buffer can be reused for the output.
"""

from __future__ import annotations

import numpy as np

from .jaxcfg import jax, jnp

_ALIGN = 512


def packing_enabled() -> bool:
    """Default: pack on accelerator backends (the per-buffer RPC tax is a
    tunnel/PCIe property); CPU 'transfers' are pointer handoffs where the
    extra memcpy is pure loss. TUPLEX_PACK_TRANSFERS=0/1 overrides (tests
    force it on under CPU for parity coverage)."""
    import os

    mode = os.environ.get("TUPLEX_PACK_TRANSFERS", "auto")
    if mode in ("0", "1"):
        return mode == "1"
    return jax.default_backend() != "cpu"


def _pad(nb: int) -> int:
    return -(-nb // _ALIGN) * _ALIGN


def _packable(dtype) -> bool:
    """Dtypes the in-executable bitcasts handle on every backend. 64-bit
    ints split into u32 halves arithmetically (the XLA-TPU x64 legalizer
    has no rule for 64-bit bitcast-convert inside large stage graphs, and
    f64<->int bitcasts fail outright on the current TPU stack — probed on
    the live chip); f64 and anything exotic transfer per-leaf instead."""
    return np.dtype(dtype) in (np.dtype(np.uint8), np.dtype(np.bool_),
                               np.dtype(np.int8), np.dtype(np.int16),
                               np.dtype(np.uint16), np.dtype(np.int32),
                               np.dtype(np.uint32), np.dtype(np.float32),
                               np.dtype(np.int64), np.dtype(np.uint64))


def _wire_dtype(k: str, dtype, arrays) -> np.dtype:
    """Transfer dtype for a leaf. A '#len' column is bounded by its
    sibling byte matrix's padded width, so when that width fits u16 the
    lens ride the ~50 MB/s download narrowed and re-widen on arrival.
    ('#err' is NOT narrowed: it packs class|op_id<<8 and operator ids
    come from a session-global counter, so values exceed u16.)"""
    if np.dtype(dtype) == np.dtype(np.int32) and k.endswith("#len"):
        sib = arrays.get(k[:-4] + "#bytes")
        if sib is not None and getattr(sib, "ndim", 0) == 2 \
                and sib.shape[1] < (1 << 16):
            return np.dtype(np.uint16)
    return np.dtype(dtype)


def _host_spec(arrays: dict):
    """Deterministic layout: (key, shape, dtype_str, offset, wire_nbytes,
    wire_dtype_str)."""
    spec = []
    off = 0
    for k in sorted(arrays):
        a = arrays[k]
        if not _packable(a.dtype):
            continue
        wd = _wire_dtype(k, a.dtype, arrays)
        nb = a.size * wd.itemsize
        spec.append((k, tuple(a.shape), a.dtype.str, off, nb, wd.str))
        off += _pad(nb)
    return tuple(spec), off


def _pack_host(arrays: dict, spec, total: int) -> np.ndarray:
    buf = np.zeros(total, dtype=np.uint8)
    for k, shape, dt, off, nb, wdt in spec:
        if nb:
            a = np.ascontiguousarray(arrays[k])
            if wdt != dt:
                a = np.ascontiguousarray(a.astype(np.dtype(wdt)))
            buf[off:off + nb] = a.view(np.uint8).reshape(-1)
    return buf


def _unpack_host(buf: np.ndarray, spec) -> dict:
    out = {}
    for k, shape, dt, off, nb, wdt in spec:
        dtype = np.dtype(dt)
        wdtype = np.dtype(wdt)
        if not nb:
            out[k] = np.zeros(shape, dtype=dtype)
            continue
        # zero-copy views: offsets are _ALIGN-ed so every element aligns
        arr = np.frombuffer(buf, dtype=wdtype,
                            count=nb // wdtype.itemsize,
                            offset=off).reshape(shape)
        out[k] = arr.astype(dtype) if wdtype != dtype else arr
    return out


def _device_unpack(buf, spec):
    """Traced: one u8 buffer -> dict of typed arrays (static slices +
    bitcasts; XLA fuses these into the stage executable). 64-bit ints
    combine from u32 halves arithmetically — no 64-bit bitcast reaches
    the TPU x64 legalizer."""
    out = {}
    for k, shape, dt, off, nb, wdt in spec:
        dtype = np.dtype(wdt)
        seg = buf[off:off + nb]
        if dtype == np.uint8:
            arr = seg.reshape(shape)
        elif dtype == np.bool_:
            arr = seg.reshape(shape).astype(jnp.bool_)
        elif dtype.itemsize == 8:
            halves = jax.lax.bitcast_convert_type(
                seg.reshape(tuple(shape) + (2, 4)), jnp.uint32)
            lo = halves[..., 0].astype(jnp.uint64)
            hi = halves[..., 1].astype(jnp.uint64)
            arr = (lo | (hi << jnp.uint64(32))).astype(jnp.dtype(dt))
        else:
            it = dtype.itemsize
            arr = jax.lax.bitcast_convert_type(
                seg.reshape(tuple(shape) + (it,)), jnp.dtype(dtype))
        if dtype != np.dtype(dt) and arr.dtype != np.dtype(dt):
            arr = arr.astype(jnp.dtype(dt))     # re-widen narrowed wires
        out[k] = arr
    return out


def _device_pack(outs: dict):
    """Traced: dict of packable arrays -> (u8 buffer, spec)."""
    segs = []
    spec = []
    off = 0
    for k in sorted(outs):
        v = jnp.asarray(outs[k])
        orig_dt = np.dtype(v.dtype).str
        wd = _wire_dtype(k, np.dtype(v.dtype), outs)
        if wd != np.dtype(v.dtype):
            v = v.astype(jnp.dtype(wd))         # narrowed wire dtype
        if v.dtype == jnp.uint8:
            u = v.reshape(-1)
        elif v.dtype == jnp.bool_:
            u = v.astype(jnp.uint8).reshape(-1)
        elif v.dtype.itemsize == 8:
            w = v.astype(jnp.uint64) if v.dtype == jnp.int64 else v
            lo = (w & jnp.uint64(0xFFFFFFFF)).astype(jnp.uint32)
            hi = (w >> jnp.uint64(32)).astype(jnp.uint32)
            halves = jnp.stack([lo, hi], axis=-1)
            u = jax.lax.bitcast_convert_type(halves, jnp.uint8).reshape(-1)
        else:
            u = jax.lax.bitcast_convert_type(v, jnp.uint8).reshape(-1)
        nb = int(u.shape[0])
        pad = _pad(nb) - nb
        if pad:
            u = jnp.pad(u, (0, pad))
        segs.append(u)
        spec.append((k, tuple(v.shape), orig_dt, off, nb, wd.str))
        off += _pad(nb)
    buf = jnp.concatenate(segs) if segs else jnp.zeros(0, jnp.uint8)
    return buf, tuple(spec)


class PackedOuts:
    """Async handle for a packed stage result: one device buffer + layout,
    plus any per-leaf arrays whose dtype can't ride the buffer (f64)."""

    __slots__ = ("buf", "spec", "extras")

    def __init__(self, buf, spec, extras=None):
        self.buf = buf
        self.spec = spec
        self.extras = extras or {}

    def to_host(self) -> dict:
        import os
        import time

        t0 = time.perf_counter()
        host = np.asarray(jax.device_get(self.buf))
        out = _unpack_host(host, self.spec)
        if self.extras:
            out.update(jax.device_get(self.extras))
        if os.environ.get("TUPLEX_PACK_DEBUG"):
            import sys

            print(f"[pack] d2h {host.nbytes >> 20}MB+{len(self.extras)}x "
                  f"{time.perf_counter() - t0:.3f}s", file=sys.stderr,
                  flush=True)
        return out


class PackedStageFn:
    """Drop-in for jit(raw_fn): __call__(arrays_dict) -> PackedOuts.

    One compiled executable per input layout (same granularity as jit's
    shape retrace). The output layout is recorded as a trace side effect."""

    def __init__(self, raw_fn, donate: bool):
        self._raw = raw_fn
        self._donate = donate
        self._fns: dict = {}

    def __call__(self, arrays: dict):
        spec, total = _host_spec(arrays)
        extras_in = {k: v for k, v in arrays.items()
                     if not _packable(v.dtype)}
        ekey = tuple(sorted((k, v.shape, v.dtype.str)
                            for k, v in extras_in.items()))
        entry = self._fns.get((spec, ekey))
        if entry is None:
            cell = {}

            def traced(buf, extras):
                args = _device_unpack(buf, spec)
                args.update(extras)
                outs = self._raw(args)
                pack_outs = {k: v for k, v in outs.items()
                             if _packable(jnp.asarray(v).dtype)}
                extra_outs = {k: v for k, v in outs.items()
                              if k not in pack_outs}
                obuf, ospec = _device_pack(pack_outs)
                cell["ospec"] = ospec
                return obuf, extra_outs

            fn = jax.jit(traced, donate_argnums=0) if self._donate \
                else jax.jit(traced)
            entry = (fn, cell)
            self._fns[(spec, ekey)] = entry
        fn, cell = entry
        import os

        if os.environ.get("TUPLEX_PACK_DEBUG"):
            import sys
            import time

            t0 = time.perf_counter()
            buf = _pack_host(arrays, spec, total)
            t1 = time.perf_counter()
            dbuf, extra_outs = fn(jax.device_put(buf), extras_in)
            jax.block_until_ready(dbuf)
            print(f"[pack] host-pack {total >> 20}MB {t1 - t0:.3f}s; "
                  f"h2d+exec {time.perf_counter() - t1:.3f}s",
                  file=sys.stderr, flush=True)
            return PackedOuts(dbuf, cell["ospec"], extra_outs)
        buf = _pack_host(arrays, spec, total)
        # explicit placement: measured 871 MB/s vs 534 MB/s letting the jit
        # call transfer its numpy argument over the tunnel
        dbuf, extra_outs = fn(jax.device_put(buf), extras_in)
        return PackedOuts(dbuf, cell["ospec"], extra_outs)
