"""Latency-budget plane: critical-path attribution, SLOs, blame.

Four telemetry planes already record *what happened* to a job — spans
(runtime/tracing), latency histograms (runtime/telemetry), device time
(runtime/devprof) and exception tiers (runtime/excprof) — but none of
them *explains* a slow job. This module turns the span timeline into an
answer:

* **critical-path attribution** — :func:`analyze_events` sweeps a job's
  span stream and attributes every instant of its end-to-end wall to
  exactly ONE canonical bucket (:data:`BUCKETS`): admission wait, stage
  queue wait, the compile trace/lower/xla split, H2D, device, the two
  resolve tiers, D2H, merge, scheduler/other — plus an explicit
  ``unattributed`` remainder so coverage is honest. Concurrency is
  resolved by a fixed priority order (what the job was actually blocked
  on): device execution beats an overlapped pool compile (overlap IS
  the optimization — off the critical path by construction), while the
  narrow host-side passes (resolve tiers, transfers, merge) beat the
  broad wrappers that contain them. The sweep touches each timeline
  slice once, so orphaned or cross-thread spans can degrade coverage
  but can never double-count.
* **tenant SLO plane** — ``tuplex.serve.sloMs`` (global) and
  ``tuplex.serve.tenantSlos`` ("a:250,b:500") declare per-tenant
  latency objectives; :func:`record_job` folds each terminal job into
  per-tenant attainment counters and two burn-rate windows (fast =
  ``tuplex.serve.sloBurnWindowS``, slow = 5x), and the ``slo`` health
  check (runtime/telemetry) goes degraded on a burning fast window and
  unhealthy on a sustained (both-window) burn — the SRE multi-window
  burn-rate alert, in-process.
* **regression blame** — each tenant keeps an EWMA baseline budget
  vector (same fold as excprof's drift anchor: ``excprof.ewma_alpha``);
  a job whose wall exceeds the baseline by ``critpathSlowFactor`` is
  reported as *which bucket grew* (``serve:slow-job`` instant span, the
  dashboard budget panel, ``python -m tuplex_tpu whyslow``).

Kill switch: ``TUPLEX_CRITPATH=0`` — the disabled path allocates
nothing (same contract as devprof/excprof). Everything here is bounded:
at most ``_MAX_ENTRIES`` tenants / retained job budgets, window deques
are capped, and one analysis looks at at most ``_MAX_SPANS`` spans.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from typing import Optional

__all__ = [
    "BUCKETS", "enable", "enabled", "configure", "apply_options",
    "analyze_events", "analyze_ring", "record_job", "job_budget",
    "tenants", "tenant_report", "drop_tenant", "burn_rates",
    "attainment", "slo_for", "clear",
]

# ---------------------------------------------------------------------------
# canonical buckets
# ---------------------------------------------------------------------------

#: the exclusive budget vector every surface shares (bench JSON keys,
#: /metrics labels, dashboard panel rows, whyslow table) — order is the
#: display order: wait planes, compile split, data/compute planes,
#: resolve tiers, the catch-all, and the honest remainder
BUCKETS = (
    "admission_wait", "queue_wait",
    "compile_trace", "compile_lower", "compile_xla",
    "h2d", "device",
    "resolve_general", "resolve_interpreter",
    "d2h", "merge",
    "scheduler_other", "unattributed",
)

#: span-name prefix -> bucket, FIRST match wins (specific before
#: catch-all). Unknown span names fall into scheduler_other: they are
#: still attributable work — only timeline gaps are "unattributed".
_SPAN_BUCKET = (
    ("compile:trace", "compile_trace"),
    ("compile:lower", "compile_lower"),
    ("compile:xla", "compile_xla"),
    ("compile:aot-load", "compile_xla"),   # artifact load = compile plane
    ("compile:queue-wait", "compile_wait"),  # caller BLOCKED on the pool
    ("compile:", "scheduler_other"),       # cache probes, bookkeeping
    ("h2d:", "h2d"),
    ("d2h:", "d2h"),
    ("resolve:general", "resolve_general"),
    ("resolve:interpreter", "resolve_interpreter"),
    ("partition:merge", "merge"),
    ("partition:collect", "d2h"),          # result materialization plane
    ("partition:dispatch", "device"),      # exclusive time = launch+wait
)

#: sweep priority per bucket — when spans overlap, the highest priority
#: owns the slice (= what the job was blocked on). Narrow host-side
#: passes beat the wrappers containing them; device execution beats an
#: overlapped background compile (pool-compile overlap is off the
#: critical path — that overlap existing is the win, not a cost).
#: ``compile_wait`` is the exception that keeps the exclusion honest:
#: the caller-side compile:queue-wait span exists only while the job
#: thread is BLOCKED on the pool, so it outranks device and folds into
#: compile_xla in the reported vector (analyze_events) — a cold inline
#: compile is blamed on the compile plane, an overlapped pre-compile
#: (no wait span on the job thread) still costs nothing.
_PRIO = {
    "resolve_interpreter": 11, "resolve_general": 10, "merge": 9,
    "d2h": 8, "h2d": 7, "compile_wait": 6, "device": 5,
    "compile_xla": 4, "compile_lower": 3, "compile_trace": 2,
    "scheduler_other": 1,
}
_PRIO_BUCKET = {p: b for b, p in _PRIO.items()}
_N_PRIO = max(_PRIO.values()) + 1


def _classify(name: str) -> str:
    for prefix, bucket in _SPAN_BUCKET:
        if name.startswith(prefix):
            return bucket
    return "scheduler_other"


# ---------------------------------------------------------------------------
# gate + knobs (devprof/excprof discipline)
# ---------------------------------------------------------------------------

def _env_disabled() -> bool:
    return os.environ.get("TUPLEX_CRITPATH", "").strip().lower() in (
        "0", "false", "off")


_enabled = not _env_disabled()

_half_life_s = 120.0      # tuplex.tpu.critpathHalfLifeS (baseline EWMA)
_slow_factor = 1.5        # tuplex.tpu.critpathSlowFactor (wall vs EWMA)
_slo_ms = 0.0             # tuplex.serve.sloMs (0 = no SLO declared)
_tenant_slos: dict = {}   # tuplex.serve.tenantSlos overrides
_burn_window_s = 60.0     # tuplex.serve.sloBurnWindowS (fast; slow = 5x)
_slo_target = 0.9         # tuplex.serve.sloTarget (attainment objective;
                          # error budget = 1 - target)
_min_base_jobs = 3        # baseline jobs before blame may fire
_MIN_SLOW_S = 0.05        # absolute slack under the factor test so
                          # microsecond jitter on tiny jobs never flags
_MAX_ENTRIES = 1024       # bound on tenants AND retained job budgets
_MAX_SPANS = 2048         # spans one analysis will look at
_PATH_CAP = 96            # critical-path segments kept per job
_WINDOW_CAP = 4096        # (t, ok) samples per tenant burn window
_EMPTY: dict = {}         # allocation-free disabled-path return


def enable(on: bool = True) -> None:
    global _enabled
    _enabled = bool(on) and not _env_disabled()


def enabled() -> bool:
    return _enabled


def parse_slos(s) -> dict:
    """"a:250,b:500" -> {"a": 250.0, "b": 500.0} (per-tenant SLO ms);
    malformed entries are skipped, dicts pass through coerced."""
    if isinstance(s, dict):
        out = {}
        for k, v in s.items():
            try:
                out[str(k)] = float(v)
            except (TypeError, ValueError):
                continue
        return out
    out = {}
    for part in (s or "").split(","):
        part = part.strip()
        if not part or ":" not in part:
            continue
        k, _, v = part.partition(":")
        try:
            out[k.strip()] = float(v)
        except ValueError:
            continue
    return out


def configure(half_life_s: Optional[float] = None,
              slow_factor: Optional[float] = None,
              slo_ms: Optional[float] = None,
              tenant_slos=None,
              burn_window_s: Optional[float] = None,
              slo_target: Optional[float] = None,
              min_base_jobs: Optional[int] = None) -> None:
    global _half_life_s, _slow_factor, _slo_ms, _tenant_slos
    global _burn_window_s, _slo_target, _min_base_jobs
    if half_life_s is not None and half_life_s > 0:
        _half_life_s = float(half_life_s)
    if slow_factor is not None and slow_factor > 1.0:
        _slow_factor = float(slow_factor)
    if slo_ms is not None and slo_ms >= 0:
        _slo_ms = float(slo_ms)
    if tenant_slos is not None:
        _tenant_slos = parse_slos(tenant_slos)
    if burn_window_s is not None and burn_window_s > 0:
        _burn_window_s = float(burn_window_s)
    if slo_target is not None and 0.0 < slo_target < 1.0:
        _slo_target = float(slo_target)
    if min_base_jobs is not None and min_base_jobs >= 1:
        _min_base_jobs = int(min_base_jobs)


def apply_options(options) -> None:
    """Wire the process gate + knobs from ContextOptions. Like
    devprof/excprof, ``tuplex.tpu.critpath`` turns attribution ON,
    never off — the only OFF switches are the env kill switch and an
    explicit ``enable(False)``."""
    if options.get_bool("tuplex.tpu.critpath", True):
        enable(True)
    slo_ms = options.get_float("tuplex.serve.sloMs", -1.0)
    configure(
        half_life_s=options.get_float("tuplex.tpu.critpathHalfLifeS", 0.0)
        or None,
        slow_factor=options.get_float("tuplex.tpu.critpathSlowFactor", 0.0)
        or None,
        slo_ms=slo_ms if slo_ms >= 0 else None,
        tenant_slos=options.get_str("tuplex.serve.tenantSlos", "") or None,
        burn_window_s=options.get_float("tuplex.serve.sloBurnWindowS", 0.0)
        or None,
        slo_target=options.get_float("tuplex.serve.sloTarget", 0.0) or None)
    if _enabled:
        _ensure_health()


# ---------------------------------------------------------------------------
# state
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()

#: tenant -> {"baseline": {bucket: ewma_s}, "wall_ewma", "unattr_ewma",
#:            "t_last", "n_base", "jobs", "slo_ok", "slo_miss",
#:            "slow_jobs", "window": deque[(monotonic, ok)]}
_TEN: "OrderedDict[str, dict]" = OrderedDict()
#: job id -> {"budget": ..., "verdict": ...} (newest _MAX_ENTRIES)
_RECENT: "OrderedDict[str, dict]" = OrderedDict()

_health_registered = False
_HEALTH_OWNER = object()


def clear() -> None:
    global _health_registered
    with _LOCK:
        _TEN.clear()
        _RECENT.clear()
        _health_registered = False


def _tenant_locked(tenant: str) -> dict:
    t = _TEN.get(tenant)
    if t is None:
        while len(_TEN) >= _MAX_ENTRIES:
            _TEN.pop(next(iter(_TEN)))
        t = _TEN[tenant] = {
            "baseline": None, "wall_ewma": None, "unattr_ewma": 0.0,
            "t_last": time.monotonic(), "n_base": 0, "jobs": 0,
            "slo_ok": 0, "slo_miss": 0, "slow_jobs": 0,
            "window": deque(maxlen=_WINDOW_CAP)}
    return t


def tenants() -> list:
    with _LOCK:
        return list(_TEN)


def drop_tenant(tenant: str) -> None:
    """Release a retired tenant's baseline + SLO windows (the serve
    retention sweep calls this — a churning tenant population must not
    grow this registry forever)."""
    with _LOCK:
        _TEN.pop(tenant, None)


def slo_for(tenant: str) -> float:
    """Resolved SLO milliseconds for `tenant` (override, else global);
    0.0 = no SLO declared."""
    return float(_tenant_slos.get(tenant, _slo_ms))


# ---------------------------------------------------------------------------
# span-tree reconstruction + critical-path sweep
# ---------------------------------------------------------------------------

def _prepare(evts) -> tuple:
    """(spans, n_orphans, n_dropped): normalize the raw event dicts to
    (ts, end, prio, name) tuples and count structural damage — spans
    that CLAIM nesting (depth > 0) but have no containing span left in
    their thread (ring-buffer wrap or embed-cap truncation severed the
    tree), and cross-thread ``complete()`` spans that straddle their
    neighbors instead of nesting. Both degrade attribution to whatever
    coarse bars remain; the sweep itself makes double-counting
    impossible regardless."""
    spans = []
    for e in evts:
        try:
            dur = float(e.get("dur"))
            ts = float(e["ts"])
        except (KeyError, TypeError, ValueError):
            continue
        if dur <= 0 or dur != dur:        # instants carry no wall time
            continue
        spans.append((ts, ts + dur, e.get("tid", 0),
                      int(e.get("depth", 0) or 0), str(e.get("name", ""))))
    n_dropped = 0
    if len(spans) > _MAX_SPANS:
        n_dropped = len(spans) - _MAX_SPANS
        spans.sort(key=lambda s: s[0] - s[1])   # keep the longest
        spans = spans[:_MAX_SPANS]
    spans.sort(key=lambda s: (s[0], s[0] - s[1]))
    # pool threads run NOTHING but compile spans inside a job's window
    # (exec/compilequeue workers re-tag themselves into the submitter's
    # stream): a tid with any non-compile span is a job thread, and a
    # compile running there is inline — it blocks the job and must
    # outrank device in the sweep, unlike an overlapped pool compile
    pool_tids = {tid for _ts, _end, tid, _d, _n in spans}
    for _ts, _end, tid, _depth, name in spans:
        if not name.startswith("compile:"):
            pool_tids.discard(tid)
    n_orphans = 0
    eps = 1.0                             # µs slack for rounded embeds
    stacks: dict = {}
    for ts, end, tid, depth, _name in spans:
        stack = stacks.setdefault(tid, [])
        while stack and stack[-1][1] + eps < end:
            if stack[-1][1] > ts + eps:   # straddles instead of nesting:
                n_orphans += 1            # a cross-thread complete() span
                break
            stack.pop()
        if not stack and depth > 0:
            n_orphans += 1                # nested child, parent gone
        stack.append((ts, end))
    return spans, pool_tids, n_orphans, n_dropped


def _sweep(spans, t0: float, t1: float, pool_tids=frozenset()) -> tuple:
    """Priority sweep over [t0, t1]: every elementary timeline slice is
    owned by the highest-priority active bucket (or by ``unattributed``
    when nothing is active), so the per-bucket sums can never exceed
    the window and never count a slice twice. Compile spans on a JOB
    thread (tid not in `pool_tids`) are inline — they block the job, so
    their priority is boosted over device while their reported bucket
    keeps the trace/lower/xla split. Returns
    (bucket_us: dict, path: [[ts, dur, bucket, name], ...])."""
    inline_prio = _PRIO["compile_wait"]
    bounds = []
    for ts, end, tid, _depth, name in spans:
        s, e = max(ts, t0), min(end, t1)
        if e <= s:
            continue
        bucket = _classify(name)
        prio = _PRIO[bucket]
        if prio < inline_prio and bucket.startswith("compile_") \
                and tid not in pool_tids:
            prio = inline_prio
        bounds.append((s, 1, prio, bucket, name))
        bounds.append((e, 0, prio, bucket, name))
    bounds.sort(key=lambda b: (b[0], b[1]))
    counts = [0] * _N_PRIO
    active = [[] for _ in range(_N_PRIO)]   # (bucket, name) per level
    bucket_us: dict = {}
    path: list = []
    t_prev = t0
    i, n = 0, len(bounds)
    while i <= n:
        t_cur = bounds[i][0] if i < n else t1
        if t_cur > t_prev:
            win = 0
            for p in range(_N_PRIO - 1, 0, -1):
                if counts[p]:
                    win = p
                    break
            if win and active[win]:
                bucket, name = active[win][-1]
            elif win:
                bucket, name = _PRIO_BUCKET[win], ""
            else:
                bucket, name = "unattributed", ""
            dur = t_cur - t_prev
            bucket_us[bucket] = bucket_us.get(bucket, 0.0) + dur
            if path and path[-1][2] == bucket and path[-1][3] == name:
                path[-1][1] += dur        # merge adjacent same-owner
            else:
                path.append([t_prev, dur, bucket, name])
            t_prev = t_cur
        if i == n:
            break
        _t, is_start, prio, bucket, name = bounds[i]
        if is_start:
            counts[prio] += 1
            active[prio].append((bucket, name))
        else:
            counts[prio] -= 1
            try:
                active[prio].remove((bucket, name))
            except ValueError:
                pass
        i += 1
    return bucket_us, path


def analyze_events(evts, wall_s: Optional[float] = None,
                   queued_s: float = 0.0, stage_queue_s: float = 0.0,
                   t0_us: Optional[float] = None,
                   t1_us: Optional[float] = None) -> Optional[dict]:
    """Attribute one job's end-to-end wall into the canonical exclusive
    bucket vector. `evts` is the job's span stream (tracing event
    dicts or recorder-embedded slices); `queued_s`/`stage_queue_s` are
    the scheduler's admission / stage-requeue waits (they happen while
    no span is active, so they ride in as scalars); `t0_us`/`t1_us`
    bound the running window on the trace clock (``tracing.
    to_trace_us``) — when omitted the span extent stands in. Returns
    None when disabled; never raises on damaged input — orphans and
    wrapped rings degrade to coarse bars with ``unattributed``
    absorbing the gap."""
    if not _enabled:
        return None
    spans, pool_tids, n_orphans, n_dropped = _prepare(evts or [])
    if spans:
        lo = min(s[0] for s in spans)
        hi = max(s[1] for s in spans)
    else:
        lo = hi = 0.0
    t0 = lo if t0_us is None else float(t0_us)
    t1 = hi if t1_us is None else float(t1_us)
    if t1 < t0:
        t0, t1 = t1, t0
    bucket_us, path = _sweep(spans, t0, t1, pool_tids) \
        if spans else ({}, [])
    # blocked-on-the-compile-pool slices report as compile_xla: the wait
    # wraps the pool's whole trace/lower/xla run, so the aggregate
    # compile bucket is the honest attribution for the blocked caller
    if "compile_wait" in bucket_us:
        bucket_us["compile_xla"] = bucket_us.get("compile_xla", 0.0) \
            + bucket_us.pop("compile_wait")
        for p in path:
            if p[2] == "compile_wait":
                p[2] = "compile_xla"
    buckets = {b: 0.0 for b in BUCKETS}
    for b, us in bucket_us.items():
        if b != "unattributed":
            buckets[b] = us / 1e6
    queued_s = max(0.0, float(queued_s or 0.0))
    stage_queue_s = max(0.0, float(stage_queue_s or 0.0))
    buckets["admission_wait"] = queued_s
    buckets["queue_wait"] = stage_queue_s
    covered = sum(v for b, v in buckets.items() if b != "unattributed")
    if wall_s is None:
        wall_s = (t1 - t0) / 1e6 + queued_s + stage_queue_s
    wall_s = max(float(wall_s), covered)  # never report >100% coverage
    buckets["unattributed"] = max(0.0, wall_s - covered)
    attributed = {b: v for b, v in buckets.items()
                  if b != "unattributed" and v > 0}
    dominant = max(attributed, key=attributed.get) \
        if attributed else "unattributed"
    unattr_frac = buckets["unattributed"] / wall_s if wall_s > 0 else 0.0
    return {
        "wall_s": round(wall_s, 6),
        "buckets": {b: round(v, 6) for b, v in buckets.items()},
        "unattributed_frac": round(unattr_frac, 4),
        "coverage_frac": round(1.0 - unattr_frac, 4),
        "dominant": dominant,
        "n_spans": len(spans),
        "n_orphans": n_orphans,
        "n_dropped": n_dropped,
        "degraded": bool(n_orphans or n_dropped),
        "path": [[round(p[0], 1), round(p[1], 1), p[2], p[3]]
                 for p in path[:_PATH_CAP]],
    }


def analyze_ring(events=None) -> Optional[dict]:
    """Whole-process convenience for one-shot Context runs (bench.py,
    ``Metrics.as_dict``): attribute the most recent top-level ``job``
    span's window from the shared tracing ring. None when disabled or
    nothing was traced."""
    if not _enabled:
        return None
    from . import tracing

    evts = events if events is not None else tracing.events()
    if not evts:
        return None
    job = None
    for e in evts:
        if e.get("name") == "job" and e.get("dur"):
            if job is None or e["ts"] >= job["ts"]:
                job = e
    if job is None:
        return analyze_events(evts)
    t0, t1 = job["ts"], job["ts"] + job["dur"]
    window = [e for e in evts
              if e.get("ts") is not None and t0 <= e["ts"] <= t1]
    return analyze_events(window, wall_s=job["dur"] / 1e6,
                          t0_us=t0, t1_us=t1)


# ---------------------------------------------------------------------------
# per-tenant baselines, SLO attainment, burn rates
# ---------------------------------------------------------------------------

def record_job(tenant: str, job_id: str, budget: Optional[dict],
               failed: bool = False) -> dict:
    """Fold one terminal job's budget into its tenant's EWMA baseline
    and SLO windows; returns the blame verdict ``{slow, blame,
    delta_s, baseline_wall_s, slo_ms, slo_ok}``. A failed job counts
    against the SLO but never calibrates the baseline (its truncated
    budget would teach the baseline a lie)."""
    if not _enabled or not budget:
        return _EMPTY
    from . import excprof

    wall = float(budget.get("wall_s", 0.0))
    obs = budget.get("buckets") or {}
    unattr = float(budget.get("unattributed_frac", 0.0))
    slo_ms = slo_for(tenant)
    now = time.monotonic()
    with _LOCK:
        t = _tenant_locked(tenant)
        t["jobs"] += 1
        slo_ok = None
        if slo_ms > 0:
            slo_ok = (not failed) and wall * 1000.0 <= slo_ms
            t["slo_ok" if slo_ok else "slo_miss"] += 1
            t["window"].append((now, slo_ok))
        slow = False
        blame = None
        delta = 0.0
        base_wall = t["wall_ewma"]
        if not failed and base_wall is not None \
                and t["n_base"] >= _min_base_jobs \
                and wall > max(base_wall * _slow_factor,
                               base_wall + _MIN_SLOW_S):
            slow = True
            t["slow_jobs"] += 1
            base = t["baseline"] or {}
            deltas = {b: obs.get(b, 0.0) - base.get(b, 0.0)
                      for b in BUCKETS}
            blame = max(deltas, key=deltas.get)
            delta = deltas[blame]
        if not failed:
            alpha = excprof.ewma_alpha(max(0.0, now - t["t_last"]),
                                       _half_life_s)
            if t["baseline"] is None:
                t["baseline"] = dict(obs)
                t["wall_ewma"] = wall
                t["unattr_ewma"] = unattr
            else:
                for b in BUCKETS:
                    t["baseline"][b] = t["baseline"].get(b, 0.0) + alpha \
                        * (obs.get(b, 0.0) - t["baseline"].get(b, 0.0))
                t["wall_ewma"] += alpha * (wall - t["wall_ewma"])
                t["unattr_ewma"] += alpha * (unattr - t["unattr_ewma"])
            t["n_base"] += 1
            t["t_last"] = now
        verdict = {"slow": slow, "blame": blame,
                   "delta_s": round(delta, 6),
                   "baseline_wall_s": round(base_wall, 6)
                   if base_wall is not None else None,
                   "slo_ms": slo_ms, "slo_ok": slo_ok}
        while len(_RECENT) >= _MAX_ENTRIES:
            _RECENT.pop(next(iter(_RECENT)))
        _RECENT[job_id] = {"tenant": tenant, "budget": budget,
                           "verdict": verdict}
    return verdict


def job_budget(job_id: str) -> Optional[dict]:
    """The retained ``{tenant, budget, verdict}`` for a recent job id
    (newest ``_MAX_ENTRIES`` jobs)."""
    with _LOCK:
        rec = _RECENT.get(job_id)
        return dict(rec) if rec is not None else None


def _burn_locked(t: dict, now: float) -> dict:
    fast_w = _burn_window_s
    slow_w = 5.0 * _burn_window_s
    budget = max(1e-9, 1.0 - _slo_target)
    fast_n = fast_miss = slow_n = slow_miss = 0
    for ts, ok in t["window"]:
        age = now - ts
        if age <= slow_w:
            slow_n += 1
            slow_miss += 0 if ok else 1
            if age <= fast_w:
                fast_n += 1
                fast_miss += 0 if ok else 1
    fast = (fast_miss / fast_n / budget) if fast_n else 0.0
    slow = (slow_miss / slow_n / budget) if slow_n else 0.0
    return {"fast": round(fast, 4), "slow": round(slow, 4),
            "fast_jobs": fast_n, "fast_misses": fast_miss,
            "slow_jobs": slow_n, "slow_misses": slow_miss}


def burn_rates(tenant: str) -> dict:
    """Multi-window burn-rate readout for `tenant`: miss fraction per
    window over the error budget (1 - sloTarget). 1.0 = burning the
    budget exactly; >1 = on track to violate the objective."""
    now = time.monotonic()
    with _LOCK:
        t = _TEN.get(tenant)
        if t is None:
            return {"fast": 0.0, "slow": 0.0, "fast_jobs": 0,
                    "fast_misses": 0, "slow_jobs": 0, "slow_misses": 0}
        return _burn_locked(t, now)


def attainment(tenant: str) -> Optional[float]:
    """Cumulative SLO attainment fraction for `tenant`; None when no
    SLO applies or nothing finished yet."""
    with _LOCK:
        t = _TEN.get(tenant)
        if t is None:
            return None
        n = t["slo_ok"] + t["slo_miss"]
        return (t["slo_ok"] / n) if n else None


def tenant_report(tenant: str) -> dict:
    """Numeric snapshot for one tenant (bench JSON / /metrics /
    dashboard all read this shape): jobs, the EWMA baseline budget
    vector, SLO attainment + burn rates, slow-job count."""
    now = time.monotonic()
    with _LOCK:
        t = _TEN.get(tenant)
        if t is None:
            return {"jobs": 0, "baseline": {}, "wall_ewma_s": 0.0,
                    "unattributed_ewma": 0.0, "slow_jobs": 0,
                    "slo_ms": slo_for(tenant), "attainment": None,
                    "burn": {"fast": 0.0, "slow": 0.0, "fast_jobs": 0,
                             "fast_misses": 0, "slow_jobs": 0,
                             "slow_misses": 0}}
        n = t["slo_ok"] + t["slo_miss"]
        return {
            "jobs": t["jobs"],
            "baseline": {b: round(v, 6)
                         for b, v in (t["baseline"] or {}).items()},
            "wall_ewma_s": round(t["wall_ewma"], 6)
            if t["wall_ewma"] is not None else 0.0,
            "unattributed_ewma": round(t["unattr_ewma"], 4),
            "slow_jobs": t["slow_jobs"],
            "slo_ms": slo_for(tenant),
            "attainment": round(t["slo_ok"] / n, 4) if n else None,
            "burn": _burn_locked(t, now),
        }


# ---------------------------------------------------------------------------
# slo health check (runtime/telemetry state machine input)
# ---------------------------------------------------------------------------

def _health_check():
    from . import telemetry

    now = time.monotonic()
    worst = telemetry.OK
    detail = None
    with _LOCK:
        snap = [(name, _burn_locked(t, now)) for name, t in _TEN.items()
                if slo_for(name) > 0]
    for name, br in snap:
        if br["fast"] >= 1.0 and br["fast_misses"] >= 1:
            sustained = br["slow"] >= 1.0 and br["slow_misses"] >= 2
            state = telemetry.UNHEALTHY if sustained \
                else telemetry.DEGRADED
            d = (f"tenant '{name}' burning its SLO budget "
                 f"(fast {br['fast']:.1f}x"
                 + (f", slow {br['slow']:.1f}x" if sustained else "")
                 + f"; {br['fast_misses']}/{br['fast_jobs']} recent "
                 f"job(s) missed {slo_for(name):.0f}ms)")
            if state == telemetry.UNHEALTHY \
                    or worst == telemetry.OK:
                worst, detail = state, d
            if worst == telemetry.UNHEALTHY:
                break
    return (worst, detail)


def _ensure_health() -> None:
    """Register the ``slo`` health check once (idempotent across
    clear(): re-registration is keyed on the registry actually holding
    the check, not just our flag)."""
    global _health_registered
    from . import telemetry

    if not telemetry.enabled():
        return
    with _LOCK:
        if _health_registered \
                and "slo" in telemetry.registry()._checks:
            return
        _health_registered = True
    telemetry.register_health_check("slo", _health_check,
                                    owner=_HEALTH_OWNER)
