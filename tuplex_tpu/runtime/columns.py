"""Columnar host memory layout + host<->device staging.

This replaces the reference's row-format Partition blocks
(reference: core/include/Partition.h:38-85, utils/include/Serializer.h:104-138)
with a TPU-first columnar layout:

  * every logical column is decomposed into fixed-shape leaf arrays
    (FlattenedTuple analog — reference: codegen/include/FlattenedTuple.h:49-57):
      - numeric leaves: one array [N]
      - str leaves:     uint8 bytes [N, W] zero-padded + int32 lengths [N]
      - Option adds a validity bool [N]
      - nested tuples flatten to dotted paths ("col.0.1")
  * a partition covers a contiguous range of original row positions; rows that
    do NOT conform to the normal-case schema keep their slot (placeholder
    zeros) and live boxed in `fallback` — this preserves order for the
    dual-mode merge (reference: ResolveTask.cc merge-in-order) with no index
    bookkeeping.
  * device staging pads N up to a bucket (and W per str col) so the jit cache
    stays small (reference analog: one LLVM module per stage; here one XLA
    executable per (stage, schema, bucket)).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Iterable, Optional, Sequence

import numpy as np

from ..core import typesys as T
from ..core.row import Row


# ---------------------------------------------------------------------------
# schema flattening
# ---------------------------------------------------------------------------

LEAF_NUMERIC = {T.BOOL: np.bool_, T.I64: np.int64, T.F64: np.float64}


def flatten_type(t: T.Type, path: str = "") -> list[tuple[str, T.Type]]:
    """Leaf (path, type) pairs for a column type. Option wraps leaves.

    Leaf paths are INDEX-based ("2", "2.0", ...) — column names are metadata
    only, so duplicate or hostile names can't collide storage keys.

    An Option[Tuple[...]] column gets an extra "<path>#opt" BOOL leaf holding
    whole-tuple validity (None vs a tuple of values), in addition to its
    element leaves which become Option-wrapped.

    Types without a fixed columnar layout (List/Dict/PYOBJECT) return a single
    pyobject leaf — columns of that type are host-boxed and force rows through
    the interpreter path when touched on device.
    """
    base = t.without_option() if t.is_optional() else t
    opt = t.is_optional()

    if isinstance(base, T.TupleType):
        out: list[tuple[str, T.Type]] = []
        if opt:
            out.append((f"{path}#opt", T.BOOL))
        for i, e in enumerate(base.elements):
            sub = f"{path}.{i}" if path else str(i)
            out.extend(flatten_type(T.option(e) if opt else e, sub))
        return out
    if base in (T.BOOL, T.I64, T.F64, T.STR, T.NULL, T.EMPTYTUPLE):
        return [(path, t)]
    return [(path, T.PYOBJECT)]


def columnar_supported(t: T.Type) -> bool:
    return all(lt is not T.PYOBJECT for _, lt in flatten_type(t))


def user_columns(schema: T.RowType):
    """Auto-generated names are '_0', '_1', ... — a schema made only of them
    is an UNNAMED row (no dict access, UDFs get bare values/tuples)."""
    cols = schema.columns
    if cols and all(c == f"_{i}" for i, c in enumerate(cols)):
        return None
    return cols if cols else None


# ---------------------------------------------------------------------------
# leaf column containers (host, numpy)
# ---------------------------------------------------------------------------

@dataclass
class NumericLeaf:
    data: np.ndarray                      # [N] bool_/int64/float64
    valid: Optional[np.ndarray] = None    # [N] bool_ when Option

    def __len__(self):
        return len(self.data)


@dataclass
class StrLeaf:
    bytes: np.ndarray                     # [N, W] uint8, zero padded
    lengths: np.ndarray                   # [N] int32
    valid: Optional[np.ndarray] = None

    def __len__(self):
        return len(self.lengths)

    @property
    def width(self) -> int:
        return self.bytes.shape[1] if self.bytes.ndim == 2 else 0

    def to_wire(self) -> tuple[np.ndarray, np.ndarray]:
        """Varlen wire view: (contiguous payload of the ACTUAL row bytes,
        int32 lengths). The inverse of from_wire; the transfer analog of
        the reference serializer's offsets+payload layout
        (Serializer.h:104-138) — offsets are implied by cumsum(lengths)."""
        return matrix_to_varlen(self.bytes, self.lengths)

    @classmethod
    def from_wire(cls, payload: np.ndarray, lengths: np.ndarray, width: int,
                  valid: Optional[np.ndarray] = None) -> "StrLeaf":
        lengths = np.asarray(lengths, dtype=np.int32)
        offs = np.concatenate(
            [[0], np.cumsum(np.clip(lengths, 0, width),
                            dtype=np.int64)])[:-1]
        return cls(varlen_to_matrix(payload, offs, lengths, width),
                   lengths, valid)


@dataclass
class NullLeaf:
    """All-None column: carries only the row count."""
    n: int

    def __len__(self):
        return self.n


@dataclass
class ObjectLeaf:
    """Host-boxed python objects (List/Dict/PYOBJECT leaves)."""
    values: list

    def __len__(self):
        return len(self.values)


Leaf = NumericLeaf | StrLeaf | NullLeaf | ObjectLeaf


def encode_str_leaf(values: Sequence[Optional[str]], optional: bool) -> StrLeaf:
    n = len(values)
    encoded = [v.encode("utf-8") if v is not None else b"" for v in values]
    w = max((len(b) for b in encoded), default=0)
    w = max(w, 1)
    mat = np.zeros((n, w), dtype=np.uint8)
    lens = np.zeros(n, dtype=np.int32)
    for i, b in enumerate(encoded):
        if b:
            mat[i, : len(b)] = np.frombuffer(b, dtype=np.uint8)
        lens[i] = len(b)
    valid = None
    if optional:
        valid = np.array([v is not None for v in values], dtype=np.bool_)
    return StrLeaf(mat, lens, valid)


def decode_str(leaf: StrLeaf, i: int) -> Optional[str]:
    if leaf.valid is not None and not bool(leaf.valid[i]):
        return None
    ln = int(leaf.lengths[i])
    return bytes(leaf.bytes[i, :ln]).decode("utf-8", errors="replace")


def encode_leaf(values: Sequence[Any], t: T.Type) -> Leaf:
    base = t.without_option() if t.is_optional() else t
    opt = t.is_optional()
    n = len(values)
    if base is T.EMPTYTUPLE and opt:
        # unit value with validity: only the valid bitmap carries information
        valid = np.array([v is not None for v in values], dtype=np.bool_)
        return NumericLeaf(np.zeros(n, dtype=np.bool_), valid)
    if base is T.NULL or base is T.EMPTYTUPLE:
        return NullLeaf(n)
    if base is T.STR:
        return encode_str_leaf(values, opt)
    if base in LEAF_NUMERIC:
        dtype = LEAF_NUMERIC[base]
        if opt:
            data = np.zeros(n, dtype=dtype)
            valid = np.zeros(n, dtype=np.bool_)
            for i, v in enumerate(values):
                if v is not None:
                    data[i] = v
                    valid[i] = True
            return NumericLeaf(data, valid)
        return NumericLeaf(np.asarray(values, dtype=dtype))
    return ObjectLeaf(list(values))


def decode_leaf(leaf: Leaf, i: int) -> Any:
    if isinstance(leaf, NullLeaf):
        return None
    if isinstance(leaf, ObjectLeaf):
        return leaf.values[i]
    if isinstance(leaf, StrLeaf):
        return decode_str(leaf, i)
    if leaf.valid is not None and not bool(leaf.valid[i]):
        return None
    v = leaf.data[i]
    if leaf.data.dtype == np.bool_:
        return bool(v)
    if np.issubdtype(leaf.data.dtype, np.integer):
        return int(v)
    return float(v)


# ---------------------------------------------------------------------------
# varlen wire view (offsets + contiguous payload — the reference
# serializer's disk layout applied to the transfer wire)
# ---------------------------------------------------------------------------

def matrix_to_varlen(mat: np.ndarray,
                     lens: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """[N, W] zero-padded byte matrix -> (payload of the actual row bytes
    concatenated, int32 lengths clamped to [0, W]). Row-major boolean
    selection keeps each row's prefix contiguous and in row order, so
    offsets are exactly the exclusive cumsum of the clamped lengths."""
    n = mat.shape[0]
    w = mat.shape[1] if mat.ndim == 2 else 0
    ln = np.clip(np.asarray(lens[:n], dtype=np.int32), 0, w)
    if n == 0 or w == 0:
        return np.zeros(0, np.uint8), ln
    keep = np.arange(w, dtype=np.int32)[None, :] < ln[:, None]
    return np.ascontiguousarray(mat[:n])[keep], ln


def varlen_to_matrix(payload: np.ndarray, offs: np.ndarray,
                     lens: np.ndarray, w: int) -> np.ndarray:
    """(payload, per-row offsets, lengths) -> [N, w] zero-padded byte
    matrix (vectorized gather — same technique as arrow_string_to_leaf)."""
    n = len(lens)
    mat = np.zeros((n, max(w, 1)), np.uint8)
    if n == 0 or w <= 0 or len(payload) == 0:
        return mat
    ln = np.clip(np.asarray(lens, dtype=np.int64), 0, w)
    idx = np.asarray(offs, dtype=np.int64)[:, None] + \
        np.arange(w, dtype=np.int64)[None, :]
    np.clip(idx, 0, len(payload) - 1, out=idx)
    g = np.asarray(payload, dtype=np.uint8)[idx]
    keep = np.arange(w, dtype=np.int64)[None, :] < ln[:, None]
    return np.where(keep, g, 0).astype(np.uint8)


# ---------------------------------------------------------------------------
# lazy (device-backed) leaves — the host side of the stage handoff
# ---------------------------------------------------------------------------

# process-wide handoff observability (tests + bench): which lazy leaf dicts
# were created and which leaf paths were ever forced to host. Reset freely.
HANDOFF_STATS = {"lazy_parts": 0, "forced": []}


class LazyLeaves(dict):
    """Leaf dict whose values materialize from device arrays on first
    access. Key-set operations (iteration, membership, len) never transfer;
    value access fetches ONLY the touched leaf — a join probing one key
    column pulls that column's bytes and nothing else. items()/values()
    force everything (spill, row-wise fallbacks).

    This is what lets an intermediate partition skip the D2H round-trip
    entirely: the host dict stays empty unless some slow path actually
    needs host bytes, while the device arrays feed the next stage."""

    def __init__(self, keys, loader, tag: str = ""):
        super().__init__()
        self._keys = tuple(keys)
        self._loader = loader            # loader(path) -> Leaf
        self._tag = tag
        HANDOFF_STATS["lazy_parts"] += 1

    # -- key-set views (no transfer) ------------------------------------
    def __iter__(self):
        return iter(self._keys)

    def keys(self):
        return tuple(self._keys)

    def __len__(self):
        return len(self._keys)

    def __contains__(self, k):
        return k in self._keys

    def __bool__(self):
        return bool(self._keys)

    # -- value access (forces the touched leaf) -------------------------
    def _load(self, k):
        if not super().__contains__(k):
            HANDOFF_STATS["forced"].append((self._tag, k))
            super().__setitem__(k, self._loader(k))
            if all(dict.__contains__(self, k2) for k2 in self._keys):
                self._loader = None   # release the device-array closure
        return super().__getitem__(k)

    def __getitem__(self, k):
        if k not in self._keys:
            raise KeyError(k)
        return self._load(k)

    def get(self, k, default=None):
        if k not in self._keys:
            return default
        return self._load(k)

    def items(self):
        return [(k, self._load(k)) for k in self._keys]

    def values(self):
        return [self._load(k) for k in self._keys]

    def materialized(self) -> bool:
        return all(dict.__contains__(self, k) for k in self._keys)

    # -- inherited-dict traps: keep copies/compares consistent ----------
    # (CPython bypasses overridden accessors for some C-level dict uses;
    # force first so a partially-materialized mapping never leaks out)
    def copy(self):
        return dict(self.items())

    def __eq__(self, other):
        if isinstance(other, dict):
            return dict(self.items()) == other
        return NotImplemented

    def __ne__(self, other):
        eq = self.__eq__(other)
        return NotImplemented if eq is NotImplemented else not eq

    __hash__ = None

    def __setitem__(self, k, v):
        if k not in self._keys:
            self._keys = self._keys + (k,)
        super().__setitem__(k, v)


def decode_key_tuples(part: "Partition", indices, kidx) -> list[tuple]:
    """Key-column values for the given NORMAL rows, touching only the key
    columns' leaves (a full decode_rows would force every lazy leaf of a
    device-resident partition to host — exactly the round-trip the handoff
    exists to avoid)."""
    out = []
    for i in indices:
        i = int(i)
        out.append(tuple(part._decode_col(str(j), part.schema.types[j], i)
                         for j in kidx))
    return out


# ---------------------------------------------------------------------------
# partition
# ---------------------------------------------------------------------------

def _leaf_paths_for_value(path: str, t: T.Type, v: Any) -> Iterable[tuple[str, Any]]:
    base = t.without_option() if t.is_optional() else t
    opt = t.is_optional()
    if isinstance(base, T.TupleType):
        if opt:
            yield (f"{path}#opt", v is not None)
        for i, e in enumerate(base.elements):
            sub = f"{path}.{i}" if path else str(i)
            et = T.option(e) if opt else e
            yield from _leaf_paths_for_value(sub, et, None if v is None else v[i])
    else:
        yield (path, v)


@dataclass
class Partition:
    """A horizontal slice of a dataset in normal-case columnar layout.

    `schema` is the normal-case RowType. `leaves` maps "<col>" or
    "<col>.<i>..." paths to leaf arrays of length == num_rows. Non-conforming
    row positions are False in `normal_mask` and boxed in `fallback`
    (original python value, pre-conversion).
    """

    schema: T.RowType
    num_rows: int
    leaves: dict[str, Leaf] = field(default_factory=dict)
    normal_mask: Optional[np.ndarray] = None      # [N] bool; None => all normal
    fallback: dict[int, Any] = field(default_factory=dict)
    start_index: int = 0                          # global row offset of row 0

    @property
    def columns(self) -> tuple[str, ...]:
        return self.schema.columns

    @property
    def user_columns(self):
        """Column names as the user sees them: None when auto-generated."""
        return user_columns(self.schema)

    def n_normal(self) -> int:
        if self.normal_mask is None:
            return self.num_rows
        return int(self.normal_mask.sum())

    # -- row access (host) --------------------------------------------------
    def decode_row(self, i: int) -> Row:
        """Reconstruct the boxed row at local position i (interpreter path
        input). Fallback rows return their original boxed value."""
        cols = self.user_columns
        if i in self.fallback:
            return Row.from_value(self.fallback[i], cols)
        vals = []
        for ci, ct in enumerate(self.schema.types):
            vals.append(self._decode_col(str(ci), ct, i))
        return Row(vals, cols)

    def _decode_col(self, path: str, t: T.Type, i: int) -> Any:
        base = t.without_option() if t.is_optional() else t
        opt = t.is_optional()
        if isinstance(base, T.TupleType):
            if opt:
                ol = self.leaves[f"{path}#opt"]
                assert isinstance(ol, NumericLeaf)  # BOOL leaf: validity in data
                if not bool(ol.data[i]):
                    return None
            return tuple(
                self._decode_col(f"{path}.{j}", T.option(e) if opt else e, i)
                for j, e in enumerate(base.elements)
            )
        if base is T.EMPTYTUPLE:
            if opt:
                leaf = self.leaves[path]
                assert isinstance(leaf, NumericLeaf) and leaf.valid is not None
                return () if bool(leaf.valid[i]) else None
            return ()
        return decode_leaf(self.leaves[path], i)

    def iter_rows(self) -> Iterable[Row]:
        for i in range(self.num_rows):
            yield self.decode_row(i)

    def nbytes(self) -> int:
        lv = self.leaves
        if isinstance(lv, LazyLeaves) and not lv.materialized():
            # unforced device-backed leaves hold no host bytes; the size
            # estimate must not itself trigger the D2H it is sizing
            return int(getattr(lv, "nbytes_hint", 0))
        total = 0
        for leaf in lv.values():
            if isinstance(leaf, NumericLeaf):
                total += leaf.data.nbytes + (leaf.valid.nbytes if leaf.valid is not None else 0)
            elif isinstance(leaf, StrLeaf):
                total += leaf.bytes.nbytes + leaf.lengths.nbytes
        return total


def build_partition(
    values: Sequence[Any],
    schema: T.RowType,
    start_index: int = 0,
) -> Partition:
    """Encode boxed python row values into a Partition against `schema`.

    Rows that don't conform to the normal-case schema keep their position as
    placeholder slots and are boxed into `fallback` (reference: fallback
    partitions of pickled objects, PythonContext.cc:617 parallelizeAnyType).
    """
    fast = _fast_partition(values, schema, start_index)
    if fast is not None:
        return fast
    n = len(values)
    # row value shape: single column -> bare value; multi -> tuple
    multi = len(schema.columns) > 1

    normal_mask = np.ones(n, dtype=np.bool_)
    fallback: dict[int, Any] = {}
    # per-leaf collected python values (placeholder None/0 for bad rows);
    # leaf paths are column-index based so duplicate names can't collide
    leaf_types: list[tuple[str, T.Type]] = []
    for ci, ct in enumerate(schema.types):
        leaf_types.extend(flatten_type(ct, str(ci)))
    leaf_values: dict[str, list] = {p: [] for p, _ in leaf_types}
    leaf_type_map = dict(leaf_types)

    placeholders = {p: _placeholder(lt) for p, lt in leaf_types}

    def conforms(row_tuple) -> bool:
        if not (isinstance(row_tuple, tuple) and
                len(row_tuple) == len(schema.columns)):
            return False
        return all(T.python_value_conforms(rv, ct)
                   for rv, ct in zip(row_tuple, schema.types))

    for i, v in enumerate(values):
        row_tuple = v if multi else (v,)
        ok = conforms(row_tuple)
        if not ok and not multi and isinstance(v, tuple) and len(v) == 1:
            # single-column rows may arrive as 1-tuples (Row semantics)
            row_tuple = v
            ok = conforms(row_tuple)
        if not ok:
            normal_mask[i] = False
            fallback[i] = v
            for p in leaf_values:
                leaf_values[p].append(placeholders[p])
            continue
        for ci, (ct, rv) in enumerate(zip(schema.types, row_tuple)):
            for p, lv in _leaf_paths_for_value(str(ci), ct, rv):
                leaf_values[p].append(lv)

    leaves = {p: encode_leaf(vals, leaf_type_map[p]) for p, vals in leaf_values.items()}
    mask = None if len(fallback) == 0 else normal_mask
    return Partition(schema=schema, num_rows=n, leaves=leaves,
                     normal_mask=mask, fallback=fallback, start_index=start_index)


def _placeholder(t: T.Type) -> Any:
    base = t.without_option() if t.is_optional() else t
    if t.is_optional() or base is T.NULL or base is T.EMPTYTUPLE:
        return None
    if base is T.STR:
        return ""
    if base is T.BOOL:
        return False
    if base is T.I64:
        return 0
    if base is T.F64:
        return 0.0
    return None


# ---------------------------------------------------------------------------
# device staging
# ---------------------------------------------------------------------------

def bucket_size(n: int, mode: str = "q8", minimum: int = 8) -> int:
    """Padded size for a real size `n`.

    "pow2"  — next power of two. Up to ~50% padding waste (round 2 measured
              31% wasted kernel time on the 100k-row bench batch padded to
              131072), at most 1 jit shape variant per octave.
    "q8"    — quantize to 1/8 of the pow2 FLOOR: waste <= 12.5% (typically
              ~6%), at most 8 shape variants per octave. The persistent
              compile cache makes the extra variants a one-time cost; this
              is the default.
    "exact" — no padding (one executable per distinct partition size; only
              sensible for single-batch jobs or tests).
    """
    if mode == "exact" or n <= 0:
        return max(n, 1)
    n = max(n, minimum)
    p2 = 1 << (n - 1).bit_length()          # pow2 ceil
    # "fixed" was a documented alias for pow2 behavior; unknown modes also
    # degrade to pow2 (the conservative shape policy) rather than silently
    # changing padding semantics
    if mode != "q8" or n == p2:
        return p2
    q = max(minimum, (p2 >> 1) >> 3)        # pow2floor / 8
    return ((n + q - 1) // q) * q


def pad_to(arr: np.ndarray, n: int, axis: int = 0) -> np.ndarray:
    cur = arr.shape[axis]
    if cur >= n:
        return arr
    pad_width = [(0, 0)] * arr.ndim
    pad_width[axis] = (0, n - cur)
    return np.pad(arr, pad_width)


@dataclass
class DeviceBatch:
    """The jit-facing view of a partition: dict of padded numpy/jnp arrays.

    arrays keys: for each leaf path P:
        P            -> numeric data     [B]
        P#bytes      -> str bytes        [B, Wb]
        P#len        -> str lengths      [B]
        P#valid      -> validity         [B]      (Option leaves only)
    plus:
        "#rowvalid"  -> [B] bool — True for real, normal-case rows
    `n` is the real row count, `b` the padded bucket size.
    """

    arrays: dict[str, np.ndarray]
    n: int
    b: int
    schema: T.RowType

    def spec(self) -> tuple:
        """Hashable shape/dtype signature — the jit cache key component."""
        return tuple(sorted(
            (k, v.shape, str(v.dtype)) for k, v in self.arrays.items()
        ))


def _leaf_keys(path: str, leaf):
    """Device array keys for one leaf — THE single definition of the
    per-leaf key layout (staged_keys and stage_partition both derive from
    it). [] for layout-free leaves (Null), None for host-only (Object)."""
    if isinstance(leaf, NullLeaf):
        return []
    if isinstance(leaf, ObjectLeaf):
        return None
    keys = [path] if isinstance(leaf, NumericLeaf) \
        else [path + "#bytes", path + "#len"]
    if leaf.valid is not None:
        keys.append(path + "#valid")
    return keys


def staged_keys(part: Partition):
    """The array keys stage_partition would produce for `part` (without
    '#rowvalid'/'#seed'), or None when a leaf has no device layout."""
    keys: set = set()
    for path, leaf in part.leaves.items():
        ks = _leaf_keys(path, leaf)
        if ks is None:
            return None
        keys.update(ks)
    return keys


def staged_keys_for_type(path: str, lt: T.Type) -> list[str]:
    """Device-array keys stage_partition would produce for a leaf of
    type `lt` at `path` — the TYPE-level twin of _leaf_keys, for layouts
    that exist only as device arrays (no Leaf instance to inspect).
    Kept next to _leaf_keys so the two definitions evolve together."""
    base = lt.without_option() if lt.is_optional() else lt
    opt = lt.is_optional()
    if path.endswith("#opt"):
        return [path]                       # BOOL validity leaf
    if base is T.NULL:
        return []
    if base is T.EMPTYTUPLE:
        return [path, path + "#valid"] if opt else []
    ks = [path + "#bytes", path + "#len"] if base is T.STR else [path]
    if opt:
        ks.append(path + "#valid")
    return ks


def partition_seed(part: Partition):
    """Per-partition PRNG seed (Weyl-mixed start index) for compiled
    `random` UDFs — distinct per partition so batches don't replay one
    sequence."""
    return np.uint32((part.start_index * 2654435761 + 97531) & 0xFFFFFFFF)


def stage_partition(part: Partition, bucket_mode: str = "q8",
                    force_b: Optional[int] = None,
                    force_widths: Optional[dict] = None) -> DeviceBatch:
    """`force_b` / `force_widths` override the data-derived bucket sizes —
    multi-process host-block staging must agree on GLOBAL shapes across
    hosts whose local data differs (parallel/hostio)."""
    dv = getattr(part, "device_batch", None)
    if dv is not None:
        # one-shot: drop the partition's reference either way so device
        # memory is released as soon as the consumer's dispatch retires
        # (host leaves stay authoritative for any retry)
        part.device_batch = None
        if force_b is None and force_widths is None \
                and dv.n == part.num_rows \
                and dv.b == bucket_size(part.num_rows, bucket_mode):
            return dv   # device-resident view from the producing stage
    n = part.num_rows
    b = force_b if force_b is not None else bucket_size(n, bucket_mode)
    arrays: dict[str, np.ndarray] = {}
    for path, leaf in part.leaves.items():
        ks = _leaf_keys(path, leaf)
        if not ks:   # NullLeaf (layout-free) or host-only ObjectLeaf:
            continue  # device code must not touch it
        if isinstance(leaf, NumericLeaf):
            arrays[path] = pad_to(leaf.data, b)
        else:   # StrLeaf
            wb = None if force_widths is None else force_widths.get(path)
            if wb is None:
                wb = bucket_size(max(leaf.width, 1), bucket_mode, minimum=8)
            arrays[path + "#bytes"] = pad_to(pad_to(leaf.bytes, b, 0), wb, 1)
            arrays[path + "#len"] = pad_to(leaf.lengths, b)
        if path + "#valid" in ks:
            arrays[path + "#valid"] = pad_to(leaf.valid, b)
    rowvalid = np.zeros(b, dtype=np.bool_)
    if part.normal_mask is None:
        rowvalid[:n] = True
    else:
        rowvalid[:n] = part.normal_mask
    arrays["#rowvalid"] = rowvalid
    # per-partition PRNG seed for compiled `random` UDFs (Weyl-mixed start
    # index so partitions draw distinct streams). Stages without random never
    # read it; jit drops unused inputs at lowering, so the executable and the
    # persistent compile cache key are untouched for such stages.
    arrays["#seed"] = partition_seed(part)
    return DeviceBatch(arrays=arrays, n=n, b=b, schema=part.schema)


# ---------------------------------------------------------------------------
# rebuild partitions from device outputs
# ---------------------------------------------------------------------------

def schema_for_result_type(t: "T.Type", columns: Optional[Sequence[str]] = None) -> T.RowType:
    """Row schema for a UDF/stage result type: a plain tuple spreads into
    columns, everything else is a single column. Auto column names start with
    '_' (the unnamed-row convention)."""
    if isinstance(t, T.TupleType) and not t.is_optional():
        names = tuple(columns) if columns and len(columns) == len(t.elements) \
            else tuple(f"_{i}" for i in range(len(t.elements)))
        return T.row_of(names, t.elements)
    name = tuple(columns) if columns and len(columns) == 1 else ("_0",)
    return T.row_of(name, (t,))


def partition_from_arrays(
    arrays: dict[str, np.ndarray],
    schema: T.RowType,
    n: int,
    normal_mask: Optional[np.ndarray] = None,
    fallback: Optional[dict[int, Any]] = None,
    start_index: int = 0,
) -> Partition:
    """Inverse of stage_partition: trim padded output arrays to n rows and
    wrap them as a Partition (leaf-path convention of flatten_type)."""
    leaves: dict[str, Leaf] = {}
    for ci, ct in enumerate(schema.types):
        for path, lt in flatten_type(ct, str(ci)):
            base = lt.without_option() if lt.is_optional() else lt
            opt = lt.is_optional()
            valid = arrays.get(path + "#valid")
            valid = None if valid is None else np.asarray(valid[:n], dtype=np.bool_)
            if path.endswith("#opt"):
                leaves[path] = NumericLeaf(np.asarray(arrays[path][:n], dtype=np.bool_))
                continue
            if base is T.STR:
                leaves[path] = StrLeaf(
                    np.asarray(arrays[path + "#bytes"][:n], dtype=np.uint8),
                    np.asarray(arrays[path + "#len"][:n], dtype=np.int32),
                    valid,
                )
            elif base is T.NULL:
                leaves[path] = NullLeaf(n)
            elif base is T.EMPTYTUPLE:
                if opt:
                    leaves[path] = NumericLeaf(np.zeros(n, dtype=np.bool_), valid)
                else:
                    leaves[path] = NullLeaf(n)
            elif base in LEAF_NUMERIC:
                leaves[path] = NumericLeaf(
                    np.asarray(arrays[path][:n], dtype=LEAF_NUMERIC[base]), valid)
            else:
                raise ValueError(f"cannot rebuild leaf {path}: {lt}")
    return Partition(schema=schema, num_rows=n, leaves=leaves,
                     normal_mask=normal_mask, fallback=dict(fallback or {}),
                     start_index=start_index)


def type_from_result_arrays(arrays: dict, path: str) -> Optional[T.Type]:
    """Reconstruct a leaf/column type from device-output array keys: the key
    suffix convention + dtypes fully determine the type, so the rebuilt
    partition always matches what the trace ACTUALLY produced (never the
    sample-speculated schema)."""
    # fast existence probe: nothing under this path => no such column
    if not any(k == path or k.startswith(path + "#") or
               k.startswith(path + ".") for k in arrays):
        return None
    opt = (path + "#valid") in arrays or (path + "#opt") in arrays
    if (path + "#bytes") in arrays:
        return T.option(T.STR) if opt else T.STR
    if (path + "#null") in arrays:
        return T.NULL
    if (path + "#unit") in arrays:
        return T.option(T.EMPTYTUPLE) if opt else T.EMPTYTUPLE
    if path in arrays:
        # dtype attribute, not np.asarray: schema probing must work on
        # DEVICE arrays without pulling their bytes to host (lazy handoff)
        dt = np.dtype(getattr(arrays[path], "dtype", None) or
                      np.asarray(arrays[path]).dtype)
        if dt == np.bool_:
            base = T.BOOL
        elif np.issubdtype(dt, np.integer):
            base = T.I64
        else:
            base = T.F64
        return T.option(base) if opt else base
    # tuple: children at path.0, path.1, ...
    elts = []
    i = 0
    while True:
        sub = f"{path}.{i}" if path else str(i)
        et = type_from_result_arrays(arrays, sub)
        if et is None:
            break
        elts.append(et)
        i += 1
    if not elts:
        return None
    tt = T.tuple_of(*[e.without_option() if opt and e.is_optional() else e
                      for e in elts]) if opt else T.tuple_of(*elts)
    return T.option(tt) if opt else tt


def partition_from_result_arrays(
    arrays: dict[str, np.ndarray],
    n: int,
    columns: Optional[Sequence[str]] = None,
    start_index: int = 0,
) -> Partition:
    """Build a Partition directly from stage-output arrays (cv_output_arrays
    key convention), deriving the schema from the arrays themselves."""
    col_types = []
    ci = 0
    while True:
        t = type_from_result_arrays(arrays, str(ci))
        if t is None:
            break
        col_types.append(t)
        ci += 1
    if not col_types:
        raise ValueError("no columns found in result arrays")
    names = tuple(columns) if columns and len(columns) == len(col_types) \
        else tuple(f"_{i}" for i in range(len(col_types)))
    schema = T.row_of(names, col_types)

    leaves: dict[str, Leaf] = {}
    for ci, ct in enumerate(col_types):
        for path, lt in flatten_type(ct, str(ci)):
            leaves[path] = leaf_from_result_arrays(arrays, path, lt, n)
    return Partition(schema=schema, num_rows=n, leaves=leaves,
                     start_index=start_index)


def result_keys_for_leaf(arrays: dict, path: str) -> list[str]:
    """The result-array keys leaf_from_result_arrays reads for `path` —
    the unit of a lazy per-leaf fetch."""
    ks = [k for k in (path, path + "#bytes", path + "#len",
                      path + "#valid", path + "#opt") if k in arrays]
    return ks


def leaf_from_result_arrays(arrays: dict, path: str, lt: T.Type,
                            n: int) -> Leaf:
    """One leaf of a result partition from stage-output arrays (the
    per-path unit of partition_from_result_arrays; lazy handoff loaders
    call it with just that leaf's fetched arrays)."""
    base = lt.without_option() if lt.is_optional() else lt
    opt = lt.is_optional()
    if path.endswith("#opt"):
        return NumericLeaf(np.asarray(arrays[path][:n], dtype=np.bool_))
    valid = arrays.get(path + "#valid")
    if valid is None and opt and (path + "#opt") in arrays:
        valid = arrays[path + "#opt"]
    valid = None if valid is None else np.asarray(valid[:n], dtype=np.bool_)
    if base is T.STR:
        return StrLeaf(
            np.asarray(arrays[path + "#bytes"][:n], dtype=np.uint8),
            np.asarray(arrays[path + "#len"][:n], dtype=np.int32),
            valid)
    if base is T.NULL:
        return NullLeaf(n)
    if base is T.EMPTYTUPLE:
        if opt:
            return NumericLeaf(
                np.zeros(n, dtype=np.bool_),
                valid if valid is not None else np.ones(n, dtype=np.bool_))
        return NullLeaf(n)
    return NumericLeaf(
        np.asarray(arrays[path][:n], dtype=LEAF_NUMERIC[base]), valid)


def gather_partition(part: Partition, out_positions: np.ndarray,
                     src_indices: np.ndarray, m: int) -> Partition:
    """New m-row partition with rows src_indices placed at out_positions
    (other slots zero placeholders, to be filled by resolved rows)."""
    leaves: dict[str, Leaf] = {}
    for path, leaf in part.leaves.items():
        if isinstance(leaf, NumericLeaf):
            data = np.zeros(m, dtype=leaf.data.dtype)
            valid = None if leaf.valid is None else np.zeros(m, np.bool_)
            if len(src_indices):
                data[out_positions] = leaf.data[src_indices]
                if valid is not None:
                    valid[out_positions] = leaf.valid[src_indices]
            leaves[path] = NumericLeaf(data, valid)
        elif isinstance(leaf, StrLeaf):
            b = np.zeros((m, max(leaf.width, 1)), dtype=np.uint8)
            ln = np.zeros(m, dtype=np.int32)
            valid = None if leaf.valid is None else np.zeros(m, np.bool_)
            if len(src_indices):
                b[out_positions] = leaf.bytes[src_indices]
                ln[out_positions] = leaf.lengths[src_indices]
                if valid is not None:
                    valid[out_positions] = leaf.valid[src_indices]
            leaves[path] = StrLeaf(b, ln, valid)
        elif isinstance(leaf, NullLeaf):
            leaves[path] = NullLeaf(m)
        else:
            vals: list = [None] * m
            for o, s in zip(out_positions.tolist(), src_indices.tolist()):
                vals[o] = leaf.values[s]
            leaves[path] = ObjectLeaf(vals)
    return Partition(schema=part.schema, num_rows=m, leaves=leaves,
                     start_index=part.start_index)


def unique_rows(sub: np.ndarray):
    """np.unique(view_as_void, return_index, return_inverse) semantics over
    the rows of a [N, W] uint8 matrix — (inverse int32, first_idx int64),
    groups numbered in byte-lexicographic order, first_idx = smallest
    original row index per group.

    np.unique on a void view argsorts with generic memcmp comparisons
    (~38ms for 60k x 24 on one core — half of tpch q1's aggregate cost);
    a stable lexsort over big-endian u64 lanes is typed and ~10x faster."""
    n, w = sub.shape
    if n == 0:
        return np.zeros(0, np.int32), np.zeros(0, np.int64)
    wp = -(-max(w, 1) // 8) * 8
    if wp != w:
        sub = np.pad(sub, ((0, 0), (0, wp - w)))
    # big-endian lanes: u64 numeric order == byte-lexicographic order
    cols = np.ascontiguousarray(sub).view(">u8").reshape(n, wp // 8)
    order = np.lexsort(cols.T[::-1])     # primary key = first lane
    s = cols[order]
    bound = np.empty(n, dtype=bool)
    bound[0] = True
    if n > 1:
        np.any(s[1:] != s[:-1], axis=1, out=bound[1:])
    gid_sorted = np.cumsum(bound) - 1
    inverse = np.empty(n, dtype=np.int64)
    inverse[order] = gid_sorted
    # lexsort is stable -> the boundary row of each group carries the
    # smallest original index among its equals
    first_idx = order[np.nonzero(bound)[0]]
    return inverse.astype(np.int32), first_idx.astype(np.int64)


def key_signature_matrix(part: Partition, cis: Sequence[int],
                         reject_nan: bool = True) -> Optional[np.ndarray]:
    """[N, W] canonical byte-signature matrix over the given key columns,
    None if any leaf isn't signature-comparable. Byte equality must IMPLY
    python equality, so every representation quirk is canonicalized first:
    invalid (None) slots are zeroed (CSV null_values keep their original
    sbytes; merge_cv Options carry the dead branch's data), str bytes past
    the length are zeroed (stage outputs carry stale padding), floats
    normalize -0.0 and (for joins) reject NaN since NaN != NaN."""
    pieces: list[np.ndarray] = []
    n = part.num_rows
    for ci in cis:
        for path, _lt in flatten_type(part.schema.types[ci], str(ci)):
            leaf = part.leaves.get(path)
            if isinstance(leaf, NumericLeaf):
                data = leaf.data
                if leaf.valid is not None:
                    data = np.where(
                        leaf.valid.reshape((n,) + (1,) * (data.ndim - 1)),
                        data, 0)
                if data.dtype.kind == "f":
                    if reject_nan and np.isnan(data).any():
                        return None  # NaN keys: python equality differs
                    data = np.where(data == 0, 0.0, data)  # -0.0 == 0.0
                pieces.append(np.ascontiguousarray(
                    data.reshape(n, -1)).view(np.uint8).reshape(n, -1))
                if leaf.valid is not None:
                    pieces.append(leaf.valid.reshape(-1, 1).view(np.uint8))
            elif isinstance(leaf, StrLeaf):
                b, ln = leaf.bytes, leaf.lengths
                if leaf.valid is not None:
                    b = np.where(leaf.valid[:, None], b, 0)
                    ln = np.where(leaf.valid, ln, 0)
                b = np.where(
                    np.arange(b.shape[1])[None, :] < ln[:, None], b, 0)
                pieces.append(b)
                pieces.append(ln.astype("<i4").view(np.uint8).reshape(n, -1))
                if leaf.valid is not None:
                    pieces.append(leaf.valid.reshape(-1, 1).view(np.uint8))
            elif isinstance(leaf, NullLeaf):
                pieces.append(np.zeros((n, 1), np.uint8))
            else:
                return None
    if not pieces:
        return None
    return np.ascontiguousarray(np.concatenate(pieces, axis=1))


def harmonize_partitions(parts: list) -> list:
    """Pad every partition's str leaves to the dataset-wide bucketed width
    and align row-count buckets, so ONE jit executable serves every partition
    (reference analog: one LLVM module per stage regardless of partition
    count). Without this each partition's distinct shapes would recompile."""
    if not parts:
        return parts
    widths: dict[str, int] = {}
    for p in parts:
        for path, leaf in p.leaves.items():
            if isinstance(leaf, StrLeaf):
                widths[path] = max(widths.get(path, 1), leaf.width)
    for path in widths:
        widths[path] = bucket_size(widths[path], minimum=8)
    for p in parts:
        for path, w in widths.items():
            leaf = p.leaves.get(path)
            if isinstance(leaf, StrLeaf) and leaf.width < w:
                leaf.bytes = pad_to(leaf.bytes, w, axis=1)
    return parts


def _leaf_to_pylist(leaf: Leaf, n: int) -> list:
    """Bulk-decode one leaf to python values (C-speed paths)."""
    if isinstance(leaf, NullLeaf):
        return [None] * n
    if isinstance(leaf, ObjectLeaf):
        return list(leaf.values[:n])
    if isinstance(leaf, NumericLeaf):
        vals = leaf.data[:n].tolist()
        if leaf.valid is not None:
            v = leaf.valid
            return [x if v[i] else None for i, x in enumerate(vals)]
        return vals
    # StrLeaf: one flat buffer + byte slicing beats per-row np indexing
    w = leaf.bytes.shape[1] if leaf.bytes.ndim == 2 else 1
    flat = np.ascontiguousarray(leaf.bytes[:n]).tobytes()
    from ..native import get as _native_get

    nat = _native_get()
    if nat is not None:
        lens_b = np.ascontiguousarray(
            leaf.lengths[:n].astype(np.int32)).tobytes()
        decoded = nat.decode_str(flat, lens_b, w, n)
        if leaf.valid is not None:
            vv = leaf.valid[:n].tolist()
            return [decoded[i] if vv[i] else None for i in range(n)]
        return decoded
    lens = leaf.lengths[:n].tolist()
    if leaf.valid is not None:
        vv = leaf.valid[:n].tolist()
        return [
            flat[i * w: i * w + lens[i]].decode("utf-8", "replace")
            if vv[i] else None
            for i in range(n)
        ]
    return [flat[i * w: i * w + lens[i]].decode("utf-8", "replace")
            for i in range(n)]


def decode_rows(part: Partition, indices) -> "list[Row]":
    """Bulk-decode the given local row positions into boxed Rows — the
    batched replacement for per-row decode_row on the interpreter path
    (reference analog: PythonDataSet.cc bulk converters)."""
    from ..core.row import Row

    idx = np.asarray(list(indices), dtype=np.int64)
    m = len(idx)
    if m == 0:
        return []
    cols = part.user_columns
    single = len(part.schema.types) == 1
    gp = gather_partition(part, np.arange(m, dtype=np.int64), idx, m)
    gp.fallback = {}
    vals = partition_to_pylist(gp)
    fb = part.fallback
    rows: list[Row] = []
    for j, i in enumerate(idx.tolist()):
        if i in fb:
            rows.append(Row.from_value(fb[i], cols))
        elif single:
            rows.append(Row((vals[j],), cols))
        else:
            rows.append(Row(vals[j], cols))
    return rows


def _decode_columns_native(part: Partition, n: int) -> Optional[list]:
    """One-pass C decode of a flat-primitive partition into row tuples
    (reference analog: PythonDataSet.cc:1400-1442 resultSetToCPython's
    per-type bulk decoders). None when the schema has nested/object
    columns or the native module is unavailable."""
    from ..native import get as native_get

    nat = native_get()
    if nat is None or not hasattr(nat, "decode_columns"):
        return None
    codes = {T.I64: 0, T.F64: 1, T.BOOL: 2, T.STR: 3}
    spec = []
    for ci, t in enumerate(part.schema.types):
        base = t.without_option() if t.is_optional() else t
        code = codes.get(base)
        leaf = part.leaves.get(str(ci))
        if code is None or leaf is None:
            return None
        valid = None
        if getattr(leaf, "valid", None) is not None:
            valid = np.ascontiguousarray(
                np.asarray(leaf.valid[:n]).astype(np.uint8, copy=False))
        if code == 3:
            if not isinstance(leaf, StrLeaf):
                return None
            mat = np.ascontiguousarray(np.asarray(leaf.bytes[:n]))
            w = mat.shape[1] if mat.ndim == 2 else 1
            lens = np.ascontiguousarray(
                np.asarray(leaf.lengths[:n]).astype(np.int32, copy=False))
            spec.append((3, mat, valid, lens, w))
        else:
            if not isinstance(leaf, NumericLeaf):
                return None
            data = np.asarray(leaf.data[:n])
            want = {0: np.int64, 1: np.float64, 2: np.uint8}[code]
            data = np.ascontiguousarray(data.astype(want, copy=False))
            spec.append((code, data, valid))
    return nat.decode_columns(spec, n)


def partition_to_pylist(part: Partition) -> list:
    """Bulk row decode (reference analog: PythonDataSet.cc fast decoders —
    bulk converters instead of per-row boxing)."""
    n = part.num_rows
    if n == 0:
        return []  # empty partitions may carry no leaf arrays at all
    single = len(part.schema.types) == 1
    out_fast = _decode_columns_native(part, n)
    if out_fast is not None:
        out = out_fast
    else:
        cols = []
        for ci, ct in enumerate(part.schema.types):
            cols.append(_column_pylist(part, str(ci), ct, n))
        if single:
            out = list(cols[0])
        else:
            out = list(zip(*cols))
    if part.fallback:
        for i, v in part.fallback.items():
            # Row.from_value semantics: single-field tuples collect bare
            if single and isinstance(v, tuple) and len(v) == 1:
                out[i] = v[0]
            else:
                out[i] = v
    return out


def _column_pylist(part: Partition, path: str, t: T.Type, n: int) -> list:
    base = t.without_option() if t.is_optional() else t
    opt = t.is_optional()
    if isinstance(base, T.TupleType):
        sub = [
            _column_pylist(part, f"{path}.{j}", T.option(e) if opt else e, n)
            for j, e in enumerate(base.elements)
        ]
        tuples = list(zip(*sub)) if sub else [()] * n
        if opt:
            ol = part.leaves[f"{path}#opt"]
            assert isinstance(ol, NumericLeaf)
            ov = ol.data[:n].tolist()
            return [tuples[i] if ov[i] else None for i in range(n)]
        return tuples
    if base is T.EMPTYTUPLE:
        if opt:
            leaf = part.leaves[path]
            assert isinstance(leaf, NumericLeaf) and leaf.valid is not None
            return [() if leaf.valid[i] else None for i in range(n)]
        return [()] * n
    return _leaf_to_pylist(part.leaves[path], n)


# ---------------------------------------------------------------------------
# native fast transfer (reference: PythonContext.cc fast paths)
# ---------------------------------------------------------------------------

def _fast_partition(values: Sequence[Any], schema: T.RowType,
                    start_index: int) -> Optional[Partition]:
    """C-kernel bulk encode for flat primitive schemas; None if the schema
    or the native module isn't eligible (generic python path then runs)."""
    from ..native import get as native_get

    nat = native_get()
    if nat is None:
        return None
    kinds = []
    for t in schema.types:
        base = t.without_option() if t.is_optional() else t
        if base is T.I64:
            kinds.append(("i64", t.is_optional()))
        elif base is T.F64:
            kinds.append(("f64", t.is_optional()))
        elif base is T.BOOL:
            kinds.append(("bool", t.is_optional()))
        elif base is T.STR:
            kinds.append(("str", t.is_optional()))
        else:
            return None
    n = len(values)
    k = len(kinds)
    multi = k > 1

    if multi and hasattr(nat, "encode_rows"):
        return _fast_partition_rows(nat, values, schema, kinds, start_index)

    # split rows into per-column python lists (C-speed zip for clean rows)
    bad_rows: set[int] = set()
    if multi:
        clean = True
        for v in values:
            if not (type(v) is tuple and len(v) == k):
                clean = False
                break
        if clean:
            cols = [list(c) for c in zip(*values)] if n else [[] for _ in kinds]
        else:
            cols = [[None] * n for _ in range(k)]
            for i, v in enumerate(values):
                if type(v) is tuple and len(v) == k:
                    for ci in range(k):
                        cols[ci][i] = v[ci]
                else:
                    bad_rows.add(i)
    else:
        cols = [[v[0] if type(v) is tuple and len(v) == 1 else v
                 for v in values]]

    leaves: dict[str, Leaf] = {}
    for ci, (kind, opt) in enumerate(kinds):
        col = cols[ci]
        if kind == "str":
            mat_b, lens_b, valid_b, w, bad = nat.encode_str(col)
            enc = (mat_b, lens_b, valid_b, w)
        else:
            encode = {"i64": nat.encode_i64, "f64": nat.encode_f64,
                      "bool": nat.encode_bool}[kind]
            data_b, valid_b, bad = encode(col)
            enc = (data_b, valid_b)
        leaves[str(ci)], valid = _leaf_from_encoded(kind, opt, enc, n)
        bad_rows.update(bad)
        if not opt:
            # None in a non-Option column deviates from the normal case
            bad_rows.update(np.nonzero(~valid)[0].tolist())
    return _partition_with_fallback(schema, n, leaves, start_index,
                                    bad_rows, values)


def _fast_partition_rows(nat, values: Sequence[Any], schema: T.RowType,
                         kinds, start_index: int) -> Partition:
    """Mixed-tuple bulk encode: ONE C pass over the row tuples builds every
    column buffer (reference analog: PythonContext.cc:860
    fastMixedSimpleTypeTupleTransfer), replacing the python-side transpose +
    per-column encoders. Non-conforming rows (arity/type/overflow) come back
    in bad_list and box into the fallback dict."""
    n = len(values)
    codes = {"i64": 0, "f64": 1, "bool": 2, "str": 3}
    cols_enc, bad = nat.encode_rows(list(values),
                                    [codes[kd] for kd, _ in kinds])
    bad_rows: set[int] = set(bad)
    leaves: dict[str, Leaf] = {}
    for ci, (kind, opt) in enumerate(kinds):
        leaves[str(ci)], valid = _leaf_from_encoded(kind, opt,
                                                    cols_enc[ci], n)
        if not opt:
            # None in a non-Option column deviates from the normal case
            bad_rows.update(np.nonzero(~valid)[0].tolist())
    return _partition_with_fallback(schema, n, leaves, start_index,
                                    bad_rows, values)


def _leaf_from_encoded(kind: str, opt: bool, enc: tuple, n: int):
    """C-encoder buffers -> Leaf + full validity array (shared by the
    per-column and mixed-tuple encode paths)."""
    if kind == "str":
        mat_b, lens_b, valid_b, w = enc
        mat = np.frombuffer(mat_b, dtype=np.uint8).reshape(n, w).copy() \
            if n else np.zeros((0, max(w, 1)), np.uint8)
        lens = np.frombuffer(lens_b, dtype=np.int32).copy()
        valid = np.frombuffer(valid_b, dtype=np.uint8).astype(np.bool_)
        return StrLeaf(mat, lens, valid.copy() if opt else None), valid
    data_b, valid_b = enc
    dtype = {"i64": np.int64, "f64": np.float64, "bool": np.uint8}[kind]
    data = np.frombuffer(data_b, dtype=dtype).copy()
    if kind == "bool":
        data = data.astype(np.bool_)
    valid = np.frombuffer(valid_b, dtype=np.uint8).astype(np.bool_)
    return NumericLeaf(data, valid.copy() if opt else None), valid


def _partition_with_fallback(schema: T.RowType, n: int, leaves: dict,
                             start_index: int, bad_rows: set,
                             values: Sequence[Any]) -> Partition:
    part = Partition(schema=schema, num_rows=n, leaves=leaves,
                     start_index=start_index)
    if bad_rows:
        mask = np.ones(n, dtype=np.bool_)
        fallback = {}
        for i in sorted(bad_rows):
            mask[i] = False
            fallback[i] = values[i]
        part.normal_mask = mask
        part.fallback = fallback
    return part


def arrow_string_to_leaf(arr, n: int, max_w: int,
                         valid: Optional[np.ndarray] = None,
                         return_full_lens: bool = False):
    """Arrow large_string array -> fixed-width byte-matrix leaf (vectorized
    offsets gather; shared by the CSV and ORC sources). With
    return_full_lens, also returns the UNCLAMPED byte lengths so callers can
    detect over-long cells without re-reading the buffers."""
    buffers = arr.buffers()
    from ..native import get as _native_get

    nat = _native_get()
    if nat is not None and hasattr(nat, "offsets_to_matrix") and n:
        mat_b, lens_b, full_b, w = nat.offsets_to_matrix(
            buffers[2] if buffers[2] else b"", buffers[1], n, arr.offset,
            max_w)
        mat = np.frombuffer(mat_b, dtype=np.uint8).reshape(n, w)
        leaf = StrLeaf(mat, np.frombuffer(lens_b, dtype=np.int32), valid)
        if return_full_lens:
            return leaf, np.frombuffer(full_b, dtype=np.int64)
        return leaf
    offsets = np.frombuffer(buffers[1], dtype=np.int64,
                            count=len(arr) + 1 + arr.offset)[arr.offset:]
    data = np.frombuffer(buffers[2], dtype=np.uint8) if buffers[2] \
        else np.zeros(0, np.uint8)
    starts = offsets[:-1]
    lens = (offsets[1:] - starts).astype(np.int64)
    w = int(min(max(int(lens.max()) if n else 1, 1), max_w))
    idx = starts[:, None] + np.arange(w, dtype=np.int64)[None, :]
    np.clip(idx, 0, max(len(data) - 1, 0), out=idx)
    mat = data[idx] if len(data) else np.zeros((n, w), np.uint8)
    keep = np.arange(w, dtype=np.int64)[None, :] < \
        np.minimum(lens, w)[:, None]
    mat = np.where(keep, mat, 0).astype(np.uint8)
    leaf = StrLeaf(mat, np.minimum(lens, w).astype(np.int32), valid)
    return (leaf, lens) if return_full_lens else leaf
