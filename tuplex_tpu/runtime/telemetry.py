"""Serve-layer telemetry: streaming histograms, sampled gauges, health.

Per-job observability already exists (api/metrics one-shot snapshots,
runtime/tracing span dumps) but a long-lived service needs the OPPOSITE
shape: cheap, always-on, mergeable AGGREGATES a scraper can pull at any
moment without touching per-event state. Three pieces:

* **log-bucketed streaming histograms** — fixed memory (~120 buckets
  spanning 1e-6..1e6 with 10 buckets/decade ≈ ±12% relative error),
  O(1) record (one log10 + one list bump under a lock), exact
  count/sum/min/max, mergeable across threads/hosts by elementwise bucket
  addition, and p50/p95/p99/max readouts by cumulative walk. The serve
  path records admission wait, stage-queue wait, per-dispatch latency and
  end-to-end job latency into per-tenant series.
* **sampled gauges** — a value or a zero-arg callable evaluated at
  export time (queue depth, busy slots, resident bytes...). Gauges and
  health checks carry an ``owner`` token so a closing JobService drops
  everything it registered (``drop_owner``) — a process that serves many
  short-lived services in tests must not accumulate dead callbacks.
* **a health state machine** — named checks return (state, detail);
  the overall state is the worst of them (ok < degraded < unhealthy).
  The JobService wires admission-queue saturation, wedged-compile age
  and slot starvation; ``/healthz`` and the Prometheus gauge expose it.

Exposition is pull-based Prometheus text (``render_prometheus``): the
registry's own series plus bridged families from the tagged counter
registry (runtime/xferstats — d2h/h2d/spill/cache and every other named
counter) and the compile queue (exec/compilequeue STATS + in-flight
ages), so ONE scrape shows the data plane, the compile plane and the
scheduler. ``start_metrics_server`` serves ``/metrics`` + ``/healthz``
on a loopback stdlib HTTP thread; ``write_prom`` drops the same text
atomically for the scratch-dir wire protocol.

Disabled (``TUPLEX_TELEMETRY=0`` env, or ``tuplex.tpu.telemetry`` false)
the record path is one module-flag check — no allocation, no lock, no
bucket write (the same zero-overhead contract the tracing no-op path
pins, test-asserted).
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Any, Callable, Optional

# ---------------------------------------------------------------------------
# enable gate (mirrors runtime/tracing: process-wide, env wins)
# ---------------------------------------------------------------------------


def _env_disabled() -> bool:
    return os.environ.get("TUPLEX_TELEMETRY", "").strip().lower() \
        in ("0", "false", "off")


_enabled = not _env_disabled()


def enabled() -> bool:
    return _enabled


def enable(on: bool = True) -> None:
    """Process-wide record gate. The env kill switch TUPLEX_TELEMETRY=0
    wins over any option-driven enable (A/B overhead timing)."""
    global _enabled
    _enabled = bool(on) and not _env_disabled()


# ---------------------------------------------------------------------------
# streaming histogram
# ---------------------------------------------------------------------------

#: bucket geometry: 12 decades from 1 microsecond to 1 megasecond covers
#: every latency this framework can see; 10 buckets/decade bounds the
#: percentile estimate's relative error at ~±12% (half a bucket width)
_LO = 1e-6
_DECADES = 12
_PER_DECADE = 10
_NBUCKETS = _DECADES * _PER_DECADE
_LOG_LO = math.log10(_LO)


def _bucket_upper(i: int) -> float:
    """Upper bound of regular bucket i (1-based within the regular run)."""
    return 10.0 ** (_LOG_LO + i / _PER_DECADE)


class Histogram:
    """Fixed-size log-bucketed streaming histogram.

    ``counts[0]`` is the underflow bucket (values <= _LO, including 0 and
    negatives), ``counts[-1]`` the overflow; count/sum/min/max are exact
    so single-sample and extreme percentiles clamp to true values.
    ``record`` is O(1); ``merge`` is elementwise and lossless, so
    per-thread or per-host instances combine into one distribution.
    """

    __slots__ = ("counts", "count", "sum", "min", "max", "_lock")

    def __init__(self):
        self.counts = [0] * (_NBUCKETS + 2)
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._lock = threading.Lock()

    # -- write ---------------------------------------------------------------
    def record(self, value: float) -> None:
        v = float(value)
        if not math.isfinite(v):        # NaN/±inf: drop — a sentinel from
            return                      # a bad division must not poison
                                        # the sum (or blow up in log10)
        if v <= _LO:
            idx = 0
        else:
            idx = 1 + int((math.log10(v) - _LOG_LO) * _PER_DECADE)
            if idx > _NBUCKETS:
                idx = _NBUCKETS + 1
        with self._lock:
            self.counts[idx] += 1
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v

    def merge(self, other: "Histogram") -> "Histogram":
        """Fold `other`'s distribution into self (both stay usable)."""
        with other._lock:
            oc = list(other.counts)
            on, os_, omin, omax = (other.count, other.sum,
                                   other.min, other.max)
        with self._lock:
            for i, c in enumerate(oc):
                self.counts[i] += c
            self.count += on
            self.sum += os_
            if omin < self.min:
                self.min = omin
            if omax > self.max:
                self.max = omax
        return self

    # -- read ----------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {"counts": list(self.counts), "count": self.count,
                    "sum": self.sum, "min": self.min, "max": self.max}

    @staticmethod
    def _pct_from(snap: dict, q: float) -> float:
        n = snap["count"]
        if n <= 0:
            return 0.0
        target = max(1, math.ceil(max(0.0, min(1.0, q)) * n))
        cum = 0
        est = snap["max"]
        for i, c in enumerate(snap["counts"]):
            cum += c
            if cum >= target:
                if i == 0:
                    est = snap["min"]
                elif i == _NBUCKETS + 1:
                    est = snap["max"]
                else:
                    est = 10.0 ** (_LOG_LO + (i - 0.5) / _PER_DECADE)
                break
        return min(max(est, snap["min"]), snap["max"])

    def percentile(self, q: float) -> float:
        """Estimate the q-quantile (q in [0,1]): cumulative bucket walk,
        geometric bucket midpoint, clamped to the exact [min, max]. 0.0
        when empty."""
        return self._pct_from(self.snapshot(), q)

    def percentiles(self) -> dict:
        """The standard readout: p50/p95/p99 + exact max/mean/count. ONE
        locked snapshot feeds every quantile, so a readout racing
        concurrent record()s stays internally consistent (four separate
        snapshots could report p99 < p50)."""
        snap = self.snapshot()
        n = snap["count"]
        return {
            "count": n,
            "mean": (snap["sum"] / n) if n else 0.0,
            "p50": self._pct_from(snap, 0.50),
            "p95": self._pct_from(snap, 0.95),
            "p99": self._pct_from(snap, 0.99),
            "max": snap["max"] if n else 0.0,
        }

    def prom_buckets(self, snap: Optional[dict] = None) \
            -> list[tuple[str, int]]:
        """Cumulative (le, count) pairs for Prometheus exposition. Sparse:
        only boundaries where the cumulative count moves are emitted (plus
        the mandatory +Inf) — 120 mostly-empty buckets per labeled series
        would swamp the scrape. Pass the snapshot the caller already took
        so _bucket/_sum/_count render from one consistent view."""
        if snap is None:
            snap = self.snapshot()
        out: list[tuple[str, int]] = []
        cum = 0
        prev = 0
        for i in range(_NBUCKETS + 1):          # underflow + regular runs
            cum += snap["counts"][i]
            if cum != prev:
                le = _bucket_upper(i) if i > 0 else _LO
                out.append((repr(le), cum))
                prev = cum
        out.append(("+Inf", snap["count"]))
        return out


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

#: health states, worst wins
OK = "ok"
DEGRADED = "degraded"
UNHEALTHY = "unhealthy"
_RANK = {OK: 0, DEGRADED: 1, UNHEALTHY: 2}


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Registry:
    """Named histogram/gauge/health-check store. Metric names use
    Prometheus spelling minus the ``tuplex_`` prefix (added at render):
    ``serve_job_latency_seconds``, labels as kwargs."""

    def __init__(self):
        self._lock = threading.Lock()
        self._hists: dict[tuple, Histogram] = {}
        # name-key -> (owner, value-or-callable)
        self._gauges: dict[tuple, tuple] = {}
        self._checks: dict[str, tuple] = {}

    # -- histograms ----------------------------------------------------------
    def histogram(self, name: str, **labels) -> Histogram:
        key = (name, _label_key(labels))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = Histogram()
            return h

    def observe(self, name: str, value: float, **labels) -> None:
        self.histogram(name, **labels).record(value)

    def histograms(self) -> dict:
        with self._lock:
            return dict(self._hists)

    def merged(self, name: str) -> Histogram:
        """All label series of `name` merged into one fresh Histogram —
        overall percentiles across tenants."""
        out = Histogram()
        for (n, _lk), h in self.histograms().items():
            if n == name:
                out.merge(h)
        return out

    # -- gauges --------------------------------------------------------------
    def set_gauge(self, name: str, value, owner=None, **labels) -> None:
        """Register a gauge: `value` may be a number or a zero-arg callable
        sampled at export (a failing callable exports nothing)."""
        with self._lock:
            self._gauges[(name, _label_key(labels))] = (owner, value)

    def remove_gauge(self, name: str, **labels) -> None:
        """Unregister one gauge series (e.g. a retired serve tenant's
        per-tenant gauge — without this, a churning tenant population
        accumulates one dead callback per tenant ever seen, each
        exported as a stale sample on every scrape)."""
        with self._lock:
            self._gauges.pop((name, _label_key(labels)), None)

    def gauge_samples(self) -> list[tuple[str, tuple, float]]:
        with self._lock:
            items = list(self._gauges.items())
        out = []
        for (name, lk), (_owner, v) in items:
            try:
                val = float(v() if callable(v) else v)
            except Exception:
                continue
            out.append((name, lk, val))
        return out

    # -- health --------------------------------------------------------------
    def register_health_check(self, name: str, fn: Callable,
                              owner=None) -> None:
        """`fn()` -> (state, detail) with state in ok|degraded|unhealthy."""
        with self._lock:
            self._checks[name] = (owner, fn)

    def health(self) -> dict:
        """Evaluate every check; overall state is the worst one. A check
        that raises reports degraded (a broken probe is a signal, not a
        crash)."""
        with self._lock:
            checks = list(self._checks.items())
        out: dict = {"state": OK, "checks": {}}
        for name, (_owner, fn) in checks:
            try:
                state, detail = fn()
                if state not in _RANK:
                    state, detail = DEGRADED, f"bad check state {state!r}"
            except Exception as e:   # noqa: BLE001 - probe failure != crash
                state, detail = DEGRADED, f"check failed: {e}"
            out["checks"][name] = {"state": state,
                                   **({"detail": detail} if detail else {})}
            if _RANK[state] > _RANK[out["state"]]:
                out["state"] = state
        return out

    # -- lifecycle -----------------------------------------------------------
    def drop_owner(self, owner) -> None:
        """Remove every gauge and health check `owner` registered (a
        closing JobService; histograms stay — they are data, not
        callbacks into dead objects)."""
        with self._lock:
            self._gauges = {k: v for k, v in self._gauges.items()
                            if v[0] is not owner}
            self._checks = {k: v for k, v in self._checks.items()
                            if v[0] is not owner}

    def clear(self) -> None:
        """Drop everything (tests)."""
        with self._lock:
            self._hists.clear()
            self._gauges.clear()
            self._checks.clear()


_REG = Registry()


def registry() -> Registry:
    return _REG


# -- module-level conveniences (the instrumented call sites) -----------------

def observe(name: str, value: float, **labels) -> None:
    """Record one histogram sample. Disabled: one flag check, nothing
    allocated — safe on any hot path."""
    if not _enabled:
        return
    _REG.observe(name, value, **labels)


def set_gauge(name: str, value, owner=None, **labels) -> None:
    if not _enabled:
        return
    _REG.set_gauge(name, value, owner=owner, **labels)


def remove_gauge(name: str, **labels) -> None:
    _REG.remove_gauge(name, **labels)


def register_health_check(name: str, fn: Callable, owner=None) -> None:
    _REG.register_health_check(name, fn, owner=owner)


def drop_owner(owner) -> None:
    _REG.drop_owner(owner)


def health() -> dict:
    return _REG.health()


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

_PREFIX = "tuplex_"


def _sanitize(name: str) -> str:
    out = []
    for i, ch in enumerate(name):
        ok = ch.isascii() and (ch.isalpha() or ch == "_" or ch == ":"
                               or (ch.isdigit() and i > 0))
        out.append(ch if ok else "_")
    return "".join(out)


def _esc(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def _fmt_labels(pairs) -> str:
    if not pairs:
        return ""
    body = ",".join(f'{_sanitize(k)}="{_esc(v)}"' for k, v in pairs)
    return "{" + body + "}"


def _fmt_val(v: float) -> str:
    f = float(v)
    if f == math.inf:
        return "+Inf"
    if f == -math.inf:
        return "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _counter_families() -> dict[str, list]:
    """Bridge runtime/xferstats into exposition families. xferstats adds a
    tagged bump to BOTH the base counter and its per-tag bucket, so a
    family with tags must not emit the base total alongside them (a PromQL
    ``sum()`` over the family would double-count): tagged families emit
    one sample per tag plus a ``tag=""`` remainder for untagged bumps;
    tagless families emit one unlabeled sample."""
    from . import xferstats

    counters = xferstats.counters()
    by_family: dict[str, dict] = {}
    for key, v in xferstats.tags().items():
        name, _, tag = key.partition(":")
        by_family.setdefault(name, {})[tag] = v
    fams: dict[str, list] = {}
    for name, total in sorted(counters.items()):
        tags = by_family.get(name)
        if not tags:
            fams[name] = [((), total)]
            continue
        rows = [((("tag", t),), v) for t, v in sorted(tags.items())]
        rest = total - sum(tags.values())
        if rest > 0:
            rows.append(((("tag", ""),), rest))
        fams[name] = rows
    return fams


def _compile_plane_lines(lines: list) -> None:
    """Compile-queue counters + in-flight gauges + the AOT hit ratio."""
    try:
        from ..exec import compilequeue as CQ
    except Exception:       # pragma: no cover - import cycle safety
        return
    stats = CQ.snapshot()
    for k in sorted(stats):
        if k == "compile_s":
            continue
        n = _PREFIX + "compile_" + _sanitize(k) + "_total"
        lines.append(f"# TYPE {n} counter")
        lines.append(f"{n} {_fmt_val(stats[k])}")
    n = _PREFIX + "compile_seconds_total"
    lines.append(f"# TYPE {n} counter")
    lines.append(f"{n} {_fmt_val(stats.get('compile_s', 0.0))}")
    hits = stats.get("aot_hits", 0)
    misses = stats.get("aot_misses", 0)
    n = _PREFIX + "aot_cache_hit_ratio"
    lines.append(f"# TYPE {n} gauge")
    lines.append(f"{n} {_fmt_val(hits / (hits + misses) if hits + misses else 0.0)}")
    try:
        info = CQ.pending_info()
    except Exception:       # pragma: no cover - older queue builds
        return
    for k, v in sorted(info.items()):
        n = _PREFIX + "compile_" + _sanitize(k)
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n} {_fmt_val(v)}")


#: devprof stage-report keys exported as per-stage gauges, with their
#: Prometheus family names (the `stage` label carries the stage cache key)
_DEVPROF_GAUGES = (
    ("device_s", "devprof_stage_device_seconds"),
    ("device_cold_s", "devprof_stage_device_cold_seconds"),
    ("device_dispatches", "devprof_stage_dispatches"),
    ("flops", "devprof_stage_flops"),
    ("device_bytes", "devprof_stage_bytes"),
    ("hbm_peak", "devprof_stage_hbm_peak_bytes"),
    ("roofline_frac", "devprof_stage_roofline_frac"),
    ("hbm_budget_frac", "devprof_stage_hbm_budget_frac"),
)


def _devprof_lines(lines: list) -> None:
    """Device-plane cost attribution (runtime/devprof): the last report
    per stage as labeled gauges, so one scrape shows measured device
    seconds, XLA flops/bytes/peak-memory and the roofline fraction next
    to the latency histograms the dispatch path already records
    (``device_dispatch_seconds{stage,state}``)."""
    try:
        from . import devprof
    except Exception:       # pragma: no cover - import cycle safety
        return
    reps = devprof.reports()
    if not reps:
        return
    trunc = devprof.STAGE_LABEL_LEN     # one truncation for histogram
    for key, fam in _DEVPROF_GAUGES:    # AND gauge labels: PromQL joins
        rows = [(tag, r[key]) for tag, r in sorted(reps.items())
                if key in r]
        if not rows:
            continue
        n = _PREFIX + fam
        lines.append(f"# TYPE {n} gauge")
        for tag, v in rows:
            lines.append(
                f"{n}{_fmt_labels((('stage', tag[:trunc]),))} "
                f"{_fmt_val(v)}")


def _excprof_lines(lines: list) -> None:
    """Exception-plane accounting (runtime/excprof): per-stage x code x
    operator counts, resolve-tier mix, and the per-scope drift readout
    (EWMA vs the plan-time baseline + the respecialize signal) as
    labeled gauges — next to the ``excprof_resolve_seconds`` histograms
    the resolve passes record through the normal registry."""
    try:
        from . import excprof
        from ..core.errors import exception_name
    except Exception:       # pragma: no cover - import cycle safety
        return
    reps = excprof.reports()
    trunc = excprof.STAGE_LABEL_LEN
    if reps:
        fams: dict[str, list] = {
            "excprof_rows_total": [], "excprof_exception_rows": [],
            "excprof_exception_rate": [], "excprof_unexpected_rows": [],
            "excprof_resolve_tier_rows": [], "excprof_baseline_codes": []}
        for tag, r in sorted(reps.items()):
            st = (("stage", tag[:trunc]),)
            fams["excprof_rows_total"].append((st, r["rows"]))
            fams["excprof_exception_rate"].append((st, r["rate"]))
            fams["excprof_unexpected_rows"].append((st, r["unexpected"]))
            for (code, op), n in sorted(r["codes"].items()):
                fams["excprof_exception_rows"].append(
                    (st + (("code", exception_name(code)),
                           ("op", str(op))), n))
            for tier, n in sorted(r["tiers"].items()):
                fams["excprof_resolve_tier_rows"].append(
                    (st + (("tier", tier),), n))
            base = r.get("baseline")
            if base is not None:
                fams["excprof_baseline_codes"].append(
                    (st + (("tier", base["tier"]),), len(base["codes"])))
        for fam, rows in fams.items():
            if not rows:
                continue
            n = _PREFIX + fam
            lines.append(f"# TYPE {n} gauge")
            for lbl, v in rows:
                lines.append(f"{n}{_fmt_labels(lbl)} {_fmt_val(v)}")
    # per-scope drift: '' = global, others = serve tenants
    scope_rows = []
    for scope in [""] + excprof.scopes():
        rep = excprof.scope_report(scope or None)
        if not rep.get("rows") and not scope:
            continue
        scope_rows.append((scope, rep))
    if scope_rows:
        for fam, key in (("excprof_drift_score", "drift_score"),
                         ("excprof_respecialize_recommended",
                          "respecialize_recommended"),
                         ("excprof_window_exception_rate", "ewma_rate")):
            n = _PREFIX + fam
            lines.append(f"# TYPE {n} gauge")
            for scope, rep in scope_rows:
                lines.append(
                    f"{n}{_fmt_labels((('scope', scope or 'global'),))} "
                    f"{_fmt_val(rep.get(key, 0.0))}")


def _critpath_lines(lines: list) -> None:
    """Latency-budget plane (runtime/critpath): per-tenant EWMA budget
    baselines (seconds per canonical bucket), SLO attainment, the
    multi-window burn-rate gauges the ``slo`` health check reads, and
    slow-job counts — the /metrics face of the same record whyslow and
    the dashboard budget panel render."""
    try:
        from . import critpath
    except Exception:       # pragma: no cover - import cycle safety
        return
    if not critpath.enabled():
        return
    tens = sorted(critpath.tenants())
    if not tens:
        return
    fams: dict[str, list] = {
        "critpath_jobs": [], "critpath_budget_seconds": [],
        "critpath_wall_ewma_seconds": [], "critpath_unattributed_frac": [],
        "critpath_slow_jobs": [], "critpath_slo_ms": [],
        "critpath_slo_attainment": [], "critpath_burn_rate": []}
    for tenant in tens:
        rep = critpath.tenant_report(tenant)
        lt = (("tenant", tenant or "global"),)
        fams["critpath_jobs"].append((lt, rep["jobs"]))
        fams["critpath_wall_ewma_seconds"].append((lt, rep["wall_ewma_s"]))
        fams["critpath_unattributed_frac"].append(
            (lt, rep["unattributed_ewma"]))
        fams["critpath_slow_jobs"].append((lt, rep["slow_jobs"]))
        for bucket, v in sorted(rep["baseline"].items()):
            fams["critpath_budget_seconds"].append(
                (lt + (("bucket", bucket),), v))
        if rep["slo_ms"] > 0:
            fams["critpath_slo_ms"].append((lt, rep["slo_ms"]))
            if rep["attainment"] is not None:
                fams["critpath_slo_attainment"].append(
                    (lt, rep["attainment"]))
            br = rep["burn"]
            fams["critpath_burn_rate"].append(
                (lt + (("window", "fast"),), br["fast"]))
            fams["critpath_burn_rate"].append(
                (lt + (("window", "slow"),), br["slow"]))
    for fam, rows in fams.items():
        if not rows:
            continue
        n = _PREFIX + fam
        lines.append(f"# TYPE {n} gauge")
        for lbl, v in rows:
            lines.append(f"{n}{_fmt_labels(lbl)} {_fmt_val(v)}")


def render_prometheus(reg: Optional[Registry] = None) -> str:
    """The full scrape: registry histograms + gauges, bridged xferstats
    counter families, compile-plane stats, and the health state as
    gauges (0=ok 1=degraded 2=unhealthy)."""
    reg = reg if reg is not None else _REG
    lines: list[str] = []

    # histograms, grouped by family name
    by_name: dict[str, list] = {}
    for (name, lk), h in sorted(reg.histograms().items()):
        by_name.setdefault(name, []).append((lk, h))
    for name, series in by_name.items():
        n = _PREFIX + _sanitize(name)
        lines.append(f"# TYPE {n} histogram")
        for lk, h in series:
            snap = h.snapshot()
            for le, cum in h.prom_buckets(snap):
                lines.append(
                    f"{n}_bucket{_fmt_labels(tuple(lk) + (('le', le),))}"
                    f" {cum}")
            lines.append(f"{n}_sum{_fmt_labels(lk)} "
                         f"{_fmt_val(snap['sum'])}")
            lines.append(f"{n}_count{_fmt_labels(lk)} {snap['count']}")

    # gauges
    gauge_rows: dict[str, list] = {}
    for name, lk, val in reg.gauge_samples():
        gauge_rows.setdefault(name, []).append((lk, val))
    for name in sorted(gauge_rows):
        n = _PREFIX + _sanitize(name)
        lines.append(f"# TYPE {n} gauge")
        for lk, val in sorted(gauge_rows[name]):
            lines.append(f"{n}{_fmt_labels(lk)} {_fmt_val(val)}")

    # tagged counter registry (xferstats)
    for name, samples in _counter_families().items():
        n = _PREFIX + _sanitize(name) + "_total"
        lines.append(f"# TYPE {n} counter")
        for lk, v in samples:
            lines.append(f"{n}{_fmt_labels(lk)} {_fmt_val(v)}")

    _compile_plane_lines(lines)
    _devprof_lines(lines)
    _excprof_lines(lines)
    _critpath_lines(lines)

    # health
    h = reg.health()
    n = _PREFIX + "health_state"
    lines.append(f"# TYPE {n} gauge")
    lines.append(f"{n} {_RANK[h['state']]}")
    if h["checks"]:
        n = _PREFIX + "health_check_state"
        lines.append(f"# TYPE {n} gauge")
        for cname in sorted(h["checks"]):
            lines.append(
                f"{n}{_fmt_labels((('check', cname),))} "
                f"{_RANK[h['checks'][cname]['state']]}")
    return "\n".join(lines) + "\n"


def write_prom(path: str, reg: Optional[Registry] = None) -> str:
    """Atomically drop the exposition text to `path` (the scratch-dir
    wire protocol's `<root>/metrics.prom`)."""
    text = render_prometheus(reg)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fp:
        fp.write(text)
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# the /metrics + /healthz HTTP server (stdlib, loopback by default)
# ---------------------------------------------------------------------------

def _make_server(port: int, host: str):
    import http.server

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802
            if self.path.split("?")[0] in ("/healthz", "/health"):
                h = health()
                body = json.dumps(h).encode()
                # degraded still returns 200 (scrapers keep reading a
                # limping service); only unhealthy is a hard 503
                code = 503 if h["state"] == UNHEALTHY else 200
                ctype = "application/json"
            elif self.path.split("?")[0] in ("/metrics", "/"):
                body = render_prometheus().encode()
                code = 200
                ctype = "text/plain; version=0.0.4; charset=utf-8"
            else:
                body = b"not found\n"
                code = 404
                ctype = "text/plain"
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet
            pass

    return http.server.HTTPServer((host, port), Handler)


def start_metrics_server(port: int, host: str = "127.0.0.1"):
    """Serve /metrics (Prometheus text) and /healthz (JSON; 503 only when
    unhealthy) on a daemon thread. port=0 picks a free port. Returns
    (server, url); call server.shutdown() to stop."""
    srv = _make_server(port, host)
    t = threading.Thread(target=srv.serve_forever, daemon=True,
                         name="tpx-metrics")
    t.start()
    return srv, f"http://{host}:{srv.server_address[1]}/"


# ---------------------------------------------------------------------------
# readout helpers (serve_bench + tests)
# ---------------------------------------------------------------------------

def latency_report(name: str = "serve_job_latency_seconds") -> dict:
    """Merged-across-tenants percentile readout for one histogram family."""
    return _REG.merged(name).percentiles()


def apply_options(options) -> None:
    """Wire the process gate from ContextOptions. Like tracing, the
    ``tuplex.tpu.telemetry`` option turns recording ON, never off — the
    gate is process-wide and another live service may depend on it, so
    one tenant's option must not freeze every other tenant's histograms.
    The only OFF switches are process-scoped by construction: the
    TUPLEX_TELEMETRY=0 env kill switch (wins over everything; enable()
    re-checks it) and an explicit ``telemetry.enable(False)``."""
    if options.get_bool("tuplex.tpu.telemetry", True):
        enable(True)
