"""Exception-plane observability: per-code fallback attribution, windowed
drift detection against the plan-time baseline, and the respecialization
signal.

Dual-mode processing is the framework's central mechanism, yet until now
the exception plane was the one plane with no telemetry: ``exec/local``
reduced an entire resolve pass to a single ``exception_rows`` count while
spans, serve histograms and device cost all stop at the compiled fast
path. Three pieces close the gap:

* **windowed accounting** — the D2H unpack and the resolve-tier passes
  (exec/local) record, per stage x operator x exception code, how many
  rows erred, which resolve tier each code finally landed on
  (exact-exit / general / interpreter) and how long each tier pass took
  (``excprof_resolve_seconds{stage,tier}`` telemetry histograms).
  Per-stage-execution accumulators are owner-scoped like devprof's
  dispatch windows, so concurrent serve jobs sharing a stage key never
  pool or steal each other's report.
* **plan-time baseline + drift** — ``capture_baseline(stage)`` snapshots
  the analyzer's exception inventory and resolve-plan verdict
  (``TransformStage.possible_exception_codes()`` / ``resolve_plan()``):
  which codes the plan EXPECTS, whether speculation pruned a cold arm,
  and whether the static verdict promised a code-free stage. Observed
  traffic folds into per-scope (per-tenant, thread-local like
  runtime/xferstats) windows; each rolled window updates an EWMA
  exception rate whose half-life is configurable. The drift score
  compares the EWMA against the scope's anchor (the plan-normal era:
  the first observed window, floored at the normal-case allowance) plus
  an unexpected-code component — codes OUTSIDE the plan inventory weigh
  far heavier, because they mean the speculation itself is stale, not
  just the data dirty. ``respecialize_recommended(scope)`` fires past
  the threshold and an ok/degraded ``exception_drift`` health check
  rides runtime/telemetry.
* **sampled deviant rows** — the first K rows per stage x code are kept
  repr-truncated, so "why did row X fall to the interpreter" is
  answerable from the dashboard without replaying the job. Bounded,
  truncated, and dead under the kill switch: the capture obeys the same
  privacy posture as exception previews (row payloads never leave the
  history file the operator already owns).

Disabled (``TUPLEX_EXCPROF=0`` env kill switch) every record path is one
module-flag check — no allocation, no lock (the zero-overhead contract
tracing/telemetry/devprof pin, test-asserted).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

# ---------------------------------------------------------------------------
# enable gate (mirrors runtime/devprof: process-wide, env kill switch wins)
# ---------------------------------------------------------------------------


def _env_disabled() -> bool:
    return os.environ.get("TUPLEX_EXCPROF", "").strip().lower() \
        in ("0", "false", "off")


_enabled = not _env_disabled()


def enabled() -> bool:
    return _enabled


def enable(on: bool = True) -> None:
    """Process-wide gate. TUPLEX_EXCPROF=0 wins over any option-driven
    enable (A/B overhead timing)."""
    global _enabled
    _enabled = bool(on) and not _env_disabled()


# ---------------------------------------------------------------------------
# configuration (apply_options wires the knobs; module defaults match
# core/options.py DEFAULTS)
# ---------------------------------------------------------------------------

#: one stage-label truncation for every exposition surface (shared
#: discipline with devprof.STAGE_LABEL_LEN so PromQL joins line up)
STAGE_LABEL_LEN = 16

_window_s = 10.0          # tuplex.serve.driftWindowS
_half_life_s = 30.0       # tuplex.tpu.excprofHalfLifeS
_threshold = 0.5          # tuplex.tpu.excprofDriftThreshold
_sample_k = 3             # tuplex.tpu.excprofSampleRows
_normal_rate = 0.05       # tuplex.tpu.excprofNormalRate (anchor floor for
                          # stages whose inventory expects exceptions)
_SAMPLE_REPR_LEN = 160    # repr truncation for captured deviant rows
_CLEAN_FLOOR = 0.005      # anchor floor when the plan promises NO codes
_UNEXPECTED_TOL = 0.01    # EWMA unexpected-code rate reading as full drift
_MAX_ENTRIES = 1024       # bound on every registry here


def configure(window_s: Optional[float] = None,
              half_life_s: Optional[float] = None,
              threshold: Optional[float] = None,
              sample_k: Optional[int] = None,
              normal_rate: Optional[float] = None) -> None:
    global _window_s, _half_life_s, _threshold, _sample_k, _normal_rate
    if window_s is not None and window_s > 0:
        _window_s = float(window_s)
    if half_life_s is not None and half_life_s > 0:
        _half_life_s = float(half_life_s)
    if threshold is not None and threshold > 0:
        _threshold = float(threshold)
    if sample_k is not None and sample_k >= 0:
        _sample_k = int(sample_k)
    if normal_rate is not None and normal_rate >= 0:
        _normal_rate = float(normal_rate)


def apply_options(options) -> None:
    """Wire the process gate + knobs from ContextOptions. Like devprof,
    ``tuplex.tpu.excprof`` turns accounting ON, never off — the gate is
    process-wide and another live Context/service may depend on it; the
    only OFF switches are the env kill switch and an explicit
    ``excprof.enable(False)``."""
    if options.get_bool("tuplex.tpu.excprof", True):
        enable(True)
    configure(
        window_s=options.get_float("tuplex.serve.driftWindowS", 0.0) or None,
        half_life_s=options.get_float("tuplex.tpu.excprofHalfLifeS", 0.0)
        or None,
        threshold=options.get_float("tuplex.tpu.excprofDriftThreshold", 0.0)
        or None,
        sample_k=options.get_int("tuplex.tpu.excprofSampleRows", 3),
        normal_rate=options.get_float("tuplex.tpu.excprofNormalRate", 0.0)
        or None)
    if _enabled:
        _ensure_health()


# ---------------------------------------------------------------------------
# state
# ---------------------------------------------------------------------------

_LOCK = threading.Lock()
_tls = threading.local()

#: stage key -> plan-time baseline {codes, tier, pruned}
_BASE: dict[str, dict] = {}
#: (owner, stage key) -> per-stage-execution accumulator (consumed by
#: stage_report into the stage metrics record)
_ACC: dict[tuple, dict] = {}
#: stage key -> cumulative exposition snapshot (the /metrics source)
_STAGE: dict[str, dict] = {}
#: scope ('' = process-global) -> drift window + EWMA state
_WIN: dict[str, dict] = {}
#: (stage key, code) -> [repr, ...] first-K deviant rows
_SAMPLES: dict[tuple, list] = {}

_health_registered = False
_HEALTH_OWNER = object()      # module-identity owner for telemetry checks


def set_scope(name: Optional[str]) -> None:
    """Attribute every record made by THIS thread to a named scope (the
    job service sets the running job's TENANT around each scheduler
    step — drift is a property of a tenant's traffic, not of one job).
    None clears the scope; scopeless records land on the '' global
    window only."""
    _tls.scope = None if name is None else str(name)


def current_scope() -> Optional[str]:
    return getattr(_tls, "scope", None)


class suppressed:
    """Context manager: every record made by THIS thread while inside is
    dropped (note_device/note_outcomes/note_tier/sample_row become
    no-ops). The respecialization canary (serve/respec) shadow-executes
    a candidate stage over rows the incumbent already accounted — its
    rows must hit neither the tenant's drift window nor the stage
    totals, or the canary itself would read as drift."""

    def __enter__(self):
        _tls.suppress = getattr(_tls, "suppress", 0) + 1
        return self

    def __exit__(self, *exc):
        _tls.suppress = max(0, getattr(_tls, "suppress", 1) - 1)
        return False


def _suppressed() -> bool:
    return bool(getattr(_tls, "suppress", 0))


def drop_scope(scope) -> Optional[dict]:
    """Release one scope's drift window/EWMA state and return its final
    cumulative snapshot (or None if the scope never recorded). The job
    service calls this when a tenant's last retained record is evicted —
    per-tenant windows/anchors otherwise live for the life of the
    process, an unbounded leak under a churning tenant population (the
    xferstats counter families already had the same retirement hook).
    The '' global window is never dropped this way."""
    name = str(scope) if scope is not None else ""
    if not name:
        return None
    with _LOCK:
        w = _WIN.pop(name, None)
        return dict(w) if w is not None else None


def reanchor(scope, rate: Optional[float] = None) -> None:
    """Adopt the scope's LIVE exception profile as its new plan-normal
    anchor — the promotion half of the respecialization loop
    (serve/respec): the re-speculated plan was specialized FOR the
    observed distribution, so that distribution is its normal case. The
    pending window folds first, then anchor and EWMA both move to the
    observed rate (floored like a first-window calibration) and the
    unexpected-code EWMA clears (the candidate's widened inventory
    expects those codes now). No-op for a scope that never recorded."""
    name = "" if scope is None else str(scope)
    now = time.monotonic()
    with _LOCK:
        w = _WIN.get(name)
        if w is None:
            return
        # the pending (not yet rolled) window is the FRESHEST evidence of
        # the live rate — the EWMA may still be converging toward it, and
        # an anchor below the true steady-state rate would re-trip on the
        # very traffic the new plan was specialized for
        pend = (w["errs"] / w["rows"]) if w["rows"] > 0 else 0.0
        _roll_locked(w, now, force=True)
        r = max(float(rate) if rate is not None else 0.0,
                w["ewma_rate"] or 0.0, pend)
        if r <= 0.0 and w["ewma_rate"] is None:
            return
        floor = _normal_rate if w["expect_codes"] else _CLEAN_FLOOR
        w["anchor"] = max(floor, r)
        w["ewma_rate"] = w["anchor"]
        w["ewma_unexpected"] = 0.0


# ---------------------------------------------------------------------------
# plan-time baseline
# ---------------------------------------------------------------------------


def capture_baseline(stage) -> None:
    """Snapshot the plan-time exception expectation for one stage: the
    analyzer's code inventory (``possible_exception_codes``), the
    resolve-plan tier verdict and whether branch speculation pruned a
    cold arm. Pure plan state — capturing twice is idempotent, and the
    snapshot survives the stage's memos being dropped."""
    if not _enabled:
        return
    try:
        key = stage.key()
    except Exception:
        return
    with _LOCK:
        if key in _BASE:
            return
    try:
        rp = stage.resolve_plan()
        base = {"codes": frozenset(int(c) for c in rp.codes),
                "tier": rp.tier,
                "pruned": bool(stage.speculation_pruned())}
    except Exception:
        base = {"codes": frozenset(), "tier": "?", "pruned": False}
    with _LOCK:
        while len(_BASE) >= _MAX_ENTRIES:
            _BASE.pop(next(iter(_BASE)))
        _BASE.setdefault(key, base)
    _ensure_health()


def baselines() -> dict:
    with _LOCK:
        return {k: dict(v) for k, v in _BASE.items()}


# ---------------------------------------------------------------------------
# recording (exec/local call sites)
# ---------------------------------------------------------------------------


def _acc(owner: int, stage: str) -> dict:
    a = _ACC.get((owner, stage))
    if a is None:
        while len(_ACC) >= _MAX_ENTRIES:
            _ACC.pop(next(iter(_ACC)))
        a = _ACC[(owner, stage)] = {
            "rows": 0, "errs": 0, "fallback": 0, "unexpected": 0,
            "tiers": {}, "tier_s": {}, "codes": {}, "code_tier": {}}
    return a


def _stage_entry(stage: str) -> dict:
    s = _STAGE.get(stage)
    if s is None:
        while len(_STAGE) >= _MAX_ENTRIES:
            _STAGE.pop(next(iter(_STAGE)))
        s = _STAGE[stage] = {
            "rows": 0, "errs": 0, "fallback": 0, "unexpected": 0,
            "codes": {}, "tiers": {}, "code_tier": {}}
    return s


def _window(scope: str) -> dict:
    w = _WIN.get(scope)
    if w is None:
        while len(_WIN) >= _MAX_ENTRIES:
            _WIN.pop(next(iter(_WIN)))
        w = _WIN[scope] = {
            "t0": time.monotonic(), "rows": 0, "errs": 0, "unexpected": 0,
            "expect_codes": False, "ewma_rate": None, "ewma_unexpected": 0.0,
            "anchor": None, "windows": 0,
            "cum_rows": 0, "cum_errs": 0, "cum_tiers": {}}
    return w


def ewma_alpha(dt_s: float, half_life_s: float) -> float:
    """Time-aware EWMA fold factor: the weight a window spanning
    ``dt_s`` seconds gets against the running estimate, parameterized
    so the old estimate retains exactly half its weight after one
    half-life. Shared with runtime/critpath's per-tenant latency-budget
    baselines so both drift detectors forget at the same, documented
    rate."""
    if half_life_s <= 0:
        return 1.0
    return 1.0 - 2.0 ** (-dt_s / half_life_s)


def _roll_locked(w: dict, now: float, force: bool = False) -> None:
    """Fold the current window into the EWMA when its span elapsed. An
    elapsed EMPTY window decays the EWMA toward the anchor — a tenant
    that stopped sending traffic must not pin the health state degraded
    forever on stale evidence."""
    dt = now - w["t0"]
    if not force and dt < _window_s:
        return
    if dt <= 0:
        dt = _window_s
    if w["rows"] > 0:
        rate = w["errs"] / w["rows"]
        unexpected = w["unexpected"] / w["rows"]
        if w["anchor"] is None:
            # the plan-normal era: the first observed window calibrates
            # the expected rate, floored at the configured allowance (a
            # code-free static verdict gets the tight floor — any
            # exception there IS evidence the speculation went stale)
            floor = _normal_rate if w["expect_codes"] else _CLEAN_FLOOR
            w["anchor"] = max(floor, rate)
    elif w["ewma_rate"] is not None and w["anchor"] is not None:
        rate = w["anchor"]
        unexpected = 0.0
    else:
        w["t0"] = now
        return
    alpha = ewma_alpha(dt, _half_life_s)
    if w["ewma_rate"] is None:
        w["ewma_rate"] = rate
        w["ewma_unexpected"] = unexpected
    else:
        w["ewma_rate"] += alpha * (rate - w["ewma_rate"])
        w["ewma_unexpected"] += alpha * (unexpected - w["ewma_unexpected"])
    w["windows"] += 1
    w["rows"] = w["errs"] = w["unexpected"] = 0
    w["t0"] = now


def _win_add_locked(stage: str, rows: int, errs: int,
                    unexpected: int) -> None:
    base = _BASE.get(stage)
    expect = bool(base and base["codes"])
    now = time.monotonic()
    sc = getattr(_tls, "scope", None)
    for name in ("",) if sc is None else ("", sc):
        w = _window(name)
        _roll_locked(w, now)
        w["rows"] += rows
        w["errs"] += errs
        w["unexpected"] += unexpected
        w["cum_rows"] += rows
        w["cum_errs"] += errs
        if expect:
            w["expect_codes"] = True


def note_device(stage: str, rows: int, packed_codes=None,
                fallback_rows: int = 0, owner: int = 0) -> None:
    """One partition's D2H unpack verdict: `rows` rows entered the
    stage, `packed_codes` is the raw device error lattice of the rows
    that erred (class code in the low byte, operator id above), and
    `fallback_rows` rows never reached the device at all (input-boxed
    fallback slots / whole-partition interpreter routing)."""
    if not _enabled or not stage or rows < 0 or _suppressed():
        return
    pairs: list = []
    n_err = 0
    if packed_codes is not None and len(packed_codes):
        import numpy as np

        arr = np.asarray(packed_codes)
        uniq, counts = np.unique(arr, return_counts=True)
        n_err = int(counts.sum())
        pairs = [(int(v) & 0xFF, int(v) >> 8, int(c))
                 for v, c in zip(uniq.tolist(), counts.tolist())]
    from ..core.errors import ExceptionCode as EC

    with _LOCK:
        base = _BASE.get(stage)
        known = base["codes"] if base else frozenset()
        unexpected = sum(c for code, _op, c in pairs if code not in known)
        a = _acc(owner, stage)
        a["rows"] += rows
        a["errs"] += n_err + fallback_rows
        a["fallback"] += fallback_rows
        a["unexpected"] += unexpected
        s = _stage_entry(stage)
        s["rows"] += rows
        s["errs"] += n_err + fallback_rows
        s["fallback"] += fallback_rows
        s["unexpected"] += unexpected
        for code, op, c in pairs:
            k = (code, op)
            s["codes"][k] = s["codes"].get(k, 0) + c
            a["codes"][k] = a["codes"].get(k, 0) + c
        if fallback_rows:
            k = (int(EC.PYTHON_FALLBACK), 0)
            s["codes"][k] = s["codes"].get(k, 0) + fallback_rows
            a["codes"][k] = a["codes"].get(k, 0) + fallback_rows
        _win_add_locked(stage, rows, n_err + fallback_rows, unexpected)
    _ensure_health()


def note_outcomes(stage: str, pairs, tier: str, owner: int = 0) -> None:
    """Final per-row attribution for one resolve tier: `pairs` is a list
    of (code, op_id) — which exception code landed on `tier`
    ('exact-exit' / 'general' / 'interpreter')."""
    if not _enabled or not stage or not pairs or _suppressed():
        return
    with _LOCK:
        a = _acc(owner, stage)
        a["tiers"][tier] = a["tiers"].get(tier, 0) + len(pairs)
        s = _stage_entry(stage)
        s["tiers"][tier] = s["tiers"].get(tier, 0) + len(pairs)
        for code, _op in pairs:
            k = (int(code), tier)
            s["code_tier"][k] = s["code_tier"].get(k, 0) + 1
            a["code_tier"][k] = a["code_tier"].get(k, 0) + 1
        sc = getattr(_tls, "scope", None)
        for name in ("",) if sc is None else ("", sc):
            ct = _window(name)["cum_tiers"]
            ct[tier] = ct.get(tier, 0) + len(pairs)


def note_tier(stage: str, tier: str, rows: int, retired: int,
              seconds: float, owner: int = 0) -> None:
    """One resolve-tier PASS over a partition's deviant rows: `rows`
    entered, `retired` left resolved, `seconds` of wall time — the
    resolve latency lands in the ``excprof_resolve_seconds{stage,tier}``
    telemetry histogram next to the serve-path latencies."""
    if not _enabled or not stage or _suppressed():
        return
    from . import telemetry

    telemetry.observe("excprof_resolve_seconds", seconds,
                      stage=stage[:STAGE_LABEL_LEN], tier=tier)
    with _LOCK:
        a = _acc(owner, stage)
        ts = a["tier_s"]
        ts[tier] = ts.get(tier, 0.0) + float(seconds)


def sample_row(stage: str, code: int, row) -> None:
    """Bounded deviant-row capture: keep the FIRST K rows per
    stage x code, repr-truncated — enough to answer "what does a row
    that falls to this tier look like" from the dashboard, small enough
    that a poison tenant cannot fill the process with row payloads."""
    if not _enabled or not stage or _sample_k <= 0 or _suppressed():
        return
    key = (stage, int(code))
    with _LOCK:
        buf = _SAMPLES.get(key)
        if buf is None:
            if len(_SAMPLES) >= _MAX_ENTRIES:
                return
            buf = _SAMPLES[key] = []
        if len(buf) >= _sample_k:
            return
        try:
            r = repr(row)
        except Exception:
            r = "<unrepresentable row>"
        if len(r) > _SAMPLE_REPR_LEN:
            r = r[:_SAMPLE_REPR_LEN] + "…"
        buf.append(r)


def code_for_name(exc_name: str) -> int:
    """Map an interpreter exception class name back onto the device code
    space ('ValueError' -> VALUEERROR); UNKNOWN for names outside it."""
    from ..core import errors

    member = errors.code_for_name(str(exc_name))
    return int(member) if member is not None \
        else int(errors.ExceptionCode.UNKNOWN)


# ---------------------------------------------------------------------------
# readouts
# ---------------------------------------------------------------------------


def stage_report(stage: str, owner: int = 0) -> Optional[dict]:
    """Consume the per-execution accumulator into FLAT NUMERIC metrics
    (they ride the stage metrics dict through Metrics.stage_breakdown
    unchanged): rows_seen, exception_rate, unexpected_code_rows and the
    per-tier retired-row counts."""
    if not _enabled or not stage:
        return None
    with _LOCK:
        a = _ACC.pop((owner, stage), None)
    if a is None or a["rows"] == 0:
        return None
    rep = {
        "rows_seen": a["rows"],
        "exception_rate": a["errs"] / a["rows"],
        "unexpected_code_rows": a["unexpected"],
        "resolve_exact_rows": a["tiers"].get("exact-exit", 0),
        "resolve_general_rows": a["tiers"].get("general", 0),
        "resolve_interpreter_rows": a["tiers"].get("interpreter", 0),
    }
    for tier, s in a["tier_s"].items():
        rep[f"resolve_{tier.replace('-', '_')}_s"] = s
    return rep


def _sub_counts(dst: dict, sub: dict) -> None:
    for k, n in sub.items():
        left = dst.get(k, 0) - n
        if left > 0:
            dst[k] = left
        else:
            dst.pop(k, None)


def discard_stage(stage: str, owner: int = 0) -> None:
    """Back out one stage execution's accounting — the _TierRestart
    path: a blown compile deadline restarts the stage from partition 0
    on a lower tier, so everything the aborted execution recorded would
    double-count against the re-run's. Pending window counts and the
    cumulative stage/scope totals are subtracted (floored at 0); window
    spans that already folded into the EWMA stay — a bounded
    approximation (restarts are rare and the EWMA forgets)."""
    if not _enabled or not stage:
        return
    with _LOCK:
        a = _ACC.pop((owner, stage), None)
        if a is None:
            return
        s = _STAGE.get(stage)
        if s is not None:
            for key in ("rows", "errs", "fallback", "unexpected"):
                s[key] = max(0, s[key] - a[key])
            _sub_counts(s["codes"], a["codes"])
            _sub_counts(s["tiers"], a["tiers"])
            _sub_counts(s["code_tier"], a["code_tier"])
        sc = getattr(_tls, "scope", None)
        for name in ("",) if sc is None else ("", sc):
            w = _WIN.get(name)
            if w is None:
                continue
            for key, src in (("rows", "rows"), ("errs", "errs"),
                             ("unexpected", "unexpected"),
                             ("cum_rows", "rows"), ("cum_errs", "errs")):
                w[key] = max(0, w[key] - a[src])
            _sub_counts(w["cum_tiers"], a["tiers"])


def reports() -> dict:
    """Cumulative per-stage accounting (the /metrics exposition source):
    {stage: {rows, errs, rate, fallback, unexpected, codes{(code,op): n},
    tiers{tier: n}, code_tier{(code,tier): n}, baseline}}."""
    with _LOCK:
        out = {}
        for k, s in _STAGE.items():
            d = {"rows": s["rows"], "errs": s["errs"],
                 "fallback": s["fallback"], "unexpected": s["unexpected"],
                 "rate": (s["errs"] / s["rows"]) if s["rows"] else 0.0,
                 "codes": dict(s["codes"]), "tiers": dict(s["tiers"]),
                 "code_tier": dict(s["code_tier"])}
            base = _BASE.get(k)
            if base is not None:
                d["baseline"] = {"codes": sorted(base["codes"]),
                                 "tier": base["tier"],
                                 "pruned": base["pruned"]}
            out[k] = d
        return out


def samples() -> dict:
    """{(stage, code): [repr, ...]} — the captured deviant rows."""
    with _LOCK:
        return {k: list(v) for k, v in _SAMPLES.items()}


def roll(force: bool = False) -> None:
    """Advance every scope window (tests + the chaos drift scenario force
    a deterministic roll instead of sleeping out the wall clock)."""
    now = time.monotonic()
    with _LOCK:
        for w in _WIN.values():
            _roll_locked(w, now, force=force)


def _score_locked(w: dict) -> float:
    if w["ewma_rate"] is None or w["anchor"] is None:
        return 0.0
    excess = max(0.0, w["ewma_rate"] - w["anchor"])
    # the configured normal-case allowance doubles as the score scale
    # floor, so lowering the knob raises drift sensitivity consistently
    scale = max(w["anchor"], _normal_rate)
    s_rate = min(1.0, excess / scale)
    s_codes = min(1.0, w["ewma_unexpected"] / _UNEXPECTED_TOL)
    return max(s_rate, s_codes)


def drift_score(scope: Optional[str] = None) -> float:
    """0..1 deviation of the scope's EWMA exception profile from its
    plan-time-anchored baseline. 0 until a full window has rolled."""
    name = "" if scope is None else str(scope)
    now = time.monotonic()
    with _LOCK:
        w = _WIN.get(name)
        if w is None:
            return 0.0
        _roll_locked(w, now)
        return _score_locked(w)


def respecialize_recommended(scope: Optional[str] = None) -> bool:
    """The ROADMAP adaptive-serving signal: this scope's live exception
    profile has drifted far enough from the plan-time expectation that a
    re-speculated (re-specialized) plan would likely beat the current
    one — rows are leaking off the compiled fast path."""
    return drift_score(scope) >= _threshold


def scope_report(scope: Optional[str] = None) -> dict:
    """One scope's full drift readout: cumulative rows/errs/tier mix plus
    the windowed EWMA, drift score and the respecialize flag (numeric 0/1
    so bench JSON consumers can gate on it)."""
    name = "" if scope is None else str(scope)
    now = time.monotonic()
    with _LOCK:
        w = _WIN.get(name)
        if w is None:
            return {"rows": 0, "errs": 0, "exception_rate": 0.0,
                    "ewma_rate": 0.0, "drift_score": 0.0,
                    "respecialize_recommended": 0, "windows": 0,
                    "tier_mix": {}}
        _roll_locked(w, now)
        score = _score_locked(w)
        total_t = sum(w["cum_tiers"].values())
        mix = {t.replace("-", "_"): (n / total_t if total_t else 0.0)
               for t, n in sorted(w["cum_tiers"].items())}
        return {
            "rows": w["cum_rows"], "errs": w["cum_errs"],
            "exception_rate": (w["cum_errs"] / w["cum_rows"])
            if w["cum_rows"] else 0.0,
            "ewma_rate": w["ewma_rate"] or 0.0,
            "anchor_rate": w["anchor"] if w["anchor"] is not None else 0.0,
            "drift_score": score,
            "respecialize_recommended": int(score >= _threshold),
            "windows": w["windows"],
            "tier_mix": mix,
        }


def scopes() -> list:
    with _LOCK:
        return [s for s in _WIN if s]


def tier_mix_total() -> dict:
    """PROCESS-GLOBAL resolve-tier mix (fractions of deviant rows retired
    per tier) from the global window's cumulative counts. Distinct from
    Metrics.resolveTierMix(), which recomputes the mix PER JOB from its
    own stages' resolve_*_rows metrics — use that for job-scoped
    readouts, this for the whole process (excstats / tests)."""
    with _LOCK:
        w = _WIN.get("")
        if w is None:
            return {}
        total = sum(w["cum_tiers"].values())
        return {t.replace("-", "_"): (n / total if total else 0.0)
                for t, n in sorted(w["cum_tiers"].items())}


# ---------------------------------------------------------------------------
# health (runtime/telemetry ok/degraded check)
# ---------------------------------------------------------------------------


def _health_check():
    from . import telemetry

    worst = 0.0
    worst_scope = ""
    now = time.monotonic()
    with _LOCK:
        for name, w in _WIN.items():
            _roll_locked(w, now)
            s = _score_locked(w)
            if s > worst:
                worst, worst_scope = s, name
    if worst >= _threshold:
        who = f"tenant {worst_scope!r}" if worst_scope else "global traffic"
        return (telemetry.DEGRADED,
                f"{who} drifted from the plan-time exception baseline "
                f"(drift_score {worst:.2f} >= {_threshold:.2f}) — "
                f"respecialization recommended")
    return (telemetry.OK, None)


def _ensure_health() -> None:
    """Register the ok/degraded exception-drift check with the telemetry
    registry (idempotent; re-registered after registry.clear() by the
    next apply_options/record — the local flag alone is not enough, a
    cleared registry must not leave the drift signal silently dark)."""
    global _health_registered
    try:
        from . import telemetry

        if _health_registered \
                and "exception_drift" in telemetry.registry()._checks:
            return
        telemetry.register_health_check("exception_drift", _health_check,
                                        owner=_HEALTH_OWNER)
        _health_registered = True
    except Exception:   # pragma: no cover - telemetry import cycle safety
        pass


# ---------------------------------------------------------------------------
# lifecycle (tests)
# ---------------------------------------------------------------------------


def clear() -> None:
    global _health_registered
    with _LOCK:
        _BASE.clear()
        _ACC.clear()
        _STAGE.clear()
        _WIN.clear()
        _SAMPLES.clear()
    _health_registered = False
