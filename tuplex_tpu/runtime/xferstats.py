"""Process-wide tagged counter registry for the data/compile plane.

Grew out of D2H-only transfer accounting: every point that moves bytes or
hits a cache notes it here, so the costs the perf PRs argue about are
MEASURED rather than asserted — bench.py reports per-run deltas
(`d2h_bytes`, `h2d_bytes`), `Metrics.as_dict()` exposes the registry, and
the history dashboard renders it per job. Counter families today:

  d2h_bytes/d2h_calls   device -> host transfers (packed-buffer fetch,
                        per-leaf device_get, lazy handoff materialization)
  h2d_bytes/h2d_calls   host -> device uploads (packed dispatch buffer,
                        per-leaf staging at dispatch)
  spill_bytes           MemoryManager swap-out volume
  cache_hits/misses     compile-side content-address lookups (compilequeue)

Counters are cumulative since process start; callers take snapshots and
diff (same pattern as MemoryManager.metrics_snapshot). Each bump may carry
a call-site TAG (`note_d2h(n, tag="packed_fetch")`) — per-tag totals
accumulate under "<name>:<tag>" and surface via ``tags()`` so a regression
points at the site, not just the family. Thread safety: bumps happen under
a lock — transfers are milliseconds, the lock is noise.
"""

from __future__ import annotations

import threading

_lock = threading.Lock()
_counters: dict[str, int] = {}
_tags: dict[str, int] = {}        # "name:tag" -> value


def bump(name: str, n: int = 1, tag: str | None = None) -> None:
    """Add `n` to counter `name` (and to its per-tag bucket when `tag` is
    given). Zero/negative increments are dropped — a counter only ever
    moves forward."""
    if n <= 0:
        return
    with _lock:
        _counters[name] = _counters.get(name, 0) + int(n)
        if tag:
            key = f"{name}:{tag}"
            _tags[key] = _tags.get(key, 0) + int(n)


def counter(name: str) -> int:
    with _lock:
        return _counters.get(name, 0)


def counters() -> dict:
    """Copy of every named counter (no tags)."""
    with _lock:
        return dict(_counters)


def tags() -> dict:
    """Copy of the per-tag breakdown ("name:tag" -> value)."""
    with _lock:
        return dict(_tags)


def as_dict() -> dict:
    """Registry view for Metrics/bench: counters + per-tag breakdown."""
    with _lock:
        d = dict(_counters)
        if _tags:
            d["by_tag"] = dict(_tags)
        return d


def snapshot() -> dict:
    """Point-in-time copy of all counters; feed to ``delta``."""
    with _lock:
        return dict(_counters)


def delta(snap: dict) -> dict:
    """Per-counter movement since `snap`. Always includes the transfer
    families (zero if untouched) so callers can read d2h/h2d
    unconditionally."""
    with _lock:
        cur = dict(_counters)
    out = {k: v - snap.get(k, 0) for k, v in cur.items()}
    for k in ("d2h_bytes", "d2h_calls", "h2d_bytes", "h2d_calls"):
        out.setdefault(k, 0)
    return out


def reset() -> None:
    """Drop every counter (tests)."""
    with _lock:
        _counters.clear()
        _tags.clear()


# -- transfer conveniences (the original xferstats API) ---------------------

def note_d2h(nbytes: int, tag: str | None = None) -> None:
    """Record one host-bound transfer of `nbytes` bytes."""
    if nbytes <= 0:
        return
    with _lock:
        _counters["d2h_bytes"] = _counters.get("d2h_bytes", 0) + int(nbytes)
        _counters["d2h_calls"] = _counters.get("d2h_calls", 0) + 1
        if tag:
            key = f"d2h_bytes:{tag}"
            _tags[key] = _tags.get(key, 0) + int(nbytes)


def note_h2d(nbytes: int, tag: str | None = None) -> None:
    """Record one device-bound upload of `nbytes` bytes."""
    if nbytes <= 0:
        return
    with _lock:
        _counters["h2d_bytes"] = _counters.get("h2d_bytes", 0) + int(nbytes)
        _counters["h2d_calls"] = _counters.get("h2d_calls", 0) + 1
        if tag:
            key = f"h2d_bytes:{tag}"
            _tags[key] = _tags.get(key, 0) + int(nbytes)


def d2h_bytes() -> int:
    return counter("d2h_bytes")


def h2d_bytes() -> int:
    return counter("h2d_bytes")
