"""Process-wide transfer accounting for the stage-boundary data plane.

Every point that actually pulls device bytes to the host (packed-buffer
fetch, per-leaf device_get, lazy handoff leaf materialization) notes its
byte count here, so the D2H tunnel tax is MEASURED rather than asserted:
bench.py reports the per-run delta as `d2h_bytes` and the varlen wire /
device-resident handoff work is judged against it (VERDICT r5: ~0.30 s of
a 0.73 s zillow job was boundary transfer).

Counters are cumulative since process start; callers take snapshots and
diff (same pattern as MemoryManager.metrics_snapshot). Thread safety:
bumps happen under a lock — fetches are milliseconds, the lock is noise.
"""

from __future__ import annotations

import threading

_lock = threading.Lock()
_d2h_bytes = 0
_d2h_calls = 0


def note_d2h(nbytes: int) -> None:
    """Record one host-bound transfer of `nbytes` bytes."""
    global _d2h_bytes, _d2h_calls
    if nbytes <= 0:
        return
    with _lock:
        _d2h_bytes += int(nbytes)
        _d2h_calls += 1


def snapshot() -> tuple[int, int]:
    with _lock:
        return (_d2h_bytes, _d2h_calls)


def delta(snap: tuple[int, int]) -> dict:
    with _lock:
        return {"d2h_bytes": _d2h_bytes - snap[0],
                "d2h_calls": _d2h_calls - snap[1]}


def d2h_bytes() -> int:
    with _lock:
        return _d2h_bytes
