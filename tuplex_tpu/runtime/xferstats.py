"""Process-wide tagged counter registry for the data/compile plane.

Grew out of D2H-only transfer accounting: every point that moves bytes or
hits a cache notes it here, so the costs the perf PRs argue about are
MEASURED rather than asserted — bench.py reports per-run deltas
(`d2h_bytes`, `h2d_bytes`), `Metrics.as_dict()` exposes the registry, and
the history dashboard renders it per job. Counter families today:

  d2h_bytes/d2h_calls   device -> host transfers (packed-buffer fetch,
                        per-leaf device_get, lazy handoff materialization)
  h2d_bytes/h2d_calls   host -> device uploads (packed dispatch buffer,
                        per-leaf staging at dispatch)
  spill_bytes           MemoryManager swap-out volume
  cache_hits/misses     compile-side content-address lookups (compilequeue)

Counters are cumulative since process start; callers take snapshots and
diff (same pattern as MemoryManager.metrics_snapshot). Each bump may carry
a call-site TAG (`note_d2h(n, tag="packed_fetch")`) — per-tag totals
accumulate under "<name>:<tag>" and surface via ``tags()`` so a regression
points at the site, not just the family. Thread safety: bumps happen under
a lock — transfers are milliseconds, the lock is noise.

Multi-tenant scoping (serve/): ``set_scope(job_id)`` makes every bump on
the calling thread ALSO accumulate into a per-scope counter family
(``scoped(job_id)``), so concurrent jobs sharing the process get isolated
accounting on top of the global totals. The scope is THREAD-local: bumps
from the job's executing thread (d2h/h2d/spill, inline-dispatch compile
counters) land in its family; bumps from shared background threads (the
compile pool's ahead-of-time compiles) attribute globally only.
"""

from __future__ import annotations

import threading

_lock = threading.Lock()
_counters: dict[str, int] = {}
_tags: dict[str, int] = {}        # "name:tag" -> value
_tls = threading.local()          # per-thread counter SCOPE (tenant/job id)
_scoped: dict[str, dict[str, int]] = {}   # scope -> {name: value}


def set_scope(name: str | None) -> None:
    """Attribute every bump made by THIS thread to a named scope (the job
    service sets the running job's id around each scheduler step). Scoped
    totals accumulate in parallel with the process-wide counters so
    concurrent tenants sharing one device get isolated counter families.
    None clears the scope."""
    _tls.scope = None if name is None else str(name)


def current_scope() -> str | None:
    return getattr(_tls, "scope", None)


def scoped(name: str) -> dict:
    """Copy of one scope's counter family ({counter: value}; empty when
    the scope never recorded anything)."""
    with _lock:
        return dict(_scoped.get(name, ()))


def scopes() -> list:
    with _lock:
        return list(_scoped)


def drop_scope(name: str) -> dict:
    """Remove (and return) one scope's family — the job service snapshots
    a finished job's counters onto its record and releases the registry
    entry, so a long-lived process doesn't accumulate one dict per job
    ever served. Global counters are untouched."""
    with _lock:
        return _scoped.pop(name, {})


def _bump_scope_locked(name: str, n: int) -> None:
    sc = getattr(_tls, "scope", None)
    if sc is not None:
        d = _scoped.setdefault(sc, {})
        d[name] = d.get(name, 0) + int(n)


def bump(name: str, n: int = 1, tag: str | None = None) -> None:
    """Add `n` to counter `name` (and to its per-tag bucket when `tag` is
    given). Zero/negative increments are dropped — a counter only ever
    moves forward."""
    if n <= 0:
        return
    with _lock:
        _counters[name] = _counters.get(name, 0) + int(n)
        _bump_scope_locked(name, n)
        if tag:
            key = f"{name}:{tag}"
            _tags[key] = _tags.get(key, 0) + int(n)


def counter(name: str) -> int:
    with _lock:
        return _counters.get(name, 0)


def counters() -> dict:
    """Copy of every named counter (no tags)."""
    with _lock:
        return dict(_counters)


def tags() -> dict:
    """Copy of the per-tag breakdown ("name:tag" -> value)."""
    with _lock:
        return dict(_tags)


def as_dict() -> dict:
    """Registry view for Metrics/bench: counters + per-tag breakdown."""
    with _lock:
        d = dict(_counters)
        if _tags:
            d["by_tag"] = dict(_tags)
        return d


def snapshot() -> dict:
    """Point-in-time copy of all counters; feed to ``delta``."""
    with _lock:
        return dict(_counters)


def delta(snap: dict) -> dict:
    """Per-counter movement since `snap`. Always includes the transfer
    families (zero if untouched) so callers can read d2h/h2d
    unconditionally."""
    with _lock:
        cur = dict(_counters)
    out = {k: v - snap.get(k, 0) for k, v in cur.items()}
    for k in ("d2h_bytes", "d2h_calls", "h2d_bytes", "h2d_calls"):
        out.setdefault(k, 0)
    return out


def reset() -> None:
    """Drop every counter (tests)."""
    with _lock:
        _counters.clear()
        _tags.clear()
        _scoped.clear()


# -- transfer conveniences (the original xferstats API) ---------------------

def note_d2h(nbytes: int, tag: str | None = None) -> None:
    """Record one host-bound transfer of `nbytes` bytes."""
    if nbytes <= 0:
        return
    with _lock:
        _counters["d2h_bytes"] = _counters.get("d2h_bytes", 0) + int(nbytes)
        _counters["d2h_calls"] = _counters.get("d2h_calls", 0) + 1
        _bump_scope_locked("d2h_bytes", nbytes)
        _bump_scope_locked("d2h_calls", 1)
        if tag:
            key = f"d2h_bytes:{tag}"
            _tags[key] = _tags.get(key, 0) + int(nbytes)


def note_h2d(nbytes: int, tag: str | None = None) -> None:
    """Record one device-bound upload of `nbytes` bytes."""
    if nbytes <= 0:
        return
    with _lock:
        _counters["h2d_bytes"] = _counters.get("h2d_bytes", 0) + int(nbytes)
        _counters["h2d_calls"] = _counters.get("h2d_calls", 0) + 1
        _bump_scope_locked("h2d_bytes", nbytes)
        _bump_scope_locked("h2d_calls", 1)
        if tag:
            key = f"h2d_bytes:{tag}"
            _tags[key] = _tags.get(key, 0) + int(nbytes)


def d2h_bytes() -> int:
    return counter("d2h_bytes")


def h2d_bytes() -> int:
    return counter("h2d_bytes")
