"""Deterministic fault injection for the compile and serve planes.

The data plane's robustness story (rows that deviate degrade, never kill
the job) is testable because exceptions are injectable — just feed bad
rows. The CONTROL plane's story (a wedged compile is killed, a crashed
serve process recovers) has no such natural lever, so this module is it:
a ``TUPLEX_FAULTS`` spec names injection points wired through
exec/compilequeue (the one expensive compile call), exec/local (the
per-partition dispatch) and the serve worker/wire loops, and
``scripts/chaos_bench.py`` drives the zillow serve workload under each
fault class asserting every job still terminates with correct results or
a clean error.

Spec grammar (comma- or semicolon-separated clauses)::

    TUPLEX_FAULTS="compile:hang:p=1:once,dispatch:raise:p=0.3"
    TUPLEX_FAULTS="serve:crash-after-admit"
    TUPLEX_FAULTS="serve:raise-step:kind=det:once"

    clause  := site ":" action [":" param]*
    site    := compile | dispatch | serve | <any maybe() site>
    action  := hang | raise | crash  [ "-" point ]
    param   := p=<float 0..1>   fire probability        (default 1)
             | once             at most one firing      (= n=1)
             | n=<int>          at most n firings
             | after=<int>      skip the first n eligible calls
             | delay=<seconds>  hang duration           (default 3600)
             | kind=det|transient   FaultInjected classification
                                (default transient — the serve retry
                                ladder retries it; det short-circuits)

The optional ``-point`` suffix on the action scopes a clause to one
named checkpoint of a site — ``serve:crash-after-admit`` fires only at
the wire loop's ``maybe("serve", point="after-admit")`` — while a bare
action matches every checkpoint of its site.

Semantics:

* **hang** sleeps ``delay`` seconds (default 3600) — inside the forked
  compile child this is exactly a wedged XLA compile: the parent's
  deadline SIGKILLs it.
* **raise** raises :class:`FaultInjected` (``transient`` attr per
  ``kind``) — exercises the dispatch retry ladder and the serve job
  retry ladder.
* **crash** calls ``os._exit(70)`` — the serve-process crash the journal
  recovery must survive.

Counting (``once``/``n``/``after``/the probability stream) is
process-local by default. Set ``TUPLEX_FAULTS_STATE=<file>`` to count
firings in a shared append-only file instead, so clauses keep their
budget across forked compile children and serve-process restarts (each
eligible call appends one byte per clause slot; the file's per-slot size
is the count). Probability draws come from ``random.Random(seed)``
(``TUPLEX_FAULTS_SEED``, default 0) — a chaos run is reproducible.

Disabled (no ``TUPLEX_FAULTS``) the hot-path cost of ``maybe()`` is one
module-attribute load and a truthiness check.
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Optional

__all__ = ["FaultInjected", "enabled", "maybe", "reset", "spec_clauses"]


class FaultInjected(RuntimeError):
    """An injected failure (``raise`` action). ``transient`` mirrors the
    clause's ``kind=`` param: the serve retry ladder retries transient
    faults and short-circuits deterministic ones — exactly the
    distinction it must make for real failures."""

    def __init__(self, msg: str, transient: bool = True):
        super().__init__(msg)
        self.transient = transient


class _Clause:
    def __init__(self, site: str, action: str, point: Optional[str],
                 p: float, limit: Optional[int], after: int,
                 delay: float, transient: bool, index: int, text: str):
        self.site = site
        self.action = action          # hang | raise | crash
        self.point = point            # None = any checkpoint of the site
        self.p = p
        self.limit = limit            # max firings (None = unlimited)
        self.after = after            # eligible calls to skip first
        self.delay = delay
        self.transient = transient
        self.index = index            # slot in the shared state file
        self.text = text
        self.calls = 0                # process-local eligible-call count
        self.fired = 0                # process-local firing count


def _parse(spec: str) -> list:
    clauses: list = []
    for idx, raw in enumerate(
            p for chunk in spec.replace(";", ",").split(",")
            if (p := chunk.strip())):
        parts = raw.split(":")
        if len(parts) < 2:
            continue                  # malformed clause: ignored, not fatal
        site, action = parts[0].strip(), parts[1].strip()
        point = None
        for base in ("hang", "raise", "crash"):
            if action == base:
                break
            if action.startswith(base + "-"):
                action, point = base, action[len(base) + 1:]
                break
        else:
            continue                  # unknown action
        p, limit, after, delay, transient = 1.0, None, 0, 3600.0, True
        for param in parts[2:]:
            param = param.strip()
            if param == "once":
                limit = 1
            elif param.startswith("p="):
                p = max(0.0, min(1.0, float(param[2:])))
            elif param.startswith("n="):
                limit = max(0, int(param[2:]))
            elif param.startswith("after="):
                after = max(0, int(param[6:]))
            elif param.startswith("delay="):
                delay = float(param[6:])
            elif param.startswith("kind="):
                transient = param[5:].strip() != "det"
        clauses.append(_Clause(site, action, point, p, limit, after,
                               delay, transient, idx, raw))
    return clauses


_LOCK = threading.Lock()
_CLAUSES: Optional[list] = None       # None = env not parsed yet
_RNG: Optional[random.Random] = None


def reset() -> None:
    """Re-read ``TUPLEX_FAULTS`` on next use (tests flip the env)."""
    global _CLAUSES, _RNG
    with _LOCK:
        _CLAUSES = None
        _RNG = None


def _load() -> list:
    global _CLAUSES, _RNG
    with _LOCK:
        if _CLAUSES is None:
            _CLAUSES = _parse(os.environ.get("TUPLEX_FAULTS", ""))
            try:
                seed = int(os.environ.get("TUPLEX_FAULTS_SEED", "0"))
            except ValueError:
                seed = 0
            _RNG = random.Random(seed)
        return _CLAUSES


def enabled() -> bool:
    return bool(_load())


def spec_clauses() -> list:
    """Parsed clause texts (chaos_bench reports what it injected)."""
    return [c.text for c in _load()]


# -- shared (cross-process) counting ----------------------------------------
# One byte appended per event per clause slot; O_APPEND makes concurrent
# writers (forked compile children, a restarted serve process) safe, and
# the count is simply the slot file's size. Slot files live next to the
# configured state file, keyed by the clause TEXT (crc) as well as its
# index — reusing one state file across different TUPLEX_FAULTS specs
# must not let an old spec's spent budget silence a new clause.

def _state_base() -> Optional[str]:
    return os.environ.get("TUPLEX_FAULTS_STATE") or None


def _bump_shared(base: str, clause: _Clause, kind: str) -> int:
    import zlib

    crc = zlib.crc32(clause.text.encode()) & 0xFFFFFFFF
    path = f"{base}.{clause.index}-{crc:08x}.{kind}"
    try:
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        try:
            os.write(fd, b".")
        finally:
            os.close(fd)
        return os.path.getsize(path)
    except OSError:                   # state file unusable: local counting
        clause_count = (clause.calls if kind == "calls" else clause.fired)
        return clause_count


def _count(clause: _Clause, kind: str) -> int:
    """Record one event (an eligible call or a firing) and return the
    TOTAL so far, shared across processes when a state file is set. The
    in-process counters bump under the lock so two threads can never
    both claim the last slot of a `once`/`n=` budget."""
    base = _state_base()
    with _LOCK:
        if kind == "calls":
            clause.calls += 1
            local = clause.calls
        else:
            clause.fired += 1
            local = clause.fired
    return _bump_shared(base, clause, kind) if base else local


def maybe(site: str, point: Optional[str] = None, **ctx) -> None:
    """Injection checkpoint. No-op unless a ``TUPLEX_FAULTS`` clause
    matches `site` (and `point`, when the clause names one); then the
    clause's action fires subject to its after/n/p budget."""
    clauses = _CLAUSES if _CLAUSES is not None else _load()
    if not clauses:
        return
    for c in clauses:
        if c.site != site or (c.point is not None and c.point != point):
            continue
        calls = _count(c, "calls")
        if calls <= c.after:
            continue
        if c.p < 1.0:
            with _LOCK:
                draw = _RNG.random()
            if draw >= c.p:
                continue
        if c.limit is not None:
            # reserve a firing slot first so concurrent callers (compile
            # pool threads, forked children) can't both claim the last one
            fired = _count(c, "fired")
            if fired > c.limit:
                continue
        else:
            _count(c, "fired")
        _fire(c, site, point, ctx)


def _fire(c: _Clause, site: str, point: Optional[str], ctx: dict) -> None:
    where = f"{site}" + (f"@{point}" if point else "")
    detail = ", ".join(f"{k}={v}" for k, v in sorted(ctx.items()))
    if c.action == "hang":
        time.sleep(c.delay)
        return
    if c.action == "crash":
        # emulate a hard process death: no atexit, no finally blocks —
        # exactly what the serve journal recovery must tolerate
        os._exit(70)
    raise FaultInjected(
        f"injected fault at {where}" + (f" ({detail})" if detail else ""),
        transient=c.transient)
