"""Apache-log parsing pipeline (reference: benchmarks/logs/runtuplex.py —
regex and string-strip parse variants over loglines, endpoint filter).

Both variants compile to the device: the strip variant as find/slice
chains + dict row, and the regex variant through the compiled re.search
subset (ops/regex.py lowers the anchored pattern to whole-column kernel
steps; ops/nfa.py and ops/pallas_nfa.py are the NFA fallbacks for patterns
the direct lowering rejects). Rows the compiled matcher cannot decide
fail-safe to the interpreter — it never succeeds with a different answer
than CPython's re.
"""

from __future__ import annotations

import random
import re

COLUMNS = ["ip", "client_id", "user_id", "date", "method", "endpoint",
           "protocol", "response_code", "content_size"]


def ParseWithStrip(x):
    y = x

    i = y.find(" ")
    ip = y[:i]
    y = y[i + 1:]

    i = y.find(" ")
    client_id = y[:i]
    y = y[i + 1:]

    i = y.find(" ")
    user_id = y[:i]
    y = y[i + 1:]

    i = y.find("]")
    date = y[:i][1:]
    y = y[i + 2:]

    y = y[y.find('"') + 1:]

    method = ""
    endpoint = ""
    protocol = ""
    failed = False
    if y.find(" ") < y.rfind('"'):
        i = y.find(" ")
        method = y[:i]
        y = y[i + 1:]

        i = y.find(" ")
        endpoint = y[:i]
        y = y[i + 1:]

        i = y.rfind('"')
        protocol = y[:i]
        protocol = protocol[protocol.rfind(" ") + 1:]
        y = y[i + 2:]
    else:
        failed = True
        i = y.rfind('"')
        y = y[i + 2:]

    i = y.find(" ")
    response_code = y[:i]
    content_size = y[i + 1:]

    if not failed:
        return {"ip": ip,
                "client_id": client_id,
                "user_id": user_id,
                "date": date,
                "method": method,
                "endpoint": endpoint,
                "protocol": protocol,
                "response_code": int(response_code),
                "content_size": 0 if content_size == "-" else
                int(content_size)}
    else:
        return {"ip": "",
                "client_id": "",
                "user_id": "",
                "date": "",
                "method": "",
                "endpoint": "",
                "protocol": "",
                "response_code": -1,
                "content_size": -1}


def ParseWithRegex(logline):
    match = re.search(
        r'^(\S+) (\S+) (\S+) \[([\w:/]+\s[+\-]\d{4})\] "(\S+) (\S+)\s*(\S*)'
        r'\s*" (\d{3}) (\S+)', logline)
    if match is None:
        return {"ip": "", "client_id": "", "user_id": "", "date": "",
                "method": "", "endpoint": "", "protocol": "",
                "response_code": -1, "content_size": -1}
    size_field = match.group(9)
    size = 0 if size_field == "-" else int(size_field)
    return {"ip": match.group(1), "client_id": match.group(2),
            "user_id": match.group(3), "date": match.group(4),
            "method": match.group(5), "endpoint": match.group(6),
            "protocol": match.group(7),
            "response_code": int(match.group(8)), "content_size": size}


def build_pipeline(ds, mode: str = "strip"):
    """reference: runtuplex.py — map(parse).filter(len(endpoint) > 0)."""
    fn = ParseWithStrip if mode == "strip" else ParseWithRegex
    return ds.map(fn).filter(lambda x: len(x["endpoint"]) > 0)


# ---------------------------------------------------------------------------

_METHODS = ["GET", "POST", "HEAD"]
_ENDPOINTS = ["/index.html", "/images/logo.gif", "/about", "/~user/page",
              "/api/v1/items", "/search?q=x"]


def gen_logline(rng: random.Random) -> str:
    if rng.random() < 0.03:   # malformed request line
        return (f"{rng.randint(1,255)}.{rng.randint(0,255)}.0.1 - - "
                f"[01/Jul/1995:00:00:0{rng.randint(0,9)} -0400] "
                f'"garbage" 400 -')
    ip = f"{rng.randint(1,255)}.{rng.randint(0,255)}.{rng.randint(0,255)}.{rng.randint(1,254)}"
    size = rng.choice(["-", str(rng.randint(100, 99999))])
    return (f"{ip} - - [0{rng.randint(1,9)}/Jul/1995:12:{rng.randint(10,59)}:"
            f"{rng.randint(10,59)} -0400] "
            f'"{rng.choice(_METHODS)} {rng.choice(_ENDPOINTS)} HTTP/1.0" '
            f"{rng.choice([200, 200, 200, 304, 404])} {size}")


def generate_log(path: str, n: int, seed: int = 17) -> str:
    rng = random.Random(seed)
    with open(path, "w") as fp:
        for _ in range(n):
            fp.write(gen_logline(rng) + "\n")
    return path


def run_reference_python(path: str, mode: str = "strip") -> list:
    fn = ParseWithStrip if mode == "strip" else ParseWithRegex
    out = []
    with open(path) as fp:
        for line in fp:
            line = line.rstrip("\n")
            try:
                d = fn(line)
                if len(d["endpoint"]) > 0:
                    out.append(tuple(d[c] for c in COLUMNS))
            except Exception:
                continue
    return out
