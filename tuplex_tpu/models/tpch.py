"""TPC-H Q1 / Q6 over lineitem (reference: benchmarks/tpch/Q06, Q19 —
filter + aggregate pipelines used to compare against Hyper/Weld).

Includes a scale-factor data generator for the lineitem columns these
queries touch, and pure-python reference implementations for golden checks.
"""

from __future__ import annotations

import random

LINEITEM_COLUMNS = ["l_quantity", "l_extendedprice", "l_discount", "l_tax",
                    "l_returnflag", "l_linestatus", "l_shipdate"]


def gen_lineitem_rows(n: int, seed: int = 7):
    rng = random.Random(seed)
    flags = ["A", "N", "R"]
    stats = ["F", "O"]
    rows = []
    for _ in range(n):
        rows.append((
            float(rng.randint(1, 50)),
            round(rng.uniform(900.0, 105000.0), 2),
            round(rng.choice([0.0, 0.01, 0.02, 0.03, 0.04, 0.05, 0.06,
                              0.07, 0.08, 0.09, 0.1]), 2),
            round(rng.uniform(0.0, 0.08), 2),
            rng.choice(flags),
            rng.choice(stats),
            f"199{rng.randint(2, 8)}-{rng.randint(1, 12):02d}-"
            f"{rng.randint(1, 28):02d}",
        ))
    return rows


def generate_csv(path: str, n: int, seed: int = 7) -> str:
    import csv

    with open(path, "w", newline="") as fp:
        w = csv.writer(fp)
        w.writerow(LINEITEM_COLUMNS)
        for r in gen_lineitem_rows(n, seed):
            w.writerow(r)
    return path


# --- Q6: revenue from discounted small-quantity shipments -------------------

def q6(ds):
    """SELECT sum(l_extendedprice * l_discount) WHERE l_shipdate in [1994,
    1995) AND l_discount in [0.05, 0.07] AND l_quantity < 24."""
    return (ds
            .filter(lambda x: x["l_shipdate"] >= "1994-01-01")
            .filter(lambda x: x["l_shipdate"] < "1995-01-01")
            .filter(lambda x: 0.05 <= x["l_discount"] <= 0.07)
            .filter(lambda x: x["l_quantity"] < 24)
            .aggregate(lambda a, b: a + b,
                       lambda a, x: a + x["l_extendedprice"] * x["l_discount"],
                       0.0))


def q6_python(rows) -> float:
    total = 0.0
    for (qty, price, disc, tax, rf, ls, ship) in rows:
        if "1994-01-01" <= ship < "1995-01-01" and \
                0.05 <= disc <= 0.07 and qty < 24:
            total += price * disc
    return total


# --- Q1: pricing summary report ---------------------------------------------

def q1(ds):
    """Grouped sums by (returnflag, linestatus) for l_shipdate <= cutoff."""
    return (ds
            .filter(lambda x: x["l_shipdate"] <= "1998-09-02")
            .aggregateByKey(
                lambda a, b: (a[0] + b[0], a[1] + b[1], a[2] + b[2],
                              a[3] + b[3]),
                lambda a, x: (a[0] + x["l_quantity"],
                              a[1] + x["l_extendedprice"],
                              a[2] + x["l_extendedprice"] *
                              (1 - x["l_discount"]),
                              a[3] + 1),
                (0.0, 0.0, 0.0, 0),
                ["l_returnflag", "l_linestatus"]))


def q1_python(rows) -> dict:
    groups: dict = {}
    for (qty, price, disc, tax, rf, ls, ship) in rows:
        if ship <= "1998-09-02":
            k = (rf, ls)
            a = groups.get(k, (0.0, 0.0, 0.0, 0))
            groups[k] = (a[0] + qty, a[1] + price,
                         a[2] + price * (1 - disc), a[3] + 1)
    return groups
