"""TPC-H Q1 / Q6 over lineitem (reference: benchmarks/tpch/Q06, Q19 —
filter + aggregate pipelines used to compare against Hyper/Weld).

Includes a scale-factor data generator for the lineitem columns these
queries touch, and pure-python reference implementations for golden checks.
"""

from __future__ import annotations

import random

LINEITEM_COLUMNS = ["l_quantity", "l_extendedprice", "l_discount", "l_tax",
                    "l_returnflag", "l_linestatus", "l_shipdate"]


def gen_lineitem_rows(n: int, seed: int = 7):
    rng = random.Random(seed)
    flags = ["A", "N", "R"]
    stats = ["F", "O"]
    rows = []
    for _ in range(n):
        rows.append((
            float(rng.randint(1, 50)),
            round(rng.uniform(900.0, 105000.0), 2),
            round(rng.choice([0.0, 0.01, 0.02, 0.03, 0.04, 0.05, 0.06,
                              0.07, 0.08, 0.09, 0.1]), 2),
            round(rng.uniform(0.0, 0.08), 2),
            rng.choice(flags),
            rng.choice(stats),
            f"199{rng.randint(2, 8)}-{rng.randint(1, 12):02d}-"
            f"{rng.randint(1, 28):02d}",
        ))
    return rows


def generate_csv(path: str, n: int, seed: int = 7) -> str:
    import csv

    with open(path, "w", newline="") as fp:
        w = csv.writer(fp)
        w.writerow(LINEITEM_COLUMNS)
        for r in gen_lineitem_rows(n, seed):
            w.writerow(r)
    return path


# --- Q6: revenue from discounted small-quantity shipments -------------------

def q6(ds):
    """SELECT sum(l_extendedprice * l_discount) WHERE l_shipdate in [1994,
    1995) AND l_discount in [0.05, 0.07] AND l_quantity < 24."""
    return (ds
            .filter(lambda x: x["l_shipdate"] >= "1994-01-01")
            .filter(lambda x: x["l_shipdate"] < "1995-01-01")
            .filter(lambda x: 0.05 <= x["l_discount"] <= 0.07)
            .filter(lambda x: x["l_quantity"] < 24)
            .aggregate(lambda a, b: a + b,
                       lambda a, x: a + x["l_extendedprice"] * x["l_discount"],
                       0.0))


def read_lineitem_csv(path: str):
    """Parse the lineitem CSV with csv+typed conversion — the pure-python
    side of the SAME work the framework pipeline does (CSV read + parse +
    query), so suite speedups compare like for like."""
    return read_csv_rows(path, (float, float, float, float, str, str, str))


def run_reference_q1(path: str) -> dict:
    return q1_python(read_lineitem_csv(path))


def run_reference_q6(path: str) -> float:
    return q6_python(read_lineitem_csv(path))


def q6_python(rows) -> float:
    total = 0.0
    for (qty, price, disc, tax, rf, ls, ship) in rows:
        if "1994-01-01" <= ship < "1995-01-01" and \
                0.05 <= disc <= 0.07 and qty < 24:
            total += price * disc
    return total


# --- Q1: pricing summary report ---------------------------------------------

def q1(ds):
    """Grouped sums by (returnflag, linestatus) for l_shipdate <= cutoff."""
    return (ds
            .filter(lambda x: x["l_shipdate"] <= "1998-09-02")
            .aggregateByKey(
                lambda a, b: (a[0] + b[0], a[1] + b[1], a[2] + b[2],
                              a[3] + b[3]),
                lambda a, x: (a[0] + x["l_quantity"],
                              a[1] + x["l_extendedprice"],
                              a[2] + x["l_extendedprice"] *
                              (1 - x["l_discount"]),
                              a[3] + 1),
                (0.0, 0.0, 0.0, 0),
                ["l_returnflag", "l_linestatus"]))


def q1_python(rows) -> dict:
    groups: dict = {}
    for (qty, price, disc, tax, rf, ls, ship) in rows:
        if ship <= "1998-09-02":
            k = (rf, ls)
            a = groups.get(k, (0.0, 0.0, 0.0, 0))
            groups[k] = (a[0] + qty, a[1] + price,
                         a[2] + price * (1 - disc), a[3] + 1)
    return groups


# --- Q19: discounted revenue over brand/container/quantity disjunction ------
# (reference: benchmarks/tpch/Q19 — lineitem JOIN part with a three-branch
# OR predicate; exercises join + compound filter + aggregate together)

PART_COLUMNS = ["p_partkey", "p_brand", "p_size", "p_container"]
LINEITEM19_COLUMNS = ["l_partkey", "l_quantity", "l_extendedprice",
                      "l_discount", "l_shipinstruct", "l_shipmode"]

_CONTAINERS_SM = ["SM CASE", "SM BOX", "SM PACK", "SM PKG"]
_CONTAINERS_MED = ["MED BAG", "MED BOX", "MED PKG", "MED PACK"]
_CONTAINERS_LG = ["LG CASE", "LG BOX", "LG PACK", "LG PKG"]


def gen_part_rows(n: int, seed: int = 19):
    rng = random.Random(seed)
    brands = [f"Brand#{i}{j}" for i in range(1, 6) for j in range(1, 6)]
    containers = (_CONTAINERS_SM + _CONTAINERS_MED + _CONTAINERS_LG +
                  ["JUMBO JAR", "WRAP CAN"])
    return [(k, rng.choice(brands), rng.randint(1, 50),
             rng.choice(containers)) for k in range(1, n + 1)]


def gen_lineitem19_rows(n: int, n_parts: int, seed: int = 23):
    rng = random.Random(seed)
    modes = ["AIR", "AIR REG", "RAIL", "TRUCK", "SHIP"]
    instr = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]
    return [(rng.randint(1, n_parts), float(rng.randint(1, 50)),
             round(rng.uniform(900.0, 105000.0), 2),
             round(rng.uniform(0.0, 0.1), 2),
             rng.choice(instr), rng.choice(modes)) for _ in range(n)]


def generate_q19_csvs(part_path: str, lineitem_path: str, n_parts: int,
                      n_items: int, seed: int = 19) -> None:
    import csv

    with open(part_path, "w", newline="") as fp:
        w = csv.writer(fp)
        w.writerow(PART_COLUMNS)
        w.writerows(gen_part_rows(n_parts, seed))
    with open(lineitem_path, "w", newline="") as fp:
        w = csv.writer(fp)
        w.writerow(LINEITEM19_COLUMNS)
        w.writerows(gen_lineitem19_rows(n_items, n_parts, seed + 4))


def _q19_pred(x) -> bool:
    return ((x["p_brand"] == "Brand#12"
             and x["p_container"] in ("SM CASE", "SM BOX", "SM PACK",
                                      "SM PKG")
             and 1 <= x["l_quantity"] <= 11 and 1 <= x["p_size"] <= 5)
            or (x["p_brand"] == "Brand#23"
                and x["p_container"] in ("MED BAG", "MED BOX", "MED PKG",
                                         "MED PACK")
                and 10 <= x["l_quantity"] <= 20 and 1 <= x["p_size"] <= 10)
            or (x["p_brand"] == "Brand#34"
                and x["p_container"] in ("LG CASE", "LG BOX", "LG PACK",
                                         "LG PKG")
                and 20 <= x["l_quantity"] <= 30
                and 1 <= x["p_size"] <= 15))


def q19(ctx, part_path: str, lineitem_path: str):
    """SELECT sum(l_extendedprice * (1 - l_discount)) over the brand/
    container/quantity disjunction, shipmode AIR/AIR REG, DELIVER IN
    PERSON."""
    part = ctx.csv(part_path)
    li = (ctx.csv(lineitem_path)
          .filter(lambda x: x["l_shipinstruct"] == "DELIVER IN PERSON")
          .filter(lambda x: x["l_shipmode"] == "AIR" or
                  x["l_shipmode"] == "AIR REG"))
    joined = li.join(part, "l_partkey", "p_partkey")
    return (joined
            .filter(_q19_pred)
            .aggregate(lambda a, b: a + b,
                       lambda a, x: a + x["l_extendedprice"] *
                       (1 - x["l_discount"]), 0.0))


def read_csv_rows(path: str, parsers) -> list:
    import csv as _csv

    out = []
    with open(path, newline="") as f:
        r = _csv.reader(f)
        next(r)
        for rec in r:
            out.append(tuple(p(c) for p, c in zip(parsers, rec)))
    return out


def run_reference_q19(part_path: str, lineitem_path: str) -> float:
    """File-based python baseline doing the SAME csv parse work."""
    parts = read_csv_rows(part_path, (int, str, int, str))
    lis = read_csv_rows(lineitem_path,
                        (int, float, float, float, str, str))
    return q19_python(parts, lis)


def q19_python(part_rows, li_rows) -> float:
    parts = {r[0]: r for r in part_rows}
    total = 0.0
    for (pk, qty, price, disc, instr, mode) in li_rows:
        if instr != "DELIVER IN PERSON" or mode not in ("AIR", "AIR REG"):
            continue
        p = parts.get(pk)
        if p is None:
            continue
        _, brand, size, container = p
        if ((brand == "Brand#12" and container in _CONTAINERS_SM
             and 1 <= qty <= 11 and 1 <= size <= 5)
                or (brand == "Brand#23" and container in _CONTAINERS_MED
                    and 10 <= qty <= 20 and 1 <= size <= 10)
                or (brand == "Brand#34" and container in _CONTAINERS_LG
                    and 20 <= qty <= 30 and 1 <= size <= 15)):
            total += price * (1 - disc)
    return total
