"""The flights cleaning pipeline — the reference's multi-join benchmark
(reference: benchmarks/flights/runtuplex.py — column renames, city/state
splits, time formatting, cancellation decoding, carrier join, two airport
leftJoins with prefixes, defunct-airline filter, delay int-casts).

UDFs re-implement the published cleaning logic; generators synthesize the
three inputs (perf CSV, L_CARRIER_HISTORY.csv, GlobalAirportDatabase.txt).
"""

from __future__ import annotations

import random
import string as _string

PERF_COLS = ["year", "month", "day_of_month", "day_of_week",
             "op_unique_carrier", "op_carrier_fl_num",
             "origin", "origin_city_name", "dest", "dest_city_name",
             "crs_dep_time", "crs_arr_time", "crs_elapsed_time",
             "actual_elapsed_time", "air_time", "distance",
             "cancelled", "cancellation_code", "diverted",
             "div_reached_dest", "div_actual_elapsed_time",
             "arr_delay", "dep_delay", "carrier_delay", "weather_delay",
             "nas_delay", "security_delay", "late_aircraft_delay",
             "taxi_in", "taxi_out"]

AIRPORT_COLS = ["ICAOCode", "IATACode", "AirportName", "AirportCity",
                "Country", "LatitudeDegrees", "LatitudeMinutes",
                "LatitudeSeconds", "LatitudeDirection", "LongitudeDegrees",
                "LongitudeMinutes", "LongitudeSeconds", "LongitudeDirection",
                "Altitude", "LatitudeDecimal", "LongitudeDecimal"]

_CARRIERS = [("UA", "United Air Lines Inc. (1931 - )"),
             ("AA", "American Airlines Inc. (1930 - )"),
             ("TW", "Trans World Airways LLC (1925 - 2001)"),
             ("PA", "Pan American World Airways (1927 - 1991)"),
             ("DL", "Delta Air Lines Inc. (1928 - )"),
             ("WN", "Southwest Airlines Co. (1967 - )")]

_AIRPORTS = [("KBOS", "BOS", "general edward lawrence logan intl", "boston"),
             ("KJFK", "JFK", "john f kennedy intl", "new york"),
             ("KLAX", "LAX", "los angeles intl", "los angeles"),
             ("KORD", "ORD", "chicago o'hare intl", "chicago"),
             ("KSFO", "SFO", "san francisco intl", "san francisco"),
             ("KSEA", "SEA", "seattle tacoma intl", "seattle")]

_CITY_STATE = [("Boston, MA", "BOS"), ("New York, NY", "JFK"),
               ("Los Angeles, CA", "LAX"), ("Chicago, IL", "ORD"),
               ("San Francisco, CA", "SFO"), ("Seattle, WA", "SEA"),
               ("Nowhere, ZZ", "XXX")]  # XXX: airport missing -> leftJoin None


# ---------------------------------------------------------------------------
# generators
# ---------------------------------------------------------------------------

def generate_perf_csv(path: str, n: int, seed: int = 13) -> str:
    import csv

    rng = random.Random(seed)
    with open(path, "w", newline="") as fp:
        w = csv.writer(fp)
        w.writerow(PERF_COLS)
        for _ in range(n):
            o_city, o_code = rng.choice(_CITY_STATE)
            d_city, d_code = rng.choice(_CITY_STATE)
            cancelled = 1.0 if rng.random() < 0.02 else 0.0
            diverted = 1.0 if rng.random() < 0.02 else 0.0
            ccode = rng.choice(["A", "B", "C", "D"]) if cancelled else ""
            div_reached = "1.00" if diverted and rng.random() < 0.5 else \
                ("0.00" if diverted else "")
            elapsed = rng.randint(40, 500)
            row = [
                rng.choice([2000, 2005, 2019]), rng.randint(1, 12),
                rng.randint(1, 28), rng.randint(1, 7),
                rng.choice(_CARRIERS)[0], rng.randint(1, 9999),
                o_code, o_city, d_code, d_city,
                rng.randint(0, 23) * 100 + rng.randint(0, 59),
                rng.randint(0, 23) * 100 + rng.randint(0, 59),
                float(elapsed + rng.randint(-10, 10)),
                "" if cancelled else float(elapsed),
                "" if cancelled else float(elapsed - rng.randint(5, 30)),
                float(rng.randint(80, 2700)),
                cancelled, ccode, diverted,
                div_reached,
                float(elapsed + 60) if div_reached == "1.00" else "",
                float(rng.randint(-20, 180)), float(rng.randint(-10, 120)),
                float(rng.randint(0, 60)), float(rng.randint(0, 40)),
                float(rng.randint(0, 50)), float(rng.randint(0, 10)),
                float(rng.randint(0, 90)),
                float(rng.randint(2, 40)), float(rng.randint(5, 50)),
            ]
            w.writerow(row)
    return path


def generate_carrier_csv(path: str) -> str:
    import csv

    with open(path, "w", newline="") as fp:
        w = csv.writer(fp)
        w.writerow(["Code", "Description"])
        for code, desc in _CARRIERS:
            w.writerow([code, desc])
    return path


def generate_airport_db(path: str) -> str:
    rng = random.Random(3)
    with open(path, "w") as fp:
        for icao, iata, name, city in _AIRPORTS:
            vals = [icao, iata, name, city, "usa",
                    rng.randint(0, 89), rng.randint(0, 59), rng.randint(0, 59),
                    "N", rng.randint(0, 179), rng.randint(0, 59),
                    rng.randint(0, 59), "W", rng.randint(0, 2000),
                    round(rng.uniform(-90, 90), 3),
                    round(rng.uniform(-180, 180), 3)]
            fp.write(":".join(str(v) for v in vals) + "\n")
    return path


# ---------------------------------------------------------------------------
# the pipeline (reference: runtuplex.py:100-289)
# ---------------------------------------------------------------------------

def cleanCode(t):
    if t["CancellationCode"] == "A":
        return "carrier"
    elif t["CancellationCode"] == "B":
        return "weather"
    elif t["CancellationCode"] == "C":
        return "national air system"
    elif t["CancellationCode"] == "D":
        return "security"
    else:
        return None


def divertedUDF(row):
    diverted = row["Diverted"]
    ccode = row["CancellationCode"]
    if diverted:
        return "diverted"
    else:
        if ccode:
            return ccode
        else:
            return "None"


def fillInTimesUDF(row):
    ACTUAL_ELAPSED_TIME = row["ActualElapsedTime"]
    if row["DivReachedDest"]:
        if float(row["DivReachedDest"]) > 0:
            return float(row["DivActualElapsedTime"])
        else:
            return ACTUAL_ELAPSED_TIME
    else:
        return ACTUAL_ELAPSED_TIME


def extractDefunctYear(t):
    x = t["Description"]
    desc = x[x.rfind("-") + 1: x.rfind(")")].strip()
    return int(desc) if len(desc) > 0 else None


NUMERIC_COLS = ["ActualElapsedTime", "AirTime", "ArrDelay", "CarrierDelay",
                "CrsElapsedTime", "DepDelay", "LateAircraftDelay", "NasDelay",
                "SecurityDelay", "TaxiIn", "TaxiOut", "WeatherDelay"]

OUTPUT_COLS = ["CarrierName", "CarrierCode", "FlightNumber", "Day", "Month",
               "Year", "DayOfWeek", "OriginCity", "OriginState",
               "OriginAirportIATACode", "OriginLongitude", "OriginLatitude",
               "OriginAltitude", "DestCity", "DestState",
               "DestAirportIATACode", "DestLongitude", "DestLatitude",
               "DestAltitude", "Distance", "CancellationReason", "Cancelled",
               "Diverted", "CrsArrTime", "CrsDepTime", "ActualElapsedTime",
               "AirTime", "ArrDelay", "CarrierDelay", "CrsElapsedTime",
               "DepDelay", "LateAircraftDelay", "NasDelay", "SecurityDelay",
               "TaxiIn", "TaxiOut", "WeatherDelay", "AirlineYearFounded",
               "AirlineYearDefunct"]


def build_pipeline(ctx, perf_path: str, carrier_path: str, airport_path: str):
    import string

    df = ctx.csv(perf_path)
    renamed = ["".join(w.capitalize() for w in c.split("_"))
               for c in df.columns]
    for i, c in enumerate(list(df.columns)):
        df = df.renameColumn(c, renamed[i])

    df_airports = ctx.csv(airport_path, columns=AIRPORT_COLS, delimiter=":",
                          header=False, null_values=["", "N/a", "N/A"])
    df_carrier = ctx.csv(carrier_path)

    df = df.withColumn(
        "OriginCity",
        lambda x: x["OriginCityName"][: x["OriginCityName"].rfind(",")].strip())
    df = df.withColumn(
        "OriginState",
        lambda x: x["OriginCityName"][x["OriginCityName"].rfind(",") + 1:].strip())
    df = df.withColumn(
        "DestCity",
        lambda x: x["DestCityName"][: x["DestCityName"].rfind(",")].strip())
    df = df.withColumn(
        "DestState",
        lambda x: x["DestCityName"][x["DestCityName"].rfind(",") + 1:].strip())
    df = df.mapColumn(
        "CrsArrTime",
        lambda x: "{:02}:{:02}".format(int(x / 100), x % 100) if x else None)
    df = df.mapColumn(
        "CrsDepTime",
        lambda x: "{:02}:{:02}".format(int(x / 100), x % 100) if x else None)
    df = df.withColumn("CancellationCode", cleanCode)
    df = df.mapColumn("Diverted", lambda x: True if x > 0 else False)
    df = df.mapColumn("Cancelled", lambda x: True if x > 0 else False)
    df = df.withColumn("CancellationReason", divertedUDF)
    df = df.withColumn("ActualElapsedTime", fillInTimesUDF).ignore(TypeError)

    df_carrier = df_carrier.withColumn(
        "AirlineName",
        lambda x: x["Description"][: x["Description"].rfind("(")].strip())
    df_carrier = df_carrier.withColumn(
        "AirlineYearFounded",
        lambda x: int(x["Description"][x["Description"].rfind("(") + 1:
                                       x["Description"].rfind("-")]))
    df_carrier = df_carrier.withColumn("AirlineYearDefunct",
                                       extractDefunctYear)

    df_airports = df_airports.mapColumn(
        "AirportName", lambda x: string.capwords(x) if x else None)
    df_airports = df_airports.mapColumn(
        "AirportCity", lambda x: string.capwords(x) if x else None)

    df_all = df.join(df_carrier, "OpUniqueCarrier", "Code")
    df_all = df_all.leftJoin(df_airports, "Origin", "IATACode",
                             prefixes=(None, "Origin"))
    df_all = df_all.leftJoin(df_airports, "Dest", "IATACode",
                             prefixes=(None, "Dest"))

    df_all = df_all.mapColumn("Distance", lambda x: x / 0.00062137119224)
    df_all = df_all.mapColumn(
        "AirlineName",
        lambda s: s.replace("Inc.", "").replace("LLC", "")
        .replace("Co.", "").strip())
    df_all = (df_all
              .renameColumn("OriginLongitudeDecimal", "OriginLongitude")
              .renameColumn("OriginLatitudeDecimal", "OriginLatitude")
              .renameColumn("DestLongitudeDecimal", "DestLongitude")
              .renameColumn("DestLatitudeDecimal", "DestLatitude")
              .renameColumn("OpUniqueCarrier", "CarrierCode")
              .renameColumn("OpCarrierFlNum", "FlightNumber")
              .renameColumn("DayOfMonth", "Day")
              .renameColumn("AirlineName", "CarrierName")
              .renameColumn("Origin", "OriginAirportIATACode")
              .renameColumn("Dest", "DestAirportIATACode"))

    def filterDefunctFlights(row):
        year = row["Year"]
        airlineYearDefunct = row["AirlineYearDefunct"]
        if airlineYearDefunct:
            return int(year) < int(airlineYearDefunct)
        else:
            return True

    df_all = df_all.filter(filterDefunctFlights)
    for c in NUMERIC_COLS:
        df_all = df_all.mapColumn(c, lambda x: int(x) if x else 0)
    return df_all.selectColumns(OUTPUT_COLS)


# ---------------------------------------------------------------------------
# pure-python reference (golden output + baseline)
# ---------------------------------------------------------------------------

def run_reference_python(perf_path: str, carrier_path: str,
                         airport_path: str) -> list:
    import csv
    import string

    carriers = {}
    with open(carrier_path, newline="") as fp:
        for row in csv.DictReader(fp):
            x = dict(row)
            d = x["Description"]
            x["AirlineName"] = d[: d.rfind("(")].strip()
            x["AirlineYearFounded"] = int(d[d.rfind("(") + 1: d.rfind("-")])
            desc = d[d.rfind("-") + 1: d.rfind(")")].strip()
            x["AirlineYearDefunct"] = int(desc) if len(desc) > 0 else None
            carriers[x["Code"]] = x

    airports = {}
    with open(airport_path) as fp:
        for line in fp:
            cells = line.rstrip("\n").split(":")
            a = dict(zip(AIRPORT_COLS, cells))
            for num_c in ("LatitudeDecimal", "LongitudeDecimal", "Altitude"):
                a[num_c] = float(a[num_c]) if a[num_c] not in (
                    "", "N/a", "N/A") else None
            a["AirportName"] = string.capwords(a["AirportName"]) \
                if a["AirportName"] else None
            a["AirportCity"] = string.capwords(a["AirportCity"]) \
                if a["AirportCity"] else None
            airports[a["IATACode"]] = a

    out = []
    with open(perf_path, newline="") as fp:
        for raw in csv.DictReader(fp):
            try:
                x = {}
                for k, v in raw.items():
                    nk = "".join(w.capitalize() for w in k.split("_"))
                    x[nk] = v
                # typed decode mirroring the csv speculation
                for k in ("Year", "Month", "DayOfMonth", "DayOfWeek",
                          "OpCarrierFlNum", "CrsDepTime", "CrsArrTime"):
                    x[k] = int(x[k])
                for k in ("CrsElapsedTime", "Distance", "Cancelled",
                          "Diverted", "ArrDelay", "DepDelay", "CarrierDelay",
                          "WeatherDelay", "NasDelay", "SecurityDelay",
                          "LateAircraftDelay", "TaxiIn", "TaxiOut"):
                    x[k] = float(x[k]) if x[k] != "" else None
                for k in ("ActualElapsedTime", "AirTime",
                          "DivActualElapsedTime"):
                    x[k] = float(x[k]) if x[k] != "" else None
                ocn = x["OriginCityName"]
                x["OriginCity"] = ocn[: ocn.rfind(",")].strip()
                x["OriginState"] = ocn[ocn.rfind(",") + 1:].strip()
                dcn = x["DestCityName"]
                x["DestCity"] = dcn[: dcn.rfind(",")].strip()
                x["DestState"] = dcn[dcn.rfind(",") + 1:].strip()
                t = x["CrsArrTime"]
                x["CrsArrTime"] = "{:02}:{:02}".format(int(t / 100), t % 100) \
                    if t else None
                t = x["CrsDepTime"]
                x["CrsDepTime"] = "{:02}:{:02}".format(int(t / 100), t % 100) \
                    if t else None
                code = x["CancellationCode"]
                x["CancellationCode"] = {"A": "carrier", "B": "weather",
                                         "C": "national air system",
                                         "D": "security"}.get(code)
                x["Diverted"] = True if x["Diverted"] > 0 else False
                x["Cancelled"] = True if x["Cancelled"] > 0 else False
                if x["Diverted"]:
                    x["CancellationReason"] = "diverted"
                else:
                    x["CancellationReason"] = x["CancellationCode"] \
                        if x["CancellationCode"] else "None"
                try:
                    if x["DivReachedDest"]:
                        if float(x["DivReachedDest"]) > 0:
                            x["ActualElapsedTime"] = float(
                                x["DivActualElapsedTime"])
                except TypeError:
                    continue
                # elapsed may be None when not diverted-and-reached
                if x["ActualElapsedTime"] is None and not (
                        x["DivReachedDest"] and
                        float(x["DivReachedDest"]) > 0):
                    pass
                carrier = carriers.get(x["OpUniqueCarrier"])
                if carrier is None:
                    continue
                x.update({k: carrier[k] for k in
                          ("AirlineName", "AirlineYearFounded",
                           "AirlineYearDefunct")})
                for side, key in (("Origin", x["Origin"]),
                                  ("Dest", x["Dest"])):
                    ap = airports.get(key)
                    for c in AIRPORT_COLS:
                        if c == "IATACode":
                            continue
                        x[side + c] = ap[c] if ap else None
                x["Distance"] = x["Distance"] / 0.00062137119224
                x["AirlineName"] = x["AirlineName"].replace("Inc.", "") \
                    .replace("LLC", "").replace("Co.", "").strip()
                x["OriginLongitude"] = x["OriginLongitudeDecimal"]
                x["OriginLatitude"] = x["OriginLatitudeDecimal"]
                x["DestLongitude"] = x["DestLongitudeDecimal"]
                x["DestLatitude"] = x["DestLatitudeDecimal"]
                x["CarrierCode"] = x["OpUniqueCarrier"]
                x["FlightNumber"] = x["OpCarrierFlNum"]
                x["Day"] = x["DayOfMonth"]
                x["CarrierName"] = x["AirlineName"]
                x["OriginAirportIATACode"] = x["Origin"]
                x["DestAirportIATACode"] = x["Dest"]
                if x["AirlineYearDefunct"]:
                    if not int(x["Year"]) < int(x["AirlineYearDefunct"]):
                        continue
                for c in NUMERIC_COLS:
                    x[c] = int(x[c]) if x[c] else 0
                out.append(tuple(x[c] for c in OUTPUT_COLS))
            except Exception:
                continue
    return out
