"""The Zillow dirty-data cleaning pipeline — the reference's headline
benchmark (reference: benchmarks/zillow/Z1/runtuplex.py — extractBd/Ba/Sqft/
Type/Offer/Price + filters; data schema from benchmarks/zillow/data).

The UDFs are re-implementations of the benchmark's published cleaning logic
(they ARE the workload being benchmarked — byte-identical semantics are the
point), plus a synthetic dirty-data generator so the benchmark runs without
the original scraped dataset.
"""

from __future__ import annotations

import random

COLUMNS = ["title", "address", "city", "state", "postal_code", "price",
           "facts and features", "real estate provider", "url", "sales_date"]


# --- the cleaning UDFs (workload under test) --------------------------------

def extractBd(x):
    val = x["facts and features"]
    max_idx = val.find(" bd")
    if max_idx < 0:
        max_idx = len(val)
    s = val[:max_idx]
    split_idx = s.rfind(",")
    if split_idx < 0:
        split_idx = 0
    else:
        split_idx += 2
    r = s[split_idx:]
    return int(r)


def extractBa(x):
    val = x["facts and features"]
    max_idx = val.find(" ba")
    if max_idx < 0:
        max_idx = len(val)
    s = val[:max_idx]
    split_idx = s.rfind(",")
    if split_idx < 0:
        split_idx = 0
    else:
        split_idx += 2
    r = s[split_idx:]
    return int(r)


def extractSqft(x):
    val = x["facts and features"]
    max_idx = val.find(" sqft")
    if max_idx < 0:
        max_idx = len(val)
    s = val[:max_idx]
    split_idx = s.rfind("ba ,")
    if split_idx < 0:
        split_idx = 0
    else:
        split_idx += 5
    r = s[split_idx:]
    r = r.replace(",", "")
    return int(r)


def extractOffer(x):
    offer = x["title"].lower()
    if "sale" in offer:
        return "sale"
    if "rent" in offer:
        return "rent"
    if "sold" in offer:
        return "sold"
    if "foreclose" in offer:
        return "foreclosed"
    return offer


def extractType(x):
    t = x["title"].lower()
    type_ = "unknown"
    if "condo" in t or "apartment" in t:
        type_ = "condo"
    if "house" in t:
        type_ = "house"
    return type_


def extractPrice(x):
    price = x["price"]
    p = 0
    if x["offer"] == "sold":
        val = x["facts and features"]
        s = val[val.find("Price/sqft:") + len("Price/sqft:") + 1:]
        r = s[s.find("$") + 1: s.find(", ") - 1]
        price_per_sqft = int(r)
        p = price_per_sqft * x["sqft"]
    elif x["offer"] == "rent":
        max_idx = price.rfind("/")
        p = int(price[1:max_idx].replace(",", ""))
    else:
        p = int(price[1:].replace(",", ""))
    return p


def build_pipeline(ds):
    """The Z1 chain (reference: runtuplex.py pipeline body)."""
    return (ds
            .withColumn("bedrooms", extractBd)
            .filter(lambda x: x["bedrooms"] < 10)
            .withColumn("type", extractType)
            .filter(lambda x: x["type"] == "house")
            .withColumn("zipcode", lambda x: "%05d" % int(x["postal_code"]))
            .mapColumn("city", lambda x: x[0].upper() + x[1:].lower())
            .withColumn("bathrooms", extractBa)
            .withColumn("sqft", extractSqft)
            .withColumn("offer", extractOffer)
            .withColumn("price", extractPrice)
            .filter(lambda x: 100000 < x["price"] <= 2e7)
            .selectColumns(["url", "zipcode", "address", "city", "state",
                            "bedrooms", "bathrooms", "sqft", "offer", "type",
                            "price"]))


# --- synthetic dirty data ---------------------------------------------------

_CITIES = ["boston", "CAMBRIDGE", "Somerville", "newton", "BROOKLINE",
           "quincy", "medford", "arlington"]
_STATES = ["MA", "NY", "CA", "WA"]
_TITLES_SALE = ["House For Sale", "Colonial house for sale",
                "New construction house - for sale!", "Big house for sale"]
_TITLES_RENT = ["Condo for rent", "Apartment For Rent", "Studio for rent"]
_TITLES_SALE_CONDO = ["Condo for sale", "Downtown condo - for sale!",
                      "Apartment for sale"]
_TITLES_SOLD = ["House recently sold", "Sold: lovely house"]
_PROVIDERS = ["RE/MAX", "Zillow", "Coldwell Banker", "agent"]


def gen_row(rng: random.Random, condo_sales: bool = False) -> dict:
    kind = rng.random()
    bd = rng.randint(1, 12)
    ba = rng.randint(1, 5)
    sqft = rng.randint(400, 9000)
    dirty = rng.random()
    if kind < 0.55:
        # Z2 filters type=='condo' AND offer=='sale': without condo-sale
        # titles that cross-cell is empty and the Z2 pipeline outputs
        # nothing (review finding — the golden test was vacuous)
        pool = _TITLES_SALE + _TITLES_SALE_CONDO if condo_sales \
            else _TITLES_SALE
        title = rng.choice(pool)
        price = f"${rng.randint(100, 3000) * 1000:,}"
    elif kind < 0.8:
        title = rng.choice(_TITLES_RENT)
        price = f"${rng.randint(800, 9000):,}/mo"
    else:
        title = rng.choice(_TITLES_SOLD)
        price = "--"
    facts = f"{bd} bds , {ba} ba , {sqft:,} sqft"
    if kind >= 0.8:
        facts += f" , Price/sqft: ${rng.randint(100, 900)} , more"
    # dirt: ~4% rows have broken facts; ~2% broken postal codes
    if dirty < 0.04:
        facts = rng.choice(["studio , no data", "-- , contact agent", ""])
    postal = f"{rng.randint(1000, 99999):05d}"
    if 0.04 <= dirty < 0.06:
        postal = rng.choice(["N/A", "0210A", ""])
    return {
        "title": title,
        "address": f"{rng.randint(1, 999)} Main St",
        "city": rng.choice(_CITIES),
        "state": rng.choice(_STATES),
        "postal_code": postal,
        "price": price,
        "facts and features": facts,
        "real estate provider": rng.choice(_PROVIDERS),
        "url": f"https://example.com/homes/{rng.randint(10**6, 10**7)}",
        "sales_date": f"202{rng.randint(0,5)}-0{rng.randint(1,9)}-1{rng.randint(0,9)}",
    }


def generate_csv(path: str, n_rows: int, seed: int = 42,
                 condo_sales: bool = False) -> str:
    import csv

    rng = random.Random(seed)
    with open(path, "w", newline="") as fp:
        w = csv.DictWriter(fp, fieldnames=COLUMNS)
        w.writeheader()
        for _ in range(n_rows):
            w.writerow(gen_row(rng, condo_sales))
    return path


def _run_reference(path: str, type_: str, ba_fn, price_pred) -> list:
    """Shared pure-CPython runner for the Z1/Z2 chains (they differ only in
    the type filter, the bathrooms UDF, and the price predicate)."""
    import csv

    cols = ["url", "zipcode", "address", "city", "state", "bedrooms",
            "bathrooms", "sqft", "offer", "type", "price"]
    out = []
    with open(path, newline="") as fp:
        for row in csv.DictReader(fp):
            try:
                x = dict(row)
                x["bedrooms"] = extractBd(x)
                if not x["bedrooms"] < 10:
                    continue
                x["type"] = extractType(x)
                if x["type"] != type_:
                    continue
                x["zipcode"] = "%05d" % int(x["postal_code"])
                c = x["city"]
                x["city"] = c[0].upper() + c[1:].lower()
                x["bathrooms"] = ba_fn(x)
                x["sqft"] = extractSqft(x)
                x["offer"] = extractOffer(x)
                x["price"] = extractPrice(x)
                if not price_pred(x):
                    continue
                out.append(tuple(x[c] for c in cols))
            except Exception:
                continue
    return out


def run_reference_python(path: str) -> list:
    """Pure-CPython implementation of the Z1 pipeline — the golden output
    AND the interpreter baseline for bench (reference analog: the pure-python
    comparison scripts in benchmarks/zillow)."""
    return _run_reference(path, "house", extractBa,
                          lambda x: 100000 < x["price"] <= 2e7)


# --- Z2 variant (reference: benchmarks/zillow/Z2/runtuplex.py) --------------

def extractBaZ2(x):
    """Z2's bathrooms: half-bath rounding via math.ceil (reference:
    Z2/runtuplex.py:31-47 — the UDF is the workload spec)."""
    import math

    val = x["facts and features"]
    max_idx = val.find(" ba")
    if max_idx < 0:
        max_idx = len(val)
    s = val[:max_idx]
    split_idx = s.rfind(",")
    if split_idx < 0:
        split_idx = 0
    else:
        split_idx += 2
    r = s[split_idx:]
    ba = math.ceil(2.0 * float(r)) / 2.0
    return ba


Z2_OUT_COLUMNS = ["url", "zipcode", "address", "city", "state", "bedrooms",
                  "bathrooms", "sqft", "offer", "type", "price"]


def build_pipeline_z2(ds):
    """The Z2 chain: condo filter, sale-only price filter, file output
    (reference: Z2/runtuplex.py:190-203 writes tocsv)."""
    return (ds
            .withColumn("bedrooms", extractBd)
            .filter(lambda x: x["bedrooms"] < 10)
            .withColumn("type", extractType)
            .filter(lambda x: x["type"] == "condo")
            .withColumn("zipcode", lambda x: "%05d" % int(x["postal_code"]))
            .mapColumn("city", lambda x: x[0].upper() + x[1:].lower())
            .withColumn("bathrooms", extractBaZ2)
            .withColumn("sqft", extractSqft)
            .withColumn("offer", extractOffer)
            .withColumn("price", extractPrice)
            .filter(lambda x: 100000 < x["price"] < 2e7
                    and x["offer"] == "sale")
            .selectColumns(Z2_OUT_COLUMNS))


def run_reference_python_z2(path: str) -> list:
    """Pure-CPython golden for the Z2 chain."""
    return _run_reference(
        path, "condo", extractBaZ2,
        lambda x: 100000 < x["price"] < 2e7 and x["offer"] == "sale")
