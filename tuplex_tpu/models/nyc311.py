"""NYC 311 service-request pipeline (reference: benchmarks/311/runtuplex.py —
csv with aggressive null_values, fix_zip_codes mapColumn, unique)."""

from __future__ import annotations

import random
import typing

NULL_VALUES = ["Unspecified", "NO CLUE", "NA", "N/A", "0", ""]


def fix_zip_codes(zips):
    if not zips:
        return None
    # Truncate everything to length 5
    s = zips[:5]
    # Set 00000 zip codes to nan
    if s == "00000":
        return None
    else:
        return s


def build_pipeline(ctx, path: str):
    from ..core import typesys as T

    df = ctx.csv(path, null_values=NULL_VALUES,
                 type_hints={0: T.option(T.STR)})
    return df.mapColumn("Incident Zip", fix_zip_codes).unique()


def generate_csv(path: str, n: int, seed: int = 23) -> str:
    import csv

    rng = random.Random(seed)
    zips = ["02139", "10025-1234", "00000", "11201", "94105", "N/A",
            "Unspecified", "021", "  ", "60614"]
    with open(path, "w", newline="") as fp:
        w = csv.writer(fp)
        w.writerow(["Incident Zip"])
        for _ in range(n):
            w.writerow([rng.choice(zips)])
    return path


def run_reference_python(path: str) -> list:
    import csv

    out = []
    seen = set()
    with open(path, newline="") as fp:
        for row in csv.DictReader(fp):
            z = row["Incident Zip"]
            if z in NULL_VALUES:
                z = None
            z = fix_zip_codes(z)
            if z not in seen:
                seen.add(z)
                out.append(z)
    return out
