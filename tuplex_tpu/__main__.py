"""``python -m tuplex_tpu`` — CLI entry point.

Bare invocation keeps the interactive shell with a ready Context and jedi
tab-completion (reference: python/tuplex/utils/interactive_shell.py
TuplexShell, launched by the `tuplex` console entry point). Subcommands:

    python -m tuplex_tpu                  # interactive shell (default)
    python -m tuplex_tpu shell            # same, explicit
    python -m tuplex_tpu lint script.py   # plan-time UDF static analysis
    python -m tuplex_tpu compilestats script.py   # compile forecast
    python -m tuplex_tpu trace out.json   # history -> Chrome trace JSON
    python -m tuplex_tpu excstats         # exception-plane readout
    python -m tuplex_tpu whyslow [job]    # latency-budget readout
    python -m tuplex_tpu serve <root>     # multi-tenant job service
    python -m tuplex_tpu version          # print the package version

`lint` runs the compiler's static analyzer (compiler/analyzer.py) over every
UDF the script hands to DataSet methods — purely syntactic — and prints
per-UDF fallback, exception-site, purity, and static-type findings with
file:line locations, plus dead-resolver warnings (a resolve()/ignore()
targeting an error the guarded UDF provably cannot raise). It then imports
the script with actions stubbed (compilestats harness: no stage executes,
nothing compiles) and prints a jaxpr findings section — every
compiler/graphlint verdict from plan-time stage vetting (compile-wedge
rules, dtype creep, broadcast blowup, static peak-memory). `--strict`
exits non-zero when any fallback finding, dead resolver, or
wedge-severity jaxpr finding exists.

`compilestats` imports the script with actions stubbed out (no stage
executes, nothing compiles), plans each action, and prints per-stage op
counts, predicted compile seconds from the split tuner's measured curve,
and which stages the content-addressed compile cache would dedup into one
executable (utils/compilestats.py).
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tuplex_tpu",
        description="tuplex_tpu — TPU-native data-processing framework")
    sub = parser.add_subparsers(dest="cmd")
    sub.add_parser("shell", help="interactive shell (the default)")
    lint = sub.add_parser(
        "lint", help="static-analyze the UDFs of a pipeline script")
    lint.add_argument("script", help="path to a python pipeline script")
    lint.add_argument("--strict", action="store_true",
                      help="exit non-zero on any fallback finding or "
                           "dead resolver")
    cs = sub.add_parser(
        "compilestats",
        help="per-stage op counts, predicted compile seconds, dedup groups")
    cs.add_argument("script", help="path to a python pipeline script")
    cs.add_argument("--platform", default=None,
                    help="compile-model platform (default: jax backend)")
    ex = sub.add_parser(
        "excstats",
        help="exception-plane readout from the job history: per-stage x "
             "code fallback counts vs the plan-time inventory, resolve-"
             "tier mix, drift score + respecialize signal, sampled "
             "deviant rows (runtime/excprof)")
    ex.add_argument("--log-dir", default=".",
                    help="directory holding tuplex_history.jsonl "
                         "(tuplex.logDir; default .)")
    ex.add_argument("--job", default=None,
                    help="only jobs whose id starts with this prefix")
    ws = sub.add_parser(
        "whyslow",
        help="latency-budget readout from the job history: per-job "
             "critical-path bucket vector vs the tenant's EWMA baseline, "
             "slow-job blame, SLO verdicts (runtime/critpath)")
    ws.add_argument("job", nargs="?", default=None,
                    help="only jobs whose id starts with this prefix")
    ws.add_argument("--log-dir", default=".",
                    help="directory holding tuplex_history.jsonl "
                         "(tuplex.logDir; default .)")
    ws.add_argument("--glossary", action="store_true",
                    help="print the bucket glossary and exit")
    tr = sub.add_parser(
        "trace",
        help="replay the job history as Chrome trace-event JSON "
             "(open in Perfetto / chrome://tracing)")
    tr.add_argument("out", help="output .json path")
    tr.add_argument("--log-dir", default=".",
                    help="directory holding tuplex_history.jsonl "
                         "(tuplex.logDir; default .)")
    sv = sub.add_parser(
        "serve",
        help="run the multi-tenant job service on this process's warm "
             "device (scratch-dir submit/poll/fetch protocol; stop by "
             "touching <root>/STOP)")
    sv.add_argument("root", help="service root directory (clients drop "
                                 "requests under <root>/inbox/)")
    sv.add_argument("--conf", default=None,
                    help="options file (YAML/JSON) merged over defaults")
    sv.add_argument("--slots", type=int, default=None,
                    help="scheduler slots (tuplex.serve.slots)")
    sv.add_argument("--queue-depth", type=int, default=None,
                    help="admission queue depth (tuplex.serve.queueDepth)")
    sv.add_argument("--metrics-port", type=int, default=None,
                    help="serve Prometheus /metrics + /healthz on this "
                         "loopback port (0 = pick a free one, announced "
                         "in <root>/metrics.port; default off — the "
                         "periodic <root>/metrics.prom drop happens "
                         "regardless; tuplex.serve.metricsPort)")
    sv.add_argument("--retry-count", type=int, default=None,
                    help="job-level retries for transient failures, and "
                         "the crash-requeue budget for jobs recovered "
                         "from a previous process's journal "
                         "(tuplex.serve.retryCount)")
    sv.add_argument("--retry-backoff", type=float, default=None,
                    help="base seconds of the exponential retry backoff "
                         "(tuplex.serve.retryBackoffS)")
    sub.add_parser("version", help="print the package version")
    args = parser.parse_args(argv)

    if args.cmd == "version":
        from . import __version__

        print(__version__)
        return 0
    if args.cmd == "lint":
        from .compiler.analyzer import lint_file

        try:
            rc = lint_file(args.script, strict=args.strict)
        except OSError as e:
            print(f"lint: {e}", file=sys.stderr)
            return 2
        # jaxpr findings section (compiler/graphlint): unlike the UDF
        # lint above this must IMPORT the script (actions stubbed, same
        # harness as compilestats — nothing executes or compiles); an
        # unimportable script degrades to the syntactic report alone
        try:
            from .utils.compilestats import lint_jaxprs

            _, n_wedge = lint_jaxprs(args.script)
            if args.strict and n_wedge:
                rc = rc or 1
        except Exception as e:
            print(f"lint: jaxpr section skipped "
                  f"({type(e).__name__}: {e})", file=sys.stderr)
        return rc
    if args.cmd == "compilestats":
        from .utils.compilestats import main as cs_main

        try:
            return cs_main(args.script, platform=args.platform)
        except OSError as e:
            print(f"compilestats: {e}", file=sys.stderr)
            return 2
    if args.cmd == "serve":
        from .core.options import ContextOptions
        from .serve.client import service_loop

        opts = ContextOptions()
        if args.conf:
            opts.update(args.conf)
        if args.slots is not None:
            opts.set("tuplex.serve.slots", args.slots)
        if args.queue_depth is not None:
            opts.set("tuplex.serve.queueDepth", args.queue_depth)
        if args.metrics_port is not None:
            opts.set("tuplex.serve.metricsPort", args.metrics_port)
        if args.retry_count is not None:
            opts.set("tuplex.serve.retryCount", args.retry_count)
        if args.retry_backoff is not None:
            opts.set("tuplex.serve.retryBackoffS", args.retry_backoff)
        try:
            n = service_loop(args.root, opts)
        except KeyboardInterrupt:
            print("serve: interrupted", file=sys.stderr)
            return 130
        print(f"serve: {n} job(s) served")
        return 0
    if args.cmd == "excstats":
        from .utils.excstats import main as ex_main

        try:
            return ex_main(args.log_dir, job=args.job)
        except OSError as e:
            print(f"excstats: {e}", file=sys.stderr)
            return 2
    if args.cmd == "whyslow":
        from .utils.whyslow import glossary, main as ws_main

        if args.glossary:
            glossary()
            return 0
        try:
            return ws_main(args.log_dir, job=args.job)
        except OSError as e:
            print(f"whyslow: {e}", file=sys.stderr)
            return 2
    if args.cmd == "trace":
        from .history.recorder import history_to_chrome

        try:
            out = history_to_chrome(args.log_dir, args.out)
        except OSError as e:
            print(f"trace: {e}", file=sys.stderr)
            return 2
        print(f"wrote {out} — open at ui.perfetto.dev or chrome://tracing")
        return 0
    # bare invocation or explicit `shell`
    from .utils.repl import interactive_shell

    interactive_shell()
    return 0


if __name__ == "__main__":
    sys.exit(main())
