"""``python -m tuplex_tpu`` — interactive shell with a ready Context and
jedi tab-completion (reference: python/tuplex/utils/interactive_shell.py
TuplexShell, launched by the `tuplex` console entry point)."""

from .utils.repl import interactive_shell

if __name__ == "__main__":
    interactive_shell()
