"""Boxed row values for driver-side sampling, transfer and the interpreter path.

Reference semantics: tuplex/utils/src/Row.cc / Field.cc — a Row is an ordered
tuple of fields, optionally with column names; single-element rows unwrap on
collect. Here rows are lightweight wrappers over plain Python values; the
columnar layout lives in `tuplex_tpu/runtime/columns.py`.
"""

from __future__ import annotations

from typing import Any, Iterator, Optional, Sequence

from .typesys import Type, infer_type, tuple_of


# column-name -> index maps interned per columns tuple: Row['col'] is on the
# interpreter hot path (reference: generated Row class resolves names to
# positions at codegen time, PythonPipelineBuilder.cc:1-60)
_COL_INDEX: dict = {}


def _col_index_map(columns: tuple) -> dict:
    m = _COL_INDEX.get(columns)
    if m is None:
        # reversed so the FIRST occurrence of a duplicated name wins,
        # matching tuple.index semantics
        m = {c: i for i, c in reversed(list(enumerate(columns)))}
        if len(_COL_INDEX) > 4096:
            # data-dependent column sets (dict-returning map UDFs) must not
            # grow the interned cache without bound
            _COL_INDEX.clear()
        _COL_INDEX[columns] = m
    return m


class Row:
    __slots__ = ("values", "columns")

    def __init__(self, values: Sequence[Any], columns: Optional[Sequence[str]] = None):
        self.values: tuple = values if type(values) is tuple else tuple(values)
        self.columns: Optional[tuple] = None if not columns else (
            columns if type(columns) is tuple else tuple(columns))

    @classmethod
    def from_value(cls, value: Any, columns: Optional[Sequence[str]] = None) -> "Row":
        """Wrap a user value as a row: tuples spread into fields, everything
        else is a single-field row (reference: Context.h parallelize)."""
        if isinstance(value, tuple):
            return cls(value, columns)
        return cls((value,), columns)

    def unwrap(self) -> Any:
        """Single-field rows collect as the bare value (reference: Row semantics
        in PythonDataSet.cc fast decoders)."""
        if len(self.values) == 1:
            return self.values[0]
        return tuple(self.values)

    def as_dict(self) -> dict:
        if self.columns is None:
            raise ValueError("row has no column names")
        return dict(zip(self.columns, self.values))

    def row_type(self) -> Type:
        return tuple_of(*(infer_type(v) for v in self.values))

    def __len__(self) -> int:
        return len(self.values)

    def __iter__(self) -> Iterator[Any]:
        return iter(self.values)

    def __getitem__(self, key):
        if isinstance(key, str):
            cols = self.columns
            if cols is None:
                raise KeyError(key)
            i = _col_index_map(cols).get(key)
            if i is None:
                return self.values[cols.index(key)]  # same error as before
            return self.values[i]
        return self.values[key]

    def __eq__(self, other) -> bool:
        if isinstance(other, Row):
            return self.values == other.values
        return self.unwrap() == other

    def __hash__(self) -> int:
        return hash(self.values)

    def __repr__(self) -> str:
        if self.columns:
            inner = ", ".join(f"{c}={v!r}" for c, v in zip(self.columns, self.values))
        else:
            inner = ", ".join(repr(v) for v in self.values)
        return f"Row({inner})"
