"""Python-ish type lattice for pipeline speculation.

Re-designs the semantics of the reference's interned type system
(reference: tuplex/utils/include/TypeSystem.h:23-60, src/TypeSystem.cc) for a
columnar TPU execution model: every type additionally knows how it maps onto
fixed-shape device buffers (see `tuplex_tpu/runtime/columns.py`).

Key semantics preserved from the reference:
  - primitives BOOL < I64 < F64 (numeric upcast chain), STR, NULL, PYOBJECT
  - Option[T] (nullable), Tuple[...], List[T], Dict[K, V], EmptyTuple
  - `super_type(a, b)`: least common supertype used for the general case
    (reference: TypeSystem.h `superType`)
  - normal-case inference: majority type over a sample at a threshold
    (reference: utils/src/CSVStatistic.cc + core FileInputOperator.cc:195-260)

Types are interned: equality is identity.
"""

from __future__ import annotations

import threading
from typing import Any, Iterable, Optional, Sequence


class Type:
    """Base of all interned types. Compare with `is` or `==` (same thing)."""

    __slots__ = ("_name", "_hash")

    def __init__(self, name: str):
        self._name = name
        self._hash = hash(name)

    @property
    def name(self) -> str:
        return self._name

    def __repr__(self) -> str:
        return self._name

    def __hash__(self) -> int:
        return self._hash

    # interning makes default identity-eq correct; keep explicit for clarity
    def __eq__(self, other) -> bool:
        return self is other

    # types are interned singletons compared with `is`; pickling must
    # therefore resolve back to the canonical instance in the TARGET
    # process (schemas cross process boundaries in tuplexfile manifests
    # and serverless stage specs). Each subclass reduces to its interning
    # constructor; primitives reduce to a name lookup.
    def __reduce__(self):
        return (_primitive_by_name, (self._name,))

    # --- lattice predicates -------------------------------------------------
    def is_optional(self) -> bool:
        return False

    def is_numeric(self) -> bool:
        return False

    def is_primitive(self) -> bool:
        return False

    def element_type(self) -> "Type":
        raise TypeError(f"{self} has no element type")

    def without_option(self) -> "Type":
        return self


class _Primitive(Type):
    __slots__ = ()

    def is_primitive(self) -> bool:
        return True


class _Numeric(_Primitive):
    __slots__ = ("rank",)

    def __init__(self, name: str, rank: int):
        super().__init__(name)
        self.rank = rank

    def is_numeric(self) -> bool:
        return True


# ---------------------------------------------------------------------------
# singletons
# ---------------------------------------------------------------------------

BOOL = _Numeric("bool", 0)
I64 = _Numeric("i64", 1)
F64 = _Numeric("f64", 2)
STR = _Primitive("str")
NULL = _Primitive("null")          # NoneType
PYOBJECT = Type("pyobject")        # escape hatch: anything, interpreter-only
UNKNOWN = Type("unknown")
EMPTYTUPLE = Type("()")
EMPTYLIST = Type("[]")
EMPTYDICT = Type("{}")

_PRIMITIVES: dict[str, Type] = {
    t.name: t for t in (BOOL, I64, F64, STR, NULL, PYOBJECT, UNKNOWN,
                        EMPTYTUPLE, EMPTYLIST, EMPTYDICT)}


def _primitive_by_name(name: str) -> Type:
    """Unpickle target for non-composite types (see Type.__reduce__)."""
    try:
        return _PRIMITIVES[name]
    except KeyError:
        raise ValueError(f"unknown primitive type {name!r}") from None


_intern_lock = threading.Lock()
_interned: dict[str, Type] = {}


def _intern(t: Type) -> Type:
    with _intern_lock:
        existing = _interned.get(t.name)
        if existing is not None:
            return existing
        _interned[t.name] = t
        return t


class OptionType(Type):
    """Option[T]: value of type T or None. Maps to (buffer, validity-bitmap)."""

    __slots__ = ("inner",)

    def __init__(self, inner: Type):
        super().__init__(f"Option[{inner.name}]")
        self.inner = inner

    def is_optional(self) -> bool:
        return True

    def without_option(self) -> Type:
        return self.inner

    def is_numeric(self) -> bool:
        return False

    def __reduce__(self):
        return (option, (self.inner,))


class TupleType(Type):
    __slots__ = ("elements",)

    def __init__(self, elements: tuple[Type, ...]):
        super().__init__("(" + ",".join(e.name for e in elements) + ")")
        self.elements = elements

    def __len__(self):
        return len(self.elements)

    def __reduce__(self):
        return (tuple_of, tuple(self.elements))


class ListType(Type):
    __slots__ = ("elt",)

    def __init__(self, elt: Type):
        super().__init__(f"List[{elt.name}]")
        self.elt = elt

    def element_type(self) -> Type:
        return self.elt

    def __reduce__(self):
        return (list_of, (self.elt,))


class DictType(Type):
    __slots__ = ("key", "val")

    def __init__(self, key: Type, val: Type):
        super().__init__(f"Dict[{key.name},{val.name}]")
        self.key = key
        self.val = val

    def __reduce__(self):
        return (dict_of, (self.key, self.val))


class RowType(Type):
    """A named, ordered set of columns — the schema of a DataSet.

    Unlike a TupleType it carries column names; the reference keeps names on
    the operator and uses plain tuple row types (Schema.h:38-80). We fold them
    together since columnar execution is name-addressed.
    """

    __slots__ = ("columns", "types")

    def __init__(self, columns: tuple[str, ...], types: tuple[Type, ...]):
        assert len(columns) == len(types)
        # repr-quote names so arbitrary column strings can't alias another
        # schema's interning key
        super().__init__(
            "Row[" + ",".join(f"{c!r}:{t.name}" for c, t in zip(columns, types)) + "]"
        )
        self.columns = columns
        self.types = types

    def __len__(self):
        return len(self.types)

    def col_type(self, name: str) -> Type:
        return self.types[self.columns.index(name)]

    def col_index(self, name: str) -> int:
        return self.columns.index(name)

    def __reduce__(self):
        return (row_of, (self.columns, self.types))


class FunctionType(Type):
    __slots__ = ("params", "ret")

    def __init__(self, params: tuple[Type, ...], ret: Type):
        super().__init__(
            "(" + ",".join(p.name for p in params) + f")->{ret.name}"
        )
        self.params = params
        self.ret = ret

    def __reduce__(self):
        return (fn_of, (self.params, self.ret))


# ---------------------------------------------------------------------------
# constructors (interned)
# ---------------------------------------------------------------------------

def option(inner: Type) -> Type:
    """Option[T]. Option[Option[T]] == Option[T]; Option[null] == null;
    Option[pyobject] == pyobject."""
    if inner.is_optional() or inner is NULL or inner is PYOBJECT:
        return inner
    return _intern(OptionType(inner))


def tuple_of(*elements: Type) -> Type:
    if not elements:
        return EMPTYTUPLE
    return _intern(TupleType(tuple(elements)))


def list_of(elt: Type) -> Type:
    return _intern(ListType(elt))


def dict_of(key: Type, val: Type) -> Type:
    return _intern(DictType(key, val))


def row_of(columns: Sequence[str], types: Sequence[Type]) -> RowType:
    return _intern(RowType(tuple(columns), tuple(types)))  # type: ignore[return-value]


def fn_of(params: Sequence[Type], ret: Type) -> FunctionType:
    return _intern(FunctionType(tuple(params), ret))  # type: ignore[return-value]


# ---------------------------------------------------------------------------
# inference from Python values
# ---------------------------------------------------------------------------

def infer_type(value: Any) -> Type:
    """Type of a single Python value (reference: PythonContext.cc:1023 inferType)."""
    if value is None:
        return NULL
    if isinstance(value, bool):
        return BOOL
    if isinstance(value, int):
        # ints beyond i64 range must go through the interpreter path
        if -(2**63) <= value < 2**63:
            return I64
        return PYOBJECT
    if isinstance(value, float):
        return F64
    if isinstance(value, str):
        return STR
    if isinstance(value, tuple):
        if not value:
            return EMPTYTUPLE
        return tuple_of(*(infer_type(v) for v in value))
    if isinstance(value, list):
        if not value:
            return EMPTYLIST
        elt = infer_type(value[0])
        for v in value[1:]:
            elt = super_type(elt, infer_type(v))
            if elt is PYOBJECT:
                break
        return list_of(elt) if elt is not PYOBJECT else PYOBJECT
    if isinstance(value, dict):
        if not value:
            return EMPTYDICT
        kt: Type = UNKNOWN
        vt: Type = UNKNOWN
        for k, v in value.items():
            kt = super_type(kt, infer_type(k)) if kt is not UNKNOWN else infer_type(k)
            nvt = infer_type(v)
            vt = super_type(vt, nvt) if vt is not UNKNOWN else nvt
        if kt is PYOBJECT or vt is PYOBJECT:
            return PYOBJECT
        return dict_of(kt, vt)
    return PYOBJECT


def super_type(a: Type, b: Type) -> Type:
    """Least common supertype; PYOBJECT is top (reference: TypeSystem.h superType).

    Numeric chain bool < i64 < f64. null + T -> Option[T]. Mismatches -> PYOBJECT.
    """
    if a is b:
        return a
    if a is UNKNOWN:
        return b
    if b is UNKNOWN:
        return a
    if a is PYOBJECT or b is PYOBJECT:
        return PYOBJECT
    # null folding -> Option
    if a is NULL:
        return option(b)
    if b is NULL:
        return option(a)
    # option unwrap
    if a.is_optional() or b.is_optional():
        inner = super_type(a.without_option(), b.without_option())
        return inner if inner is PYOBJECT else option(inner)
    if a.is_numeric() and b.is_numeric():
        return a if a.rank >= b.rank else b  # type: ignore[union-attr]
    if isinstance(a, TupleType) and isinstance(b, TupleType) and len(a) == len(b):
        elts = tuple(super_type(x, y) for x, y in zip(a.elements, b.elements))
        if any(e is PYOBJECT for e in elts):
            return PYOBJECT
        return tuple_of(*elts)
    if isinstance(a, ListType) and isinstance(b, ListType):
        e = super_type(a.elt, b.elt)
        return PYOBJECT if e is PYOBJECT else list_of(e)
    if a is EMPTYLIST and isinstance(b, ListType):
        return b
    if b is EMPTYLIST and isinstance(a, ListType):
        return a
    if isinstance(a, DictType) and isinstance(b, DictType):
        k = super_type(a.key, b.key)
        v = super_type(a.val, b.val)
        if k is PYOBJECT or v is PYOBJECT:
            return PYOBJECT
        return dict_of(k, v)
    if a is EMPTYDICT and isinstance(b, DictType):
        return b
    if b is EMPTYDICT and isinstance(a, DictType):
        return a
    if isinstance(a, RowType) and isinstance(b, RowType) and a.columns == b.columns:
        ts = tuple(super_type(x, y) for x, y in zip(a.types, b.types))
        if any(t is PYOBJECT for t in ts):
            return PYOBJECT
        return row_of(a.columns, ts)
    return PYOBJECT


def normal_case_type(
    sample: Iterable[Any], threshold: float = 0.9
) -> tuple[Type, Type, float]:
    """Data-driven speculation over a sample of values.

    Returns (normal_case, general_case, normal_fraction):
      - normal_case: the majority type if its frequency >= threshold, else the
        super type (i.e. no specialization pays off)
      - general_case: super type of everything in the sample
      - normal_fraction: fraction of sample rows conforming to normal_case

    Reference semantics: FileInputOperator.cc:228-232 + CSVStatistic
    (majority >= tuplex.normalcaseThreshold, default 0.9 at
    ContextOptions.cc:507).
    """
    counts: dict[Type, int] = {}
    general: Type = UNKNOWN
    n = 0
    for v in sample:
        t = infer_type(v)
        counts[t] = counts.get(t, 0) + 1
        general = super_type(general, t) if general is not UNKNOWN else t
        n += 1
    if n == 0:
        return UNKNOWN, UNKNOWN, 0.0
    best_t, best_c = max(counts.items(), key=lambda kv: kv[1])
    # strict conformance, matching python_value_conforms: no silent numeric
    # upcast (autoUpcast is a separate opt-in, reference ContextOptions)
    def conforms(t: Type, nc: Type) -> bool:
        if t is nc:
            return True
        if nc.is_optional() and (t is NULL or t is nc.without_option()):
            return True
        return False

    # consider promoting majority with nulls into Option[majority]
    candidates = [best_t]
    if NULL in counts and best_t is not NULL:
        candidates.append(option(best_t))
    best_frac = 0.0
    best_nc = best_t
    for cand in candidates:
        c = sum(cnt for t, cnt in counts.items() if conforms(t, cand))
        frac = c / n
        if frac > best_frac:
            best_frac, best_nc = frac, cand
    if best_frac >= threshold:
        return best_nc, general, best_frac
    return general, general, 1.0


def python_value_conforms(value: Any, t: Type) -> bool:
    """Does `value` fit in the columnar layout of type `t` exactly?"""
    if t is PYOBJECT:
        return True  # boxed object columns accept anything
    vt = infer_type(value)
    if vt is t:
        return True
    if t.is_optional():
        return vt is NULL or python_value_conforms(value, t.without_option())
    if t is F64 and vt is I64:
        return False  # no silent upcast on the normal path: a deviation
    if isinstance(t, TupleType) and isinstance(vt, TupleType) and len(t) == len(vt):
        return all(python_value_conforms(v, et) for v, et in zip(value, t.elements))
    if isinstance(t, ListType) and isinstance(vt, ListType):
        return all(python_value_conforms(v, t.elt) for v in value)
    return False
