"""Flat key-value config system.

Keeps the reference's option names (reference: core/src/ContextOptions.cc:198-250
release defaults; python/tuplex/context.py:147-187 normalization) so pipelines
written against tuplex/tuplex configure this framework unchanged, and adds
`tuplex.tpu.*` keys for the device execution model.

Values are stored stringly (like the reference) with typed getters; inputs may
be nested dicts / kwargs / YAML files, all flattened to `tuplex.`-prefixed keys.
"""

from __future__ import annotations

import json
import os
from typing import Any, Mapping


def _size_to_bytes(s: str | int | float) -> int:
    if isinstance(s, (int, float)):
        return int(s)
    s = s.strip()
    units = {
        "KB": 1 << 10, "MB": 1 << 20, "GB": 1 << 30, "TB": 1 << 40,
        "K": 1 << 10, "M": 1 << 20, "G": 1 << 30, "T": 1 << 40, "B": 1,
    }
    for suffix in sorted(units, key=len, reverse=True):
        if s.upper().endswith(suffix):
            return int(float(s[: -len(suffix)]) * units[suffix])
    return int(float(s))


def _to_bool(v: Any) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).strip().lower() in ("true", "1", "yes", "on")


#: defaults mirror the reference's release table where the key carries over
DEFAULTS: dict[str, str] = {
    "tuplex.backend": "local",                 # local | tpu | multihost
    "tuplex.executorCount": "auto",            # host worker threads for IO/decode
    "tuplex.executorMemory": "1GB",
    "tuplex.driverMemory": "1GB",
    "tuplex.partitionSize": "32MB",
    "tuplex.runTimeMemory": "128MB",
    "tuplex.inputSplitSize": "64MB",
    "tuplex.useLLVMOptimizer": "true",         # accepted, ignored (XLA optimizes)
    "tuplex.autoUpcast": "false",
    "tuplex.allowUndefinedBehavior": "false",
    "tuplex.scratchDir": "/tmp/tuplex_tpu",
    "tuplex.logDir": ".",
    "tuplex.normalcaseThreshold": "0.9",
    "tuplex.optimizer.nullValueOptimization": "true",
    "tuplex.optimizer.speculateBranches": "true",
    "tuplex.optimizer.filterPushdown": "true",
    "tuplex.optimizer.selectionPushdown": "true",
    "tuplex.optimizer.operatorReordering": "false",
    "tuplex.optimizer.mergeExceptionsInOrder": "true",
    "tuplex.optimizer.sharedObjectPropagation": "true",
    "tuplex.csv.selectionPushdown": "true",
    "tuplex.csv.maxDetectionMemory": "256KB",
    "tuplex.csv.maxDetectionRows": "1000",
    "tuplex.csv.separators": "[',', ';', '|', '\\t']",
    "tuplex.csv.quotechar": '"',
    "tuplex.csv.comments": "['#']",
    "tuplex.sample.maxDetectionRows": "1000",
    "tuplex.webui.enable": "false",
    "tuplex.webui.port": "5000",
    "tuplex.webui.url": "localhost",
    "tuplex.webui.exceptionDisplayLimit": "5",
    "tuplex.redirectToPythonLogging": "false",
    "tuplex.aws.scratchDir": "",
    "tuplex.aws.maxConcurrency": "100",
    "tuplex.aws.requestTimeout": "600",     # per-task seconds
    "tuplex.aws.retryCount": "2",           # re-invocations before degrade
    "tuplex.aws.workerPlatform": "cpu",     # jax platform inside workers
                                            # ("" = inherit; one local chip
                                            # cannot be shared by N procs)
    "tuplex.aws.reuseWorkers": "true",      # warm container reuse analog
    # --- job-service keys (serve/: multi-tenant pipelines, one warm device)
    "tuplex.serve.queueDepth": "64",        # max queued+running jobs; a
                                            # submit past this blocks for
                                            # admissionTimeoutS, then is
                                            # REJECTED (backpressure, never
                                            # an unbounded backlog)
    "tuplex.serve.admissionTimeoutS": "30", # seconds a submit may wait on a
                                            # full queue before rejection
    "tuplex.serve.slots": "1",              # scheduler worker slots = max
                                            # concurrent in-flight device
                                            # dispatches (1 on a single
                                            # chip: no job can monopolize
                                            # it, nothing oversubscribes it)
    "tuplex.serve.jobMemory": "256MB",      # default per-job memory budget:
                                            # each job's private
                                            # MemoryManager budget — beyond
                                            # it the job's partitions SPILL
                                            # (runtime/spill.py LRU) instead
                                            # of OOM-ing the shared process
    "tuplex.serve.maxJobMemory": "0",       # cap on a request's memory
                                            # budget; a request asking more
                                            # is rejected at admission with
                                            # a clear error (0 = uncapped)
    "tuplex.serve.retainJobs": "256",       # completed/failed job records
                                            # (incl. materialized result
                                            # rows) the service keeps for
                                            # late fetches; older terminal
                                            # records are dropped so a
                                            # long-lived service stays
                                            # bounded (held JobHandles keep
                                            # their own record alive)
    "tuplex.serve.retryCount": "2",         # job-level retry ladder: a job
                                            # whose failure classifies as
                                            # TRANSIENT (device/dispatch
                                            # runtime errors, compile
                                            # deadline, injected transient
                                            # faults) is requeued up to
                                            # this many times from stage 0;
                                            # deterministic failures (user
                                            # code, bad requests) short-
                                            # circuit with a clear error.
                                            # Every attempt lands in the
                                            # job record + tenant span
                                            # stream + the
                                            # serve_job_retries counter.
                                            # The wire loop reuses it as
                                            # the crash-requeue budget: a
                                            # job that was in flight when
                                            # the serve process died is
                                            # requeued on restart until
                                            # its requeue count exceeds
                                            # this, then failed cleanly
    "tuplex.serve.retryBackoffS": "0.5",    # base of the exponential
                                            # retry backoff: attempt k
                                            # waits retryBackoffS * 2^(k-1)
                                            # seconds before requeueing
                                            # (the slot is freed while it
                                            # waits; 0 = immediate)
    "tuplex.serve.tenantWeights": "",       # "tenantA:2,tenantB:1" —
                                            # deficit-weighted round-robin:
                                            # weight w = w consecutive stage
                                            # dispatches per scheduler cycle
                                            # (unlisted tenants weigh 1)
    "tuplex.serve.metricsPort": "-1",       # loopback HTTP port for
                                            # Prometheus /metrics +
                                            # /healthz on `python -m
                                            # tuplex_tpu serve` (runtime/
                                            # telemetry). -1 = no server;
                                            # 0 = pick a free port and
                                            # announce it in
                                            # <root>/metrics.port
    "tuplex.serve.metricsPromS": "5",       # seconds between atomic
                                            # <root>/metrics.prom text
                                            # drops by the serve loop (the
                                            # wire protocol's no-socket
                                            # telemetry leg; <=0 disables)
    "tuplex.serve.healthSaturation": "0.9", # admission-queue fill fraction
                                            # (open/queueDepth) at which
                                            # the health state degrades;
                                            # full + rejecting = unhealthy
    "tuplex.serve.healthWedgedCompileS": "300",  # oldest in-flight compile
                                            # age (s) before the health
                                            # state degrades (the wedged-
                                            # compile watchdog; 3x ->
                                            # unhealthy)
    "tuplex.serve.healthStarvationS": "120",  # ready jobs waiting with all
                                            # slots busy and no turn
                                            # finishing for this long ->
                                            # degraded (4x -> unhealthy)
    "tuplex.serve.driftWindowS": "10",      # exception-plane drift window
                                            # (runtime/excprof): observed
                                            # per-tenant exception traffic
                                            # folds into the EWMA profile
                                            # every this-many seconds; the
                                            # drift score compares the
                                            # EWMA against the tenant's
                                            # plan-time-anchored baseline
                                            # and trips
                                            # respecialize_recommended one
                                            # window after a distribution
                                            # shift
    "tuplex.serve.sloMs": "0",              # per-job latency objective
                                            # (milliseconds, end-to-end:
                                            # admission to terminal) every
                                            # tenant is held to by the
                                            # latency-budget plane
                                            # (runtime/critpath): each
                                            # finished job counts toward
                                            # its tenant's attainment and
                                            # burn-rate windows, and the
                                            # `slo` health check degrades
                                            # on a burning fast window.
                                            # 0 = no SLO declared
    "tuplex.serve.tenantSlos": "",          # "tenantA:250,tenantB:1000" —
                                            # per-tenant SLO overrides in
                                            # milliseconds (unlisted
                                            # tenants use sloMs)
    "tuplex.serve.sloBurnWindowS": "60",    # the FAST burn-rate window in
                                            # seconds (the slow window is
                                            # 5x): burn = window miss
                                            # fraction / error budget;
                                            # fast >= 1 -> degraded, fast
                                            # AND slow >= 1 (sustained)
                                            # -> unhealthy, recovery is
                                            # automatic as misses age out
    "tuplex.serve.sloTarget": "0.9",        # attainment objective the
                                            # burn rate is normalized
                                            # against (error budget =
                                            # 1 - target; 0.9 = 10% of
                                            # jobs may miss before burn
                                            # reads 1.0)
    "tuplex.serve.respec": "true",          # closed-loop self-healing
                                            # (serve/respec.py): when a
                                            # tenant's exception-plane
                                            # drift trips respecialize_
                                            # recommended (runtime/
                                            # excprof), re-speculate its
                                            # plan from the LIVE observed
                                            # code distribution, compile
                                            # the candidate on the
                                            # background compile lane,
                                            # canary it on the tenant's
                                            # next job, and hot-swap at a
                                            # job boundary (the incumbent
                                            # stays the fallback rung in
                                            # exec/local's tier-restart
                                            # ladder). false = sense only
                                            # (the PR-13 behavior)
    "tuplex.serve.respecCheckS": "1",       # seconds between controller
                                            # drift polls per tenant
    "tuplex.serve.respecDebounce": "2",     # consecutive polls a tenant
                                            # must stay respecialize-
                                            # recommended before a
                                            # candidate build starts (one
                                            # noisy window must not spend
                                            # a background compile)
    "tuplex.serve.respecCooldownS": "120",  # minimum seconds between
                                            # respecialization attempts
                                            # for one tenant (promote or
                                            # abandon both arm it)
    "tuplex.serve.respecCanaryFrac": "0.25",  # fraction of the canary
                                            # job's partitions shadow-
                                            # executed on the candidate
                                            # per stage (>=1 partition;
                                            # the job's OWN results always
                                            # come from the incumbent)
    "tuplex.serve.respecCompileDeadlineS": "120",  # ceiling on the whole
                                            # candidate compile phase; a
                                            # candidate that cannot
                                            # compile in time is
                                            # quarantined, never promoted
    "tuplex.serve.respecQuarantineS": "300",  # base cooldown after a
                                            # quarantined candidate; the
                                            # SAME candidate signature
                                            # (content-addressed
                                            # `.respecquar` marker)
                                            # doubles it per repeat so a
                                            # poisoned respec cannot flap
    # --- TPU-native keys ---------------------------------------------------
    "tuplex.tpu.deviceBatchSize": "1048576",    # rows per device dispatch
    "tuplex.tpu.padBucketing": "q8",            # q8 | pow2 | exact
    "tuplex.tpu.filterCompaction": "true",      # selection-vector compaction
    "tuplex.tpu.maxStrBytes": "4096",           # cap for fixed-width str cols
    "tuplex.tpu.meshShape": "auto",             # e.g. "8" or "4x2"
    "tuplex.tpu.meshAxes": "data",
    "tuplex.tpu.donateBuffers": "true",
    "tuplex.tpu.interpretOnly": "false",        # force interpreter (debugging)
    "tuplex.tpu.jitCacheSize": "128",
    "tuplex.tpu.profileDir": "",            # jax.profiler trace per action
    "tuplex.tpu.compileBudgetS": "480",     # ceiling on a stage's predicted
                                            # compile seconds: the split
                                            # tuner (plan/splittuner.py)
                                            # splits finer or degrades to a
                                            # host-CPU compile to stay under
    "tuplex.tpu.compileDeadlineS": "300",   # hard ceiling per stage
                                            # compile, DEFAULT ON: the
                                            # compile runs in a killable
                                            # forked child (exec/
                                            # compilequeue isolation_mode;
                                            # TUPLEX_COMPILE_ISOLATION=
                                            # thread reverts to the old
                                            # abandon-on-a-thread wait) and
                                            # a blown deadline SIGKILLs it,
                                            # writes a content-addressed
                                            # `.timeout` marker so later
                                            # processes skip the wedge
                                            # instantly, and degrades the
                                            # WHOLE stage to one slower
                                            # tier (host-CPU compile, else
                                            # interpreter — never a
                                            # mid-stage compiled/
                                            # interpreted row split).
                                            # 0 disables
    "tuplex.tpu.parallelCompile": "true",   # plan-level AOT compile pool
                                            # (exec/compilequeue.py);
                                            # TUPLEX_PARALLEL_COMPILE=0 also
                                            # disables
    "tuplex.tpu.staticTypes": "true",       # sample-free specialization
                                            # (compiler/typeinfer.py):
                                            # abstract-interpret UDF ASTs
                                            # and skip the CPython sample
                                            # trace when the output type is
                                            # exactly decidable. Default on;
                                            # TUPLEX_STATIC_TYPES=0 is the
                                            # env escape hatch (wins over
                                            # the option, for A/B timing)
    "tuplex.tpu.telemetry": "true",         # serve-layer telemetry
                                            # (runtime/telemetry.py):
                                            # streaming latency histograms,
                                            # sampled gauges, health checks
                                            # behind Metrics.
                                            # export_prometheus() and the
                                            # serve /metrics endpoint.
                                            # Default on (O(1) per record).
                                            # Like tuplex.tpu.trace the
                                            # gate is process-wide and the
                                            # option only ever turns it ON;
                                            # the TUPLEX_TELEMETRY=0 env
                                            # kill switch (wins over all)
                                            # makes every record a single
                                            # flag check, zero allocation
    "tuplex.tpu.devprof": "true",           # device-plane cost
                                            # attribution (runtime/
                                            # devprof.py): harvests XLA
                                            # cost/memory analysis per
                                            # compiled stage (persisted
                                            # next to the AOT artifact),
                                            # measures device time per
                                            # dispatch (launch→ready,
                                            # cold/warm split) and emits
                                            # roofline readouts into
                                            # stage metrics, bench JSON,
                                            # /metrics gauges, spans and
                                            # the dashboard. Default on.
                                            # NOTE the enabled dispatch
                                            # path blocks each partition
                                            # until the device finishes
                                            # (that IS the measurement) —
                                            # TUPLEX_DEVPROF=0 is the env
                                            # kill switch restoring the
                                            # fully-async window with a
                                            # single flag check (zero
                                            # allocation, test-pinned).
                                            # Like trace/telemetry the
                                            # gate is process-wide and
                                            # the option only turns it ON
    "tuplex.tpu.excprof": "true",           # exception-plane observability
                                            # (runtime/excprof.py): per-
                                            # stage x op x code windowed
                                            # accounting at the D2H unpack
                                            # + resolve-tier boundaries,
                                            # a plan-time baseline snapshot
                                            # (analyzer inventory + resolve
                                            # plan) with an EWMA drift
                                            # detector, the per-tenant
                                            # respecialize_recommended
                                            # signal and bounded sampled
                                            # deviant rows. Default on.
                                            # TUPLEX_EXCPROF=0 is the env
                                            # kill switch (wins over all):
                                            # every record path collapses
                                            # to one flag check, zero
                                            # allocation (test-pinned).
                                            # Like trace/telemetry/devprof
                                            # the gate is process-wide and
                                            # the option only turns it ON
    "tuplex.tpu.graphlint": "true",         # jaxpr-plane static analysis
                                            # (compiler/graphlint.py):
                                            # every stage jaxpr is vetted
                                            # BEFORE submission to XLA —
                                            # eqn census, static peak-
                                            # memory bound, dtype-creep /
                                            # broadcast-blowup lint, and
                                            # named compile-hazard rules
                                            # (the wide-str-compaction
                                            # XLA:CPU wedge). A wedge (or
                                            # a score past the threshold
                                            # below) pre-degrades at plan
                                            # time or vetoes at compile
                                            # time (CompileHazard rides
                                            # the normal tier ladder), so
                                            # pathological stages never
                                            # burn a deadline + SIGKILL.
                                            # Default on. TUPLEX_
                                            # GRAPHLINT=0 is the env kill
                                            # switch (wins over all):
                                            # every hook collapses to one
                                            # flag check, zero allocation
                                            # (test-pinned). Like devprof
                                            # the gate is process-wide
                                            # and the option only ever
                                            # turns it ON
    "tuplex.tpu.hazardThreshold": "60",     # hazard-score veto line in
                                            # predicted compile SECONDS
                                            # (graphlint's construct-
                                            # weighted census). 60 s sits
                                            # 2.6x above the worst clean
                                            # bundled stage (22.9 s), so
                                            # by default only a wedge-
                                            # severity finding crosses
                                            # it; <= 0 disables the score
                                            # veto (wedge rules still
                                            # veto). Also the per-segment
                                            # budget when a hazard score
                                            # forces a stage split
    "tuplex.tpu.excprofHalfLifeS": "30",    # EWMA half-life of the drift
                                            # detector: how fast the
                                            # observed exception profile
                                            # forgets old windows. Shorter
                                            # = trips faster on a shift
                                            # but noisier on bursty input
    "tuplex.tpu.excprofDriftThreshold": "0.5",  # drift_score (0..1) at
                                            # which respecialize_
                                            # recommended fires and the
                                            # exception_drift health check
                                            # reads degraded
    "tuplex.tpu.excprofSampleRows": "3",    # deviant rows captured per
                                            # stage x exception code
                                            # (first K, repr-truncated to
                                            # 160 chars) for the dashboard
                                            # "why did this row fall off
                                            # the fast path" panel. 0
                                            # disables capture entirely —
                                            # row payloads then never
                                            # leave the exec path
    "tuplex.tpu.excprofNormalRate": "0.05",  # exception-rate allowance
                                            # anchoring the drift baseline
                                            # for stages whose plan-time
                                            # inventory EXPECTS codes; a
                                            # code-free static verdict
                                            # gets a tight 0.005 floor
                                            # instead (any exception there
                                            # is evidence the speculation
                                            # went stale)
    "tuplex.tpu.critpath": "true",          # latency-budget plane
                                            # (runtime/critpath): per-job
                                            # critical-path attribution
                                            # over the span timeline into
                                            # the canonical exclusive
                                            # buckets (admission/queue
                                            # waits, compile trace/lower/
                                            # xla, h2d, device, resolve
                                            # tiers, d2h, merge,
                                            # scheduler/other,
                                            # unattributed), per-tenant
                                            # EWMA budget baselines with
                                            # slow-job blame, and the SLO
                                            # attainment/burn plane.
                                            # Surfaced via `python -m
                                            # tuplex_tpu whyslow`, the
                                            # dashboard budget panel,
                                            # tuplex_critpath_* /metrics
                                            # gauges and bench
                                            # latency_budget.* keys. Needs
                                            # tuplex.tpu.trace for full
                                            # coverage (without spans only
                                            # the wait buckets resolve).
                                            # TUPLEX_CRITPATH=0 kills it
                                            # with a zero-allocation
                                            # disabled path
    "tuplex.tpu.critpathHalfLifeS": "120",  # EWMA half-life of the per-
                                            # tenant baseline budget
                                            # vectors (the regression-
                                            # blame anchor; same fold as
                                            # excprof's drift EWMA)
    "tuplex.tpu.critpathSlowFactor": "1.5",  # a job whose end-to-end wall
                                            # exceeds its tenant's EWMA
                                            # baseline by this factor is
                                            # SLOW: the grown bucket is
                                            # blamed (serve:slow-job
                                            # instant + dashboard +
                                            # whyslow)
    "tuplex.tpu.trace": "false",            # structured span tracing
                                            # (runtime/tracing.py): nested
                                            # spans across plan/compile/
                                            # execute/merge, exported as
                                            # Chrome trace-event JSON via
                                            # Metrics.export_trace(path) /
                                            # `python -m tuplex_tpu trace`.
                                            # Off = zero overhead (no-op
                                            # spans). TUPLEX_TRACE=1 also
                                            # enables; TUPLEX_TRACE_BUFFER
                                            # sizes the ring (default 65536
                                            # spans)
}


class ContextOptions:
    def __init__(self, conf: Mapping[str, Any] | None = None, **kwargs: Any):
        self._store: dict[str, str] = dict(DEFAULTS)
        if conf:
            self.update(conf)
        if kwargs:
            self.update(kwargs)

    # -- updates ------------------------------------------------------------
    def update(self, conf: Mapping[str, Any] | str) -> None:
        if isinstance(conf, str):
            # YAML/JSON file path
            with open(conf) as fp:
                text = fp.read()
            try:
                data = json.loads(text)
            except json.JSONDecodeError:
                data = _parse_simple_yaml(text)
            self.update(data)
            return
        for k, v in _flatten(conf).items():
            self._store[_normalize_key(k)] = _stringify(v)

    def set(self, key: str, value: Any) -> None:
        self._store[_normalize_key(key)] = _stringify(value)

    def to_dict(self) -> dict[str, str]:
        """Flat copy for shipping to workers (serverless InvocationRequest
        carries the full option set, reference: Lambda.proto settings)."""
        return dict(self._store)

    # -- getters ------------------------------------------------------------
    def get(self, key: str, default: Any = None) -> Any:
        return self._store.get(_normalize_key(key), default)

    def get_str(self, key: str, default: str = "") -> str:
        return str(self.get(key, default))

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.get(key)
        return default if v is None else _to_bool(v)

    def get_int(self, key: str, default: int = 0) -> int:
        v = self.get(key)
        if v is None:
            return default
        if isinstance(v, str) and v == "auto":
            return default
        return int(float(v))

    def get_float(self, key: str, default: float = 0.0) -> float:
        v = self.get(key)
        return default if v is None else float(v)

    def get_size(self, key: str, default: int = 0) -> int:
        v = self.get(key)
        return default if v is None else _size_to_bytes(v)

    def executor_count(self) -> int:
        v = self.get_str("tuplex.executorCount", "auto")
        if v == "auto":
            return max(1, (os.cpu_count() or 2) - 1)
        return int(v)

    def as_dict(self) -> dict[str, str]:
        return dict(self._store)

    def __contains__(self, key: str) -> bool:
        return _normalize_key(key) in self._store

    def __repr__(self) -> str:
        return f"ContextOptions({len(self._store)} keys)"


def _normalize_key(key: str) -> str:
    # reference: context.py:183-187 — keys are normalized to tuplex.*
    return key if key.startswith("tuplex.") else "tuplex." + key


def _stringify(v: Any) -> str:
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


def _flatten(d: Mapping[str, Any], prefix: str = "") -> dict[str, Any]:
    out: dict[str, Any] = {}
    for k, v in d.items():
        key = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, Mapping):
            out.update(_flatten(v, key))
        else:
            out[key] = v
    return out


def _parse_simple_yaml(text: str) -> dict[str, Any]:
    """Tiny `key: value` YAML subset (nested via indentation not supported —
    use dotted keys). Avoids a yaml dependency for config files."""
    out: dict[str, Any] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#") or ":" not in line:
            continue
        k, _, v = line.partition(":")
        out[k.strip()] = v.strip().strip("\"'")
    return out
