"""Exception-code lattice for dual-mode execution.

On device, every fused pipeline computes a per-row int32 error code alongside
its outputs; code 0 means the row took the normal path. Non-zero rows are
masked out of device outputs and shipped to the interpreter resolve path.

Re-designs the reference's exception-code enum + exception partitions
(reference: tuplex/utils/include/ExceptionCodes.h:24-118, compiled branch to
exception_handler_f at core/include/physical/CodeDefs.h:43) as a vectorized
code lattice: composed ops propagate the FIRST error per row (lower op index
wins), matching sequential Python semantics.
"""

from __future__ import annotations

import enum


class ExceptionCode(enum.IntEnum):
    OK = 0
    # Python exception classes reproducible by compiled paths
    ZERODIVISIONERROR = 1
    VALUEERROR = 2
    TYPEERROR = 3
    INDEXERROR = 4
    KEYERROR = 5
    ATTRIBUTEERROR = 6
    OVERFLOWERROR = 7
    STOPITERATION = 8
    ASSERTIONERROR = 9
    # internal codes (reference: ExceptionCodes.h NORMALCASEVIOLATION etc.)
    NORMALCASEVIOLATION = 100
    BADPARSE_STRING_INPUT = 101
    NULLERROR = 102            # unexpected None on a non-Option path
    GENERALCASEVIOLATION = 103
    LOOPCAPEXCEEDED = 104      # while-loop unroll cap hit: interpreter row
    PYTHON_FALLBACK = 110      # UDF not compilable: row routed to interpreter
    UNKNOWN = 120


_PY_TO_CODE = {
    ZeroDivisionError: ExceptionCode.ZERODIVISIONERROR,
    ValueError: ExceptionCode.VALUEERROR,
    TypeError: ExceptionCode.TYPEERROR,
    IndexError: ExceptionCode.INDEXERROR,
    KeyError: ExceptionCode.KEYERROR,
    AttributeError: ExceptionCode.ATTRIBUTEERROR,
    OverflowError: ExceptionCode.OVERFLOWERROR,
    StopIteration: ExceptionCode.STOPITERATION,
    AssertionError: ExceptionCode.ASSERTIONERROR,
}

_CODE_TO_PY = {v: k for k, v in _PY_TO_CODE.items()}


def code_for_exception(exc: BaseException) -> ExceptionCode:
    for cls in type(exc).__mro__:
        if cls in _PY_TO_CODE:
            return _PY_TO_CODE[cls]
    return ExceptionCode.UNKNOWN


_CODE_INT_TO_PY = {int(c): _CODE_TO_PY.get(c) for c in ExceptionCode}


def exception_class_for_code(code: int):
    """Python exception class for a code (None for internal codes). Plain
    dict lookup: enum construction showed up at 0.3s/1M rows on the
    exact-exception exit."""
    return _CODE_INT_TO_PY.get(code)


_CODE_INT_TO_NAME = {
    int(c): (_CODE_TO_PY[c].__name__ if c in _CODE_TO_PY else c.name)
    for c in ExceptionCode
}


def exception_name(code: int) -> str:
    name = _CODE_INT_TO_NAME.get(code)
    return name if name is not None else f"code{code}"


def code_for_exception_class(cls):
    """ExceptionCode for an exception CLASS (mro-aware, like
    code_for_exception but without a live instance), or None when no
    compiled-path code maps exactly — base classes like Exception or
    LookupError return None, which callers must treat as "covers
    anything" (the dead-resolver lint skips them)."""
    for c in getattr(cls, "__mro__", ()):
        if c in _PY_TO_CODE:
            return _PY_TO_CODE[c]
    return None


def code_for_name(name: str):
    """ExceptionCode for a Python exception-class NAME ('ValueError' →
    VALUEERROR), or None when no compiled-path code exists for it. Static
    analysis maps `raise X` sites through this without a live exception
    instance (compiler/analyzer.py exception-site inventory)."""
    return ExceptionCode.__members__.get(name.upper()) if name else None


# Packed device-lattice layout: exception-class code in the low byte,
# logical-operator id above it. One int32 per row carries both — a second
# per-row operator lattice measured a 20x kLoop recompute pathology on
# XLA-CPU. Operator ids are process-global and unbounded; ids that would
# overflow the 23 bits left in an int32 pack as 0 ("unknown operator") —
# attribution degrades, correctness (the class code) never does.
_OP_ID_LIMIT = 1 << 23


def pack_device_code(code: int, op_id: int) -> int:
    if not 0 < op_id < _OP_ID_LIMIT:
        op_id = 0
    return int(code) | (op_id << 8)


def unpack_device_code(packed: int) -> tuple[int, int]:
    """packed -> (exception-class code, operator id)."""
    return packed & 0xFF, packed >> 8


def unpack_device_codes(codes):
    """Vectorized unpack over a numpy int array -> iterator of (code,
    op_id) tuples. Same layout as unpack_device_code; per-row python calls
    measurably hurt at zillow's ~6% error-row rate."""
    return zip((codes & 0xFF).tolist(), (codes >> 8).tolist())


class TuplexException(Exception):
    """Driver-side framework error (not a per-row exception)."""


class NotCompilable(TuplexException):
    """Raised by the emitter when a UDF uses constructs outside the compiled
    subset; the operator then runs rows on the interpreter path (reference:
    fallback mode, python/tests/test_fallback.py semantics)."""
