#!/bin/bash
# Standing TPU-liveness watch: probe every 30 min; on success, leave a loud
# marker file so the round's bench can switch to the chip.
while true; do
  ts=$(date -u +%FT%TZ)
  timeout -s KILL 240 python /root/repo/tpu_diag/probe_basic.py > /tmp/tpu_probe_last.log 2>&1
  # require an actual TPU device line, not just PROBE_OK: a fast-failing
  # plugin could fall back to CPU and still complete the probe
  if grep -q PROBE_OK /tmp/tpu_probe_last.log && \
     grep -iq "devices:.*tpu" /tmp/tpu_probe_last.log; then
    echo "$ts PROBE_OK — TUNNEL ALIVE" >> /root/repo/tpu_diag/watch.log
    cp /tmp/tpu_probe_last.log /root/repo/tpu_diag/probe_SUCCESS.log
  else
    echo "$ts wedge (no PROBE_OK)" >> /root/repo/tpu_diag/watch.log
  fi
  sleep 1800
done
