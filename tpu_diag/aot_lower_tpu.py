"""TPU-targeted AOT lowering from the CPU host (no chip needed).

The axon tunnel has never completed PJRT init in four rounds (see
TPU_DIAGNOSIS.md). This script is the fallback evidence VERDICT r3 asked
for: lower the flagship fused Zillow stage kernel and the Pallas NFA regex
kernel for the TPU platform via jax.export's cross-platform lowering, and
save the StableHLO artifacts. If TPU lowering itself fails, the error is
recorded — that too is a data point.

Run:  python tpu_diag/aot_lower_tpu.py          (forces CPU backend)
Artifacts land in tpu_diag/aot/.
"""
from __future__ import annotations

import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

OUT = os.path.join(os.path.dirname(os.path.abspath(__file__)), "aot")


def main():
    os.makedirs(OUT, exist_ok=True)
    sys.setrecursionlimit(20000)   # Mosaic serialization recurses deeply
    import jax

    jax.config.update("jax_platforms", "cpu")   # post-import: beats the plugin
    import jax.numpy as jnp
    import numpy as np

    report = []

    def attempt(name, make_exported):
        ok_path = os.path.join(OUT, f"{name}.stablehlo.mlir")
        fail_path = os.path.join(OUT, f"{name}.FAILED.txt")
        t0 = time.perf_counter()
        try:
            exp = make_exported()
            hlo = exp.mlir_module()
            with open(ok_path, "w") as f:
                f.write(hlo)
            if os.path.exists(fail_path):   # stale contradictory evidence
                os.unlink(fail_path)
            msg = (f"{name}: OK platforms={exp.platforms} "
                   f"bytes={len(hlo)} lower_s={time.perf_counter()-t0:.1f}")
        except Exception as e:
            with open(fail_path, "w") as f:
                f.write(traceback.format_exc())
            if os.path.exists(ok_path):
                os.unlink(ok_path)
            msg = (f"{name}: FAILED {type(e).__name__}: {str(e)[:200]} "
                   f"(full traceback in {os.path.basename(fail_path)})")
        print(msg, flush=True)
        report.append(msg)

    # --- 1. the fused Zillow stage kernel (the flagship single-chip step) --
    import __graft_entry__ as GE

    raw_fn, (batch,) = GE.entry()

    def export_zillow():
        from jax import export as jexport

        args = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), batch)
        return jexport.export(jax.jit(raw_fn), platforms=["tpu"])(args)

    attempt("zillow_stage_tpu", export_zillow)

    # --- 2. the Pallas NFA kernel (dense Glushkov, VMEM-resident) ----------
    def export_pallas_nfa():
        from jax import export as jexport

        from tuplex_tpu.ops.nfa import NFARegex
        from tuplex_tpu.ops import pallas_nfa

        rx = NFARegex(r"\d+-\d+")
        n, w = 4096, 64
        bytes_sds = jax.ShapeDtypeStruct((n, w), np.uint8)
        lens_sds = jax.ShapeDtypeStruct((n,), np.int32)

        def kern(b, l):
            return pallas_nfa.match_pallas(rx, b, l, interpret=False)

        return jexport.export(jax.jit(kern),
                              platforms=["tpu"])(bytes_sds, lens_sds)

    attempt("pallas_nfa_tpu", export_pallas_nfa)

    # --- 3. dense-MXU NFA engine (matmul transition) -----------------------
    def export_dense_nfa():
        from jax import export as jexport

        from tuplex_tpu.ops.nfa import NFARegex

        rx = NFARegex(r"\d+-\d+")
        n, w = 4096, 64
        bytes_sds = jax.ShapeDtypeStruct((n, w), np.uint8)
        lens_sds = jax.ShapeDtypeStruct((n,), np.int32)
        return jexport.export(jax.jit(rx.match_dense),
                              platforms=["tpu"])(bytes_sds, lens_sds)

    attempt("dense_nfa_tpu", export_dense_nfa)

    # --- 4. the MULTICHIP path: zillow stage row-sharded over an 8-device
    # ABSTRACT TPU mesh (the dryrun's sharded compute, lowered for real
    # TPU — no chips needed; nr_devices lands in the artifact) ------------
    def export_zillow_mesh():
        from jax import export as jexport
        from jax.sharding import (AbstractMesh, NamedSharding,
                                  PartitionSpec as P)

        from tuplex_tpu.parallel.mesh import pad_batch_for_mesh

        mesh = AbstractMesh((8,), ("data",))
        shard = NamedSharding(mesh, P("data"))
        repl = NamedSharding(mesh, P())
        arrays = pad_batch_for_mesh(batch, 8)
        shardings = {k: shard if np.ndim(v) else repl
                     for k, v in arrays.items()}
        sds = {k: jax.ShapeDtypeStruct(np.shape(v), np.asarray(v).dtype,
                                       sharding=shardings[k])
               for k, v in arrays.items()}
        return jexport.export(jax.jit(raw_fn, in_shardings=(shardings,)),
                              platforms=["tpu"])(sds)

    attempt("zillow_stage_mesh8_tpu", export_zillow_mesh)

    with open(os.path.join(OUT, "REPORT.txt"), "w") as f:
        f.write("\n".join(report) + "\n")
    print("done", flush=True)


if __name__ == "__main__":
    main()
