"""Measure the x64 emulation tax on the live chip for framework-shaped ops.

v5e has no native i64/f64: XLA emulates both. The framework traces under
jax_enable_x64=True for CPython parity; this probe prices that choice on the
byte-matrix kernels' dominant primitives so narrowing work can be targeted.
"""
import json
import time

import jax

jax.config.update("jax_enable_x64", True)
import jax.numpy as jnp
import numpy as np


def t(fn, n=5):
    fn()
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


R, W = 106496, 96
mat = jax.device_put(np.random.randint(48, 58, (R, W), np.uint8))
mat.block_until_ready()

for name, dt in (("i32", jnp.int32), ("i64", jnp.int64)):
    f = jax.jit(lambda m, dt=dt: jnp.cumsum(m.astype(dt), axis=1)[:, -1])
    sec = t(lambda: f(mat).block_until_ready())
    print(json.dumps({"probe": f"cumsum_{name}_{R}x{W}", "sec": round(sec, 5)}),
          flush=True)

for name, dt in (("f32", jnp.float32), ("f64", jnp.float64)):
    f = jax.jit(lambda m, dt=dt: (m.astype(dt) * 1.0001 + 3.0).sum(axis=1))
    sec = t(lambda: f(mat).block_until_ready())
    print(json.dumps({"probe": f"fma_{name}_{R}x{W}", "sec": round(sec, 5)}),
          flush=True)

# digit-parse shape: per-row positional powers (the int-parse kernel's core)
for name, dt in (("i32", jnp.int32), ("i64", jnp.int64)):
    pw = jnp.cumprod(jnp.full((W,), 10, dt)[::-1])[::-1]

    def parse(m, pw=pw, dt=dt):
        d = (m - 48).astype(dt)
        return (d * pw[None, :]).sum(axis=1)

    f = jax.jit(parse)
    sec = t(lambda: f(mat).block_until_ready())
    print(json.dumps({"probe": f"digitparse_{name}", "sec": round(sec, 5)}),
          flush=True)

# sort (replace-deletion kernel core)
key = jax.device_put(np.random.randint(0, 1 << 20, (R, 64), np.int32))
key.block_until_ready()
for name, dt in (("i32", jnp.int32), ("i64", jnp.int64)):
    f = jax.jit(lambda k, dt=dt: jnp.sort(k.astype(dt), axis=1))
    sec = t(lambda: f(key).block_until_ready())
    print(json.dumps({"probe": f"rowsort_{name}_{R}x64", "sec": round(sec, 5)}),
          flush=True)

# gather (string indexing / compaction core)
idx = jax.device_put(np.random.randint(0, R, (R,), np.int32))
idx.block_until_ready()
f = jax.jit(lambda m, i: m[i])
sec = t(lambda: f(mat, idx).block_until_ready())
print(json.dumps({"probe": "gather_rows_u8", "sec": round(sec, 5)}), flush=True)
