"""On-chip perf characterization of the tunneled TPU data plane.

Measures the three costs that bound end-to-end pipeline throughput on the
axon tunnel: (1) per-dispatch RPC latency, (2) H2D/D2H bandwidth,
(3) raw on-chip compute throughput (MXU matmul + VPU elementwise on the
byte-matrix shapes the framework actually ships).

Run: python tpu_diag/perf_probe.py   (prints one JSON line per probe)
"""
import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def t(fn, n=5):
    fn()  # warm
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    dev = jax.devices()[0]
    print(json.dumps({"probe": "device", "platform": dev.platform,
                      "kind": getattr(dev, "device_kind", "?")}), flush=True)

    # 1. dispatch latency: trivial kernel roundtrip
    one = jnp.ones((8, 8), jnp.float32)
    f = jax.jit(lambda x: x + 1)
    lat = t(lambda: f(one).block_until_ready(), n=20)
    print(json.dumps({"probe": "dispatch_latency_ms",
                      "value": round(lat * 1e3, 2)}), flush=True)

    # 2. H2D bandwidth at framework-like sizes
    for mb in (1, 8, 32, 128):
        host = np.zeros((mb << 20,), np.uint8)
        sec = t(lambda: jax.device_put(host).block_until_ready(), n=3)
        print(json.dumps({"probe": f"h2d_{mb}MB",
                          "sec": round(sec, 4),
                          "MBps": round(mb / sec, 1)}), flush=True)

    # 3. D2H bandwidth
    for mb in (1, 32):
        devarr = jax.device_put(np.zeros((mb << 20,), np.uint8))
        devarr.block_until_ready()
        sec = t(lambda: np.asarray(devarr), n=3)
        print(json.dumps({"probe": f"d2h_{mb}MB",
                          "sec": round(sec, 4),
                          "MBps": round(mb / sec, 1)}), flush=True)

    # 4. MXU: bf16 matmul FLOPs
    for n in (1024, 4096):
        a = jnp.ones((n, n), jnp.bfloat16)
        mm = jax.jit(lambda x: x @ x)
        sec = t(lambda: mm(a).block_until_ready(), n=5)
        tflops = 2 * n ** 3 / sec / 1e12
        print(json.dumps({"probe": f"matmul_bf16_{n}",
                          "sec": round(sec, 5),
                          "TFLOPs": round(tflops, 2)}), flush=True)

    # 5. VPU elementwise on a framework-shaped byte matrix (100k x 200B):
    #    the zillow batch is ~20 uint8 columns; model one fused pass over it.
    rows = 106496
    mat = jax.device_put(np.zeros((rows, 200), np.uint8))
    mat.block_until_ready()

    def stagelike(m):
        x = m.astype(jnp.int32)
        d = (x >= ord("0")) & (x <= ord("9"))
        acc = jnp.where(d, x - 48, 0).cumsum(axis=1)
        return (acc[:, -1] % 251).astype(jnp.uint8)

    g = jax.jit(stagelike)
    sec = t(lambda: g(mat).block_until_ready(), n=5)
    print(json.dumps({"probe": "vpu_bytepass_106k_200B",
                      "sec": round(sec, 5),
                      "rows_per_sec": round(rows / sec, 0)}), flush=True)

    # 6. many-small-dispatch cost (the window pipeline's per-partition cost)
    small = jax.device_put(np.zeros((2048, 200), np.uint8))
    small.block_until_ready()
    sec = t(lambda: [g2.block_until_ready()
                     for g2 in [g(small) for _ in range(20)]][-1], n=3)
    print(json.dumps({"probe": "dispatch_20x_small",
                      "sec": round(sec, 4),
                      "per_call_ms": round(sec / 20 * 1e3, 2)}), flush=True)


if __name__ == "__main__":
    main()
