"""Count device round-trips in one warm zillow run on the live chip.

Patches the three host<->device seams (device_put staging, compiled stage-fn
executions, D2H materialization) and reports count + wall per seam for the
steady-state (2nd) run. The ~62ms/execution tunnel tax (perf_probe.py) makes
round-trip count the dominant perf variable.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np

COUNTS = {}


def _tick(name, sec):
    c, s = COUNTS.get(name, (0, 0.0))
    COUNTS[name] = (c + 1, s + sec)


_orig_put = jax.device_put


def put(x, *a, **k):
    t0 = time.perf_counter()
    r = _orig_put(x, *a, **k)
    _tick("device_put", time.perf_counter() - t0)
    return r


jax.device_put = put

_orig_asarray = np.asarray


def asarray(x, *a, **k):
    isdev = isinstance(x, jax.Array) and not isinstance(x, np.ndarray)
    t0 = time.perf_counter()
    r = _orig_asarray(x, *a, **k)
    if isdev:
        _tick("np.asarray(devarr)", time.perf_counter() - t0)
    return r


np.asarray = asarray

import tuplex_tpu
from tuplex_tpu.exec.local import LocalBackend

_orig_jit = LocalBackend._jit_stage_fn


def jit_counted(self, raw_fn, **kw):
    fn = _orig_jit(self, raw_fn, **kw)

    def wrapped(*a, **k):
        t0 = time.perf_counter()
        leaves = jax.tree.leaves((a, k))
        nbytes = sum(getattr(x, "nbytes", 0) for x in leaves)
        da, dk = _orig_put((a, k))
        jax.block_until_ready(jax.tree.leaves((da, dk)))
        t1 = time.perf_counter()
        _tick(f"h2d_stage_args[{nbytes >> 20}MB]", t1 - t0)
        out = fn(*da, **dk)
        jax.block_until_ready(out)
        t2 = time.perf_counter()
        _tick(f"stage_fn_exec[{nbytes >> 20}MB]", t2 - t1)
        host = jax.device_get(out)
        obytes = sum(getattr(x, "nbytes", 0) for x in jax.tree.leaves(host))
        _tick(f"d2h_outputs[{obytes >> 20}MB]", time.perf_counter() - t2)
        return host

    return wrapped


LocalBackend._jit_stage_fn = jit_counted

from tuplex_tpu.models import zillow

path = "/tmp/tuplex_tpu_bench/zillow_100000.csv"
if not os.path.exists(path):
    os.makedirs(os.path.dirname(path), exist_ok=True)
    zillow.generate_csv(path, 100000)

ctx = tuplex_tpu.Context()
zillow.build_pipeline(ctx.csv(path)).collect()  # warm: compile + transfers
COUNTS.clear()
t0 = time.perf_counter()
rows = zillow.build_pipeline(ctx.csv(path)).collect()
total = time.perf_counter() - t0
print(f"steady run: {total:.3f}s  rows={len(rows)}")
acc = 0.0
for name, (c, s) in sorted(COUNTS.items(), key=lambda kv: -kv[1][1]):
    acc += s
    print(f"  {name:24s} calls={c:5d}  wall={s:.3f}s")
print(f"  {'(unattributed host)':24s}              wall={total-acc:.3f}s")
