"""Price TPU alternatives to per-row u8 gathers (the profiled hot spot).

take_along_axis on u8[N,W] runs on the scalar core (~48ms for [81920,56] in
the zillow stage profile). Candidates:
  B. shift-sum: for idx = start+arange(W) (slices/shifts), accumulate W
     statically-shifted copies weighted by (start == s).
  C. one-hot bf16 matmul: out[n,j] = sum_k B[n,k] * (idx[n,j] == k) — exact
     for byte values (<=255 fits bf16's 8-bit mantissa; one term per sum).
"""
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)


def t(fn, n=5):
    fn()
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


N, W = 81920, 56
B = jax.device_put(np.random.randint(32, 127, (N, W), np.uint8))
start = jax.device_put(np.random.randint(0, W, (N,), np.int32))
idx = jax.device_put(np.random.randint(0, W, (N, W), np.int32))
jax.block_until_ready((B, start, idx))


@jax.jit
def gatherA(b, ix):
    return jnp.take_along_axis(b, ix, axis=1)


@jax.jit
def shiftB(b, s):
    pad = jnp.pad(b, ((0, 0), (0, W)))
    acc = jnp.zeros((N, W), jnp.uint8)
    for sh in range(W):
        acc = acc + jnp.where((s == sh)[:, None], pad[:, sh:sh + W], 0)
    return acc


@jax.jit
def onehotC(b, ix):
    oh = (ix[:, :, None] == jnp.arange(W, dtype=jnp.int32)[None, None, :])
    out = jnp.einsum("njk,nk->nj", oh.astype(jnp.bfloat16),
                     b.astype(jnp.bfloat16),
                     preferred_element_type=jnp.float32)
    return out.astype(jnp.uint8)


@jax.jit
def onehotC_shift(b, s):
    ix = jnp.clip(s[:, None] + jnp.arange(W, dtype=jnp.int32)[None, :],
                  0, W - 1)
    oh = (ix[:, :, None] == jnp.arange(W, dtype=jnp.int32)[None, None, :])
    out = jnp.einsum("njk,nk->nj", oh.astype(jnp.bfloat16),
                     b.astype(jnp.bfloat16),
                     preferred_element_type=jnp.float32)
    return out.astype(jnp.uint8)


ixs = jnp.clip(start[:, None] + jnp.arange(W, dtype=jnp.int32)[None, :],
               0, W - 1)
want_shift = np.asarray(gatherA(B, ixs))
want_arb = np.asarray(gatherA(B, idx))

for name, fn, args, want in (
        ("A_take_along_shift", gatherA, (B, ixs), want_shift),
        ("A_take_along_arb", gatherA, (B, idx), want_arb),
        ("B_shiftsum", shiftB, (B, start), want_shift),
        ("C_onehot_arb", onehotC, (B, idx), want_arb),
        ("C_onehot_shift", onehotC_shift, (B, start), want_shift)):
    got = np.asarray(fn(*args))
    ok = bool((got == want).all())
    sec = t(lambda: fn(*args).block_until_ready())
    print(json.dumps({"probe": name, "sec": round(sec, 5), "exact": ok}),
          flush=True)
