"""TPU liveness probe with verbose PJRT logging. Prints stages as it goes."""
import os, sys, time, faulthandler, threading
faulthandler.enable()
# dump all thread stacks every 60s so a wedge leaves evidence
faulthandler.dump_traceback_later(60, repeat=True, file=sys.stderr)
t0 = time.time()
print(f"[{time.time()-t0:.1f}s] importing jax", flush=True)
import jax
print(f"[{time.time()-t0:.1f}s] jax {jax.__version__} imported; calling jax.devices()", flush=True)
devs = jax.devices()
print(f"[{time.time()-t0:.1f}s] devices: {devs}", flush=True)
import jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
y = (x @ x).sum()
y.block_until_ready()
print(f"[{time.time()-t0:.1f}s] matmul ok: {float(y)}", flush=True)
print("PROBE_OK", flush=True)
