"""Device-time comparison of u8 per-row gather formulations (xplane-based:
block_until_ready is async-unreliable over the tunnel, so host wall lies;
the profiler's device timestamps don't)."""
import collections
import glob
import json
import os

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_enable_x64", True)

N, W = 81920, 56
B = np.random.randint(32, 127, (N, W), np.uint8)
IX = np.random.randint(0, W, (N, W), np.int32)
db, dix = jax.device_put(B), jax.device_put(IX)
jax.block_until_ready((db, dix))


def take(b, ix):
    return jnp.take_along_axis(b, ix, axis=1)


def onehot(b, ix):
    oh = (ix[:, :, None] == jnp.arange(W, dtype=jnp.int32)[None, None, :])
    o = jnp.einsum("njk,nk->nj", oh.astype(jnp.bfloat16),
                   b.astype(jnp.bfloat16),
                   preferred_element_type=jnp.float32)
    return o.astype(jnp.uint8)


def take_i32(b, ix):
    return jnp.take_along_axis(b.astype(jnp.int32), ix, axis=1) \
        .astype(jnp.uint8)


fns = {"take_u8": take, "onehot_mxu": onehot, "take_i32": take_i32}
compiled = {k: jax.jit(v) for k, v in fns.items()}
for k, f in compiled.items():
    got = np.asarray(f(db, dix))
    want = np.take_along_axis(B, IX, axis=1)
    assert (got == want).all(), k

TR = "/tmp/tpx_trace_gather"
os.system(f"rm -rf {TR}")
with jax.profiler.trace(TR):
    for k, f in compiled.items():
        for _ in range(3):
            f(db, dix).block_until_ready()

from tensorflow.tsl.profiler.protobuf import xplane_pb2

xs = sorted(glob.glob(f"{TR}/**/*.xplane.pb", recursive=True),
            key=os.path.getmtime)
sp = xplane_pb2.XSpace()
sp.ParseFromString(open(xs[-1], "rb").read())
for plane in sp.planes:
    if "TPU" not in plane.name:
        continue
    md = plane.event_metadata
    for line in plane.lines:
        if line.name != "XLA Modules":
            continue
        agg = collections.Counter()
        cnt = collections.Counter()
        for ev in line.events:
            name = md[ev.metadata_id].name
            agg[name] += ev.duration_ps / 1e6
            cnt[name] += 1
        for name, us in agg.most_common(10):
            print(json.dumps({"module": name.split("(")[0],
                              "total_us": round(us),
                              "runs": cnt[name],
                              "per_run_ms": round(us / cnt[name] / 1e3, 2)}))
