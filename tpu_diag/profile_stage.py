"""Capture a jax.profiler trace of the warm zillow stage exec on the live
chip and print the top HLO ops by self time (tensorboard_plugin_profile
parses the xplane offline — no tensorboard server needed)."""
import glob
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

import tuplex_tpu
from tuplex_tpu.exec.local import LocalBackend
from tuplex_tpu.models import zillow

TRACE = "/tmp/tpx_trace"

_orig_jit = LocalBackend._jit_stage_fn
STATE = {"n": 0}


def jit_traced(self, raw_fn, **kw):
    fn = _orig_jit(self, raw_fn, **kw)

    def wrapped(*a, **k):
        da = jax.device_put(a)
        jax.block_until_ready(jax.tree.leaves(da))
        big = sum(getattr(x, "nbytes", 0)
                  for x in jax.tree.leaves(da)) > (1 << 20)
        if big and STATE["n"] == 1:  # 2nd warm big call only
            with jax.profiler.trace(TRACE):
                out = fn(*da, **k)
                jax.block_until_ready(out)
        else:
            out = fn(*da, **k)
            jax.block_until_ready(out)
        if big:
            STATE["n"] += 1
        return out

    return wrapped


LocalBackend._jit_stage_fn = jit_traced

path = "/tmp/tuplex_tpu_bench/zillow_100000.csv"
ctx = tuplex_tpu.Context()
zillow.build_pipeline(ctx.csv(path)).collect()
t0 = time.perf_counter()
zillow.build_pipeline(ctx.csv(path)).collect()
print(f"traced run: {time.perf_counter()-t0:.3f}s", flush=True)

# ---- parse the xplane: top ops by self time
from tensorboard_plugin_profile.convert import raw_to_tool_data as rttd

xs = glob.glob(os.path.join(TRACE, "**", "*.xplane.pb"), recursive=True)
xs.sort(key=os.path.getmtime)
print(f"xplanes: {xs}", flush=True)
data, _ = rttd.xspace_to_tool_data([xs[-1]], "hlo_stats^", {})
import csv as _csv
import io

rows = list(_csv.reader(io.StringIO(data.decode()
                                    if isinstance(data, bytes) else data)))
hdr = rows[0]
print("columns:", hdr, flush=True)
try:
    sel = [hdr.index(c) for c in
           ("HLO Op Name", "Self Duration (us)", "Category")]
except ValueError:
    sel = None
body = rows[1:]
if sel:
    body.sort(key=lambda r: -float(r[sel[1]] or 0))
    total = sum(float(r[sel[1]] or 0) for r in body)
    print(f"total self us: {total:.0f}")
    for r in body[:35]:
        print(f"  {float(r[sel[1]]):>10.0f}us  {r[sel[2]]:<18s} {r[sel[0]][:90]}")
else:
    for r in body[:10]:
        print(r)
