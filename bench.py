#!/usr/bin/env python3
"""Benchmark driver: Zillow Z1 cleaning pipeline end-to-end.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

value      = input rows/sec through the full framework pipeline (CSV read +
             device decode + 10-op fused UDF stage + dual-mode resolve +
             collect), steady-state (post-compile), best of N runs.
             NOTE: defaults are 100k rows / best-of-2 since round 1 (override
             with BENCH_ROWS/BENCH_RUNS); rows are always reported on stderr
             so runs at different sizes aren't silently compared.
vs_baseline = speedup over the pure-CPython interpreter implementation of the
             SAME pipeline on the same data (the reference's own comparison
             methodology: benchmarks/zillow runs 1 warmup + timed runs).
Output parity with the interpreter implementation is asserted every run.

Platform strategy (round 2): the axon TPU tunnel wedges for long stretches
and a probe-subprocess that inits the TPU then exits can itself poison the
very next init (round 1's mid-trace UNAVAILABLE). So: run the ENTIRE bench
in ONE child process per platform attempt — TPU child first (a single
client, a single backend init, generous timeout, retried), CPU XLA child as
the loud fallback. The parent never touches jax.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

N_ROWS = int(os.environ.get("BENCH_ROWS", "100000"))
BASELINE_ROWS = int(os.environ.get("BENCH_BASELINE_ROWS", "40000"))
RUNS = int(os.environ.get("BENCH_RUNS", "2"))
# cold numbers through the tunnel: backend init ~2 min, zillow stage compile
# ~6 min (persistent cache makes reruns fast, but never assume a warm cache)
TPU_TIMEOUT_S = int(os.environ.get("BENCH_TPU_TIMEOUT", "1800"))
TPU_ATTEMPTS = int(os.environ.get("BENCH_TPU_ATTEMPTS", "2"))
TPU_RETRY_WAIT_S = int(os.environ.get("BENCH_TPU_RETRY_WAIT", "120"))
CPU_TIMEOUT_S = int(os.environ.get("BENCH_CPU_TIMEOUT", "1200"))


def _run_child(platform: str, timeout_s: int):
    """Run one full bench pass in a child. Returns the result dict or None."""
    env = dict(os.environ)
    env["TPX_BENCH_PLATFORM"] = platform
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child"],
            capture_output=True, text=True, timeout=timeout_s, env=env)
    except subprocess.TimeoutExpired as e:
        err = e.stderr or b""
        if isinstance(err, bytes):
            err = err.decode(errors="replace")
        sys.stderr.write(err[-4000:])
        print(f"bench: {platform} child timed out after {timeout_s}s "
              "(wedged tunnel?)", file=sys.stderr)
        return None
    sys.stderr.write(r.stderr[-4000:])
    for line in r.stdout.splitlines():
        if line.startswith("{"):
            try:
                d = json.loads(line)
                if "metric" in d:
                    return d
            except json.JSONDecodeError:
                pass
    print(f"bench: {platform} child failed rc={r.returncode}",
          file=sys.stderr)
    return None


def main() -> None:
    result = None
    for attempt in range(TPU_ATTEMPTS):
        result = _run_child("tpu", TPU_TIMEOUT_S)
        if result is not None and result.get("platform") != "cpu":
            break
        result = None
        if attempt + 1 < TPU_ATTEMPTS:
            print(f"bench: TPU attempt {attempt + 1} failed; retrying in "
                  f"{TPU_RETRY_WAIT_S}s", file=sys.stderr)
            time.sleep(TPU_RETRY_WAIT_S)
    if result is None:
        print("bench: *** TPU UNAVAILABLE — benchmarking on CPU XLA. This "
              "is NOT the headline configuration. ***", file=sys.stderr)
        result = _run_child("cpu", CPU_TIMEOUT_S)
    if result is None:
        print("bench: all platforms failed", file=sys.stderr)
        sys.exit(1)
    print(json.dumps(result))


def child() -> None:
    platform = os.environ["TPX_BENCH_PLATFORM"]
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import tempfile

    import jax

    if platform == "cpu":
        # sitecustomize force-registers the axon plugin; only a post-import
        # config update keeps backend init off the wedge-prone tunnel
        jax.config.update("jax_platforms", "cpu")
    t0 = time.perf_counter()
    actual = jax.devices()[0].platform
    print(f"bench[{platform}]: backend up in "
          f"{time.perf_counter() - t0:.1f}s -> {actual}", file=sys.stderr)
    if platform == "tpu" and actual == "cpu":
        sys.exit(3)  # silently downgraded: let the parent record the miss

    import tuplex_tpu
    from tuplex_tpu.models import zillow

    cache_dir = os.path.join(tempfile.gettempdir(), "tuplex_tpu_bench")
    os.makedirs(cache_dir, exist_ok=True)
    data = os.path.join(cache_dir, f"zillow_{N_ROWS}.csv")
    if not os.path.exists(data):
        zillow.generate_csv(data, N_ROWS, seed=42)
    base_data = os.path.join(cache_dir, f"zillow_{BASELINE_ROWS}.csv")
    if not os.path.exists(base_data):
        zillow.generate_csv(base_data, BASELINE_ROWS, seed=42)

    # --- pure-python interpreter baseline (same pipeline, same data gen) ---
    t0 = time.perf_counter()
    zillow.run_reference_python(base_data)
    base_s = time.perf_counter() - t0
    base_rate = BASELINE_ROWS / base_s

    # --- framework, warmup (compile) + timed runs --------------------------
    ctx = tuplex_tpu.Context()
    got = None
    times = []
    for i in range(RUNS + 1):
        t0 = time.perf_counter()
        ds = zillow.build_pipeline(ctx.csv(data))
        got = ds.collect()
        dt = time.perf_counter() - t0
        if i > 0:  # first run includes XLA compile
            times.append(dt)
    best = min(times)
    rate = N_ROWS / best

    # --- correctness gate --------------------------------------------------
    want = zillow.run_reference_python(data)
    ok = got == want
    if not ok:
        print(f"OUTPUT MISMATCH: got {len(got)} rows, want {len(want)}",
              file=sys.stderr)

    fast_s = ctx.metrics.fastPathWallTime()
    result = {
        "metric": "zillow_z1_rows_per_sec",
        "value": round(rate, 1),
        "unit": "rows/s",
        "vs_baseline": round(rate / base_rate, 3),
        "platform": actual,
    }
    # extra context on stderr (driver only parses stdout JSON line)
    print(json.dumps({
        "rows": N_ROWS, "best_s": round(best, 3),
        "runs_s": [round(t, 3) for t in times],
        "platform": actual,
        "interp_rows_per_sec": round(base_rate, 1),
        "output_rows": len(got) if got else 0,
        "output_matches_interpreter": ok,
        "fast_path_s": round(fast_s, 3),
        "slow_path_s": round(ctx.metrics.slowPathWallTime(), 3),
    }), file=sys.stderr)
    if fast_s == 0.0:
        # the whole pipeline ran on the interpreter: the number above does
        # not measure the compiled path at all. Never report that silently.
        print("bench: *** FAST PATH NEVER RAN — the number above measures "
              "the interpreter fallback, not the framework. ***",
              file=sys.stderr)
        if platform == "tpu":
            sys.exit(4)  # never report an interpreter number as a TPU run
        if os.environ.get("BENCH_REQUIRE_FAST"):
            sys.exit(1)
    print(json.dumps(result))


if __name__ == "__main__":
    if "--child" in sys.argv:
        child()
    else:
        main()
