#!/usr/bin/env python3
"""Benchmark driver: Zillow Z1 cleaning pipeline end-to-end.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

value      = input rows/sec through the full framework pipeline (CSV read +
             device decode + 10-op fused UDF stage + dual-mode resolve +
             collect), steady-state (post-compile), best of N runs.
             NOTE: defaults are 100k rows / best-of-2 since round 1 (override
             with BENCH_ROWS/BENCH_RUNS); rows are always reported on stderr
             so runs at different sizes aren't silently compared.
vs_baseline = speedup over the pure-CPython interpreter implementation of the
             SAME pipeline on the same data (the reference's own comparison
             methodology: benchmarks/zillow runs 1 warmup + timed runs).
Output parity with the interpreter implementation is asserted every run.

Platform strategy (round 3): the axon TPU tunnel wedges for long stretches,
and in round 2 the driver killed the bench mid-TPU-retry before any JSON was
printed. So: bank a CPU XLA result first (fast, reliable), then spend the
rest of a self-imposed budget (BENCH_BUDGET) on TPU attempts, each a single
child process with one backend init. SIGTERM/SIGINT print the best banked
result before exit. The parent never touches jax.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

N_ROWS = int(os.environ.get("BENCH_ROWS", "100000"))
BASELINE_ROWS = int(os.environ.get("BENCH_BASELINE_ROWS", "40000"))
# n_trials >= 3 so the JSON line carries a best-of-N spread (BENCH_r06
# requirement: spread <= 10% or the number is machine noise, r4 measured
# the baseline swinging 1.5x across a day)
RUNS = int(os.environ.get("BENCH_RUNS", "3"))
# Round-2 lesson: the driver killed the whole bench (rc=124) mid-TPU-retry
# and got NO json line. So (a) bank a CPU result FIRST, (b) spend the rest of
# a self-imposed budget on the TPU, (c) a SIGTERM/SIGINT handler prints the
# best banked result before dying. The driver gets a line no matter what.
BUDGET_S = int(os.environ.get("BENCH_BUDGET", "1500"))
CPU_TIMEOUT_S = int(os.environ.get("BENCH_CPU_TIMEOUT", "600"))

_T0 = time.monotonic()
_BEST: dict | None = None
_CHILD: subprocess.Popen | None = None


def _remaining() -> float:
    return BUDGET_S - (time.monotonic() - _T0)


def _emit_and_exit(signum=None, frame=None):
    if _CHILD is not None and _CHILD.poll() is None:
        try:
            _CHILD.kill()
        except OSError:
            pass
    if _BEST is not None:
        print(json.dumps(_BEST), flush=True)
        print(f"bench: emitted banked result on signal {signum}",
              file=sys.stderr)
        os._exit(0)
    print(f"bench: killed (signal {signum}) before any result", file=sys.stderr)
    os._exit(1)


def _filter_stderr(err: str) -> str:
    """Drop line-noise (multi-KB XLA AOT feature dumps, plugin warnings)
    before truncating, so the suite/summary lines survive the tail cap."""
    keep = [ln for ln in (err or "").splitlines()
            if "cpu_aot_loader" not in ln
            and "Platform 'axon' is experimental" not in ln]
    return "\n".join(keep)[-8000:] + "\n"


def _run_child(platform: str, timeout_s: float):
    """Run one full bench pass in a child. Returns the result dict or None."""
    global _CHILD
    if timeout_s < 30:
        print(f"bench: skipping {platform} child ({timeout_s:.0f}s left)",
              file=sys.stderr)
        return None
    env = dict(os.environ)
    env["TPX_BENCH_PLATFORM"] = platform
    # soft deadline for the child's secondary suite: the primary metric is
    # printed (and flushed) first, so the suite must never cost it
    env["BENCH_CHILD_DEADLINE"] = str(time.time() + timeout_s - 20)
    _CHILD = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
    timed_out = False
    try:
        out, err = _CHILD.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        _CHILD.kill()
        out, err = _CHILD.communicate()
        timed_out = True
    sys.stderr.write(_filter_stderr(err))
    for line in (out or "").splitlines():
        if line.startswith("{"):
            try:
                d = json.loads(line)
                if "metric" in d:
                    # valid even on a timeout kill: the child prints the
                    # primary metric before the (cut-short) suite
                    return d
            except json.JSONDecodeError:
                pass
    if timed_out:
        print(f"bench: {platform} child timed out after {timeout_s:.0f}s "
              "with no result (wedged tunnel?)", file=sys.stderr)
    else:
        print(f"bench: {platform} child failed rc={_CHILD.returncode}",
              file=sys.stderr)
    return None


def main() -> None:
    global _BEST
    signal.signal(signal.SIGTERM, _emit_and_exit)
    signal.signal(signal.SIGINT, _emit_and_exit)

    # Phase 1: bank a CPU XLA number (fast, reliable).
    _BEST = _run_child("cpu", min(CPU_TIMEOUT_S, _remaining() - 60))
    if _BEST is not None:
        print(f"bench: banked CPU result {_BEST['value']} {_BEST['unit']} "
              f"({_remaining():.0f}s budget left)", file=sys.stderr)

    # Phase 2: spend everything left on the TPU (the headline platform).
    while _remaining() > 90:
        result = _run_child("tpu", _remaining() - 30)
        if result is not None and result.get("platform") != "cpu":
            _BEST = result
            break
        if _remaining() > 150:
            print("bench: TPU attempt failed; retrying in 60s", file=sys.stderr)
            time.sleep(60)
        else:
            break

    if _BEST is None:
        print("bench: all platforms failed", file=sys.stderr)
        sys.exit(1)
    if _BEST.get("platform") != "tpu":
        print("bench: *** TPU UNAVAILABLE — reporting CPU XLA. This is NOT "
              "the headline configuration. ***", file=sys.stderr)
    print(json.dumps(_BEST))


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def _vs_llvm(rate: float):
    """Speedup vs the reference LLVM engine's rows/s on the same pipeline
    (scripts/llvm_baseline.py records the denominator — measured where the
    reference engine is installed, else an explicitly-labeled estimate —
    into BASELINE_LLVM.json). (None, "") when no denominator is recorded."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BASELINE_LLVM.json")
    try:
        with open(path) as fp:
            d = json.load(fp)
        base = float(d["zillow_rows_per_sec"])
        if base > 0:
            return round(rate / base, 3), d.get("kind", "unknown")
    except (OSError, KeyError, ValueError, TypeError):
        pass
    return None, ""


def child() -> None:
    platform = os.environ["TPX_BENCH_PLATFORM"]
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import tempfile

    import jax

    if platform == "cpu":
        # sitecustomize force-registers the axon plugin; only a post-import
        # config update keeps backend init off the wedge-prone tunnel
        jax.config.update("jax_platforms", "cpu")
    t0 = time.perf_counter()
    actual = jax.devices()[0].platform
    print(f"bench[{platform}]: backend up in "
          f"{time.perf_counter() - t0:.1f}s -> {actual}", file=sys.stderr)
    if platform == "tpu" and actual == "cpu":
        sys.exit(3)  # silently downgraded: let the parent record the miss

    import tuplex_tpu
    from tuplex_tpu.models import zillow

    cache_dir = os.path.join(tempfile.gettempdir(), "tuplex_tpu_bench")
    os.makedirs(cache_dir, exist_ok=True)
    data = os.path.join(cache_dir, f"zillow_{N_ROWS}.csv")
    if not os.path.exists(data):
        zillow.generate_csv(data, N_ROWS, seed=42)
    base_data = os.path.join(cache_dir, f"zillow_{BASELINE_ROWS}.csv")
    if not os.path.exists(base_data):
        zillow.generate_csv(base_data, BASELINE_ROWS, seed=42)

    # --- framework + pure-python baseline, INTERLEAVED -------------------
    # The 1-core box drifts minute to minute (r4 measured the interpreter
    # baseline swinging 105-156k rows/s across a day, moving vs_baseline
    # 0.94-1.22x with no code change). Alternating fw/py samples makes
    # both sides see the same machine state; best-of-N per side.
    from tuplex_tpu.runtime import xferstats

    conf = {}
    spec_env = os.environ.get("BENCH_SPECULATE")
    spec_on = spec_env is not None and spec_env not in ("0", "false")
    if spec_env is not None:
        # A/B flag for the branch-speculation measurement (STATUS round 7):
        # BENCH_SPECULATE=0 re-runs the same bench with sample-driven
        # dead-branch pruning off so the kernel delta is one env var away
        conf["tuplex.optimizer.speculateBranches"] = spec_on
    ctx = tuplex_tpu.Context(conf)
    got = None
    times = []
    d2h_per_run = []
    h2d_per_run = []
    base_times = []
    stage_slices = []    # (start, end) into ctx.metrics.stages per timed run
    for i in range(RUNS + 1):
        xsnap = xferstats.snapshot()
        n_stages0 = len(ctx.metrics.stages)
        t0 = time.perf_counter()
        ds = zillow.build_pipeline(ctx.csv(data))
        got = ds.collect()
        dt = time.perf_counter() - t0
        if i > 0:  # first run includes XLA compile
            times.append(dt)
            stage_slices.append((n_stages0, len(ctx.metrics.stages)))
            xd = xferstats.delta(xsnap)
            d2h_per_run.append(xd["d2h_bytes"])
            h2d_per_run.append(xd["h2d_bytes"])
        base_times.append(_timed(
            lambda: zillow.run_reference_python(base_data)))
    best = min(times)
    rate = N_ROWS / best
    base_rate = BASELINE_ROWS / min(base_times)
    # boundary-transfer tax of the steady-state run (runtime/xferstats):
    # this is the number the varlen wire + device-resident handoff shrink
    d2h_bytes = d2h_per_run[times.index(best)] if d2h_per_run else 0
    h2d_bytes = h2d_per_run[times.index(best)] if h2d_per_run else 0
    spread = (max(times) - min(times)) / min(times) if times else 0.0

    # --- correctness gate --------------------------------------------------
    want = zillow.run_reference_python(data)
    ok = got == want
    if not ok:
        print(f"OUTPUT MISMATCH: got {len(got)} rows, want {len(want)}",
              file=sys.stderr)

    # --- latency budget (runtime/critpath) ---------------------------------
    # one extra NON-timed run with tracing on (the timed loop above runs
    # untraced so the ring append never rides the measurement), then sweep
    # the span timeline into the exclusive bucket vector: bench_diff gates
    # the dotted latency_budget.* keys (the interpreter-resolve share and
    # the unattributed remainder must not grow)
    latency_budget = {}
    try:
        from tuplex_tpu.runtime import tracing
        was_on = tracing.enabled()
        tracing.enable(True)
        tracing.clear()
        zillow.build_pipeline(ctx.csv(data)).collect()
        latency_budget = ctx.metrics.latencyBudget()
        tracing.enable(was_on)
        tracing.clear()
    except Exception as e:   # readout is best-effort, never fails the bench
        print(f"latency_budget skipped: {type(e).__name__}: {e}",
              file=sys.stderr)

    fast_s = ctx.metrics.fastPathWallTime()
    vs_llvm, llvm_kind = _vs_llvm(rate)
    # device-plane cost attribution (runtime/devprof) for the BEST timed
    # run's stages: measured device seconds, XLA flops/bytes, peak device
    # memory and the roofline fraction per stage — the numbers the
    # /metrics exposition and the dashboard stage table also show
    lo, hi = stage_slices[times.index(best)]
    stage_costs = {}
    device_s = 0.0
    hbm_peak = 0
    for si, m in enumerate(ctx.metrics.stage_breakdown()[lo:hi]):
        if "device_s" not in m:
            continue
        device_s += m["device_s"]
        hbm_peak = max(hbm_peak, int(m.get("hbm_peak", 0)))
        stage_costs[str(si)] = {
            k: (round(m[k], 6) if isinstance(m[k], float) else m[k])
            for k in ("device_s", "flops", "device_bytes", "hbm_peak",
                      "roofline_frac", "wall_s", "compile_s")
            if k in m}
    result = {
        "metric": "zillow_z1_rows_per_sec",
        "value": round(rate, 1),
        "unit": "rows/s",
        "vs_baseline": round(rate / base_rate, 3),
        # vs the reference LLVM engine's measured-or-estimated rows/s
        # (scripts/llvm_baseline.py -> BASELINE_LLVM.json); null until a
        # denominator is recorded, and the kind says whether it was a real
        # measurement or a labeled estimate
        "vs_llvm": vs_llvm,
        "vs_llvm_kind": llvm_kind,
        "platform": actual,
        "d2h_bytes": int(d2h_bytes),
        "h2d_bytes": int(h2d_bytes),
        "n_trials": len(times),
        "spread": round(spread, 3),
        # compile pipeline: total stage-executable compile seconds across
        # the whole child (first run pays it, steady-state runs are free;
        # 0.0 with a warm AOT artifact cache) + actual XLA compile count
        "compile_s": round(ctx.metrics.compileTime(), 3),
        "stage_compiles": ctx.metrics.stageCompileCount(),
        # measured device seconds of the best run + the largest stage
        # executable's peak device-memory footprint, with the per-stage
        # breakdown (device_s/flops/device_bytes/hbm_peak/roofline_frac)
        # under dotted keys bench_diff gates directionally
        "device_s": round(device_s, 4),
        "hbm_peak": hbm_peak,
        "stage_costs": stage_costs,
        # plan-time static-analysis cost + how many operators the analyzer
        # routed to the interpreter without ever invoking the emitter
        "analyzer_ms": round(ctx.metrics.analyzerTimeMs(), 3),
        "plan_fallback_ops": ctx.metrics.planFallbackOps(),
        # sample-free specialization: operators typed exactly from the AST
        # and the CPython sample traces that verdict let planning skip
        "analyzer_inferred_ops": ctx.metrics.analyzerInferredOps(),
        "sample_traces_skipped": ctx.metrics.sampleTracesSkipped(),
        # critical-path wall attribution of one traced steady-state run
        # (runtime/critpath): bucket seconds + unattributed_frac under
        # dotted keys bench_diff gates directionally
        "latency_budget": latency_budget,
    }
    if spec_env is not None:
        result["speculate_branches"] = spec_on
    # extra context on stderr (driver only parses stdout JSON line)
    print(json.dumps({
        "rows": N_ROWS, "best_s": round(best, 3),
        "runs_s": [round(t, 3) for t in times],
        "spread": round(spread, 3),
        "d2h_bytes_per_run": [int(b) for b in d2h_per_run],
        "h2d_bytes_per_run": [int(b) for b in h2d_per_run],
        "platform": actual,
        "interp_rows_per_sec": round(base_rate, 1),
        "output_rows": len(got) if got else 0,
        "output_matches_interpreter": ok,
        "fast_path_s": round(fast_s, 3),
        "slow_path_s": round(ctx.metrics.slowPathWallTime(), 3),
        "compile_s": round(ctx.metrics.compileTime(), 3),
    }), file=sys.stderr)
    if fast_s == 0.0:
        # the whole pipeline ran on the interpreter: the number above does
        # not measure the compiled path at all. Never report that silently.
        print("bench: *** FAST PATH NEVER RAN — the number above measures "
              "the interpreter fallback, not the framework. ***",
              file=sys.stderr)
        if platform == "tpu":
            sys.exit(4)  # never report an interpreter number as a TPU run
        if os.environ.get("BENCH_REQUIRE_FAST"):
            sys.exit(1)
    # print the primary result BEFORE the suite: a wedged/slow secondary
    # config must never forfeit an already-computed banked number
    print(json.dumps(result), flush=True)
    if os.environ.get("BENCH_SUITE", "1") != "0":
        _suite(cache_dir, actual)


def _suite(cache_dir: str, platform: str) -> None:
    """Secondary tracked configs (BASELINE.md): flights, logs-regex,
    TPC-H Q1/Q6, NYC 311. One stderr JSON line each — rows/s + speedup over
    the pure-python implementation of the same pipeline. The primary stdout
    metric stays zillow-only; this records breadth."""
    import time

    import tuplex_tpu
    from tuplex_tpu.models import flights, logs, nyc311, tpch

    n = int(os.environ.get("BENCH_SUITE_ROWS", "60000"))

    def prep(name, gen):
        path = os.path.join(cache_dir, name)
        if not os.path.exists(path):
            gen(path)
        return path

    fp = prep(f"perf_{n}.csv", lambda p: flights.generate_perf_csv(p, n))
    cp = prep("carrier.csv", flights.generate_carrier_csv)
    ap = prep("airport.db", flights.generate_airport_db)
    lg = prep(f"logs_{n}.txt", lambda p: logs.generate_log(p, n))
    li = prep(f"lineitem_{n}.csv", lambda p: tpch.generate_csv(p, n))
    nc = prep(f"n311_{n}.csv", lambda p: nyc311.generate_csv(p, n))
    pq = os.path.join(cache_dir, f"q19part_{n}.csv")
    lq = os.path.join(cache_dir, f"q19li_{n}.csv")
    if not (os.path.exists(pq) and os.path.exists(lq)):
        tpch.generate_q19_csvs(pq, lq, max(200, n // 50), n)

    ctx = tuplex_tpu.Context()
    metrics = ctx.metrics
    # cheap configs first: on the tunneled TPU, flights' many-stage compile
    # can eat the whole child deadline, and a config that overruns kills
    # every config queued behind it
    configs = [
        ("tpch_q6", lambda: tpch.q6(ctx.csv(li)).collect(),
         lambda: tpch.run_reference_q6(li)),
        ("tpch_q1", lambda: tpch.q1(ctx.csv(li)).collect(),
         lambda: tpch.run_reference_q1(li)),
        ("nyc311", lambda: nyc311.build_pipeline(ctx, nc).collect(),
         lambda: nyc311.run_reference_python(nc)),
        ("logs_regex", lambda: logs.build_pipeline(ctx.text(lg),
                                                   "regex").collect(),
         lambda: logs.run_reference_python(lg, "regex")),
        ("tpch_q19", lambda: tpch.q19(ctx, pq, lq).collect(),
         lambda: tpch.run_reference_q19(pq, lq)),
        ("flights", lambda: flights.build_pipeline(ctx, fp, cp, ap).collect(),
         lambda: flights.run_reference_python(fp, cp, ap)),
    ]
    deadline = float(os.environ.get("BENCH_CHILD_DEADLINE", "0")) or None
    for name, run, ref in configs:
        if deadline is not None and time.time() > deadline - 30:
            print(json.dumps({"suite": name, "error": "skipped: deadline"}),
                  file=sys.stderr)
            continue
        try:
            run()                              # warm (compile)
            fast0 = metrics.fastPathWallTime()
            t0 = time.perf_counter()
            run()
            fw = time.perf_counter() - t0
            if metrics.fastPathWallTime() <= fast0:
                # compiled path never ran: an interpreter number must not
                # masquerade as framework throughput (same guard as the
                # primary metric)
                print(json.dumps({"suite": name,
                                  "error": "fast path never ran"}),
                      file=sys.stderr)
                continue
            py = min(_timed(ref) for _ in range(2))  # baseline jitter guard
            print(json.dumps({
                "suite": name, "rows": n, "platform": platform,
                "framework_s": round(fw, 3), "python_s": round(py, 3),
                "rows_per_sec": round(n / fw, 1),
                "speedup_vs_python": round(py / fw, 2)}), file=sys.stderr)
        except Exception as e:  # a broken secondary config must not kill
            print(json.dumps({"suite": name,                # the bench
                              "error": f"{type(e).__name__}: {e}"}),
                  file=sys.stderr)

    # serverless fan-out (AWSLambdaBackend analog): zillow across 4 warm
    # workers vs the 1x local number — on this single-core driver the tasks
    # serialize, so the delta above 1x IS the fan-out overhead (spec ship +
    # worker parse + part-file round-trip); compute scales out on real
    # deployments where each worker owns a host
    if deadline is None or time.time() < deadline - 150:
        try:
            from tuplex_tpu.models import zillow as _z

            zs = []
            for i in range(4):
                p = os.path.join(cache_dir, f"zsrv_{i}.csv")
                if not os.path.exists(p):
                    _z.generate_csv(p, 100000, seed=100 + i)
                zs.append(p)
            pat = os.path.join(cache_dir, "zsrv_*.csv")
            lc = tuplex_tpu.Context()
            _z.build_pipeline(lc.csv(pat)).collect()
            t0 = time.perf_counter()
            want = _z.build_pipeline(lc.csv(pat)).collect()
            local_s = time.perf_counter() - t0
            sc = tuplex_tpu.Context({"tuplex.backend": "serverless",
                                     "tuplex.aws.maxConcurrency": 4})
            _z.build_pipeline(sc.csv(pat)).collect()   # warm pool + traces
            t0 = time.perf_counter()
            got = _z.build_pipeline(sc.csv(pat)).collect()
            srv_s = time.perf_counter() - t0
            sc.close()
            n_rows = 4 * 100000
            print(json.dumps({
                "suite": "serverless_zillow_4w", "rows": n_rows,
                "platform": "cpu-workers",
                "local_1x_s": round(local_s, 3),
                "serverless_s": round(srv_s, 3),
                "rows_per_sec": round(n_rows / srv_s, 1),
                "output_matches_local": got == want,
                "overhead_vs_local": round(srv_s / local_s, 2)}),
                file=sys.stderr)
        except Exception as e:
            print(json.dumps({"suite": "serverless_zillow_4w",
                              "error": f"{type(e).__name__}: {e}"}),
                  file=sys.stderr)


if __name__ == "__main__":
    if "--child" in sys.argv:
        child()
    else:
        main()
