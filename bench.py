#!/usr/bin/env python3
"""Benchmark driver: Zillow Z1 cleaning pipeline end-to-end.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

value      = input rows/sec through the full framework pipeline (CSV read +
             device decode + 10-op fused UDF stage + dual-mode resolve +
             collect), steady-state (post-compile), best of N runs.
             NOTE: defaults are 100k rows / best-of-2 since round 1 (override
             with BENCH_ROWS/BENCH_RUNS); rows are always reported on stderr
             so runs at different sizes aren't silently compared.
vs_baseline = speedup over the pure-CPython interpreter implementation of the
             SAME pipeline on the same data (the reference's own comparison
             methodology: benchmarks/zillow runs 1 warmup + timed runs).
Output parity with the interpreter implementation is asserted every run.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time

N_ROWS = int(os.environ.get("BENCH_ROWS", "100000"))
BASELINE_ROWS = int(os.environ.get("BENCH_BASELINE_ROWS", "40000"))
RUNS = int(os.environ.get("BENCH_RUNS", "2"))
PROBE_TIMEOUT_S = int(os.environ.get("BENCH_TPU_PROBE_TIMEOUT", "240"))
PROBE_ATTEMPTS = int(os.environ.get("BENCH_TPU_PROBE_ATTEMPTS", "3"))


def _probe_tpu() -> str:
    """Decide the platform BEFORE any in-process backend init.

    Round 1 failed here: the axon TPU tunnel raised UNAVAILABLE mid-trace,
    the framework silently fell back to the interpreter, and the recorded
    number measured the wrong thing entirely. Strategy: probe the TPU in a
    SUBPROCESS (a wedged tunnel then hangs the child, not the bench), retry
    with backoff, and if the TPU is genuinely unreachable run on CPU XLA —
    the compiled path still executes and fast_path_s stays honest — while
    shouting the platform downgrade on stderr.
    """
    probe_src = (
        "import jax; ds = jax.devices(); "
        "print('PLATFORM=' + ds[0].platform)"
    )
    for attempt in range(PROBE_ATTEMPTS):
        try:
            r = subprocess.run([sys.executable, "-c", probe_src],
                               capture_output=True, text=True,
                               timeout=PROBE_TIMEOUT_S)
            for line in r.stdout.splitlines():
                if line.startswith("PLATFORM="):
                    plat = line.split("=", 1)[1]
                    print(f"bench: TPU probe attempt {attempt + 1}: "
                          f"platform={plat}", file=sys.stderr)
                    if plat != "cpu":
                        return plat
            print(f"bench: TPU probe attempt {attempt + 1} failed "
                  f"(rc={r.returncode}): {r.stderr.strip()[-400:]}",
                  file=sys.stderr)
        except subprocess.TimeoutExpired:
            print(f"bench: TPU probe attempt {attempt + 1} timed out after "
                  f"{PROBE_TIMEOUT_S}s (wedged tunnel?)", file=sys.stderr)
        if attempt + 1 < PROBE_ATTEMPTS:
            time.sleep(15 * (attempt + 1))
    print("bench: *** TPU UNAVAILABLE — benchmarking on CPU XLA. This is "
          "NOT the headline configuration. ***", file=sys.stderr)
    return "cpu"


def main() -> None:
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    platform = _probe_tpu()
    import jax

    if platform == "cpu":
        # sitecustomize force-registers the axon plugin; only a post-import
        # config update keeps backend init off the wedge-prone tunnel
        jax.config.update("jax_platforms", "cpu")
    import tuplex_tpu
    from tuplex_tpu.models import zillow

    cache_dir = os.path.join(tempfile.gettempdir(), "tuplex_tpu_bench")
    os.makedirs(cache_dir, exist_ok=True)
    data = os.path.join(cache_dir, f"zillow_{N_ROWS}.csv")
    if not os.path.exists(data):
        zillow.generate_csv(data, N_ROWS, seed=42)
    base_data = os.path.join(cache_dir, f"zillow_{BASELINE_ROWS}.csv")
    if not os.path.exists(base_data):
        zillow.generate_csv(base_data, BASELINE_ROWS, seed=42)

    # --- pure-python interpreter baseline (same pipeline, same data gen) ---
    t0 = time.perf_counter()
    base_out = zillow.run_reference_python(base_data)
    base_s = time.perf_counter() - t0
    base_rate = BASELINE_ROWS / base_s

    # --- framework, warmup (compile) + timed runs --------------------------
    ctx = tuplex_tpu.Context()
    got = None
    times = []
    for i in range(RUNS + 1):
        t0 = time.perf_counter()
        ds = zillow.build_pipeline(ctx.csv(data))
        got = ds.collect()
        dt = time.perf_counter() - t0
        if i > 0:  # first run includes XLA compile
            times.append(dt)
    best = min(times)
    rate = N_ROWS / best

    # --- correctness gate --------------------------------------------------
    want = zillow.run_reference_python(data)
    ok = got == want
    if not ok:
        print(f"OUTPUT MISMATCH: got {len(got)} rows, want {len(want)}",
              file=sys.stderr)

    fast_s = ctx.metrics.fastPathWallTime()
    result = {
        "metric": "zillow_z1_rows_per_sec",
        "value": round(rate, 1),
        "unit": "rows/s",
        "vs_baseline": round(rate / base_rate, 3),
        "platform": platform,
    }
    # extra context on stderr (driver only parses stdout JSON line)
    print(json.dumps({
        "rows": N_ROWS, "best_s": round(best, 3),
        "runs_s": [round(t, 3) for t in times],
        "platform": platform,
        "interp_rows_per_sec": round(base_rate, 1),
        "output_rows": len(got) if got else 0,
        "output_matches_interpreter": ok,
        "fast_path_s": round(fast_s, 3),
        "slow_path_s": round(ctx.metrics.slowPathWallTime(), 3),
    }), file=sys.stderr)
    if fast_s == 0.0:
        # the whole pipeline ran on the interpreter: the number above does
        # not measure the compiled path at all. Never report that silently.
        print("bench: *** FAST PATH NEVER RAN — the number above measures "
              "the interpreter fallback, not the framework. ***",
              file=sys.stderr)
        if os.environ.get("BENCH_REQUIRE_FAST"):
            sys.exit(1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
